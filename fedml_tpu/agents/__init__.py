"""MLOps agent daemons over the pub/sub control plane.

Parity target: the reference's scheduler agents — a slave agent binds to
the platform, receives start/stop-run commands over MQTT topics, executes
jobs, and streams status back through a message center with a retry queue
(``computing/scheduler/scheduler_core/message_center.py:21,184``,
``status_center.py:18,178``, ``slave/base_slave_protocol_manager.py``).

TPU-native redesign, local-first: the transport is the repo's own stdlib
pub/sub broker (``core/distributed/communication/pubsub``, the MQTT
analogue with last-will), job execution is :mod:`fedml_tpu.api`'s run
registry (subprocess + meta.json), and the daemons are threads or
standalone processes (``python -m fedml_tpu.cli agent``).

Topic scheme (reference ``flclient_agent/<edge>/start_train`` shape):

- ``flclient_agent/<device>/start_train``  master -> slave: job spec
- ``flclient_agent/<device>/stop_train``   master -> slave: stop a run
- ``fl_client/mlops/status``               slave -> master: device/run status
- ``fl_client/agent/online``               slave presence; last-will posts
  the OFFLINE payload on abnormal disconnect
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..core.distributed.communication.pubsub import (_recv_frame,
                                                     _send_frame,
                                                     client_connect)

logger = logging.getLogger(__name__)

# device statuses (reference status_center.py DeviceStatus, reduced to the
# lifecycle a local-first deployment has)
DEVICE_IDLE = "IDLE"
DEVICE_RUNNING = "RUNNING"
DEVICE_OFFLINE = "OFFLINE"

# job statuses re-exported from the run registry plus the pre-launch one
JOB_PROVISIONING = "PROVISIONING"
JOB_RUNNING = "RUNNING"
JOB_FINISHED = "FINISHED"
JOB_FAILED = "FAILED"
JOB_KILLED = "KILLED"

TOPIC_STATUS = "fl_client/mlops/status"
TOPIC_ONLINE = "fl_client/agent/online"


# Signed commands are only honored within this window of their signing
# time, and a MAC is single-use within it — together these close the
# replay a passive broker observer could otherwise mount (capturing a
# signed stop_train and firing it later at a re-used request id).
JOB_MAC_TTL_S = 300.0

# check_job reason for an exact re-delivery of an already-honored frame —
# callers treat this one specially: the message center's at-least-once
# sender can legitimately produce byte-identical resends (same MAC), which
# must be re-announced, never reported as a failure of the live request
REASON_REPLAY = "replayed command (MAC already seen)"


def agent_secret() -> Optional[bytes]:
    """Shared bind token for job dispatch (``FEDML_TPU_AGENT_SECRET``).
    Independent of the broker secret: even a peer that can reach the
    broker cannot start jobs without it. None = no token configured —
    daemons REFUSE to start that way unless told ``insecure_open``.
    Reference counterpart: device binding through the account manager
    (``scheduler_core/account_manager.py:1-469``)."""
    s = os.environ.get("FEDML_TPU_AGENT_SECRET", "")
    return s.encode() if s else None


def _job_mac(secret: bytes, payload: dict) -> str:
    """HMAC over the canonical job command (everything except the mac
    itself), binding request id, target, yaml content, and the signing
    timestamp + nonce added by :func:`sign_job`."""
    import hashlib
    import hmac as _hmac
    body = json.dumps({k: v for k, v in sorted(payload.items())
                       if k != "auth"}, sort_keys=True,
                      separators=(",", ":"))
    return _hmac.new(secret, body.encode(), hashlib.sha256).hexdigest()


def sign_job(payload: dict, secret: Optional[bytes] = None) -> dict:
    secret = secret if secret is not None else agent_secret()
    if secret is not None:
        payload = dict(payload)
        payload["ts"] = time.time()
        payload["nonce"] = uuid.uuid4().hex
        payload["auth"] = _job_mac(secret, payload)
    return payload


def check_job(payload: dict, secret: Optional[bytes] = None,
              seen_macs: Optional[Dict[str, float]] = None) -> Optional[str]:
    """None iff the command carries a valid, fresh, never-before-seen
    MAC; otherwise a human-readable refusal reason. A bad token and a
    stale timestamp are DIFFERENT operational failures (rotate secrets
    vs fix NTP) and are reported distinctly.

    ``secret=None`` (and no env token) accepts everything — callers own
    that decision; the daemons only reach it through an explicit
    ``insecure_open``. ``seen_macs`` is the caller's replay ledger
    (mac -> first-seen time). Only a freshness window of entries ever
    needs keeping (older frames fail the ts check on their own), so
    pruning drops entries older than the TTL and, under a flood, evicts
    oldest-first down to the cap instead of scanning forever.
    """
    import hmac as _hmac
    secret = secret if secret is not None else agent_secret()
    if secret is None:
        return None  # explicit insecure-open deployment
    mac = payload.get("auth")
    if not mac or not _hmac.compare_digest(str(mac),
                                           _job_mac(secret, payload)):
        return "bad or missing bind token"
    ts = payload.get("ts")
    now = time.time()
    if not isinstance(ts, (int, float)) or abs(now - ts) > JOB_MAC_TTL_S:
        return ("stale or clock-skewed command timestamp (>%.0fs; fix "
                "NTP or re-dispatch)" % JOB_MAC_TTL_S)
    if seen_macs is not None:
        if mac in seen_macs:
            return REASON_REPLAY
        seen_macs[str(mac)] = now
        if len(seen_macs) > 4096:
            for m, t in list(seen_macs.items()):
                if now - t > JOB_MAC_TTL_S:
                    del seen_macs[m]
            while len(seen_macs) > 4096:  # flood of still-fresh MACs
                seen_macs.pop(min(seen_macs, key=seen_macs.get))
    return None


def verify_job(payload: dict, secret: Optional[bytes] = None,
               seen_macs: Optional[Dict[str, float]] = None) -> bool:
    return check_job(payload, secret, seen_macs) is None


def _topic_start(device_id: int) -> str:
    return f"flclient_agent/{device_id}/start_train"


def _topic_stop(device_id: int) -> str:
    return f"flclient_agent/{device_id}/stop_train"


def _topic_upgrade(device_id: int) -> str:
    return f"flclient_agent/{device_id}/upgrade"


class MessageCenter:
    """Broker client with a durable sender: publishes ride a queue drained
    by a sender thread with bounded retries, and sent/received records land
    in JSONL files (reference ``message_center.py`` RETRY_COUNT=3 +
    message-sent-records.log). Subscriptions dispatch to topic handlers on
    a receive thread."""

    RETRY_COUNT = 3
    RETRY_DELAY_S = 0.5

    def __init__(self, broker_host: str, broker_port: int,
                 record_dir: Optional[str] = None,
                 will_topic: Optional[str] = None,
                 will_payload=None):
        self._addr = (broker_host, int(broker_port))
        self._handlers: Dict[str, Callable[[dict], None]] = {}
        self._subs: List[str] = []
        # will_payload may be a dict or a CALLABLE returning one: the LWT
        # is re-installed on every reconnect, and a proof-carrying will
        # must be minted fresh each time (the master's nonce ledger makes
        # proofs single-use — a reused will would be dropped as replay
        # exactly when the device actually dies)
        self._will = (will_topic, will_payload)
        self._sock: Optional[socket.socket] = None
        self._sock_lock = threading.Lock()
        self._queue: List[dict] = []
        self._queue_cv = threading.Condition()
        self._in_flight = False   # sender popped an item it hasn't settled
        self._running = False
        self._record_dir = record_dir
        if record_dir:
            os.makedirs(record_dir, exist_ok=True)

    # --- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._connect()
        self._running = True
        threading.Thread(target=self._recv_loop, daemon=True).start()
        threading.Thread(target=self._send_loop, daemon=True).start()

    def stop(self, graceful: bool = True) -> None:
        self._running = False
        with self._queue_cv:
            self._queue_cv.notify_all()
        with self._sock_lock:
            if self._sock is not None:
                try:
                    if graceful:
                        _send_frame(self._sock, {"kind": "disconnect"})
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _connect(self) -> None:
        sock = client_connect(self._addr[0], self._addr[1])
        for topic in self._subs:
            _send_frame(sock, {"kind": "sub", "topic": topic})
        if self._will[0] is not None:
            will = self._will[1]
            if callable(will):
                will = will()  # fresh nonce/proof per connection
            _send_frame(sock, {"kind": "lwt", "topic": self._will[0],
                               "payload": json.dumps(will)})
        self._sock = sock

    # --- pub/sub -----------------------------------------------------------
    def subscribe(self, topic: str, handler: Callable[[dict], None]) -> None:
        self._handlers[topic] = handler
        self._subs.append(topic)
        with self._sock_lock:
            if self._sock is not None:
                _send_frame(self._sock, {"kind": "sub", "topic": topic})

    def publish(self, topic: str, payload: dict) -> None:
        """Enqueue for the durable sender (returns immediately)."""
        with self._queue_cv:
            self._queue.append({"topic": topic, "payload": payload,
                                "id": uuid.uuid4().hex, "tries": 0})
            self._queue_cv.notify()

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until the sender has drained the queue AND settled the
        item it popped (sent or dropped) — needed before process
        replacement (OTA re-exec): the sender pops before sending, so
        queue-empty alone would let execve clobber an UPGRADED status
        that is still on its way to the socket."""
        deadline = time.time() + timeout_s
        with self._queue_cv:
            while time.time() < deadline:
                if not self._queue and not self._in_flight:
                    return True
                self._queue_cv.wait(timeout=min(
                    0.05, max(deadline - time.time(), 0.001)))
        return False

    def _record(self, name: str, entry: dict) -> None:
        if not self._record_dir:
            return
        try:
            with open(os.path.join(self._record_dir, name), "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError:
            pass

    def _send_loop(self) -> None:
        while True:
            with self._queue_cv:
                while self._running and not self._queue:
                    self._queue_cv.wait(timeout=1.0)
                if not self._running:
                    return
                item = self._queue.pop(0)
                self._in_flight = True
            try:
                self._record("message-sent-records.log",
                             {"id": item["id"], "topic": item["topic"],
                              "ts": time.time()})
                ok = False
                while item["tries"] < self.RETRY_COUNT and not ok:
                    item["tries"] += 1
                    try:
                        with self._sock_lock:
                            if self._sock is None:
                                if not self._running:
                                    break  # stopped: don't resurrect the
                                    # socket (it would re-install the LWT
                                    # and later fire a spurious OFFLINE)
                                self._connect()
                            _send_frame(self._sock, {
                                "kind": "pub", "topic": item["topic"],
                                "payload": json.dumps(item["payload"])})
                        ok = True
                    except OSError as e:
                        logger.warning("message center: publish failed "
                                       "(try %d/%d): %s", item["tries"],
                                       self.RETRY_COUNT, e)
                        with self._sock_lock:
                            self._sock = None
                        time.sleep(self.RETRY_DELAY_S * item["tries"])
                if ok:
                    self._record("message-sent-success-records.log",
                                 {"id": item["id"], "topic": item["topic"],
                                  "ts": time.time()})
                else:
                    self._record("message-dropped-records.log",
                                 {"id": item["id"], "topic": item["topic"],
                                  "ts": time.time()})
            except Exception:  # e.g. unserializable payload: drop the
                # item, keep the sender alive for the rest of the queue
                logger.exception("message center: dropping unsendable "
                                 "message %s", item["id"])
                self._record("message-dropped-records.log",
                             {"id": item["id"], "topic": item["topic"],
                              "ts": time.time()})
            finally:
                # ALWAYS settle, even if publish raised something beyond
                # OSError (e.g. an unserializable payload) — a stuck
                # in-flight flag would make every future flush() time out
                with self._queue_cv:
                    self._in_flight = False
                    self._queue_cv.notify_all()

    def _recv_loop(self) -> None:
        backoff = 0.2
        while self._running:
            with self._sock_lock:
                sock = self._sock
            if sock is None:
                # reconnect here too: a recv-only agent (a slave waiting
                # for commands) would otherwise go permanently deaf after
                # a broker restart — _connect replays subscriptions + LWT
                try:
                    with self._sock_lock:
                        if self._sock is None:
                            self._connect()
                    backoff = 0.2
                except OSError:
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 5.0)
                continue
            try:
                frame = _recv_frame(sock)
            except OSError:
                frame = None
            if frame is None:
                with self._sock_lock:
                    if self._sock is sock:  # dead socket: force reconnect
                        self._sock = None
                if self._running:
                    time.sleep(0.2)
                continue
            topic = frame.get("topic")
            try:
                payload = json.loads(frame.get("payload"))
            except (TypeError, ValueError):
                logger.warning("message center: undecodable payload on %r",
                               topic)
                continue
            self._record("message-received-records.log",
                         {"topic": topic, "ts": time.time()})
            handler = self._handlers.get(topic)
            if handler is None:
                continue
            try:
                handler(payload)
            except Exception:  # a bad handler must not kill the daemon
                logger.exception("message center: handler for %r failed",
                                 topic)


class SlaveAgent:
    """Compute-agent daemon (reference ``base_slave_protocol_manager``):
    binds to the broker, executes start-train commands through the local
    run registry, streams status transitions back, and dies loudly (the
    broker fires its last-will) on abnormal disconnect."""

    def __init__(self, device_id: int, broker_host: str, broker_port: int,
                 poll_s: float = 0.5, secret: Optional[bytes] = None,
                 insecure_open: bool = False,
                 device_token: Optional[str] = None):
        self.device_id = int(device_id)
        self.poll_s = poll_s
        # per-device credential from the account registry (reference
        # account_manager binding); shown in presence so a registry-wired
        # master only schedules onto enrolled devices
        self.device_token = (device_token
                             or os.environ.get("FEDML_TPU_DEVICE_TOKEN"))
        self.current_version: Optional[str] = None
        # secure by default: a daemon that executes arbitrary shell jobs
        # must not come up accepting ANY start_train published to its
        # topic — open deployment is an explicit flag, never a default
        self._secret = secret if secret is not None else agent_secret()
        if self._secret is None and not insecure_open:
            raise RuntimeError(
                "SlaveAgent: refusing to start without a bind token. Set "
                "FEDML_TPU_AGENT_SECRET (or pass secret=) so job dispatch "
                "is authenticated, or pass insecure_open=True to "
                "explicitly accept unauthenticated commands.")
        self._insecure_open = insecure_open and self._secret is None
        from ..api import _runs_root
        # the replay ledger persists across daemon restarts: an in-memory
        # ledger alone would re-accept a captured frame replayed inside
        # the freshness window right after a crash/relaunch
        self._ledger_path = os.path.join(
            _runs_root(), f"agent_{device_id}", "seen-macs.log")
        self._seen_macs: Dict[str, float] = self._load_ledger()
        # the LWT must pass the same registry gate as live presence, or a
        # bound device's crash would be silently dropped; it is a FACTORY
        # so every reconnect installs a fresh nonce/proof (the master's
        # ledger makes proofs single-use), and the master verifies
        # OFFLINE proofs without freshness (computed at connect time)
        self.center = MessageCenter(
            broker_host, broker_port,
            record_dir=os.path.join(_runs_root(), f"agent_{device_id}"),
            will_topic=TOPIC_ONLINE,
            will_payload=lambda: self._presence(DEVICE_OFFLINE))
        # request run-id -> registry run-id (for stop routing)
        self.runs: Dict[str, str] = {}
        self._seen_requests = set()
        # last status published per request — a redelivered start_train
        # re-announces THIS (a finished job must not be resurrected to
        # RUNNING by a duplicate frame)
        self._last_status: Dict[str, Dict[str, Any]] = {}
        self._watchers: Dict[str, threading.Thread] = {}

    # --- replay ledger persistence -----------------------------------------
    def _load_ledger(self) -> Dict[str, float]:
        seen: Dict[str, float] = {}
        now = time.time()
        try:
            with open(self._ledger_path) as f:
                for line in f:
                    try:
                        mac, ts = line.split()
                        if now - float(ts) <= 2 * JOB_MAC_TTL_S:
                            seen[mac] = float(ts)
                    except ValueError:
                        continue
        except OSError:
            return seen
        # compact: the file is append-only while running, so rewrite it at
        # load with only the surviving (freshness-window) entries — a
        # long-lived daemon must not accrete an unbounded ledger
        try:
            tmp = self._ledger_path + ".tmp"
            with open(tmp, "w") as f:
                for mac, ts in seen.items():
                    f.write(f"{mac} {ts}\n")
            os.replace(tmp, self._ledger_path)
        except OSError:
            pass
        return seen

    def _remember_mac(self, payload: dict) -> None:
        mac = payload.get("auth")
        if not mac:
            return
        try:
            os.makedirs(os.path.dirname(self._ledger_path), exist_ok=True)
            with open(self._ledger_path, "a") as f:
                f.write(f"{mac} {self._seen_macs.get(str(mac), time.time())}\n")
        except OSError:
            pass

    def _check(self, payload: dict) -> Optional[str]:
        reason = check_job(payload, self._secret,
                           seen_macs=self._seen_macs)
        if reason is None:
            self._remember_mac(payload)
        return reason

    def _reannounce(self, request_id: str) -> bool:
        """Re-publish the request's ACTUAL last status (the anti-
        poisoning contract for duplicates/replays: never hardcode RUNNING
        — it would resurrect a finished job — and never emit FAILED for
        a live one). True if a status was re-announced."""
        last = self._last_status.get(request_id)
        if request_id in self._seen_requests and last:
            self._status(request_id, last["status"],
                         **{k: v for k, v in last.items()
                            if k != "status"})
            return True
        return False

    def _presence(self, status: str) -> dict:
        """Presence payload. With a device token, it carries an HMAC
        PROOF over (device_id, status, ts, nonce) — never the token
        itself, which a broker peer could harvest from the shared
        topic."""
        p = {"device_id": self.device_id, "status": status}
        if self.device_token:
            from .accounts import presence_proof
            p["ts"] = time.time()
            p["nonce"] = uuid.uuid4().hex
            p["proof"] = presence_proof(self.device_token,
                                        str(self.device_id), status,
                                        p["ts"], p["nonce"])
        return p

    def start(self, presence_interval_s: float = 30.0) -> None:
        c = self.center
        c.subscribe(_topic_start(self.device_id), self._on_start)
        c.subscribe(_topic_stop(self.device_id), self._on_stop)
        c.subscribe(_topic_upgrade(self.device_id), self._on_upgrade)
        c.start()
        c.publish(TOPIC_ONLINE, self._presence(DEVICE_IDLE))
        # heartbeat: the broker retains nothing, so a master that starts
        # (or restarts) after this agent would otherwise never see it —
        # and a registry-wired master gates ALL traffic on presence
        self._presence_interval = float(presence_interval_s)
        self._presence_stop = threading.Event()
        t = threading.Thread(target=self._presence_loop, daemon=True)
        self._presence_thread = t
        t.start()

    def _presence_loop(self) -> None:
        stop = self._presence_stop
        while not stop.wait(self._presence_interval):
            try:
                # announce the ACTUAL state: a heartbeat claiming IDLE
                # while jobs run would mislead schedulers gating on it.
                # list() snapshot: the receive thread mutates _watchers
                busy = any(t.is_alive()
                           for t in list(self._watchers.values()))
                self.center.publish(
                    TOPIC_ONLINE,
                    self._presence(DEVICE_RUNNING if busy
                                   else DEVICE_IDLE))
            except Exception:
                logger.exception("presence heartbeat failed")

    def stop(self) -> None:
        stop = getattr(self, "_presence_stop", None)
        if stop is not None:
            stop.set()
        self.center.stop()

    def _status(self, request_id: str, status: str, **extra) -> None:
        self._last_status[request_id] = {"status": status, **extra}
        payload = {"device_id": self.device_id, "request_id": request_id,
                   "status": status, "ts": time.time(), **extra}
        if self.device_token:
            # status frames carry an HMAC like presence proofs: without
            # one, any broker-authenticated peer could flip this device's
            # live job to FAILED/FINISHED on a registry-wired master.
            # Re-announcements mint a fresh nonce (proofs are single-use)
            from .accounts import status_proof
            payload["nonce"] = uuid.uuid4().hex
            payload["proof"] = status_proof(
                self.device_token, str(self.device_id), request_id,
                status, payload["ts"], payload["nonce"])
        self.center.publish(TOPIC_STATUS, payload)

    def _on_start(self, payload: dict) -> None:
        from .. import api
        request_id = str(payload.get("request_id") or uuid.uuid4().hex)
        reason = self._check(payload)
        if reason is not None:
            if reason == REASON_REPLAY:
                # byte-identical redelivery (at-least-once sender retry,
                # or an actual replay)
                if not self._reannounce(request_id):
                    logger.error("agent %s: dropping replayed start_train "
                                 "%s", self.device_id, request_id)
                return
            # refuse unauthenticated job dispatch — but NEVER by publishing
            # a status for a request id we already honor: an unauthenticated
            # peer echoing a live request id must not be able to flip that
            # job to FAILED on the master (status poisoning)
            logger.error("agent %s: REFUSING start_train %s — %s",
                         self.device_id, request_id, reason)
            if request_id not in self._seen_requests:
                # unknown id: tell the (possibly legitimate, misconfigured)
                # sender instead of leaving them waiting at PROVISIONING
                self._status(request_id, JOB_FAILED,
                             error=f"start_train refused: {reason}")
            return
        # idempotency: the master re-publishes start_train until it sees a
        # status (the broker has no retained messages, so a command sent
        # before this agent subscribed is simply gone) — a duplicate must
        # re-announce, never re-execute
        if request_id in self._seen_requests:
            self._reannounce(request_id)
            return
        self._seen_requests.add(request_id)
        self._status(request_id, JOB_PROVISIONING)
        if "job_yaml_content" in payload:
            # the master ships yaml CONTENT (master and agent need not
            # share a filesystem); materialize a job dir that also serves
            # as the default workspace
            from ..api import _runs_root
            jdir = os.path.join(_runs_root(), f"agent_{self.device_id}",
                                "jobs", request_id)
            os.makedirs(jdir, exist_ok=True)
            yaml_file = os.path.join(
                jdir, payload.get("job_yaml_name") or "job.yaml")
            with open(yaml_file, "w") as f:
                f.write(payload["job_yaml_content"])
        else:  # same-host dispatch may still send a path
            yaml_file = payload.get("job_yaml")
        if not yaml_file:
            # a malformed command must surface as FAILED, not stall the
            # requester's FSM at PROVISIONING until their timeout
            self._status(request_id, JOB_FAILED,
                         error="start_train without job yaml")
            return
        res = api.launch_job(yaml_file)
        if res.result_code != 0:
            self._status(request_id, JOB_FAILED,
                         error=res.result_message)
            return
        self.runs[request_id] = res.run_id
        self._status(request_id, JOB_RUNNING, run_id=res.run_id)
        t = threading.Thread(target=self._watch, args=(request_id,
                                                       res.run_id),
                             daemon=True)
        self._watchers[request_id] = t
        t.start()

    def _watch(self, request_id: str, run_id: str) -> None:
        from .. import api
        while True:
            status = api.run_status(run_id)
            if status is None:
                self._status(request_id, JOB_FAILED, error="run lost")
                return
            if status != api.STATUS_RUNNING:
                self._status(request_id, status, run_id=run_id,
                             log_tail=api.run_logs(run_id, tail=5))
                return
            time.sleep(self.poll_s)

    def _on_stop(self, payload: dict) -> None:
        from .. import api
        request_id = str(payload.get("request_id", ""))
        reason = self._check(payload)
        if reason is not None:
            logger.error("agent %s: REFUSING stop_train %s — %s",
                         self.device_id, request_id, reason)
            return
        run_id = self.runs.get(request_id)
        if run_id is None:
            self._status(request_id, JOB_FAILED, error="unknown run")
            return
        api.run_stop(run_id)
        # the watcher thread reports the terminal KILLED status

    def _on_upgrade(self, payload: dict) -> None:
        """OTA agent upgrade (reference ``scheduler_core/ota_upgrade.py``):
        a SIGNED command ships a zip package + version + sha256; the
        agent verifies the digest, stages the package under its runs dir,
        records the version, and reports UPGRADED. Process swap-over is
        deployment policy: with FEDML_TPU_AGENT_ALLOW_REEXEC=1 the daemon
        re-execs itself so the staged package (prepended to PYTHONPATH)
        takes effect; otherwise the supervisor restarts it."""
        import base64
        import hashlib
        import zipfile
        from ..api import _runs_root
        request_id = str(payload.get("request_id", ""))
        reason = self._check(payload)
        if reason is not None:
            if reason == REASON_REPLAY:
                # identical redelivery: re-announce, never fail
                if not self._reannounce(request_id):
                    logger.error("agent %s: dropping replayed upgrade %s",
                                 self.device_id, request_id)
                return
            logger.error("agent %s: REFUSING upgrade %s — %s",
                         self.device_id, request_id, reason)
            if request_id not in self._seen_requests:
                # unknown id only: an unauthenticated peer echoing a live
                # request id must not flip it to FAILED
                self._status(request_id, JOB_FAILED,
                             error=f"upgrade refused: {reason}")
            return
        if request_id in self._seen_requests:
            self._reannounce(request_id)  # fresh-MAC redelivery
            return
        self._seen_requests.add(request_id)
        version = str(payload.get("version", ""))
        blob = base64.b64decode(payload.get("package_b64", ""))
        digest = hashlib.sha256(blob).hexdigest()
        if not version or digest != payload.get("sha256"):
            logger.error("agent %s: upgrade %s digest mismatch",
                         self.device_id, request_id)
            self._status(request_id, JOB_FAILED,
                         error="upgrade package digest mismatch")
            return
        import re as _re
        if not _re.fullmatch(r"[A-Za-z0-9._-]{1,64}", version) \
                or version in (".", ".."):
            # the version names the staging directory — a signed payload
            # is still not trusted to choose arbitrary paths
            self._status(request_id, JOB_FAILED,
                         error="upgrade version must be a plain "
                               "identifier")
            return
        pkg_dir = os.path.join(_runs_root(), f"agent_{self.device_id}",
                               "pkgs", version)
        os.makedirs(pkg_dir, exist_ok=True)
        import io
        try:
            with zipfile.ZipFile(io.BytesIO(blob)) as z:
                # refuse traversal: every member must land inside pkg_dir
                for m in z.namelist():
                    dest = os.path.realpath(os.path.join(pkg_dir, m))
                    if not dest.startswith(
                            os.path.realpath(pkg_dir) + os.sep):
                        self._status(request_id, JOB_FAILED,
                                     error="upgrade package escapes "
                                           "target dir")
                        return
                z.extractall(pkg_dir)
        except (zipfile.BadZipFile, OSError, ValueError) as e:
            # a digest-valid but unreadable package must still resolve
            # the request (the master is waiting on this id)
            logger.error("agent %s: upgrade %s unusable package: %s",
                         self.device_id, request_id, e)
            self._status(request_id, JOB_FAILED,
                         error=f"upgrade package unusable: {e}")
            return
        cur = os.path.join(_runs_root(), f"agent_{self.device_id}",
                           "current_version.json")
        with open(cur + ".tmp", "w") as f:
            json.dump({"version": version, "path": pkg_dir,
                       "ts": time.time()}, f)
        os.replace(cur + ".tmp", cur)
        self.current_version = version
        logger.warning("agent %s: upgraded to %s (staged at %s)",
                       self.device_id, version, pkg_dir)
        self._status(request_id, "UPGRADED", version=version)
        if os.environ.get("FEDML_TPU_AGENT_ALLOW_REEXEC"):
            import sys
            # the UPGRADED status rides the async sender — it must reach
            # the wire BEFORE this process image is replaced
            self.center.flush(timeout_s=10.0)
            env = dict(os.environ)
            env["PYTHONPATH"] = (pkg_dir + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            argv = [sys.executable, "-m", "fedml_tpu.cli", "agent",
                    "--broker", f"{self.center._addr[0]}:"
                                f"{self.center._addr[1]}",
                    "--device-id", str(self.device_id)]
            if self._insecure_open:
                argv.append("--insecure-open")  # or the new process
                # would refuse to start without the bind token
            os.execve(sys.executable, argv, env)


class MasterAgent:
    """Server-side agent (reference master protocol manager + status
    center): tracks the device table from presence/last-will messages and
    the per-request job status FSM from the status topic; dispatches
    start/stop commands."""

    def __init__(self, broker_host: str, broker_port: int, registry=None):
        from ..api import _runs_root
        self.center = MessageCenter(
            broker_host, broker_port,
            record_dir=os.path.join(_runs_root(), "agent_master"))
        self.devices: Dict[int, Dict[str, Any]] = {}
        self.jobs: Dict[str, Dict[str, Any]] = {}
        # optional AccountRegistry: with one wired, presence from devices
        # that are not enrolled (or present a bad/revoked token) is
        # DROPPED — dispatch can only target bound devices (reference
        # account_manager device binding)
        self.registry = registry
        # single-use presence nonces: a harvested proof (incl. the LWT,
        # whose freshness is necessarily exempt) must not be replayable —
        # at worst a captured LWT can be spent ONCE early, which the
        # heartbeat heals within one interval
        self._presence_nonces: Dict[str, float] = {}
        self._cv = threading.Condition()

    def start(self) -> None:
        self.center.subscribe(TOPIC_ONLINE, self._on_presence)
        self.center.subscribe(TOPIC_STATUS, self._on_status)
        self.center.start()

    def stop(self) -> None:
        self.center.stop()

    def _spend_nonce(self, key: str) -> bool:
        """Single-use nonce ledger shared by presence and status proofs
        (callers namespace their keys). False = already spent. Pruning:
        age out entries past the freshness window; under a flood of
        still-fresh nonces, evict oldest-first down to the cap rather
        than growing (and scanning) forever."""
        with self._cv:
            if key in self._presence_nonces:
                return False
            now = time.time()
            self._presence_nonces[key] = now
            if len(self._presence_nonces) > 8192:
                for k, t in list(self._presence_nonces.items()):
                    if now - t > 600:
                        del self._presence_nonces[k]
                while len(self._presence_nonces) > 8192:
                    self._presence_nonces.pop(
                        min(self._presence_nonces,
                            key=self._presence_nonces.get))
            return True

    def _on_presence(self, payload: dict) -> None:
        did = int(payload.get("device_id", -1))
        status = payload.get("status")
        if self.registry is not None:
            # OFFLINE = last-will: its proof was computed at connect time
            # (the broker fires it at crash time), so skip freshness; the
            # nonce ledger below still makes every proof single-use
            ok = self.registry.verify_presence(
                str(did), str(status), payload.get("ts"),
                payload.get("nonce"), payload.get("proof"),
                check_freshness=(status != DEVICE_OFFLINE))
            if not ok:
                logger.warning("master: dropping presence from unbound "
                               "device %s", did)
                return
            if not self._spend_nonce(f"{did}:{payload.get('nonce')}"):
                logger.warning("master: dropping replayed presence "
                               "for device %s", did)
                return
        with self._cv:
            dev = self.devices.setdefault(did, {})
            # MERGE, don't clobber: a heartbeat must not erase the
            # running-jobs bookkeeping _on_status maintains — a device
            # with live jobs stays RUNNING regardless of what the
            # (job-agnostic) presence loop says
            dev["ts"] = time.time()
            if status == DEVICE_OFFLINE or not dev.get("running"):
                dev["status"] = status
            self._cv.notify_all()

    def _on_status(self, payload: dict) -> None:
        did = int(payload.get("device_id", -1))
        if self.registry is not None:
            # status frames must carry a device-credential HMAC (like
            # presence proofs): a broker-authenticated peer without the
            # bind token must not be able to flip a bound device's live
            # job to FAILED/FINISHED, conjure a dispatchable device, or
            # poison the version column. verify_status also rejects
            # unenrolled/revoked devices and stale timestamps.
            ok = self.registry.verify_status(
                str(did), str(payload.get("request_id", "")),
                str(payload.get("status")), payload.get("ts"),
                payload.get("nonce"), payload.get("proof"))
            if not ok:
                logger.warning("master: dropping unauthenticated status "
                               "for device %s", did)
                return
            # single-use, same ledger/pruning as presence nonces (the
            # 'status:' prefix keeps the namespaces apart)
            if not self._spend_nonce(f"status:{did}:{payload.get('nonce')}"):
                logger.warning("master: dropping replayed status for "
                               "device %s", did)
                return
        if (payload.get("status") == "UPGRADED" and self.registry
                and payload.get("version")):
            # record only for upgrades THIS master dispatched to THAT
            # device: the MAC gate above authenticates the sender, but a
            # validly-bound device still must not rewrite its own version
            # column via UPGRADED statuses for jobs never dispatched
            with self._cv:
                job = self.jobs.get(str(payload.get("request_id", "")))
            if (job and job.get("kind") == "upgrade"
                    and int(job.get("device_id", -2)) == did):
                self.registry.record_version(
                    str(did), str(payload["version"]))
        with self._cv:
            rid = str(payload.get("request_id", ""))
            status = payload.get("status")
            job = self.jobs.setdefault(rid, {"history": []})
            job["history"].append(payload)
            job["status"] = status
            job["device_id"] = payload.get("device_id")
            if "run_id" in payload:
                job["run_id"] = payload["run_id"]
            dev = self.devices.setdefault(did, {})
            # a device is RUNNING while ANY of its jobs runs — one job's
            # PROVISIONING/terminal status must not mark a busy device idle
            running = dev.setdefault("running", set())
            if status in (JOB_RUNNING, JOB_PROVISIONING):
                running.add(rid)
            else:
                running.discard(rid)
            dev["status"] = DEVICE_RUNNING if running else DEVICE_IDLE
            dev["ts"] = time.time()
            self._cv.notify_all()

    # --- commands ----------------------------------------------------------
    def dispatch(self, device_id: int, job_yaml: str,
                 request_id: Optional[str] = None) -> str:
        """Send a start-train command; returns the request id used to track
        the job on the status FSM. The yaml CONTENT is shipped (not the
        path) so the agent can live on another machine; its workspace
        defaults to the agent-side job dir."""
        request_id = request_id or uuid.uuid4().hex
        path = os.path.abspath(os.path.expanduser(job_yaml))
        try:
            with open(path) as f:
                content = f.read()
        except OSError as e:
            # still dispatch: the slave reports the failure through the
            # status FSM so the caller sees FAILED rather than an exception
            content = None
            logger.warning("dispatch: cannot read %s (%s); sending path",
                           path, e)
        msg = {"request_id": request_id}
        if content is not None:
            msg["job_yaml_content"] = content
            msg["job_yaml_name"] = os.path.basename(path)
        else:
            msg["job_yaml"] = path
        self.center.publish(_topic_start(device_id), sign_job(msg))
        with self._cv:
            self.jobs.setdefault(request_id, {"history": []})[
                "device_id"] = device_id
        return request_id

    def dispatch_upgrade(self, device_id: int, package_zip: str,
                         version: str,
                         request_id: Optional[str] = None) -> str:
        """OTA: ship a signed upgrade package (zip bytes + sha256 +
        version) to a device agent. Returns the request id tracking the
        UPGRADED/FAILED status."""
        import base64
        import hashlib
        request_id = request_id or uuid.uuid4().hex
        with open(package_zip, "rb") as f:
            blob = f.read()
        msg = {"request_id": request_id, "version": str(version),
               "sha256": hashlib.sha256(blob).hexdigest(),
               "package_b64": base64.b64encode(blob).decode()}
        self.center.publish(_topic_upgrade(device_id), sign_job(msg))
        with self._cv:
            job = self.jobs.setdefault(request_id, {"history": []})
            job["device_id"] = device_id
            job["kind"] = "upgrade"
        return request_id

    def stop_job(self, request_id: str) -> None:
        with self._cv:
            device_id = self.jobs.get(request_id, {}).get("device_id")
        if device_id is None:
            raise KeyError(f"unknown request {request_id!r}")
        self.center.publish(_topic_stop(int(device_id)),
                            sign_job({"request_id": request_id}))

    # --- queries -----------------------------------------------------------
    def job_status(self, request_id: str) -> Optional[str]:
        with self._cv:
            return self.jobs.get(request_id, {}).get("status")

    def wait_for_status(self, request_id: str, statuses,
                        timeout_s: float = 60.0) -> Optional[str]:
        if isinstance(statuses, str):
            statuses = {statuses}
        deadline = time.time() + timeout_s
        with self._cv:
            while True:
                cur = self.jobs.get(request_id, {}).get("status")
                if cur in statuses:
                    return cur
                remaining = deadline - time.time()
                if remaining <= 0:
                    return cur
                self._cv.wait(timeout=min(remaining, 1.0))

    def wait_for_device(self, device_id: int, status: str,
                        timeout_s: float = 60.0) -> Optional[str]:
        deadline = time.time() + timeout_s
        with self._cv:
            while True:
                cur = self.devices.get(int(device_id), {}).get("status")
                if cur == status:
                    return cur
                remaining = deadline - time.time()
                if remaining <= 0:
                    return cur
                self._cv.wait(timeout=min(remaining, 1.0))


def launch_job_remote(job_yaml: str, device_id: int, master: MasterAgent,
                      timeout_s: float = 120.0,
                      redispatch_s: float = 3.0) -> Dict[str, Any]:
    """``fedml launch --remote`` analogue: dispatch through the master
    agent's broker and wait for a terminal status. The broker keeps no
    retained messages, so until the FIRST status arrives the command is
    re-published every ``redispatch_s`` (agents dedup by request id) —
    an agent that subscribed a beat after the dispatch still gets it."""
    rid = master.dispatch(device_id, job_yaml)
    deadline = time.time() + timeout_s
    while (master.job_status(rid) is None
           and time.time() < deadline):
        master.wait_for_status(rid, {JOB_PROVISIONING, JOB_RUNNING,
                                     JOB_FINISHED, JOB_FAILED, JOB_KILLED},
                               timeout_s=redispatch_s)
        if master.job_status(rid) is None:
            master.dispatch(device_id, job_yaml, request_id=rid)
    final = master.wait_for_status(
        rid, {JOB_FINISHED, JOB_FAILED, JOB_KILLED},
        timeout_s=max(deadline - time.time(), 0.0))
    with master._cv:
        info = dict(master.jobs.get(rid, {}))
    info["request_id"] = rid
    info["status"] = final
    return info
