"""Device-binding account registry for the agent plane.

Parity target: reference
``computing/scheduler/scheduler_core/account_manager.py:1-469`` — a
device binds to an account with an API key and receives a persistent
device identity + credential that later commands are checked against
(the reference stores this against the MLOps platform; local-first here
is a sqlite registry under the runs root).

Model: an account is the hash of its API key (never stored raw); a
device registration mints a random device token returned ONCE. The
registry keeps a salted hash of the token (for direct ``verify_device``
checks) plus a DERIVED mac key — presence announcements never carry the
token itself, only an HMAC proof over (device_id, status, ts, nonce)
computed from the derived key, so a broker peer watching the presence
topic cannot harvest a credential it can replay as its own enrollment
(proofs are freshness-bound; see :meth:`verify_presence`). A master
wired to the registry drops presence from unbound devices, so job
dispatch only targets devices an operator actually enrolled —
per-device revocation included, which the deployment-wide broker/bind
secrets cannot give.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import secrets
import sqlite3
import time
from typing import Any, Dict, List, Optional, Tuple


def _hash(value: str, salt: str = "") -> str:
    return hashlib.sha256((salt + value).encode()).hexdigest()


def mac_key_for(token: str) -> bytes:
    """Presence-proof key derived from the device token. The registry
    stores THIS (a server-side verifier, like any symmetric-key store),
    never the token; the agent derives it locally from its token."""
    return hashlib.sha256(b"fedml-tpu/presence-mac:"
                          + token.encode()).digest()


def _presence_body(device_id: str, status: str, ts, nonce) -> bytes:
    """ONE definition of the signed presence body — prover and verifier
    must never drift apart on field order/format."""
    return f"{device_id}|{status}|{ts}|{nonce}".encode()


def presence_proof(token: str, device_id: str, status: str, ts: float,
                   nonce: str) -> str:
    import hmac
    return hmac.new(mac_key_for(token),
                    _presence_body(device_id, status, ts, nonce),
                    hashlib.sha256).hexdigest()


def _status_body(device_id: str, request_id: str, status: str, ts,
                 nonce) -> bytes:
    """ONE definition of the signed job-status body (prover = slave,
    verifier = registry-wired master). The leading 'status:' tag domain-
    separates it from presence proofs — the two share the mac key, and a
    harvested presence proof must never verify as a job status (or vice
    versa)."""
    return f"status:{device_id}|{request_id}|{status}|{ts}|{nonce}".encode()


def status_proof(token: str, device_id: str, request_id: str, status: str,
                 ts: float, nonce: str) -> str:
    """HMAC proof a slave attaches to job-status frames: without it, any
    broker-authenticated peer could flip a bound device's live job to
    FAILED/FINISHED on the master (status poisoning)."""
    import hmac
    return hmac.new(mac_key_for(token),
                    _status_body(device_id, request_id, status, ts, nonce),
                    hashlib.sha256).hexdigest()


PRESENCE_TTL_S = 300.0


class AccountRegistry:
    """Sqlite account/device store (reference ``account_manager.py``)."""

    def __init__(self, path: Optional[str] = None):
        if path is None:
            from ..api import _runs_root
            path = os.path.join(_runs_root(), "accounts.db")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.path = path
        with self._conn() as c:
            c.execute("""CREATE TABLE IF NOT EXISTS accounts (
                account_id TEXT PRIMARY KEY,
                api_key_hash TEXT NOT NULL,
                api_key_salt TEXT NOT NULL DEFAULT '',
                created REAL NOT NULL)""")
            c.execute("""CREATE TABLE IF NOT EXISTS devices (
                device_id TEXT PRIMARY KEY,
                account_id TEXT NOT NULL,
                token_salt TEXT NOT NULL,
                token_hash TEXT NOT NULL,
                mac_key TEXT NOT NULL,
                registered REAL NOT NULL,
                last_seen REAL,
                revoked INTEGER DEFAULT 0,
                version TEXT DEFAULT '')""")
            # migration: a pre-mac_key devices table gains the column
            # with an empty default — those devices fail presence proofs
            # (graceful: re-enroll) instead of crashing every callback
            cols = [r[1] for r in
                    c.execute("PRAGMA table_info(devices)").fetchall()]
            if "mac_key" not in cols:
                c.execute("ALTER TABLE devices ADD COLUMN mac_key TEXT "
                          "NOT NULL DEFAULT ''")
            # migration: pre-salt accounts keep salt '' — _hash(key, '')
            # equals the legacy unsalted digest, so old rows still match
            acc_cols = [r[1] for r in
                        c.execute("PRAGMA table_info(accounts)").fetchall()]
            if "api_key_salt" not in acc_cols:
                c.execute("ALTER TABLE accounts ADD COLUMN api_key_salt "
                          "TEXT NOT NULL DEFAULT ''")

    @contextlib.contextmanager
    def _conn(self):
        conn = sqlite3.connect(self.path, timeout=10.0)
        conn.isolation_level = None
        try:
            yield conn
        finally:
            conn.close()

    # --- accounts -----------------------------------------------------------
    def login(self, api_key: str) -> str:
        """Idempotent account creation from an API key; returns the
        account id (reference ``login_with_api_key``). The key persists
        only as a SALTED hash — parity with the device-token hashing in
        the same table; an unsalted digest would let one rainbow table
        hit every deployment — and the account id derives from the
        salted digest, so no column leaks a precomputable digest.
        Idempotency without an unsalted lookup key means scanning the
        (operator-scale, a handful of rows) account list and re-hashing
        against each row's salt; legacy salt-less rows compare with
        ``_hash(key, '')`` which equals their original unsalted digest."""
        import hmac
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")  # serialize concurrent first-logins
            try:
                rows = c.execute("SELECT account_id, api_key_hash, "
                                 "api_key_salt FROM accounts").fetchall()
                for account_id, key_hash, salt in rows:
                    if hmac.compare_digest(_hash(api_key, salt or ""),
                                           key_hash):
                        c.execute("COMMIT")
                        return account_id
                salt = secrets.token_hex(8)
                digest = _hash(api_key, salt)
                account_id = digest[:16]
                c.execute("INSERT INTO accounts (account_id, api_key_hash,"
                          " api_key_salt, created) VALUES (?, ?, ?, ?)",
                          (account_id, digest, salt, time.time()))
                c.execute("COMMIT")
            except sqlite3.Error:
                c.execute("ROLLBACK")
                raise
        return account_id

    # --- devices ------------------------------------------------------------
    def register_device(self, api_key: str,
                        device_id: Optional[str] = None
                        ) -> Tuple[str, str]:
        """Bind a device to the API key's account. Returns
        ``(device_id, device_token)`` — the token is shown exactly once;
        only its salted hash persists. An existing (or revoked) device id
        cannot be silently re-bound: re-binding would let anyone with any
        API key hijack the identity or undo a revocation — explicitly
        ``revoke`` + choose a NEW id instead.

        Generated ids are numeric (the agent plane addresses devices by
        integer id in its topics)."""
        account_id = self.login(api_key)
        device_id = device_id or str(secrets.randbelow(10 ** 9) + 10 ** 8)
        token = secrets.token_hex(24)
        salt = secrets.token_hex(8)
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            try:
                row = c.execute("SELECT 1 FROM devices WHERE device_id=?",
                                (device_id,)).fetchone()
                if row is not None:
                    c.execute("ROLLBACK")
                    raise ValueError(
                        f"device {device_id!r} is already registered "
                        "(revoked identities stay dead; enroll a new id)")
                # named columns: a migrated (pre-mac_key) table has the
                # new column LAST, so positional inserts would scramble
                c.execute("INSERT INTO devices (device_id, account_id, "
                          "token_salt, token_hash, mac_key, registered, "
                          "last_seen, revoked, version) "
                          "VALUES (?, ?, ?, ?, ?, ?, NULL, 0, '')",
                          (device_id, account_id, salt,
                           _hash(token, salt),
                           mac_key_for(token).hex(), time.time()))
                c.execute("COMMIT")
            except sqlite3.Error:
                c.execute("ROLLBACK")
                raise
        return device_id, token

    def verify_device(self, device_id: str, token: str) -> bool:
        """Constant-time credential check; touches last_seen on success."""
        import hmac
        with self._conn() as c:
            row = c.execute(
                "SELECT token_salt, token_hash, revoked FROM devices "
                "WHERE device_id=?", (str(device_id),)).fetchone()
            if row is None or int(row[2]):
                return False
            ok = hmac.compare_digest(_hash(str(token), row[0]), row[1])
            if ok:
                c.execute("UPDATE devices SET last_seen=? "
                          "WHERE device_id=?", (time.time(),
                                                str(device_id)))
            return ok

    def verify_presence(self, device_id: str, status: str, ts, nonce,
                        proof, check_freshness: bool = True) -> bool:
        """Verify a presence HMAC proof (the token itself never rides the
        topic). ``check_freshness=False`` is for LAST-WILL payloads: the
        broker fires them at crash time with the proof computed at
        connect time, so their ts is legitimately stale — the only thing
        a replayed OFFLINE can do is re-mark a dead device dead."""
        import hmac
        try:
            ts_f = float(ts)
        except (TypeError, ValueError):
            return False
        if check_freshness and abs(time.time() - ts_f) > PRESENCE_TTL_S:
            return False
        with self._conn() as c:
            row = c.execute(
                "SELECT mac_key, revoked FROM devices WHERE device_id=?",
                (str(device_id),)).fetchone()
            if row is None or int(row[1]) or not row[0]:
                return False  # unknown, revoked, or pre-migration row
            want = hmac.new(bytes.fromhex(row[0]),
                            _presence_body(str(device_id), str(status),
                                           ts, nonce),
                            hashlib.sha256).hexdigest()
            ok = hmac.compare_digest(str(proof), want)
            if ok:
                c.execute("UPDATE devices SET last_seen=? "
                          "WHERE device_id=?", (time.time(),
                                                str(device_id)))
            return ok

    def verify_status(self, device_id: str, request_id: str, status: str,
                      ts, nonce, proof) -> bool:
        """Verify a job-status HMAC proof (freshness-bound like live
        presence; statuses are minted at event time, so a stale ts means
        replay or broken clocks either way)."""
        import hmac
        try:
            ts_f = float(ts)
        except (TypeError, ValueError):
            return False
        if abs(time.time() - ts_f) > PRESENCE_TTL_S:
            return False
        with self._conn() as c:
            row = c.execute(
                "SELECT mac_key, revoked FROM devices WHERE device_id=?",
                (str(device_id),)).fetchone()
            if row is None or int(row[1]) or not row[0]:
                return False  # unknown, revoked, or pre-migration row
            want = hmac.new(bytes.fromhex(row[0]),
                            _status_body(str(device_id), str(request_id),
                                         str(status), ts, nonce),
                            hashlib.sha256).hexdigest()
            # deliberately NO last_seen touch here: the master's replay
            # (nonce) check runs AFTER this verification, so a replayed
            # frame would otherwise keep refreshing liveness for a dead
            # device — presence proofs remain the only liveness signal
            return hmac.compare_digest(str(proof), want)

    def revoke_device(self, device_id: str) -> bool:
        with self._conn() as c:
            cur = c.execute("UPDATE devices SET revoked=1 "
                            "WHERE device_id=?", (str(device_id),))
            return cur.rowcount > 0

    def record_version(self, device_id: str, version: str) -> None:
        with self._conn() as c:
            c.execute("UPDATE devices SET version=? WHERE device_id=?",
                      (str(version), str(device_id)))

    def devices(self) -> List[Dict[str, Any]]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT device_id, account_id, registered, last_seen, "
                "revoked, version FROM devices").fetchall()
        return [{"device_id": d, "account_id": a, "registered": r,
                 "last_seen": ls, "revoked": bool(rv), "version": v}
                for d, a, r, ls, rv, v in rows]
