"""Workflow: a DAG of jobs with dependencies, executed locally.

Parity target: reference ``workflow/workflow.py:42-111`` + ``jobs.py`` (a
``Workflow`` of ``Job`` nodes with dependency edges; each job is a platform
launch). Local-first redesign: a job is either a python callable or a job
yaml launched through :mod:`fedml_tpu.api`; ``run()`` executes in
dependency (topological) order, independent ready jobs run concurrently on
a thread pool, failures cancel dependents, and each job's output is made
available to its dependents via ``workflow.outputs``.
"""

from __future__ import annotations

import enum
import logging
from abc import ABC, abstractmethod
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


class JobStatus(enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"


class Job(ABC):
    """One node of the workflow DAG (reference ``jobs.py`` Job ABC)."""

    def __init__(self, name: str):
        self.name = name
        self.status = JobStatus.PENDING
        self.output: Any = None
        self.error: Optional[BaseException] = None
        self.dependencies: List["Job"] = []

    @abstractmethod
    def run(self, inputs: Dict[str, Any]) -> Any:
        """Execute; ``inputs`` maps dependency name → its output."""

    def kill(self) -> None:
        """Best-effort cancellation hook (launch jobs stop their run)."""


class CallableJob(Job):
    """Wrap a python callable. The callable may accept zero args or one
    (the inputs dict)."""

    def __init__(self, name: str, fn: Callable[..., Any]):
        super().__init__(name)
        self.fn = fn

    def run(self, inputs: Dict[str, Any]) -> Any:
        try:
            return self.fn(inputs)
        except TypeError:
            # zero-arg callables are common; detect by signature, not by
            # swallowing errors from the body (advisor finding on flow)
            import inspect
            if len(inspect.signature(self.fn).parameters) == 0:
                return self.fn()
            raise


class LaunchJob(Job):
    """Launch a job yaml via the local platform and wait for completion."""

    def __init__(self, name: str, yaml_file: str,
                 poll_interval_s: float = 0.5):
        super().__init__(name)
        self.yaml_file = yaml_file
        self.poll_interval_s = poll_interval_s
        self.run_id: Optional[str] = None

    def run(self, inputs: Dict[str, Any]) -> Any:
        import time

        from .. import api
        res = api.launch_job(self.yaml_file)
        if res.result_code != 0:
            raise RuntimeError(f"launch failed: {res.result_message}")
        self.run_id = res.run_id
        while True:
            status = api.run_status(self.run_id)
            if status == api.STATUS_FINISHED:
                return {"run_id": self.run_id,
                        "logs": api.run_logs(self.run_id, tail=20)}
            if status in (api.STATUS_FAILED, api.STATUS_KILLED, None):
                raise RuntimeError(
                    f"job {self.name} ({self.run_id}) ended {status}; last "
                    f"log lines: "
                    f"{api.run_logs(self.run_id, tail=5) if self.run_id else []}")
            time.sleep(self.poll_interval_s)

    def kill(self) -> None:
        from .. import api
        if self.run_id:
            api.run_stop(self.run_id)


class Workflow:
    """DAG of jobs (reference ``workflow.py:42``: ``add_job(job,
    dependencies)``, ``run()``)."""

    def __init__(self, name: str = "workflow", max_workers: int = 4):
        self.name = name
        self.jobs: Dict[str, Job] = {}
        self.max_workers = max_workers
        self.outputs: Dict[str, Any] = {}

    def add_job(self, job: Job,
                dependencies: Optional[List[Job]] = None) -> Job:
        if job.name in self.jobs:
            raise ValueError(f"job {job.name!r} already in workflow")
        for dep in dependencies or []:
            if dep.name not in self.jobs:
                raise ValueError(
                    f"dependency {dep.name!r} must be added before "
                    f"{job.name!r}")
        job.dependencies = list(dependencies or [])
        self.jobs[job.name] = job
        return job

    def _check_acyclic(self) -> None:
        seen: Dict[str, int] = {}  # 0=visiting 1=done

        def visit(j: Job) -> None:
            state = seen.get(j.name)
            if state == 0:
                raise ValueError(f"cyclic dependency through {j.name!r}")
            if state == 1:
                return
            seen[j.name] = 0
            for d in j.dependencies:
                visit(d)
            seen[j.name] = 1

        for j in self.jobs.values():
            visit(j)

    def run(self) -> Dict[str, Any]:
        """Execute the DAG; returns ``{job_name: output}``. Raises after all
        runnable jobs finish if any job failed."""
        self._check_acyclic()
        pending = dict(self.jobs)
        futures: Dict[Future, Job] = {}

        def ready(j: Job) -> bool:
            return all(d.status == JobStatus.FINISHED
                       for d in j.dependencies)

        def blocked_forever(j: Job) -> bool:
            return any(d.status in (JobStatus.FAILED, JobStatus.CANCELLED)
                       for d in j.dependencies)

        def launch(j: Job, pool: ThreadPoolExecutor) -> None:
            j.status = JobStatus.RUNNING
            inputs = {d.name: d.output for d in j.dependencies}

            def body() -> Any:
                logger.info("workflow %s: job %s starting", self.name, j.name)
                return j.run(inputs)

            futures[pool.submit(body)] = j

        with ThreadPoolExecutor(max_workers=self.max_workers) as pool:
            while pending or futures:
                for name in [n for n, j in pending.items() if ready(j)]:
                    launch(pending.pop(name), pool)
                for name in [n for n, j in pending.items()
                             if blocked_forever(j)]:
                    pending[name].status = JobStatus.CANCELLED
                    del pending[name]
                if not futures:
                    if pending:  # nothing running, nothing ready: stuck
                        for j in pending.values():
                            j.status = JobStatus.CANCELLED
                        pending.clear()
                    continue
                done, _ = wait(list(futures), return_when=FIRST_COMPLETED)
                for fut in done:
                    j = futures.pop(fut)
                    try:
                        j.output = fut.result()
                        j.status = JobStatus.FINISHED
                        self.outputs[j.name] = j.output
                        logger.info("workflow %s: job %s finished",
                                    self.name, j.name)
                    except BaseException as e:  # noqa: BLE001
                        j.error = e
                        j.status = JobStatus.FAILED
                        logger.error("workflow %s: job %s FAILED: %s",
                                     self.name, j.name, e)
        failed = [j for j in self.jobs.values()
                  if j.status == JobStatus.FAILED]
        if failed:
            raise RuntimeError(
                f"workflow {self.name}: {len(failed)} job(s) failed: "
                + ", ".join(f"{j.name} ({j.error})" for j in failed))
        return dict(self.outputs)

    def status(self) -> Dict[str, str]:
        return {n: j.status.value for n, j in self.jobs.items()}
