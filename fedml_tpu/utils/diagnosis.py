"""Client diagnosis: loopback echo checks of the comm backends.

Parity target: reference ``computing/scheduler/slave/client_diagnosis.py:24``
(connectivity probes to MQTT/S3/platform + client↔server echo test). This
framework is local-first, so diagnosis probes what actually carries traffic
here: the gRPC and TCP WAN transports (send → receive round-trip on
loopback) and the JAX device runtime.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple


def _echo_backend(make_manager) -> Tuple[bool, str]:
    import threading

    from ..core.distributed.communication.base_com_manager import Observer
    from ..core.distributed.communication.message import Message

    class _Sink(Observer):
        def __init__(self):
            self.got = threading.Event()

        def receive_message(self, msg_type, msg):
            self.got.set()

    a = b = None
    try:
        a = make_manager(0)
        b = make_manager(1)
        sink = _Sink()
        b.add_observer(sink)
        threading.Thread(target=b.handle_receive_message,
                         daemon=True).start()
        msg = Message("diag_echo", 0, 1)
        msg.add_params("payload", [1, 2, 3])
        t0 = time.perf_counter()
        a.send_message(msg)
        if not sink.got.wait(timeout=5.0):
            return False, "no message within 5s"
        ms = (time.perf_counter() - t0) * 1e3
        return True, f"echo round-trip {ms:.1f} ms"
    except Exception as e:  # noqa: BLE001 — diagnosis must report, not die
        return False, str(e)
    finally:
        for m in (a, b):
            try:
                if m is not None:
                    m.stop_receive_message()
            except Exception:
                pass


def run_diagnosis() -> Dict[str, Tuple[bool, str]]:
    report: Dict[str, Tuple[bool, str]] = {}

    # device runtime
    try:
        import jax
        import jax.numpy as jnp
        val = float(jax.jit(lambda x: (x * x).sum())(jnp.arange(8.0)))
        devs = jax.devices()
        report["device"] = (val == 140.0,
                            f"{len(devs)} x {devs[0].device_kind}")
    except Exception as e:  # noqa: BLE001
        report["device"] = (False, str(e))

    from ..core.distributed.communication.grpc import GRPCCommManager
    from ..core.distributed.communication.tcp import TCPCommManager

    report["grpc"] = _echo_backend(
        lambda rank: GRPCCommManager(rank, base_port=39790))
    report["tcp"] = _echo_backend(
        lambda rank: TCPCommManager(rank, base_port=39890))
    return report


if __name__ == "__main__":
    for name, (ok, detail) in run_diagnosis().items():
        print(f"{name:<10} {'OK' if ok else 'FAIL'}  {detail}")
