"""Gradient/update compression (reference ``utils/compression.py``:
top-k and random-k sparsification with index bookkeeping).

TPU-native design: compressors are jit-able pure functions on flat vectors
(dense in, (values, indices) out), so they can run inside the round program
before a cross-DCN hop. ``compress_tree``/``decompress_tree`` lift them to
pytrees for the WAN managers, whose payloads shrink by the sparsity factor.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


def topk_compress(vec: jnp.ndarray, k: int
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Keep the k largest-magnitude entries: returns (values[k], idx[k])."""
    k = max(min(int(k), vec.shape[0]), 1)
    _, idx = jax.lax.top_k(jnp.abs(vec), k)
    return vec[idx], idx.astype(jnp.int32)


def randk_compress(vec: jnp.ndarray, k: int, rng: jax.Array,
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Keep k uniformly-random entries, unbiased-rescaled by d/k so the
    expected decompressed vector equals the input."""
    d = vec.shape[0]
    k = max(min(int(k), d), 1)
    idx = jax.random.choice(rng, d, shape=(k,), replace=False).astype(
        jnp.int32)
    return vec[idx] * (d / k), idx


def decompress(values: jnp.ndarray, idx: jnp.ndarray, d: int) -> jnp.ndarray:
    return jnp.zeros(d, values.dtype).at[idx].set(values)


def compress_tree(tree: PyTree, ratio: float, method: str = "topk",
                  rng: jax.Array = None) -> Dict[str, Any]:
    """Flatten a pytree and sparsify to ``ratio`` of its entries; the
    result is a wire-friendly dict (values, indices, length)."""
    from ..core.collectives import tree_flatten_to_vector
    vec = tree_flatten_to_vector(tree)
    d = vec.shape[0]
    k = max(int(d * float(ratio)), 1)
    if method == "topk":
        vals, idx = topk_compress(vec, k)
    elif method in ("randk", "random_k"):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        vals, idx = randk_compress(vec, k, rng)
    else:
        raise ValueError(f"unknown compression method {method!r} "
                         f"(topk, randk)")
    return {"values": vals, "indices": idx, "length": d}


def decompress_tree(blob: Dict[str, Any], template: PyTree) -> PyTree:
    from ..core.collectives import vector_to_tree_like
    vec = decompress(jnp.asarray(blob["values"]),
                     jnp.asarray(blob["indices"]), int(blob["length"]))
    return vector_to_tree_like(vec, template)


# --- wire-efficient cross-silo updates -------------------------------------
#
# QSGD-style stochastic int8 quantization (Alistarh et al., 2017) composed
# with top-k/rand-k sparsification and per-sender error feedback (Lin et
# al., 2018 Deep Gradient Compression; Karimireddy et al., 2019 EF-SGD).
# The compress cores are jit-able pure functions on flat f32 vectors; the
# WAN managers carry the residual across rounds so biased compressors
# still converge.

#: int8 carries sign * level with level in [0, 127]
QSGD_MAX_LEVELS = 127

#: marker key identifying a compressed-update payload on the wire
WIRE_FLAG = "__cc__"

from ..constants import (COMM_BROADCAST_BF16, COMM_BROADCAST_COMPRESS,
                         COMM_BROADCAST_FULL, COMM_COMPRESSION_METHODS)


@dataclass(frozen=True)
class CommCompressionSpec:
    """Parsed ``comm_compression`` config (see ``arguments.py`` knobs).
    ``method=None`` is a broadcast-only spec (bf16 downlink, dense f32
    uplink)."""
    method: Optional[str]       # one of COMM_COMPRESSION_METHODS, or None
    ratio: float = 0.1          # sparsifier keep-ratio (ignored by 'qsgd')
    levels: int = QSGD_MAX_LEVELS   # quantization levels (<= 127 for int8)
    broadcast: str = "full"     # server->client sync: full | bf16 | compress

    def __post_init__(self):
        if self.method is not None \
                and self.method not in COMM_COMPRESSION_METHODS:
            raise ValueError(
                f"unknown comm_compression method {self.method!r} "
                f"(one of {COMM_COMPRESSION_METHODS})")
        if not 0.0 < float(self.ratio) <= 1.0:
            raise ValueError(f"comm_compression_ratio must be in (0, 1], "
                             f"got {self.ratio}")
        if not 1 <= int(self.levels) <= QSGD_MAX_LEVELS:
            raise ValueError(f"comm_quantize_levels must be in [1, "
                             f"{QSGD_MAX_LEVELS}], got {self.levels}")
        if self.broadcast not in (COMM_BROADCAST_FULL, COMM_BROADCAST_BF16,
                                  COMM_BROADCAST_COMPRESS):
            raise ValueError(f"comm_compression_broadcast must be full|"
                             f"bf16|compress, got {self.broadcast!r}")
        if self.method is None and self.broadcast == COMM_BROADCAST_COMPRESS:
            raise ValueError(
                "comm_compression_broadcast=compress needs a compressor: "
                f"set comm_compression (one of {COMM_COMPRESSION_METHODS})")

    @property
    def quantized(self) -> bool:
        return bool(self.method) and self.method.endswith("qsgd")


def spec_from_args(args) -> Optional[CommCompressionSpec]:
    """Build the spec from flat config; None = compression off (the
    default — wire payloads stay byte-identical to the uncompressed
    path)."""
    method = getattr(args, "comm_compression", None)
    if not method or str(method).lower() in ("none", "off", "false", "0"):
        method = None
    # None-checks, not `or`: an explicit 0 must reach the spec validation
    # and be rejected there, not silently become the default
    ratio = getattr(args, "comm_compression_ratio", None)
    levels = getattr(args, "comm_quantize_levels", None)
    broadcast = getattr(args, "comm_compression_broadcast", None)
    broadcast = "full" if broadcast is None else str(broadcast).lower()
    if method is None and broadcast == COMM_BROADCAST_FULL:
        return None
    # a non-full broadcast alone still yields a spec (bf16-only downlink
    # must not be silently ignored; compress-only is rejected in __post_init__)
    return CommCompressionSpec(
        method=None if method is None else str(method).lower(),
        ratio=0.1 if ratio is None else float(ratio),
        levels=QSGD_MAX_LEVELS if levels is None else int(levels),
        broadcast=broadcast)


def _stochastic_round(x: jnp.ndarray, rng: jax.Array) -> jnp.ndarray:
    """Unbiased randomized rounding: E[floor(x + U[0,1))] = x."""
    return jnp.floor(x + jax.random.uniform(rng, x.shape, x.dtype))


def qsgd_quantize(vec: jnp.ndarray, levels: int, rng: jax.Array
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stochastic uniform quantization to int8 sign*level: returns
    (q[int8], scale[f32]) with E[dequantize(q, scale)] = vec."""
    levels = int(levels)
    vec = vec.astype(jnp.float32)
    scale = jnp.max(jnp.abs(vec)) if vec.shape[0] else jnp.float32(0)
    safe = jnp.where(scale > 0, scale, 1.0)
    mag = _stochastic_round(jnp.abs(vec) / safe * levels, rng)
    q = jnp.sign(vec) * jnp.clip(mag, 0, levels)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def qsgd_dequantize(q: jnp.ndarray, scale, levels: int) -> jnp.ndarray:
    return q.astype(jnp.float32) * (jnp.float32(scale) / int(levels))


@functools.lru_cache(maxsize=None)
def _ef_compress_core(method: str, d: int, k: int, levels: int):
    """Jitted (compensate -> sparsify -> quantize -> residual) core for a
    given static shape/config. Returns (values, indices, scale, residual)
    with indices/scale possibly unused depending on the method."""

    def core(vec, residual, rng):
        comp = vec.astype(jnp.float32) + residual
        srng, qrng = jax.random.split(rng)
        if method.startswith("topk"):
            vals, idx = topk_compress(comp, k)
        elif method.startswith("randk"):
            # contractive rand-k (no d/k rescale): error feedback re-injects
            # the dropped mass next round — the unbiased rescale of
            # randk_compress would make the residual grow without bound here
            idx = jax.random.choice(srng, d, shape=(k,),
                                    replace=False).astype(jnp.int32)
            vals = comp[idx]
        else:  # pure qsgd: dense quantization
            vals, idx = comp, jnp.arange(d, dtype=jnp.int32)
        if method.endswith("qsgd"):
            q, scale = qsgd_quantize(vals, levels, qrng)
            deq = qsgd_dequantize(q, scale, levels)
            out_vals: Any = q
        else:
            deq = vals
            scale = jnp.float32(0)
            out_vals = vals
        restored = jnp.zeros(d, jnp.float32).at[idx].set(deq)
        return out_vals, idx, scale, comp - restored

    return jax.jit(core)


def ef_compress_vec(vec, residual, spec: CommCompressionSpec,
                    rng: jax.Array) -> Tuple[Dict[str, Any], np.ndarray]:
    """Compress a flat f32 update with error feedback.

    ``residual`` is the sender's carry-over from previous rounds (None on
    round 0). Returns ``(wire_blob, new_residual)`` — the blob is a
    msgpack-friendly dict of host numpy arrays; the residual must be fed
    back on the next call so compression error is re-injected instead of
    lost (this is what makes biased sparsifiers converge)."""
    vec = np.asarray(vec, np.float32).ravel()
    d = int(vec.shape[0])
    if residual is None:
        residual = np.zeros(d, np.float32)
    k = max(int(d * float(spec.ratio)), 1) if spec.method != "qsgd" else d
    vals, idx, scale, new_residual = _ef_compress_core(
        spec.method, d, k, int(spec.levels))(vec, np.asarray(residual,
                                                            np.float32), rng)
    blob: Dict[str, Any] = {WIRE_FLAG: 1, "m": spec.method, "d": d,
                            "v": np.asarray(vals)}
    if spec.method != "qsgd":  # dense qsgd needs no index list
        host_idx = np.asarray(idx)
        blob["i"] = host_idx.astype(
            np.uint16 if d <= np.iinfo(np.uint16).max else np.int32)
    if spec.quantized:
        blob["s"] = float(scale)
        blob["L"] = int(spec.levels)
    return blob, np.asarray(new_residual)


def is_compressed_payload(payload: Any) -> bool:
    return isinstance(payload, dict) and bool(payload.get(WIRE_FLAG))


def decompress_vec(blob: Dict[str, Any]) -> np.ndarray:
    """Inverse of :func:`ef_compress_vec` (host-side, numpy only — the
    receiver need not touch the accelerator to reassemble the update)."""
    d = int(blob["d"])
    vals = np.asarray(blob["v"])
    if "s" in blob:  # quantized values: int8 sign*level -> f32
        vals = vals.astype(np.float32) * (float(blob["s"])
                                          / int(blob["L"]))
    else:
        vals = vals.astype(np.float32)
    if "i" not in blob:
        return vals.astype(np.float32, copy=False)
    out = np.zeros(d, np.float32)
    out[np.asarray(blob["i"]).astype(np.int64)] = vals
    return out
