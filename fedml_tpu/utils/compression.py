"""Gradient/update compression (reference ``utils/compression.py``:
top-k and random-k sparsification with index bookkeeping).

TPU-native design: compressors are jit-able pure functions on flat vectors
(dense in, (values, indices) out), so they can run inside the round program
before a cross-DCN hop. ``compress_tree``/``decompress_tree`` lift them to
pytrees for the WAN managers, whose payloads shrink by the sparsity factor.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

PyTree = Any


def topk_compress(vec: jnp.ndarray, k: int
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Keep the k largest-magnitude entries: returns (values[k], idx[k])."""
    k = max(min(int(k), vec.shape[0]), 1)
    _, idx = jax.lax.top_k(jnp.abs(vec), k)
    return vec[idx], idx.astype(jnp.int32)


def randk_compress(vec: jnp.ndarray, k: int, rng: jax.Array,
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Keep k uniformly-random entries, unbiased-rescaled by d/k so the
    expected decompressed vector equals the input."""
    d = vec.shape[0]
    k = max(min(int(k), d), 1)
    idx = jax.random.choice(rng, d, shape=(k,), replace=False).astype(
        jnp.int32)
    return vec[idx] * (d / k), idx


def decompress(values: jnp.ndarray, idx: jnp.ndarray, d: int) -> jnp.ndarray:
    return jnp.zeros(d, values.dtype).at[idx].set(values)


def compress_tree(tree: PyTree, ratio: float, method: str = "topk",
                  rng: jax.Array = None) -> Dict[str, Any]:
    """Flatten a pytree and sparsify to ``ratio`` of its entries; the
    result is a wire-friendly dict (values, indices, length)."""
    from ..core.collectives import tree_flatten_to_vector
    vec = tree_flatten_to_vector(tree)
    d = vec.shape[0]
    k = max(int(d * float(ratio)), 1)
    if method == "topk":
        vals, idx = topk_compress(vec, k)
    elif method in ("randk", "random_k"):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        vals, idx = randk_compress(vec, k, rng)
    else:
        raise ValueError(f"unknown compression method {method!r} "
                         f"(topk, randk)")
    return {"values": vals, "indices": idx, "length": d}


def decompress_tree(blob: Dict[str, Any], template: PyTree) -> PyTree:
    from ..core.collectives import vector_to_tree_like
    vec = decompress(jnp.asarray(blob["values"]),
                     jnp.asarray(blob["indices"]), int(blob["length"]))
    return vector_to_tree_like(vec, template)
