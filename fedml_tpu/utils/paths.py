"""Filesystem path hygiene for peer-supplied paths."""

from __future__ import annotations

import os


def confine_path(path: str, root: str) -> str:
    """Resolve ``path`` and require it to live inside ``root``.

    File paths that arrive in wire messages from peers (cross-device model
    artifacts, object-store keys) must never escape their cache dir — an
    adversarial peer could otherwise point the process at an arbitrary
    local file. Combined with the msgpack artifact codec (no pickle) this
    makes file exchange read-only and confined."""
    real = os.path.realpath(path)
    root_real = os.path.realpath(root)
    if os.path.commonpath([real, root_real]) != root_real:
        raise ValueError(
            f"model file path {path!r} escapes the cache dir {root!r}")
    return real
