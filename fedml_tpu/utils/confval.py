"""Config-value access that treats an explicit 0/0.0/False as meaningful.

The ``getattr(args, k, d) or d`` idiom silently replaces legitimate
zero-valued hyperparameters (slsgd alpha: 0.0, attack_scale: 0.0) with the
default; use :func:`get_arg` instead — only None/missing fall back.
"""

from __future__ import annotations

from typing import Any


def get_arg(args: Any, name: str, default: Any = None) -> Any:
    val = getattr(args, name, None)
    return default if val is None else val


def get_float(args: Any, name: str, default: float) -> float:
    return float(get_arg(args, name, default))


def get_int(args: Any, name: str, default: int) -> int:
    return int(get_arg(args, name, default))
