"""Environment collection (reference
``computing/scheduler/env/collect_env.py:11`` — prints OS/python/framework/
accelerator inventory at init or via ``fedml_tpu env``)."""

from __future__ import annotations

import os
import platform
import sys


def collect_env() -> str:
    lines = []
    lines.append("======== fedml_tpu environment ========")
    import fedml_tpu
    lines.append(f"fedml_tpu version: {fedml_tpu.__version__}")
    lines.append(f"python:            {sys.version.split()[0]}")
    lines.append(f"os:                {platform.platform()}")
    lines.append(f"cpu count:         {os.cpu_count()}")
    try:
        import psutil
        vm = psutil.virtual_memory()
        lines.append(f"memory:            {vm.total / 2**30:.1f} GiB "
                     f"({vm.percent}% used)")
    except ImportError:
        pass
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
                "numpy"):
        try:
            m = __import__(mod)
            lines.append(f"{mod + ':':<19}{getattr(m, '__version__', '?')}")
        except ImportError:
            lines.append(f"{mod + ':':<19}not installed")
    lines.append("-------- accelerators --------")
    try:
        import jax
        devs = jax.devices()
        lines.append(f"jax backend:       {jax.default_backend()}")
        lines.append(f"devices:           {len(devs)}")
        for d in devs[:8]:
            lines.append(f"  - {d.platform}:{d.id} {d.device_kind}")
        if len(devs) > 8:
            lines.append(f"  ... and {len(devs) - 8} more")
    except Exception as e:  # noqa: BLE001 — report, never crash env print
        lines.append(f"jax devices unavailable: {e}")
    return "\n".join(lines)


if __name__ == "__main__":
    print(collect_env())
