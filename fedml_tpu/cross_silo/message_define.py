"""Cross-silo message types (reference
``simulation/mpi/fedavg/message_define.py:1-31`` and
``cross_silo/server/message_define.py``)."""


class MyMessage:
    # server -> client
    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    MSG_TYPE_S2C_FINISH = 7
    # client -> server
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
    MSG_TYPE_C2S_CLIENT_STATUS = 5
    # payload keys
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"
    MSG_ARG_KEY_CLIENT_METRICS = "client_metrics"
    # wire-efficient updates (utils/compression.py): a compressed delta
    # blob replaces MODEL_PARAMS in whichever direction is compressed;
    # WIRE_DTYPE tags a dense payload whose leaves cross at reduced
    # precision (bf16 bit views)
    MSG_ARG_KEY_MODEL_UPDATE = "model_update"
    MSG_ARG_KEY_WIRE_DTYPE = "wire_dtype"
    # adaptive wire pipeline (core/wire): the sync carries the round's
    # keep-ratio when the stats-driven schedule is on, so client uplinks
    # and the server decoder agree per round; absent otherwise (the
    # default wire stays byte-identical)
    MSG_ARG_KEY_CC_RATIO = "cc_ratio"
    # statuses
    MSG_CLIENT_STATUS_ONLINE = "ONLINE"
    MSG_CLIENT_STATUS_IDLE = "IDLE"
