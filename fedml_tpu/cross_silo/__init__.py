"""Cross-silo runtimes: horizontal FedAvg/SecAgg FSMs, split learning,
vertical FL, and serverless gossip — all over the same comm stack."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


def run_inproc_session(args, build_managers: Callable[[], List[Any]],
                       join_timeout_s: float = 60.0) -> Optional[Dict]:
    """Run a whole multi-party session as threads over the in-proc broker:
    the exact distributed FSM of a TCP/gRPC deployment without sockets.
    ``build_managers`` is called AFTER ``args.inproc_broker`` is set and
    returns the managers; the first runs on the calling thread (it owns
    the session result), the rest on daemon threads."""
    import threading

    from ..core.distributed.communication.inproc import InProcBroker
    args.inproc_broker = InProcBroker()
    managers = build_managers()
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in managers[1:]]
    for t in threads:
        t.start()
    managers[0].run()
    for t in threads:
        t.join(timeout=join_timeout_s)
    return getattr(managers[0], "result", None)
