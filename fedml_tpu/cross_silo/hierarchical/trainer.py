"""Hierarchical cross-silo: intra-silo data parallelism (the DDP analogue).

Parity target: reference hierarchical cross-silo — a silo is one *master*
process (rank 0 of the silo, speaks the WAN FSM) plus N-1 *slave* processes
running DDP replicas coordinated over a torch process group
(``cross_silo/client/fedml_client_slave_manager.py:9``,
``process_group_manager.py:8``, ``fedml_trainer_dist_adapter.py``).

TPU-native redesign: DDP IS a sharding. The silo's local-SGD step is jitted
over an *inner mesh* of the silo's devices with a ``data`` axis; batches
are sharded on the batch dimension, parameters are replicated, and XLA
inserts the gradient all-reduce the torch PG did by hand. The slave-manager
machinery (PG broadcast of round/model, replica sync barriers) therefore
collapses into one SPMD program per silo — multi-host silos join the same
program via ``jax.distributed`` (see :mod:`.process_group`).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...constants import AXIS_DATA
from ...core.algframe.local_training import run_local_sgd
from ...core.algframe.types import TrainHyper


class HierarchicalSiloTrainer:
    """SiloTrainer whose local step runs data-parallel over an inner mesh
    of this silo's devices."""

    def __init__(self, args, fed_dataset, bundle, spec, optimizer,
                 devices: Sequence[jax.Device]):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.spec = spec
        self.opt = optimizer
        self.devices = list(devices)
        if not self.devices:
            raise ValueError("hierarchical silo needs >= 1 device")
        self.mesh = Mesh(np.asarray(self.devices), axis_names=(AXIS_DATA,))
        self.repl = NamedSharding(self.mesh, P())
        # batches are [nb, bs, ...]: shard the *sample* axis over the silo's
        # devices — the DDP per-replica micro-batch
        self.batch_shard = NamedSharding(self.mesh, P(None, AXIS_DATA))
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(rng)
        sample = fed_dataset.train.x[0, 0]
        self.params_template = bundle.init(init_rng, sample)

        def impl(params, cdata, rng, hyper):
            inner_opt = self.opt.make_inner_opt(hyper)
            new_params, _, metrics = run_local_sgd(
                self.spec, inner_opt, params, cdata, rng, hyper,
                grad_transform=self.opt.grad_transform,
                ctx={"global_params": params, "server_state": {},
                     "client_state": {}, "hyper": hyper})
            return new_params, metrics

        self._train_jit = jax.jit(impl)

    def _place(self, cdata):
        def shard_leaf(a):
            a = jnp.asarray(a)
            if a.ndim >= 2 and a.shape[1] % len(self.devices) == 0:
                return jax.device_put(a, self.batch_shard)
            return jax.device_put(a, self.repl)

        return jax.tree_util.tree_map(shard_leaf, cdata)

    def train(self, params, client_idx: int, round_idx: int
              ) -> Tuple[dict, float, Dict[str, float]]:
        cdata = jax.tree_util.tree_map(lambda a: a[client_idx],
                                       self.fed.train)
        cdata = self._place(cdata)
        params = jax.device_put(
            jax.tree_util.tree_map(jnp.asarray, params), self.repl)
        hyper = TrainHyper(
            learning_rate=jnp.float32(self.args.learning_rate),
            epochs=int(self.args.epochs),
            round_idx=jnp.int32(round_idx))
        key = jax.random.fold_in(jax.random.fold_in(self.rng, round_idx),
                                 client_idx)
        with self.mesh:
            new_params, metrics = self._train_jit(params, cdata, key, hyper)
        n = float(cdata.num_samples)
        cnt = max(float(metrics["count"]), 1.0)
        return (jax.device_get(new_params), n,
                {"train_loss": float(metrics["loss_sum"]) / cnt,
                 "train_acc": float(metrics["correct"]) / cnt})
