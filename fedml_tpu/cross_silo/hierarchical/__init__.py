"""Hierarchical cross-silo (Octopus hierarchical / Cheetah analogue):
silo-internal data parallelism over an inner ``data``-axis mesh, WAN FSM
unchanged. See :mod:`.trainer` for the DDP-collapse design note and
:mod:`.process_group` for multi-host silos."""

from .process_group import init_silo_process_group  # noqa: F401
from .runner import run_hierarchical_cross_silo_inproc  # noqa: F401
from .trainer import HierarchicalSiloTrainer  # noqa: F401
