"""Multi-host silo bootstrap (reference ``process_group_manager.py:8``).

The reference's hierarchical silo spawns torchrun-style worker processes
and builds a torch ``ProcessGroup`` from RANK/WORLD_SIZE/MASTER_ADDR env
vars (``__init__.py:354-365``). The JAX equivalent is
``jax.distributed.initialize``: every host of a silo runs the SAME program;
after initialization ``jax.devices()`` spans the silo and the jitted
silo step (:mod:`.trainer`) is automatically SPMD across hosts — there is
no slave event loop to write.

Env contract (torchrun-compatible names so reference launch scripts port):
``MASTER_ADDR``/``MASTER_PORT`` → coordinator, ``WORLD_SIZE`` → number of
silo hosts, ``RANK`` → this host's index.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_initialized = False


def init_silo_process_group(coordinator: Optional[str] = None,
                            num_hosts: Optional[int] = None,
                            host_rank: Optional[int] = None) -> bool:
    """Join this host to the silo's JAX distributed runtime. No-op (False)
    when single-host (WORLD_SIZE absent or 1)."""
    global _initialized
    if _initialized:
        return True
    num_hosts = int(num_hosts
                    if num_hosts is not None
                    else os.environ.get("WORLD_SIZE", "1"))
    if num_hosts <= 1:
        return False
    host_rank = int(host_rank
                    if host_rank is not None
                    else os.environ.get("RANK", "0"))
    coordinator = coordinator or (
        os.environ.get("MASTER_ADDR", "127.0.0.1") + ":" +
        os.environ.get("MASTER_PORT", "29500"))
    import jax
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_hosts,
                               process_id=host_rank)
    _initialized = True
    logger.info("silo process group up: host %d/%d via %s", host_rank,
                num_hosts, coordinator)
    return True
