"""Hierarchical cross-silo session builder.

The in-proc session partitions the local device pool into per-silo slices
(silo i gets ``devices[i*k:(i+1)*k]``) — 2 silos x 2 devices each on the
8-device CPU mesh is the reference test topology. On real hardware each
silo is its own host(s)/slice and gets its devices from
``jax.local_devices()`` after :func:`init_silo_process_group`.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence

import jax

from ...core.algframe.client_trainer import make_trainer_spec
from ...optimizers.registry import create_optimizer
from ..client.fedml_client_master_manager import ClientMasterManager
from ..horizontal.runner import build_server
from .trainer import HierarchicalSiloTrainer


def build_hierarchical_client(args, fed, bundle, rank: int,
                              devices: Sequence[jax.Device],
                              backend: str = "INPROC", spec=None):
    spec = spec if spec is not None else make_trainer_spec(fed, bundle)
    optimizer = create_optimizer(args, spec)
    trainer = HierarchicalSiloTrainer(args, fed, bundle, spec, optimizer,
                                      devices)
    size = int(getattr(args, "client_num_per_round", 1)) + 1
    return ClientMasterManager(args, trainer, rank=rank, size=size,
                               backend=backend)


def run_hierarchical_cross_silo_inproc(
        args, fed, bundle, devices_per_silo: Optional[int] = None
) -> Dict[str, Any]:
    """Server + N hierarchical silos (threads), each training data-parallel
    over its own device slice."""
    from ...core.distributed.communication.inproc import InProcBroker
    broker = InProcBroker()
    args.inproc_broker = broker
    n = int(getattr(args, "client_num_per_round", 2))
    devices = jax.devices()
    k = devices_per_silo or max(len(devices) // n, 1)
    server = build_server(args, fed, bundle, backend="INPROC")
    clients = []
    for r in range(1, n + 1):
        slice_ = devices[(r - 1) * k: r * k] or devices[:1]
        clients.append(build_hierarchical_client(
            args, fed, bundle, rank=r, devices=slice_, backend="INPROC"))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30.0)
    return server.result


class HierarchicalCrossSiloRunner:
    """Single-role entry: server side is the plain horizontal server; the
    client side is one hierarchical silo master that joins the silo's
    multi-host runtime (if any) and trains over its local device slice."""

    def __init__(self, args, dataset, model, client_trainer=None,
                 server_aggregator=None):
        from .process_group import init_silo_process_group
        role = str(getattr(args, "role", "client")).lower()
        if role == "server":
            self.manager = build_server(args, dataset, model, client_trainer)
        else:
            init_silo_process_group()
            rank = max(int(getattr(args, "rank", 1) or 1), 1)
            self.manager = build_hierarchical_client(
                args, dataset, model, rank=rank,
                devices=jax.local_devices(),
                backend=str(getattr(args, "backend", "GRPC")).upper(),
                spec=client_trainer)

    def run(self, comm_round=None):
        self.manager.run()
        return getattr(self.manager, "result", None)
