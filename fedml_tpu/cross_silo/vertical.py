"""Classical vertical FL as a REAL distributed session over the comm
stack — parties hold disjoint FEATURE slices of the same samples; the
label party (rank 0) coordinates batches, sums logit contributions, and
returns only d(loss)/d(logits) to each party.

Parity target: reference ``simulation/sp/classical_vertical_fl/vfl_api.py``
(guest/host parties exchanging logit contributions and gradients) run as a
message protocol the way the reference's MPI protocols run, over the
repo's :class:`FedMLCommManager` (INPROC threads, TCP, or gRPC across OS
processes). Party-local math is jitted JAX on both sides: a party's
contribution forward and vjp update are each one compiled program; the
server's gradient step (loss + dlogits) is one compiled program.

Numerically identical to the fused SP simulator
(``simulation/sp/vertical_fl.py``): the joint gradient factors through
d(loss)/d(total_logits), which is the only tensor that needs to cross the
party boundary — the parity test asserts it.

Privacy boundary: features never leave a party; labels never leave the
server; only logit contributions (forward) and the shared logit gradient
(backward) cross.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..simulation.sp.vertical_fl import _PartyNet

logger = logging.getLogger(__name__)


class VFLMsg:
    # party -> server
    P2S_ONLINE = 201
    P2S_CONTRIB = 202       # logit contribution for the current batch
    P2S_EVAL_CONTRIB = 203  # logit contribution over the test set
    # server -> party
    S2P_BATCH = 211         # sample indices of the next batch
    S2P_GRAD = 212          # d(loss)/d(total_logits) for that batch
    S2P_EVALUATE = 213
    S2P_FINISH = 214

    K_IDX = "batch_idx"
    K_LOGITS = "logits"
    K_GRAD = "dlogits"
    K_ROUND = "round_idx"
    K_SEQ = "seq"  # server-side total order; parties replay it exactly


def _pool_train(fed) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pool all clients' train data exactly like the SP simulator: VFL has
    one logical dataset, feature-split."""
    x = np.asarray(fed.train.x)
    y = np.asarray(fed.train.y)
    m = np.asarray(fed.train.mask)
    x = x.reshape((-1,) + x.shape[3:])
    feat = int(np.prod(x.shape[1:]))
    return x.reshape(x.shape[0], feat), y.reshape(-1), m.reshape(-1)


def _pool_test(fed, feat: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    tx = np.asarray(fed.test["x"])
    ty = np.asarray(fed.test["y"])
    tm = np.asarray(fed.test["mask"])
    tx = tx.reshape((-1,) + tx.shape[2:]).reshape(-1, feat)
    return tx, ty.reshape(-1), tm.reshape(-1)


def party_slices(feat: int, party_num: int) -> List[Tuple[int, int]]:
    """Contiguous feature split — identical to the SP simulator's."""
    splits = np.linspace(0, feat, party_num + 1).astype(int)
    return [(int(splits[i]), int(splits[i + 1]))
            for i in range(party_num)]


class VFLServerManager(FedMLCommManager):
    """Rank 0 — the label party. Holds y/mask only; generates the batch
    schedule (same RandomState stream as the SP simulator), sums party
    contributions, and broadcasts the logit gradient."""

    def __init__(self, args, fed, comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.party_num = size - 1
        x, y, m = _pool_train(fed)
        self.y = jnp.asarray(y)
        self.mask = jnp.asarray(m)
        feat = x.shape[1]
        _, ty, tm = _pool_test(fed, feat)
        self.test_y = jnp.asarray(ty)
        self.test_mask = jnp.asarray(tm)
        self.n = int(y.shape[0])
        self.bs = int(args.batch_size)
        self.steps = max(self.n // self.bs, 1)
        self.rounds = int(getattr(args, "comm_round", 1))
        self.freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
        self._rng = np.random.RandomState(
            int(getattr(args, "random_seed", 0)))
        self.round_idx = 0
        self.step_idx = 0
        self._perm: Optional[np.ndarray] = None
        self._online: List[int] = []
        self._contribs: Dict[int, jnp.ndarray] = {}
        self._eval_contribs: Dict[int, jnp.ndarray] = {}
        self._out_seq = 0  # total order over every S2P send (broadcasts
        # are identical per party, so one counter covers all of them)
        self.history: List[Dict[str, Any]] = []
        self.result: Optional[dict] = None
        self._grad_step = jax.jit(self._grad_step_impl)
        self._acc = jax.jit(self._acc_impl)

    # --- jitted math --------------------------------------------------------
    def _loss(self, logits, y, mask):
        per_ex = optax.softmax_cross_entropy_with_integer_labels(
            logits, y.astype(jnp.int32))
        mask = mask.astype(per_ex.dtype)
        return jnp.sum(per_ex * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def _grad_step_impl(self, logits, y, mask):
        loss, dlogits = jax.value_and_grad(self._loss)(logits, y, mask)
        return loss, dlogits

    def _acc_impl(self, logits, y, mask):
        correct = jnp.sum((jnp.argmax(logits, -1) == y) * mask)
        return correct, jnp.sum(mask)

    # --- FSM ----------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(VFLMsg.P2S_ONLINE,
                                              self._on_online)
        self.register_message_receive_handler(VFLMsg.P2S_CONTRIB,
                                              self._on_contrib)
        self.register_message_receive_handler(VFLMsg.P2S_EVAL_CONTRIB,
                                              self._on_eval_contrib)

    def _on_online(self, msg: Message) -> None:
        rank = msg.get_sender_id()
        if rank not in self._online:
            self._online.append(rank)
        logger.info("vfl server: %d/%d parties online", len(self._online),
                    self.party_num)
        if len(self._online) >= self.party_num:
            self._online.sort()
            self._start_round()

    def _start_round(self) -> None:
        self._perm = self._rng.permutation(self.n)
        self.step_idx = 0
        self._send_batch()

    def _broadcast(self, msg_type, **params) -> None:
        """One logical broadcast event = one seq number: parties process
        S2P messages strictly in seq order, so a transport that reorders
        back-to-back sends (TCP opens a connection per message) cannot
        make a party apply a gradient against the wrong batch."""
        seq = self._out_seq
        self._out_seq += 1
        for rank in self._online:
            m = Message(msg_type, self.rank, rank)
            for key, val in params.items():
                m.add_params(key, val)
            m.add_params(VFLMsg.K_SEQ, seq)
            self.send_message(m)

    def _send_batch(self) -> None:
        idx = self._perm[self.step_idx * self.bs:
                         (self.step_idx + 1) * self.bs]
        self._contribs = {}
        self._cur_idx = idx
        self._broadcast(VFLMsg.S2P_BATCH, **{
            VFLMsg.K_IDX: np.asarray(idx),
            VFLMsg.K_ROUND: self.round_idx})

    def _on_contrib(self, msg: Message) -> None:
        self._contribs[msg.get_sender_id()] = jnp.asarray(
            msg.get(VFLMsg.K_LOGITS))
        if len(self._contribs) < self.party_num:
            return
        total = sum(self._contribs.values())
        idx = jnp.asarray(self._cur_idx)
        loss, dlogits = self._grad_step(total, self.y[idx], self.mask[idx])
        self._broadcast(VFLMsg.S2P_GRAD,
                        **{VFLMsg.K_GRAD: np.asarray(dlogits)})
        self.step_idx += 1
        if self.step_idx < self.steps:
            self._send_batch()
            return
        # round complete (freq <= 0: never evaluate in-loop)
        if self.freq > 0 and (self.round_idx % self.freq == 0
                              or self.round_idx == self.rounds - 1):
            self._eval_contribs = {}
            self._broadcast(VFLMsg.S2P_EVALUATE)
            return
        self.history.append({"round": self.round_idx})
        self._advance()

    def _on_eval_contrib(self, msg: Message) -> None:
        self._eval_contribs[msg.get_sender_id()] = jnp.asarray(
            msg.get(VFLMsg.K_LOGITS))
        if len(self._eval_contribs) < self.party_num:
            return
        total = sum(self._eval_contribs.values())
        correct, count = self._acc(total, self.test_y, self.test_mask)
        acc = float(correct) / max(float(count), 1.0)
        logger.info("vfl server round %d: acc=%.4f", self.round_idx, acc)
        self.history.append({"round": self.round_idx, "test_acc": acc})
        self._advance()

    def _advance(self) -> None:
        self.round_idx += 1
        if self.round_idx >= self.rounds:
            self._broadcast(VFLMsg.S2P_FINISH)
            last = next((r for r in reversed(self.history)
                         if "test_acc" in r), {})
            self.result = {"history": self.history,
                           "final_test_acc": last.get("test_acc"),
                           "rounds": self.rounds}
            self.finish()
            return
        self._start_round()


class VFLPartyManager(FedMLCommManager):
    """Rank k>=1 — holds feature slice k-1. Applies the shared logit
    gradient through its own net's vjp; parameters never leave."""

    def __init__(self, args, fed, comm=None, rank: int = 1, size: int = 0,
                 backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.party_num = size - 1
        x, _, _ = _pool_train(fed)
        feat = x.shape[1]
        k = self.rank - 1
        s, e = party_slices(feat, self.party_num)[k]
        self.x = jnp.asarray(x[:, s:e])
        tx, _, _ = _pool_test(fed, feat)
        self.test_x = jnp.asarray(tx[:, s:e])
        self.net = _PartyNet(fed.num_classes)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        keys = jax.random.split(rng, self.party_num + 1)
        self.params = self.net.init(keys[k], self.x[:2])
        self.lr = float(args.learning_rate)
        self._fwd = jax.jit(self.net.apply)
        self._upd = jax.jit(self._upd_impl)
        self._cur_idx: Optional[jnp.ndarray] = None
        # in-order delivery: every S2P handler is funneled through the
        # server's seq numbers; out-of-order arrivals wait here
        self._pending: Dict[int, tuple] = {}
        self._next_seq = 0

    def _upd_impl(self, p, x, dlogits):
        _, vjp = jax.vjp(lambda pp: self.net.apply(pp, x), p)
        (gp,) = vjp(dlogits)
        return jax.tree_util.tree_map(lambda w, g: w - self.lr * g, p, gp)

    def register_message_receive_handlers(self) -> None:
        for t, h in ((VFLMsg.S2P_BATCH, self._on_batch),
                     (VFLMsg.S2P_GRAD, self._on_grad),
                     (VFLMsg.S2P_EVALUATE, self._on_evaluate),
                     (VFLMsg.S2P_FINISH, self._on_finish)):
            self.register_message_receive_handler(
                t, functools.partial(self._in_order, h))

    def _in_order(self, handler, msg: Message) -> None:
        """Process S2P messages strictly in the server's send order: the
        gradient for batch t must be applied before batch t+1's forward,
        and a transport may reorder back-to-back sends."""
        seq = msg.get(VFLMsg.K_SEQ)
        if seq is None:  # direct (non-broadcast) message: run immediately
            handler(msg)
            return
        self._pending[int(seq)] = (handler, msg)
        while self._next_seq in self._pending:
            h, m = self._pending.pop(self._next_seq)
            self._next_seq += 1
            h(m)

    def run(self) -> None:
        self.send_message(Message(VFLMsg.P2S_ONLINE, self.rank, 0))
        super().run()

    def _on_batch(self, msg: Message) -> None:
        idx = jnp.asarray(msg.get(VFLMsg.K_IDX))
        self._cur_idx = idx
        c = self._fwd(self.params, self.x[idx])
        out = Message(VFLMsg.P2S_CONTRIB, self.rank, 0)
        out.add_params(VFLMsg.K_LOGITS, np.asarray(c))
        self.send_message(out)

    def _on_grad(self, msg: Message) -> None:
        dlogits = jnp.asarray(msg.get(VFLMsg.K_GRAD))
        self.params = self._upd(self.params, self.x[self._cur_idx], dlogits)

    def _on_evaluate(self, msg: Message) -> None:
        c = self._fwd(self.params, self.test_x)
        out = Message(VFLMsg.P2S_EVAL_CONTRIB, self.rank, 0)
        out.add_params(VFLMsg.K_LOGITS, np.asarray(c))
        self.send_message(out)

    def _on_finish(self, msg: Message) -> None:
        logger.info("vfl party rank %d: finish", self.rank)
        self.finish()


def run_vfl_inproc(args, fed) -> Dict[str, Any]:
    """Server + N feature parties over the in-proc broker."""
    from . import run_inproc_session
    n = int(getattr(args, "party_num", 2) or 2)
    return run_inproc_session(args, lambda: [
        VFLServerManager(args, fed, size=n + 1, backend="INPROC"),
        *[VFLPartyManager(args, fed, rank=r, size=n + 1, backend="INPROC")
          for r in range(1, n + 1)]])
