"""Cross-silo runner dispatch (Octopus parity). Placeholder wiring until the
WAN runtime lands; gives a clear error instead of ModuleNotFoundError."""

from __future__ import annotations


def build_cross_silo_runner(args, dataset, model, client_trainer=None,
                            server_aggregator=None):
    scenario = str(getattr(args, "scenario", "horizontal")).lower()
    if scenario == "hierarchical":
        from .hierarchical.runner import HierarchicalCrossSiloRunner
        return HierarchicalCrossSiloRunner(args, dataset, model,
                                           client_trainer, server_aggregator)
    from .horizontal.runner import CrossSiloRunner
    return CrossSiloRunner(args, dataset, model, client_trainer,
                           server_aggregator)
