"""Horizontal cross-silo runner — full WAN FSM runtime lands with the
cross-silo milestone; until then the entrypoint fails with a clear message."""

from __future__ import annotations


class CrossSiloRunner:
    def __init__(self, args, dataset, model, client_trainer=None,
                 server_aggregator=None):
        raise NotImplementedError(
            "cross-silo runtime is not built yet in this checkout; "
            "use training_type='simulation' (backends: 'sp', 'tpu')")
