"""Horizontal cross-silo runner (Octopus parity).

Builds the server or client side per ``args.role``/``args.rank`` over the
chosen WAN backend (reference ``cross_silo/fedml_client.py`` /
``fedml_server.py`` facades), plus :func:`run_cross_silo_inproc` — the
"multi-node without a cluster" mode (SURVEY §4): server + N silo clients as
threads over the in-proc broker, exercising the exact Message FSM of a real
deployment.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax

from ...core.algframe.client_trainer import make_trainer_spec
from ...core.algframe.local_training import evaluate
from ...optimizers.registry import create_optimizer
from ..client.fedml_client_master_manager import ClientMasterManager
from ..client.trainer import SiloTrainer
from ..server.fedml_aggregator import FedMLAggregator
from ..server.fedml_server_manager import FedMLServerManager

logger = logging.getLogger(__name__)


def _build_spec(fed, bundle, client_trainer):
    return (client_trainer if client_trainer is not None
            else make_trainer_spec(fed, bundle))


def _make_eval_fn(spec, fed):
    ev = jax.jit(lambda p: evaluate(spec, p, fed.test["x"], fed.test["y"],
                                    fed.test["mask"]))

    def eval_fn(params):
        stats = ev(params)
        n = max(float(stats["count"]), 1.0)
        return {"test_acc": float(stats["correct"]) / n,
                "test_loss": float(stats["loss_sum"]) / n}

    return eval_fn


def build_server(args, fed, bundle, spec=None, backend: Optional[str] = None,
                 comm=None):
    spec = _build_spec(fed, bundle, spec)
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    init_rng, _ = jax.random.split(rng)
    global_params = bundle.init(init_rng, fed.train.x[0, 0])
    size = int(getattr(args, "client_num_per_round", 1)) + 1
    from ...core.async_rounds import round_mode_from_args
    if round_mode_from_args(args) == "async_buffered":
        # buffered-async session: pours replace rounds (no barrier FSM)
        from ..server.async_server import (AsyncFedMLAggregator,
                                           AsyncFedMLServerManager)
        aggregator = AsyncFedMLAggregator(args, global_params,
                                          eval_fn=_make_eval_fn(spec, fed))
        return AsyncFedMLServerManager(
            args, aggregator, comm=comm, rank=0, size=size,
            backend=backend or _wan_backend(args))
    aggregator = FedMLAggregator(args, global_params,
                                 eval_fn=_make_eval_fn(spec, fed))
    return FedMLServerManager(
        args, aggregator, comm=comm, rank=0, size=size,
        backend=backend or _wan_backend(args))


def build_client(args, fed, bundle, rank: int, spec=None,
                 backend: Optional[str] = None, comm=None):
    spec = _build_spec(fed, bundle, spec)
    optimizer = create_optimizer(args, spec)
    trainer = SiloTrainer(args, fed, bundle, spec, optimizer)
    size = int(getattr(args, "client_num_per_round", 1)) + 1
    return ClientMasterManager(
        args, trainer, comm=comm, rank=rank, size=size,
        backend=backend or _wan_backend(args))


def _wan_backend(args) -> str:
    b = str(getattr(args, "backend", "") or "").upper()
    return b if b in ("INPROC", "TCP", "GRPC") else "GRPC"


class CrossSiloRunner:
    """Single-role entry (reference FedMLRunner path): ``args.role`` decides
    server vs client; ``run()`` blocks until the FL session finishes."""

    def __init__(self, args, dataset, model, client_trainer=None,
                 server_aggregator=None):
        self.args = args
        self.fed = dataset
        self.bundle = model
        role = str(getattr(args, "role", "client")).lower()
        rank = int(getattr(args, "rank", 1) or 1)
        fo = str(getattr(args, "federated_optimizer", "FedAvg")).lower()
        if fo in ("sa", "secagg", "lsa", "lightsecagg"):
            self.manager = self._build_secure(args, dataset, model,
                                              client_trainer, fo, role, rank)
        elif fo in ("split_nn", "splitnn"):
            # split learning as a real distributed session: parties
            # exchange activations/grads over the transport
            from ..split_learning import (SplitNNClientManager,
                                          SplitNNServerManager)
            n = int(getattr(args, "client_num_per_round", 1))
            if role == "server":
                self.manager = SplitNNServerManager(
                    args, dataset.num_classes, size=n + 1,
                    backend=_wan_backend(args))
            else:
                self.manager = SplitNNClientManager(
                    args, dataset, rank=max(rank, 1), size=n + 1,
                    backend=_wan_backend(args))
        elif fo in ("decentralized_fl", "gossip"):
            # serverless: every process is a gossip node; rank == node idx
            from ..decentralized import GossipNodeManager
            n = int(getattr(args, "client_num_in_total", 2))
            self.manager = GossipNodeManager(
                args, dataset, model,
                rank=0 if role == "server" else max(rank, 1), size=n,
                backend=_wan_backend(args))
        elif fo in ("classical_vertical", "vertical_fl", "vfl"):
            from ..vertical import VFLPartyManager, VFLServerManager
            n = int(getattr(args, "party_num", 2) or 2)
            if role == "server":
                self.manager = VFLServerManager(
                    args, dataset, size=n + 1, backend=_wan_backend(args))
            else:
                self.manager = VFLPartyManager(
                    args, dataset, rank=max(rank, 1), size=n + 1,
                    backend=_wan_backend(args))
        elif role == "server":
            self.manager = build_server(args, dataset, model, client_trainer)
        else:
            self.manager = build_client(args, dataset, model,
                                        max(rank, 1), client_trainer)

    @staticmethod
    def _build_secure(args, fed, bundle, client_trainer, fo, role, rank):
        """Secure-aggregation runtimes (reference fedml_client.py:1-64 /
        fedml_server.py dispatch on SA vs LSA vs plain)."""
        from ...optimizers.registry import create_optimizer
        from ..client.trainer import SiloTrainer
        spec = _build_spec(fed, bundle, client_trainer)
        n = int(getattr(args, "client_num_per_round", 1))
        if role == "server":
            rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
            init_rng, _ = jax.random.split(rng)
            global_params = jax.device_get(
                bundle.init(init_rng, fed.train.x[0, 0]))
            kw = dict(eval_fn=_make_eval_fn(spec, fed), rank=0, size=n + 1,
                      backend=_wan_backend(args))
            if fo in ("sa", "secagg"):
                from ..secagg import SecAggServerManager
                return SecAggServerManager(args, global_params, **kw)
            from ..lightsecagg import LSAServerManager
            return LSAServerManager(args, global_params, **kw)
        import copy
        inner_args = copy.copy(args)
        inner_args.federated_optimizer = "FedAvg"  # local step is FedAvg
        optimizer = create_optimizer(inner_args, spec)
        trainer = SiloTrainer(args, fed, bundle, spec, optimizer)
        kw = dict(rank=max(rank, 1), size=n + 1,
                  backend=_wan_backend(args))
        if fo in ("sa", "secagg"):
            from ..secagg import SecAggClientManager
            return SecAggClientManager(args, trainer, **kw)
        from ..lightsecagg import LSAClientManager
        return LSAClientManager(args, trainer, **kw)

    def run(self, comm_round=None) -> Any:
        self.manager.run()
        return getattr(self.manager, "result", None)


def run_cross_silo_inproc(args, fed, bundle, spec=None) -> Dict[str, Any]:
    """Server + N silo clients as threads over the in-proc broker."""
    from .. import run_inproc_session
    n = int(getattr(args, "client_num_per_round", 2))
    return run_inproc_session(args, lambda: [
        build_server(args, fed, bundle, spec, backend="INPROC"),
        *[build_client(args, fed, bundle, rank=r, spec=spec,
                       backend="INPROC") for r in range(1, n + 1)]],
        join_timeout_s=30.0)
