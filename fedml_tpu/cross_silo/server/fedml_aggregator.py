"""Server-side aggregation state for the WAN FSM.

Parity target: reference ``cross_silo/server/fedml_aggregator.py:13``
(``add_local_trained_result`` :58, all-received barrier :69, ``aggregate``
:78 with defense/DP hooks, ``data_silo_selection`` :113,
``client_selection`` :139). The all-received barrier additionally supports a
timeout with re-weighted aggregation over the silos that did report —
SURVEY §5.3 flags the reference's training loop as having no elasticity (a
dead client stalls the round forever); round-timeout + renormalize is the
capability add.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.collectives import (tree_weighted_average,
                                 vector_to_tree_like)
from ...core.dp import FedMLDifferentialPrivacy
from ...core.security import FedMLDefender, stack_to_matrix
from ...core.selection import ClientStatsStore
from ...simulation.sampling import (client_sampling,
                                    sampling_stream_from_args)

logger = logging.getLogger(__name__)


def clamped_wait(remaining: Optional[float], cap: float = 1.0,
                 floor: float = 0.05) -> float:
    """Bound a condition-variable wait derived from a deadline.

    The old inline expression ``min(remaining or 1.0, 1.0)`` was a trap:
    ``remaining == 0.0`` is falsy and became a full extra second past the
    deadline, and a negative underflow passed a negative timeout straight
    to ``Condition.wait``. Clamp to ``[floor, cap]`` — the floor also
    keeps a passed-deadline-below-quorum loop from busy-spinning."""
    if remaining is None:
        return cap
    return min(max(float(remaining), floor), cap)


class FedMLAggregator:
    def __init__(self, args, global_params, eval_fn=None):
        self.args = args
        self.global_params = global_params
        self.eval_fn = eval_fn
        self.client_num = int(getattr(args, "client_num_per_round", 1))
        self.defender = FedMLDefender(args)
        self.dp = FedMLDifferentialPrivacy(args)
        self.round_timeout_s = float(getattr(args, "round_timeout_s", 0) or 0)
        # fault tolerance: a timed-out round aggregates only when at least
        # ``quorum`` silos reported (ceil(round_quorum_frac * expected),
        # min 1) — averaging a one-silo sliver under heavy chaos is worse
        # than waiting another timeout interval
        frac = float(getattr(args, "round_quorum_frac", 0.0) or 0.0)
        self._quorum_frac = frac
        self._base_quorum = max(1, int(np.ceil(frac * self.client_num))) \
            if frac > 0 else 1
        self.quorum = self._base_quorum
        # silo selection (core/selection): per-RANK observed upload
        # latencies + quorum history (which silos missed their rounds),
        # consulted by select_silos when a non-uniform client_selection
        # strategy is configured. Passive (records only) otherwise.
        self.selection_strategy = str(getattr(args, "client_selection",
                                              "uniform") or "uniform").lower()
        self.silo_stats = ClientStatsStore(
            max(self.client_num + 1, 2),
            loss_window=int(getattr(args, "selection_loss_window", 8) or 8),
            ema_alpha=float(getattr(args, "selection_ema_alpha", 0.2)
                            or 0.2),
            # light prior: a silo server gets ONE availability observation
            # per (slow, minutes-long) round — benching must react within
            # a handful of missed rounds, not nineteen
            drop_prior=(1.0, 4.0))
        self._expected = self.client_num
        self._lock = threading.Condition()
        self._reset_round()

    def _reset_round(self) -> None:
        self.model_dict: Dict[int, Any] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.flag_client_model_uploaded_dict: Dict[int, bool] = {}
        self._round_start = time.time()
        # restore BOTH per-round values: a quorum scaled down by
        # set_round_expected must not leak into later rounds that bench
        # nobody (it would silently weaken the configured quorum floor)
        self._expected = self.client_num
        self.quorum = getattr(self, "_base_quorum", 1)

    # --- per-round expected cohort (silo selection seam) --------------------
    def set_round_expected(self, n: int) -> None:
        """Shrink THIS round's all-received barrier to the silos actually
        selected (select_silos). Quorum scales with it. Reset to the full
        cohort by the post-aggregation _reset_round."""
        with self._lock:
            self._expected = max(1, min(int(n), self.client_num))
            if self._quorum_frac > 0:
                self.quorum = max(1, int(np.ceil(self._quorum_frac
                                                 * self._expected)))
            self._lock.notify_all()

    # --- observed silo behavior (fed by the server FSM) ---------------------
    def observe_upload(self, rank: int, latency_s: float) -> None:
        """One silo upload's broadcast→receipt latency."""
        if 0 <= int(rank) < self.silo_stats.n:
            self.silo_stats.record_latency(int(rank), float(latency_s))

    def observe_round(self, reported, expected) -> None:
        """Round-close quorum history: which of the silos the round
        expected actually reported — the Beta-posterior dropout evidence
        silo selection runs on. ``expected`` must be the SELECTED cohort
        only: a benched silo losing the shrunken barrier's race is not
        dropout evidence (counting it would self-reinforce the bench
        forever). A benched silo that DOES report heals — that is the
        redemption path."""
        rep = set(int(r) for r in reported)
        exp = set(int(r) for r in expected)
        for r in exp:
            if 0 <= r < self.silo_stats.n:
                self.silo_stats.record_availability(r, participated=r in rep)
        for r in rep - exp:
            if 0 <= r < self.silo_stats.n:
                self.silo_stats.record_availability(r, participated=True)

    def select_silos(self, online_ranks) -> List[int]:
        """Which online silos to include in the next round. ``uniform``
        (default): all of them — byte-identical FSM. Non-uniform
        strategies bench silos whose posterior dropout probability is
        high (they would only burn the round timeout), never benching
        below max(quorum, min_keep_frac) of the online set."""
        ranks = sorted(int(r) for r in online_ranks)
        if self.selection_strategy == "uniform" or len(ranks) <= 1:
            return ranks
        from ...core.selection.strategies import cap_bench, rep_bench_knobs
        # two independent bench signals: the dropout POSTERIOR (silos
        # that will only burn the round timeout) and — since ISSUE 7's
        # defended async pours feed defense verdicts into silo_stats —
        # the REPUTATION posterior (silos the defenses keep excluding).
        # Reputation only bites where verdict evidence exists; undefended
        # sessions see rep == 1 everywhere and behave exactly as before.
        post = self.silo_stats.dropout_posterior_mean()
        rep = self.silo_stats.reputation
        rep_thresh, keep_frac = rep_bench_knobs(self.args)
        flaky = [r for r in ranks
                 if r < self.silo_stats.n
                 and (post[r] > 0.5 or rep[r] < rep_thresh)]
        benched = set(cap_bench(
            len(ranks), flaky,
            badness=lambda r: float(post[r]) + float(1.0 - rep[r]),
            keep_frac=keep_frac, quorum=self.quorum))
        return [r for r in ranks if r not in benched]

    def add_local_trained_result(self, index: int, model_params,
                                 sample_num: float) -> None:
        with self._lock:
            self.model_dict[index] = model_params
            self.sample_num_dict[index] = float(sample_num)
            self.flag_client_model_uploaded_dict[index] = True
            self._lock.notify_all()

    def add_local_trained_delta(self, index: int, delta_vec,
                                sample_num: float,
                                base_vec=None) -> None:
        """Wire-efficient upload path: reconstruct the sender's full model
        from a decompressed update delta (host f32 vector, flattened in
        the global tree's leaf order), then store it like any dense
        upload — weighted aggregation, defenses, and DP all run
        downstream in float32, unchanged.

        ``base_vec`` is the model vector the SENDER trained from. It must
        be supplied when the broadcast itself was compressed: the clients
        hold a reconstruction that differs from the server's exact global,
        and adding their deltas to the wrong base re-injects that gap into
        the average every round (a systematic bias that diverges). When
        the broadcast was dense, the current global IS the base."""
        if base_vec is not None:
            vec = jnp.asarray(base_vec, jnp.float32) + jnp.asarray(
                delta_vec, jnp.float32)
            params = vector_to_tree_like(vec, self.global_params)
        else:
            delta = vector_to_tree_like(jnp.asarray(delta_vec, jnp.float32),
                                        self.global_params)
            params = jax.tree_util.tree_map(
                lambda g, d: jnp.asarray(g) + d, self.global_params, delta)
        self.add_local_trained_result(index, params, sample_num)

    def check_whether_all_receive(self) -> bool:
        with self._lock:
            return len(self.model_dict) >= self._expected

    def wait_all_or_timeout(self) -> bool:
        """Block until every expected silo reported, or the round timeout
        elapsed with at least ``quorum`` reports. Returns True if
        aggregation can proceed; False when the (doubled, as a hard cap)
        deadline passes below quorum. Waits are clamped
        (:func:`clamped_wait`) so deadline underflow can neither overshoot
        the deadline by a spurious second nor busy-spin / pass a negative
        timeout to ``Condition.wait``."""
        with self._lock:
            while True:
                n = len(self.model_dict)
                if n >= self._expected:
                    return True
                remaining = None
                if self.round_timeout_s > 0:
                    elapsed = time.time() - self._round_start
                    remaining = self.round_timeout_s - elapsed
                    if remaining <= 0:
                        if n >= self.quorum:
                            return True
                        # below quorum: grant a grace interval (one more
                        # timeout) before giving up on the round
                        if elapsed >= 2.0 * self.round_timeout_s:
                            return False
                self._lock.wait(timeout=clamped_wait(remaining))

    def aggregate(self, round_key=None):
        """Weighted average of received silo models (hook chain: defense ->
        aggregate -> DP noise, reference ``server_aggregator.py:44-103``)."""
        with self._lock:
            idxs = sorted(self.model_dict)
            models = [self.model_dict[i] for i in idxs]
            weights = jnp.asarray([self.sample_num_dict[i] for i in idxs],
                                  jnp.float32)
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(
            [jnp.asarray(x) for x in xs]), *models)
        if self.defender.is_defense_enabled():
            # defenses act on deltas from the current global model
            deltas = jax.tree_util.tree_map(
                lambda s, g: s - jnp.asarray(g)[None], stacked,
                self.global_params)
            agg_delta, _ = self.defender.defend(deltas, weights, round_key,
                                                np.asarray(idxs))
            new_global = jax.tree_util.tree_map(
                lambda g, d: jnp.asarray(g) + d, self.global_params, agg_delta)
        else:
            new_global = tree_weighted_average(stacked, weights)
        if self.dp.is_global_dp_enabled() and round_key is not None:
            delta = jax.tree_util.tree_map(
                lambda n, g: n - jnp.asarray(g), new_global, self.global_params)
            delta = self.dp.add_global_noise(delta, round_key)
            new_global = jax.tree_util.tree_map(
                lambda g, d: jnp.asarray(g) + d, self.global_params, delta)
        self.global_params = new_global
        self._reset_round()
        return new_global

    def test_on_server(self) -> Optional[Dict[str, float]]:
        if self.eval_fn is None:
            return None
        return self.eval_fn(self.global_params)

    # --- selection (reference :113,:139) ------------------------------------
    # Both draws ride simulation.sampling.client_sampling: the legacy
    # stream (default) reproduces the reference's np.random.seed(round_idx)
    # sequence bit-for-bit WITHOUT clobbering the process-global RNG, and
    # sampling_stream: seeded folds random_seed in.
    def client_selection(self, round_idx: int, client_num_in_total: int,
                         client_num_per_round: int) -> List[int]:
        return [int(c) for c in client_sampling(
            round_idx, client_num_in_total, client_num_per_round,
            random_seed=int(getattr(self.args, "random_seed", 0) or 0),
            stream=sampling_stream_from_args(self.args))]

    def data_silo_selection(self, round_idx: int, data_silo_num: int,
                            client_num_in_total: int) -> List[int]:
        if data_silo_num <= client_num_in_total:
            return list(range(client_num_in_total))
        return [int(c) for c in client_sampling(
            round_idx, data_silo_num, client_num_in_total,
            random_seed=int(getattr(self.args, "random_seed", 0) or 0),
            stream=sampling_stream_from_args(self.args))]

    def assign_data_indices(self, ranks, client_indexes) -> Dict[int, int]:
        """rank -> DATA index for this round's broadcast.

        ``silo_index_assignment: legacy`` (default) is the reference's
        round-robin — the i-th rank in iteration order gets
        ``client_indexes[i % len]``, bit-identical to before. ``scored``
        closes the PR 5 leftover: ranks are scored by the stats store
        (availability posterior over observed latency — the silo most
        likely to actually deliver, fastest), and the FIRST-sampled data
        indices go to the best-scoring silos: the partitions the sampler
        put at the head of the round's list are the ones most likely to
        make it into the aggregate, and soonest. Ties (and unobserved
        silos, which score neutral) keep rank order, so a cold store
        degrades to legacy exactly."""
        mode = str(getattr(self.args, "silo_index_assignment", "legacy")
                   or "legacy").lower()
        ranks = [int(r) for r in ranks]
        idx = list(client_indexes)
        if mode == "legacy" or len(ranks) <= 1:
            return {r: int(idx[i % len(idx)]) for i, r in enumerate(ranks)}
        if mode != "scored":
            raise ValueError(
                f"silo_index_assignment {mode!r} unknown; choose from "
                "('legacy', 'scored')")
        st = self.silo_stats
        post = st.dropout_posterior_mean()
        lat = np.where(st.has_latency > 0, st.ema_latency, np.nan)
        obs = lat[np.isfinite(lat)]
        fill = float(np.median(obs)) if obs.size else 1.0
        score = []
        for r in ranks:
            if 0 <= r < st.n:
                avail = 1.0 - float(post[r])
                speed = fill if not np.isfinite(lat[r]) else float(lat[r])
            else:
                avail, speed = 1.0 - float(np.mean(post)), fill
            score.append(avail / max(speed, 1e-9))
        order = np.argsort(-np.asarray(score), kind="stable")
        return {ranks[int(r)]: int(idx[i % len(idx)])
                for i, r in enumerate(order)}
