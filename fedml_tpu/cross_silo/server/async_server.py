"""Cross-silo buffered-async server — no round barrier, ever.

``round_mode: async_buffered`` replaces the all-received barrier of the
sync FSM (:mod:`.fedml_server_manager`) with a FedBuff pour loop: the
server aggregates whenever K staleness-weighted uploads are buffered, and
``comm_round`` counts POURS (global model versions). The sync FSM's
failure machinery — round timers, quorum, grace intervals, stale-upload
DROPS — is replaced wholesale, because its premises (a round, a cohort, a
deadline) no longer exist:

* **Stale uploads are down-weighted, never dropped.** Every sync/upload
  carries the model version it was trained from; staleness at pour time
  is ``server_version - upload_version``, weighted by the shared
  ``core/async_rounds`` decay. A per-version base ring (bounded by the
  staleness cap) lets the server form each silo's DELTA against the exact
  base it trained from — dense and compressed uplinks alike — so a
  straggler from five versions ago still contributes, just faintly.

* **Crashed silos simply stop contributing.** Nothing waits for them; the
  pour-timeout valve (``async_pour_timeout_s``) pours a partial buffer
  (>= 1 update) so a decimated fleet keeps making progress, and an empty
  fire re-broadcasts the current model to every online silo — the nudge
  that recovers link-lost syncs without per-message bookkeeping. A silo
  that re-announces ONLINE after the session started is immediately
  handed the current model: the redemption path.

* **Arrival-rate posteriors feed the staleness cap.** Per-silo upload
  latencies (sync→receipt, clocked per-silo because broadcasts are no
  longer simultaneous) land in the PR 5 stats store; with
  ``async_staleness_cap: 0`` the cap tracks observed latency / pour
  interval instead of a constant.

* **Defended pours (ISSUE 7).** Robust defenses compose with the buffer:
  at pour time every buffered delta is RE-BASED onto the current version
  (subtracting the server movement it missed, read straight off the base
  ring the server already owns), the staleness decay folds into the
  defense's row weights, and ``defend_matrix`` aggregates the re-based
  rows — at staleness 0 this is exactly the sync defended round's math.
  The defense's per-silo verdict feeds the stats store's reputation
  posterior, and non-uniform ``client_selection`` benches silos the
  defenses keep excluding out of the post-pour re-sync (the empty-fire
  nudge remains their probation path). ``weak_dp``/``crfl`` stay refused:
  noise-adding defenses are DP by another name, and per-pour noise
  accounting over a mixed-staleness buffer is the same open design that
  keeps async+DP refused.

Per-update arrival timestamps and staleness are recorded in the
FaultLedger (``record_pour``) and mirrored to ``mlops.log_chaos`` so the
bench and post-mortems can reconstruct the arrival distribution.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core import mlops
from ...core.obs import trace as obs_trace
from ...core.security.defense import verdict_from_info
from ...core.async_rounds import (UpdateBuffer, adaptive_staleness_cap,
                                  buffer_k_from_args, make_staleness_fn,
                                  merge_alpha_from_args, pour_weights,
                                  staleness_cap_from_args,
                                  staleness_fn_from_args,
                                  weighting_knobs_from_args)
from ...core.collectives import tree_flatten_to_vector, vector_to_tree_like
from ...core.distributed.communication.message import (Message, tree_to_wire,
                                                       wire_to_tree)
from ...core.wire import wire_checkpointer, wire_state_template
from ...utils.compression import decompress_vec, is_compressed_payload
from ..message_define import MyMessage
from .fedml_aggregator import FedMLAggregator
from .fedml_server_manager import FedMLServerManager

logger = logging.getLogger(__name__)


class AsyncFedMLAggregator(FedMLAggregator):
    """Buffered-async aggregation state: an :class:`UpdateBuffer` of silo
    deltas plus the per-version base ring they are formed against."""

    def __init__(self, args, global_params, eval_fn=None):
        super().__init__(args, global_params, eval_fn=eval_fn)
        if self.dp.is_dp_enabled():
            raise ValueError(
                "round_mode: async_buffered does not yet compose with DP "
                "on the cross-silo server (per-pour accounting under "
                "stale mixed cohorts is an open design); use "
                "round_mode: sync")
        if (self.defender.is_defense_enabled()
                and self.defender.defense_type in ("weak_dp", "crfl")):
            raise ValueError(
                "round_mode: async_buffered refuses defense_type "
                f"{self.defender.defense_type!r}: noise-adding defenses "
                "are DP by another name, and per-pour noise accounting "
                "over a mixed-staleness buffer is the same open design "
                "that keeps async+DP refused; use round_mode: sync")
        # defended pours draw their defense keys from a dedicated seeded
        # stream (one fold per pour — deterministic for a given trace)
        self._defense_key = jax.random.PRNGKey(
            int(getattr(args, "random_seed", 0) or 0) + 71)
        self.version = 0
        self.k = buffer_k_from_args(args, self.client_num)
        self.merge_alpha = merge_alpha_from_args(args)
        self.staleness_fn = staleness_fn_from_args(args)
        self.staleness_cap = staleness_cap_from_args(args)
        self._cap_adaptive = int(getattr(args, "async_staleness_cap", 16)
                                 or 0) == 0
        self._weighting_args = args
        self.buffer = UpdateBuffer(self.k)
        self._pour_interval_ema: Optional[float] = None
        self._last_pour_t: Optional[float] = None
        # version -> host f32 model vector. Ring-bounded by the staleness
        # cap: uploads older than the ring fall back to the OLDEST
        # retained base — the residual base drift is folded into an update
        # whose staleness weight is already saturated-tiny
        self._base_ring: Dict[int, np.ndarray] = {
            0: np.asarray(tree_flatten_to_vector(global_params),
                          np.float32)}
        # defended pours over COMPRESSED uplinks: a compressed upload is a
        # delta the silo's error-feedback already committed — a defense
        # exclusion silently loses that movement and the silo never
        # re-sends it. Thread a server-side EF loop through the base
        # ring instead: an excluded re-based row is carried per sender
        # (stamped with the version it was re-based to), re-based again
        # onto the pour's version, and folded into the sender's NEXT row
        # before the defense re-judges it; a kept verdict clears it.
        # Dense uploads stay uncarried — their next upload is absolute.
        self._ef_carry: Dict[int, tuple] = {}
        self._compressed_senders: set = set()

    # --- uploads ------------------------------------------------------------
    def base_for(self, version: int) -> np.ndarray:
        ring = self._base_ring
        if int(version) in ring:
            return ring[int(version)]
        oldest = min(ring)
        logger.warning(
            "async upload from version %s predates the base ring "
            "(oldest retained: %d) — using the oldest base; the update's "
            "staleness weight is saturated anyway", version, oldest)
        return ring[oldest]

    def add_async_upload(self, rank: int, payload, sample_num: float,
                         up_version: int, arrival_t: float,
                         compressed: bool, trace=None) -> int:
        """Buffer one silo upload as a delta vs its dispatch base.
        Returns the buffered count (the pour trigger reads it under the
        same lock discipline as the add). ``trace`` is the upload span's
        context — the pour span links it, staleness attached."""
        if compressed:
            # a compressed upload IS the delta vs the broadcast the silo
            # holds — exactly its dispatch base; no reconstruction needed
            delta = np.asarray(payload, np.float32)
            self._compressed_senders.add(int(rank))
        else:
            # payload: the uploaded model as a flat f32 vector (callers
            # flatten OUTSIDE any lock — see the manager) or a tree
            vec = (np.asarray(payload, np.float32)
                   if isinstance(payload, np.ndarray)
                   else np.asarray(tree_flatten_to_vector(payload),
                                   np.float32))
            delta = vec - self.base_for(up_version)
        self.buffer.add(int(rank), delta, weight=float(sample_num),
                        version=int(up_version), arrival_t=float(arrival_t),
                        trace=trace)
        return len(self.buffer)

    # --- the pour -----------------------------------------------------------
    def pour(self, max_n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Aggregate up to K buffered deltas: staleness-weighted average,
        damped by the merge scale, applied to the current global. Returns
        the per-update arrival records (empty list = nothing to pour)."""
        entries = self.buffer.pour(self.version, max_n=max_n)
        if not entries:
            return []
        if self._cap_adaptive:
            # arrival-rate posteriors -> staleness cap: observed silo
            # latency over the pour interval is how many versions a
            # routine upload lags
            self.staleness_cap = adaptive_staleness_cap(
                self.silo_stats.ema_latency[self.silo_stats.has_latency > 0],
                self._pour_interval_ema or 0.0)
            kind, poly_a, hinge_b = weighting_knobs_from_args(
                self._weighting_args)
            self.staleness_fn = make_staleness_fn(kind, poly_a, hinge_b,
                                                  self.staleness_cap)
        stal = np.asarray([e.staleness(self.version) for e in entries],
                          np.float64)
        w = np.asarray([e.weight for e in entries], np.float64)
        norm_w, merge_scale = pour_weights(w, stal, self.staleness_fn,
                                           self.merge_alpha)
        base = self._base_ring[self.version]
        if self.defender.is_defense_enabled():
            # DEFENDED pour: robust kernels compare update vectors, but
            # each buffered delta was formed against the base its silo
            # trained from — re-base every row onto the CURRENT version
            # by subtracting the server movement it missed (the base ring
            # the server already owns), fold the staleness decay into the
            # defense's row weights, and let the defense aggregate. At
            # staleness 0 the correction is zero and the pour is exactly
            # the sync defended round's math. The poured K varies, which
            # is fine host-side (the kernels retrace per shape).
            rows = []
            for e in entries:
                row = (np.asarray(e.update, np.float32)
                       - (base - self.base_for(e.version)))
                carry = self._ef_carry.pop(int(e.client_id), None)
                if carry is not None:
                    # the stored row satisfied base_{v_s} + row = target;
                    # re-expressing against the CURRENT base subtracts the
                    # server movement since v_s — same algebra as the
                    # fresh row's own re-base, read off the same ring
                    cv, cres = carry
                    row = row + (cres - (base - self.base_for(cv)))
                rows.append(row)
            # norm_w IS the staleness-folded relative mix (pour_weights,
            # the one staleness implementation); the kernels normalize
            # internally, so passing it is exactly the decayed weighting
            ranks = np.asarray([e.client_id for e in entries], np.int32)
            vec, info = self.defender.defend_matrix(
                jnp.asarray(np.stack(rows)),
                jnp.asarray(norm_w, jnp.float32),
                rng=jax.random.fold_in(self._defense_key, self.version),
                client_ids=ranks)
            agg = np.asarray(jax.device_get(vec), np.float32)
            verdict = verdict_from_info(info, len(entries))
            if verdict is not None:
                for i, e in enumerate(entries):
                    rid = int(e.client_id)
                    if (float(np.asarray(verdict)[i]) < 0.5
                            and rid in self._compressed_senders):
                        self._ef_carry[rid] = (self.version,
                                               np.asarray(rows[i],
                                                          np.float32))
            if verdict is not None:
                # defense verdicts are the silo reputation stream —
                # select_silos benches silos the defenses keep excluding.
                # Bounds-guarded like every other silo_stats write: an
                # out-of-range rank must not kill the pour thread.
                keep = [i for i, r in enumerate(ranks)
                        if 0 <= int(r) < self.silo_stats.n]
                if keep:
                    self.silo_stats.record_verdict(
                        [int(ranks[i]) for i in keep],
                        np.asarray(verdict)[keep])
        else:
            agg = np.zeros(entries[0].update.shape, np.float32)
            for nw, e in zip(norm_w, entries):
                agg = agg + np.asarray(e.update, np.float32) * np.float32(nw)
        new_vec = base + np.float32(merge_scale) * agg
        self.global_params = jax.tree_util.tree_map(
            np.asarray,
            vector_to_tree_like(np.asarray(new_vec), self.global_params))
        self.version += 1
        self._base_ring[self.version] = np.asarray(new_vec, np.float32)
        for v in [v for v in self._base_ring
                  if v < self.version - self.staleness_cap]:
            del self._base_ring[v]
        now = time.time()
        if self._last_pour_t is not None:
            dt = now - self._last_pour_t
            self._pour_interval_ema = (
                dt if self._pour_interval_ema is None
                else 0.8 * self._pour_interval_ema + 0.2 * dt)
        self._last_pour_t = now
        return [{"client": e.client_id, "staleness": int(s),
                 "arrival_t": e.arrival_t, "dispatch_version": e.version,
                 "weight": e.weight, "norm_weight": float(nw),
                 "merge_scale": float(merge_scale),
                 # the producing upload span's traceparent (None when the
                 # silo predates tracing or the header was stripped): the
                 # pour span links it, and the ledger record carries it so
                 # post-mortems can jump from a pour to its uploads
                 "trace": (e.trace.traceparent()
                           if e.trace is not None else None)}
                for e, s, nw in zip(entries, stal, norm_w)]


class AsyncFedMLServerManager(FedMLServerManager):
    """Rank 0 of an async session. The sync FSM's round machinery is
    inert here — this class overrides the two seams that drove it (the
    upload handler and the post-aggregation sync) with the pour loop."""

    DEFAULT_POUR_TIMEOUT_S = 30.0

    def __init__(self, args, aggregator, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, aggregator, comm=comm, rank=rank, size=size,
                         backend=backend)
        if not isinstance(aggregator, AsyncFedMLAggregator):
            raise ValueError("AsyncFedMLServerManager needs an "
                             "AsyncFedMLAggregator")
        if self.cc_spec is not None \
                and str(getattr(self.cc_spec, "broadcast", "full")) != "full":
            raise ValueError(
                "round_mode: async_buffered needs a dense broadcast "
                "(comm_compression_broadcast: full): bf16/compressed "
                "downlinks track ONE shared client reconstruction, but "
                "async silos are synced at different versions")
        self._pour_lock = threading.Lock()
        # timer cancel/replace must be atomic: an upload thread re-arming
        # after a pour races the timer thread re-arming after an empty
        # fire — unsynchronized, both cancel the same old timer and one
        # of the two replacements is orphaned alive, firing spuriously
        self._timer_lock = threading.Lock()
        self._pour_timer: Optional[threading.Timer] = None
        self._done = False
        # per-silo sync timestamps: broadcasts are no longer simultaneous,
        # so upload latency must be clocked against the silo's OWN sync.
        # _outstanding tracks silos with a sync awaiting an upload — a
        # re-sync of such a silo keeps the FIRST timestamp (re-clocking
        # would understate a slow silo's latency and shrink the adaptive
        # staleness cap in exactly the wrong direction)
        self._sync_t: Dict[int, float] = {}
        self._outstanding: Dict[int, int] = {}
        self._empty_fires = 0
        self._last_arrival: Dict[int, float] = {}
        # liveness valve fallback chain: async_pour_timeout_s ->
        # round_timeout_s -> a positive default. It must NOT bottom out at
        # 0 (both knobs default to 0): with K silos crashed the pour
        # trigger can never fire, and without a timer the session would
        # hang forever — the exact failure mode this mode exists to remove
        t = float(getattr(args, "async_pour_timeout_s", 0.0) or 0.0)
        self.pour_timeout_s = (t if t > 0 else self.round_timeout_s
                               if self.round_timeout_s > 0
                               else self.DEFAULT_POUR_TIMEOUT_S)
        # async wire state (ISSUE 19 satellite): the sync manager's slot
        # holds broadcast EF state, which async never has (dense
        # broadcasts only) — replace it with this mode's own namespace
        # carrying the defended-pour per-sender EF residuals
        self._wire_ckpt = wire_checkpointer(args, "async_server")
        if self._wire_ckpt is not None:
            self._restore_wire_state()

    # --- wire-state checkpointing (per-sender pour residuals) ---------------
    def _wire_template(self) -> dict:
        n = int(getattr(self.args, "client_num_in_total",
                        self.client_num)) + 1
        d = int(self.aggregator._base_ring[
            min(self.aggregator._base_ring)].shape[0])
        t = wire_state_template(d, (), matrices={"ef_residual": n})
        t["ef_version"] = np.zeros((n,), np.int32)
        t["compressed"] = np.zeros((n,), np.int32)
        return t

    def _save_wire_state(self, completed_round: int) -> None:
        if self._wire_ckpt is None or not self._wire_ckpt.enabled:
            return
        st = self._wire_template()
        st["round"] = np.asarray(completed_round, np.int32)
        n = st["compressed"].shape[0]
        for rid in self.aggregator._compressed_senders:
            if 0 <= rid < n:
                st["compressed"][rid] = 1
        for rid, (cv, cres) in self.aggregator._ef_carry.items():
            if 0 <= rid < n:
                st["ef_residual_set"][rid] = 1
                st["ef_version"][rid] = cv
                st["ef_residual"][rid] = cres
        self._wire_ckpt.maybe_save(completed_round, st)

    def _restore_wire_state(self) -> None:
        if self._wire_ckpt is None or not self._wire_ckpt.enabled:
            return
        got = self._wire_ckpt.latest(self._wire_template())
        if got is None:
            return
        _, st = got
        agg = self.aggregator
        agg._compressed_senders = {
            int(r) for r in np.flatnonzero(np.asarray(st["compressed"]))}
        agg._ef_carry = {
            int(r): (int(st["ef_version"][r]),
                     np.asarray(st["ef_residual"][r], np.float32))
            for r in np.flatnonzero(np.asarray(st["ef_residual_set"]))}
        logger.info("async server: restored wire EF state for %d senders",
                    len(agg._ef_carry))

    # --- handshake + redemption ---------------------------------------------
    def handle_message_client_status_update(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        already = bool(self.client_online_status.get(sender))
        super().handle_message_client_status_update(msg)
        if self.is_initialized and already and not self._done:
            # a silo re-announcing ONLINE mid-session is a reconnect
            # (crash-recovered process, healed link): hand it the current
            # model so it rejoins the rotation — redemption, not a replay
            logger.info("async server: silo %s reconnected — syncing "
                        "version %d", sender, self.aggregator.version)
            self._sync_ranks([sender])

    def send_init_msg(self) -> None:
        client_indexes = self.aggregator.client_selection(
            0, int(self.args.client_num_in_total), self.client_num)
        wire = tree_to_wire(self.aggregator.global_params)
        self._round_targets = sorted(self.client_online_status)
        now = time.time()
        assign = self.aggregator.assign_data_indices(self._round_targets,
                                                     client_indexes)
        with obs_trace.tracer.span(
                "async.sync", root=True,
                attrs={"role": "server", "version": self.aggregator.version,
                       "targets": len(self._round_targets),
                       "init": True}) as ssp:
            for rank in self._round_targets:
                msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank,
                              rank)
                msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, wire)
                msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                               assign[rank])
                msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX,
                               self.aggregator.version)
                obs_trace.inject(msg, ssp)
                self._sync_t[rank] = now
                self._outstanding[rank] = self.aggregator.version
                self.send_message(msg)
        self._arm_pour_timer()

    # --- the async upload seam ----------------------------------------------
    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        if self._done:
            return
        sender = msg.get_sender_id()
        if sender not in self._outstanding:
            # replay guard: every sync to a silo expects exactly ONE
            # upload (popped below on first receipt). A second copy —
            # chaos link duplication, a transport retry whose first copy
            # was delivered, a slow silo answering both the original sync
            # and a timeout nudge — would double that silo's weight in
            # the pour and corrupt the arrival-rate EMA with a near-zero
            # gap. The sync path's stale-tag drop played this role; the
            # async path replaces it with the outstanding marker.
            logger.warning(
                "async server: dropping upload from silo %s with no "
                "outstanding sync (duplicate or replayed copy)", sender)
            return
        recv_t = time.time()
        n = float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0))
        update = msg.get(MyMessage.MSG_ARG_KEY_MODEL_UPDATE)
        up_version = msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
        up_version = (self.aggregator.version if up_version is None
                      else int(up_version))
        # deserialize + flatten OUTSIDE the pour lock: full-model wire
        # decodes are model-sized work, and doing them under the lock
        # would serialize every transport thread behind every pour —
        # inflating the very arrival latencies the adaptive staleness
        # cap is estimated from. Only the base-ring read + buffer add
        # (cheap) need the lock.
        if is_compressed_payload(update):
            payload, compressed = decompress_vec(update), True
        else:
            wire = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            payload = np.asarray(tree_flatten_to_vector(
                wire_to_tree(wire, self.aggregator.global_params)),
                np.float32)
            compressed = False
        with self._pour_lock:
            buffered = self.aggregator.add_async_upload(
                sender, payload, n, up_version, recv_t,
                compressed=compressed, trace=obs_trace.extract(msg))
        # arrival-rate observations: latency vs this silo's OWN sync,
        # inter-arrival gap (the arrival-rate posterior), and
        # participation evidence for the dropout posterior
        t0 = self._sync_t.get(sender)
        if t0 is not None:
            self.aggregator.observe_upload(sender, recv_t - t0)
            from ...core.obs import metrics as obs_metrics
            obs_metrics.record_arrival(recv_t - t0)
        self._outstanding.pop(sender, None)
        prev = self._last_arrival.get(sender)
        if prev is not None and 0 <= int(sender) < \
                self.aggregator.silo_stats.n:
            self.aggregator.silo_stats.record_arrival(sender,
                                                      recv_t - prev)
        self.aggregator.observe_round([sender], [sender])
        self._last_arrival[sender] = recv_t
        if buffered >= self.aggregator.k:
            self._pour(reason="buffer")

    # --- the pour loop ------------------------------------------------------
    def _arm_pour_timer(self) -> None:
        if self.pour_timeout_s <= 0 or self._done:
            return
        with self._timer_lock:
            if self._done:
                return
            if self._pour_timer is not None:
                self._pour_timer.cancel()
            self._pour_timer = threading.Timer(self.pour_timeout_s,
                                               self._on_pour_timeout)
            self._pour_timer.daemon = True
            self._pour_timer.start()

    def _on_pour_timeout(self) -> None:
        if self._done:
            return
        if len(self.aggregator.buffer) >= 1:
            # liveness valve: a decimated fleet (crashes, drops) may never
            # fill K — pour what arrived rather than stalling the session
            self._pour(reason="timeout")
        else:
            # empty fire: nothing arrived within the window. Silos with NO
            # outstanding sync are idle for lack of a model — re-sync them
            # always. Silos with a sync outstanding are either slow (still
            # training — leave them alone, a re-sync would just queue
            # duplicate work) or lost their sync/upload to the link — give
            # those a nudge only every SECOND empty fire, so a genuinely
            # slow silo is at most halved into duplicates while a
            # link-lost one still recovers
            self._empty_fires += 1
            online = sorted(self.client_online_status)
            idle = [r for r in online if r not in self._outstanding]
            nudge = idle if self._empty_fires % 2 else online
            logger.warning(
                "async server: pour timeout with empty buffer at version "
                "%d — re-syncing %s (of %d online, %d outstanding)",
                self.aggregator.version, nudge, len(online),
                len(self._outstanding))
            self._sync_ranks(nudge)
            self._arm_pour_timer()

    def _pour(self, reason: str) -> None:
        # the pour is its own trace: it consumes uploads from MANY sync
        # traces, so parentage cannot express the fan-in — LINKS to the
        # K contributing upload spans do, staleness attached per link
        psp = obs_trace.tracer.start_span(
            "pour", root=True,
            attrs={"role": "server", "reason": reason,
                   "version": self.aggregator.version})
        with psp:
            with self._pour_lock:
                if self._done:
                    return
                with obs_trace.span("aggregate",
                                    attrs={"reason": reason}):
                    arrivals = self.aggregator.pour()
                if not arrivals:
                    psp.set_attr("empty", True)
                    self._arm_pour_timer()
                    return
                version = self.aggregator.version  # post-pour version
                self.chaos_ledger.record_pour(
                    version - 1, arrivals,
                    observed={"poured": len(arrivals),
                              "buffered": len(self.aggregator.buffer),
                              "reason": reason,
                              "staleness_cap":
                                  self.aggregator.staleness_cap})
                contributors = sorted({int(a["client"]) for a in arrivals})
                self._save_wire_state(version - 1)
            psp.set_attr("poured", len(arrivals))
            for a in arrivals:
                if a.get("trace"):
                    psp.add_link(a["trace"], client=int(a["client"]),
                                 staleness=int(a["staleness"]),
                                 dispatch_version=int(
                                     a["dispatch_version"]))
            freq = int(getattr(self.args, "frequency_of_the_test", 5)
                       or 5)
            rec: Dict[str, Any] = {
                "round": version - 1, "poured": len(arrivals),
                "staleness_mean": float(np.mean([a["staleness"]
                                                 for a in arrivals])),
                "staleness_max": int(max(a["staleness"]
                                         for a in arrivals)),
            }
            if freq > 0 and ((version - 1) % freq == 0
                             or version >= self.round_num):
                with obs_trace.span("eval",
                                    attrs={"version": version - 1}):
                    stats = self.aggregator.test_on_server()
                if stats:
                    rec.update(stats)
                    logger.info("async server pour %d (staleness mean "
                                "%.2f): %s", version - 1,
                                rec["staleness_mean"], stats)
            with obs_trace.span("host.close",
                                attrs={"version": version - 1}):
                self.history.append(rec)
                mlops.log_round_info(self.round_num, version - 1)
        if version >= self.round_num:
            self.finish_session()
            return
        # non-uniform strategies bench flaky/byzantine silos here: a
        # benched contributor gets no fresh sync (it idles instead of
        # poisoning the next pour), but the empty-fire nudge still
        # reaches every online silo — the probation/redemption path.
        # uniform (default): select_silos returns everyone, unchanged.
        survivors = self.aggregator.select_silos(contributors)
        if len(survivors) < len(contributors):
            logger.info(
                "async server: benching silos %s after pour %d "
                "(reputation/dropout posterior)",
                sorted(set(contributors) - set(survivors)), version - 1)
        self._sync_ranks(survivors)
        self._arm_pour_timer()

    def _sync_ranks(self, ranks: List[int]) -> None:
        """Hand the CURRENT model to the given silos (the ones whose
        updates were just consumed, a reconnecting silo, or — on an empty
        timeout — everyone). Version rides every sync; uploads echo it."""
        if not ranks:
            return
        version = self.aggregator.version
        client_indexes = self.aggregator.client_selection(
            version, int(self.args.client_num_in_total), self.client_num)
        wire = tree_to_wire(self.aggregator.global_params)
        now = time.time()
        assign = self.aggregator.assign_data_indices(ranks, client_indexes)
        # one sync span per batch, a fresh trace per model version: each
        # silo's train/upload joins THIS version's trace, and the pour
        # that eventually consumes the upload links back to it
        with obs_trace.tracer.span(
                "async.sync", root=True,
                attrs={"role": "server", "version": version,
                       "targets": len(ranks)}) as ssp:
            for rank in ranks:
                msg = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                              self.rank, rank)
                msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, wire)
                msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                               assign[rank])
                msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, version)
                obs_trace.inject(msg, ssp)
                if rank not in self._outstanding:
                    # first sync of this outstanding period wins the
                    # clock: a timeout-nudge re-sync must not re-zero a
                    # slow silo's observed latency
                    self._sync_t[rank] = now
                self._outstanding[rank] = version
                self.send_message(msg)

    def _finish_step(self) -> int:
        return int(self.aggregator.version)

    def finish_session(self) -> None:
        self._done = True
        with self._timer_lock:
            if self._pour_timer is not None:
                self._pour_timer.cancel()
                self._pour_timer = None
        super().finish_session()
