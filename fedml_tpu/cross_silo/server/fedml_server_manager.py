"""Cross-silo FL server FSM.

Parity target: reference ``cross_silo/server/fedml_server_manager.py:15`` —
client ONLINE handshake before round 0 (:101-146), ``send_init_msg`` :48,
collect models -> aggregate -> re-sample -> sync (:174), FINISH broadcast.
Runs over any transport backend (in-proc for tests, TCP/gRPC for real WANs).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

import jax
import numpy as np

from ...core import mlops
from ...core.distributed.communication.message import (Message, tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class FedMLServerManager(FedMLCommManager):
    """Rank 0. Client ranks are 1..N."""

    def __init__(self, args, aggregator, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.client_num = int(getattr(args, "client_num_per_round", size - 1))
        self.client_online_status: Dict[int, bool] = {}
        self.is_initialized = False
        import jax.random as jrandom
        self._root_key = jrandom.PRNGKey(
            int(getattr(args, "random_seed", 0)) + 17)
        self.result: Optional[dict] = None
        self.history = []
        self.round_timeout_s = float(getattr(args, "round_timeout_s", 0) or 0)
        self._round_lock = threading.Lock()
        self._round_timer: Optional[threading.Timer] = None

    # --- FSM wiring ---------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client)

    def handle_message_client_status_update(self, msg: Message) -> None:
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        if status == MyMessage.MSG_CLIENT_STATUS_ONLINE:
            self.client_online_status[msg.get_sender_id()] = True
        all_online = len(self.client_online_status) >= self.client_num
        logger.info("server: %d/%d clients online",
                    len(self.client_online_status), self.client_num)
        if all_online and not self.is_initialized:
            self.is_initialized = True
            mlops.log_aggregation_status("RUNNING")
            self.send_init_msg()

    def send_init_msg(self) -> None:
        """(reference :48-86) ship round-0 model + data-silo index."""
        client_indexes = self.aggregator.client_selection(
            self.round_idx, int(self.args.client_num_in_total),
            self.client_num)
        wire = tree_to_wire(self.aggregator.global_params)
        for i, rank in enumerate(sorted(self.client_online_status)):
            msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, wire)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           int(client_indexes[i % len(client_indexes)]))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
            self.send_message(msg)

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        wire = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        params = wire_to_tree(wire, self.aggregator.global_params)
        n = float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0))
        self.aggregator.add_local_trained_result(sender, params, n)
        if not self.aggregator.check_whether_all_receive():
            # elastic rounds (capability beyond the reference, SURVEY §5.3):
            # a dead silo must not stall the barrier forever — arm a
            # timeout that aggregates whatever arrived
            if self.round_timeout_s > 0 and self._round_timer is None:
                this_round = self.round_idx
                self._round_timer = threading.Timer(
                    self.round_timeout_s,
                    lambda: self._on_round_timeout(this_round))
                self._round_timer.daemon = True
                self._round_timer.start()
            return
        self._complete_round()

    def _on_round_timeout(self, round_when_armed: int) -> None:
        # round-validity is re-checked inside _complete_round under the SAME
        # lock acquisition that aggregates — checking here and aggregating in
        # a second acquisition would race a normal completion in the gap and
        # prematurely aggregate the next round's early arrivals.
        self._complete_round(expected_round=round_when_armed,
                             from_timeout=True)

    def _complete_round(self, expected_round: Optional[int] = None,
                        from_timeout: bool = False) -> None:
        with self._round_lock:
            if expected_round is not None and self.round_idx != expected_round:
                return  # round already completed normally
            if not self.aggregator.model_dict:
                return  # already aggregated by a racing path
            if self._round_timer is not None:
                self._round_timer.cancel()
                self._round_timer = None
            if from_timeout:
                logger.warning(
                    "server round %d: timeout with %d/%d models — "
                    "aggregating the silos that reported", self.round_idx,
                    len(self.aggregator.model_dict),
                    self.aggregator.client_num)
            import jax.random as jrandom
            round_key = jrandom.fold_in(self._root_key, self.round_idx)
            self.aggregator.aggregate(round_key)
        stats = self.aggregator.test_on_server()
        rec = {"round": self.round_idx}
        if stats:
            rec.update(stats)
            logger.info("server round %d: %s", self.round_idx, stats)
        self.history.append(rec)
        mlops.log_round_info(self.round_num, self.round_idx)
        with self._round_lock:
            self.round_idx += 1
        if self.round_idx >= self.round_num:
            self.finish_session()
            return
        self.sync_model_to_clients()

    def sync_model_to_clients(self) -> None:
        client_indexes = self.aggregator.client_selection(
            self.round_idx, int(self.args.client_num_in_total),
            self.client_num)
        wire = tree_to_wire(self.aggregator.global_params)
        for i, rank in enumerate(sorted(self.client_online_status)):
            msg = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                          self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, wire)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           int(client_indexes[i % len(client_indexes)]))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
            self.send_message(msg)

    def finish_session(self) -> None:
        for rank in sorted(self.client_online_status):
            self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH,
                                      self.rank, rank))
        last_eval = next((r for r in reversed(self.history) if "test_acc" in r),
                         {})
        self.result = {"params": self.aggregator.global_params,
                       "history": self.history,
                       "final_test_acc": last_eval.get("test_acc"),
                       "rounds": self.round_num}
        mlops.log_aggregation_status("FINISHED")
        self.finish()
