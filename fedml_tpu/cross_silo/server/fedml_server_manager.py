"""Cross-silo FL server FSM.

Parity target: reference ``cross_silo/server/fedml_server_manager.py:15`` —
client ONLINE handshake before round 0 (:101-146), ``send_init_msg`` :48,
collect models -> aggregate -> re-sample -> sync (:174), FINISH broadcast.
Runs over any transport backend (in-proc for tests, TCP/gRPC for real WANs).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

import jax
import numpy as np

from ...core import mlops
from ...core.obs import trace as obs_trace
from ...core.chaos import FaultLedger, FaultPlan
from ...core.collectives import tree_flatten_to_vector
from ...core.distributed.communication.message import (WIRE_DTYPE_BF16,
                                                       WIRE_STATS, Message,
                                                       bf16_wire_to_tree,
                                                       tree_to_wire,
                                                       tree_to_wire_bf16,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.wire import (AdaptiveRatioBounds, adaptive_keep_ratio,
                          decode_update, encode_update, pack_optional_vec,
                          unpack_optional_vec, wire_checkpointer,
                          wire_state_template)
from ...utils.compression import is_compressed_payload, spec_from_args
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class FedMLServerManager(FedMLCommManager):
    """Rank 0. Client ranks are 1..N."""

    # class-level fallbacks: a disabled plan + quorum 1, so FSM methods
    # stay callable on partially-constructed instances (tests via __new__)
    chaos = FaultPlan()
    quorum = 1
    _timeout_graced = False
    _wire_ckpt = None
    _cc_adaptive = None
    _bcast_t0 = None
    _round_targets: list = []
    _round_selected: list = []
    # tracing: one trace per round — the ROOT span covers broadcast →
    # wait → aggregate; WAIT is the explicit straggler-time span between
    # broadcast end and round close (upload receipts land on it as
    # events), so trace_report can attribute the round's wall time
    _round_span = obs_trace.NOOP_SPAN
    _wait_span = obs_trace.NOOP_SPAN

    def __init__(self, args, aggregator, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.client_num = int(getattr(args, "client_num_per_round", size - 1))
        self.client_online_status: Dict[int, bool] = {}
        self.is_initialized = False
        import jax.random as jrandom
        self._root_key = jrandom.PRNGKey(
            int(getattr(args, "random_seed", 0)) + 17)
        self.result: Optional[dict] = None
        self.history = []
        self.round_timeout_s = float(getattr(args, "round_timeout_s", 0) or 0)
        self._round_lock = threading.Lock()
        self._round_timer: Optional[threading.Timer] = None
        # chaos: the server holds the same seeded plan as the clients (it
        # is stateless), so the fault ledger can reconcile what was
        # INJECTED (scheduled dropouts) against what it OBSERVED (silos
        # that actually reported before the round closed)
        self.chaos = FaultPlan.from_args(args)
        self.chaos_ledger = FaultLedger()
        # quorum for the timeout path: below it, grant ONE grace interval
        # before degrading (single source of truth: FedMLAggregator.quorum
        # — read LIVE in _complete_round, because silo selection scales it
        # per round via set_round_expected; a snapshot here would diverge)
        self.quorum = self.aggregator.quorum
        self._timeout_graced = False
        # wire-efficient updates: clients upload compressed deltas that
        # handle_message_receive_model_from_client decompresses; the
        # sync broadcast optionally ships bf16 or (with its own server-side
        # error-feedback residual) a compressed global delta.
        self.cc_spec = spec_from_args(args)
        self._bcast_prev_vec = None   # what the CLIENTS have reconstructed
        self._bcast_residual = None
        self._cc_rng = jax.random.PRNGKey(
            int(getattr(args, "random_seed", 0)) + 53)
        # adaptive keep-ratio schedule (core/wire/adaptive): the stats
        # store's observed upload latency + dropout posterior pick each
        # round's ratio within configured bounds; the chosen ratio rides
        # the sync so client uplinks agree. Off by default.
        self._cc_adaptive = None
        if (getattr(args, "comm_compression_adaptive", False)
                and self.cc_spec is not None
                and self.cc_spec.method is not None):
            rmax = float(getattr(args, "comm_compression_ratio_max", None)
                         or self.cc_spec.ratio)
            rmin = float(getattr(args, "comm_compression_ratio_min", None)
                         or max(rmax / 4.0, 1e-4))
            budget = getattr(args, "comm_compression_latency_budget_s", None)
            self._cc_adaptive = AdaptiveRatioBounds(
                rmin, rmax, float(budget) if budget else None)
        # crash-resume: the broadcast base + server-side EF residual join
        # the round checkpoint (core/wire/state) — see the client manager
        self._wire_ckpt = None
        if self.cc_spec is not None and self.cc_spec.method is not None:
            self._wire_ckpt = wire_checkpointer(args, "server")
            self._restore_wire_state()
        # bytes-on-wire ledger mark for per-round accounting (counts this
        # process's encodes: all S2C traffic; in-proc sessions also count
        # the client threads' uploads, which is what the bench wants)
        self._wire_mark = WIRE_STATS.total_bytes
        # silo selection (core/selection): the broadcast timestamp clocks
        # per-silo upload latencies; _round_targets is the rank set the
        # round expects (all online ranks at default knobs — byte-
        # identical FSM; non-uniform strategies may bench flaky silos)
        self._bcast_t0: Optional[float] = None
        self._round_targets: list = []

    def _global_f32_vec(self) -> np.ndarray:
        """The global model flattened to a host f32 vector — the SINGLE
        definition of the base-tracking representation the compressed-delta
        protocol hangs on (clients flatten the same way via params_to_vec;
        any divergence in dtype/ordering silently corrupts every delta)."""
        return np.asarray(
            tree_flatten_to_vector(self.aggregator.global_params),
            np.float32)

    # --- wire-state checkpointing (ISSUE 19 satellite) ----------------------
    def _save_wire_state(self, completed_round: int) -> None:
        if self._wire_ckpt is None or not self._wire_ckpt.enabled:
            return
        d = int(self._global_f32_vec().shape[0])
        bf, bv = pack_optional_vec(self._bcast_prev_vec, d)
        rf, res = pack_optional_vec(self._bcast_residual, d)
        self._wire_ckpt.maybe_save(completed_round, {
            "round": np.asarray(completed_round, np.int32),
            "bcast_prev_vec_set": bf, "bcast_prev_vec": bv,
            "bcast_residual_set": rf, "bcast_residual": res})

    def _restore_wire_state(self) -> None:
        if self._wire_ckpt is None or not self._wire_ckpt.enabled:
            return
        got = self._wire_ckpt.latest(wire_state_template(
            int(self._global_f32_vec().shape[0]),
            ("bcast_prev_vec", "bcast_residual")))
        if got is None:
            return
        step, st = got
        self._bcast_prev_vec = unpack_optional_vec(
            st["bcast_prev_vec_set"], st["bcast_prev_vec"])
        self._bcast_residual = unpack_optional_vec(
            st["bcast_residual_set"], st["bcast_residual"])
        logger.info("server: restored wire state from round %d", step)

    # --- FSM wiring ---------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client)

    def handle_message_client_status_update(self, msg: Message) -> None:
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        if status == MyMessage.MSG_CLIENT_STATUS_ONLINE:
            self.client_online_status[msg.get_sender_id()] = True
        all_online = len(self.client_online_status) >= self.client_num
        logger.info("server: %d/%d clients online",
                    len(self.client_online_status), self.client_num)
        if all_online and not self.is_initialized:
            self.is_initialized = True
            mlops.log_aggregation_status("RUNNING")
            self.send_init_msg()

    def send_init_msg(self) -> None:
        """(reference :48-86) ship round-0 model + data-silo index. Always
        dense: the init model is the common reference both sides compute
        deltas against (a ``compress`` broadcast needs every client to hold
        the exact vector the server tracks in ``_bcast_prev_vec``)."""
        self._begin_round_trace()
        client_indexes = self.aggregator.client_selection(
            self.round_idx, int(self.args.client_num_in_total),
            self.client_num)
        bsp = obs_trace.tracer.start_span(
            "broadcast", parent=self._round_span,
            attrs={"round_idx": self.round_idx})
        with bsp:  # payload build INSIDE the span: prep time is broadcast
            # time, and a prep exception still emits the span (error attr)
            wire = tree_to_wire(self.aggregator.global_params)
            if self.cc_spec is not None and self.cc_spec.method is not None:
                # whenever clients upload deltas the server must track the
                # base they refer to (what the clients reconstruct) — for
                # EVERY broadcast mode, including dense 'full': the upload
                # handler captures this base under _round_lock, so a
                # round-timeout aggregation racing a late upload cannot
                # swap the base mid-flight. After a dense init it is the
                # exact global vector. Broadcast-only specs (method None)
                # get no deltas: skip.
                self._bcast_prev_vec = self._global_f32_vec()
            self._round_targets = sorted(self.client_online_status)
            self._round_selected = list(self._round_targets)
            self._bcast_t0 = time.time()
            assign = self.aggregator.assign_data_indices(
                self._round_targets, client_indexes)
            bsp.set_attr("targets", len(self._round_targets))
            for rank in self._round_targets:
                msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank,
                              rank)
                msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, wire)
                msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                               assign[rank])
                msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX,
                               self.round_idx)
                # the broadcast span's context rides every sync: each
                # silo's train/upload spans join THIS round's trace
                obs_trace.inject(msg, bsp)
                self.send_message(msg)
        self._begin_wait_span()
        if self.chaos.enabled:
            # under chaos the whole round's uploads can vanish — the
            # timeout must run from the broadcast, not from an upload
            # that may never come
            self._arm_round_timer()

    # --- round tracing (core/obs) -------------------------------------------
    def _begin_round_trace(self) -> None:
        """Open a fresh trace for the round about to broadcast (root=True:
        round boundaries are trace boundaries)."""
        self._end_round_trace()  # a skipped round may have left one open
        self._round_span = obs_trace.tracer.start_span(
            "round", root=True, attrs={"role": "server",
                                       "round_idx": self.round_idx})

    def _begin_wait_span(self) -> None:
        """The straggler-time span: broadcast done → round close. Upload
        receipts land on it as events (from transport threads — the span
        is internally locked)."""
        self._wait_span = obs_trace.tracer.start_span(
            "wait.uploads", parent=self._round_span,
            attrs={"round_idx": self.round_idx})

    def _end_round_trace(self, **attrs) -> None:
        self._wait_span.end()
        self._wait_span = obs_trace.NOOP_SPAN
        for k, v in attrs.items():
            self._round_span.set_attr(k, v)
        self._round_span.end()
        self._round_span = obs_trace.NOOP_SPAN

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        recv_t = time.time()
        # receipt lands on the wait span: an event with the sender plus a
        # link to the silo's upload span (its context rode the wire), so
        # the round trace shows WHEN each straggler finally reported.
        # Recorded only after the stale check resolves: a chaos-delayed
        # upload from a timed-out round belongs to the OLD round's trace,
        # and must not read as a receipt the current round consumed.
        up_ctx = obs_trace.extract(msg)

        def _record_receipt(stale: bool) -> None:
            # a silo fast enough to upload while the server is still
            # inside the broadcast send loop beats _begin_wait_span(); the
            # receipt then falls back to the ROUND span (live since before
            # the broadcast) instead of vanishing into the NOOP wait span
            sp = self._wait_span
            if sp is obs_trace.NOOP_SPAN:
                sp = self._round_span
            sp.add_event("upload", sender=int(sender), stale=bool(stale))
            if up_ctx is not None:
                sp.add_link(up_ctx, sender=int(sender), stale=bool(stale))

        n = float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0))
        update = msg.get(MyMessage.MSG_ARG_KEY_MODEL_UPDATE)
        if is_compressed_payload(update):  # delta vs the broadcast model
            up_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
            delta = decode_update(update)  # stateless: outside the lock
            with self._round_lock:
                stale = (up_round is not None
                         and int(up_round) != self.round_idx)
                if not stale:
                    # the add must share the stale check's lock
                    # acquisition: a round-timeout aggregation slipping
                    # between them would advance the round and let this
                    # round's model land in the NEXT round's pool
                    self.aggregator.add_local_trained_delta(
                        sender, delta, n, base_vec=self._bcast_prev_vec)
            if stale:
                # a straggler from a timed-out round: its delta refers
                # to a base the server already advanced past —
                # reconstructing against the new base would store a
                # model that is neither the sender's nor anyone's
                logger.warning(
                    "server: dropping stale compressed update from silo "
                    "%s (round %s, now %d)", sender, up_round,
                    self.round_idx)
                _record_receipt(stale=True)
                return
        else:
            wire = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            params = wire_to_tree(wire, self.aggregator.global_params)
            up_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
            with self._round_lock:
                # the round tag rides dense uploads only under link chaos
                # (delayed/duplicated copies can outlive their round);
                # check-and-add shares one lock acquisition like the
                # compressed path, so a racing timeout aggregation cannot
                # advance the round between them
                stale = (up_round is not None
                         and int(up_round) != self.round_idx)
                if not stale:
                    self.aggregator.add_local_trained_result(sender, params,
                                                             n)
            if stale:
                logger.warning(
                    "server: dropping stale upload from silo %s "
                    "(round %s, now %d)", sender, up_round, self.round_idx)
                _record_receipt(stale=True)
                return
        _record_receipt(stale=False)
        if self._bcast_t0 is not None:
            # broadcast→receipt wall time: the silo-selection latency
            # signal (the silo's train time + both wire hops — what the
            # round critical path pays for this silo). Recorded only for
            # CURRENT-round uploads: a chaos-delayed duplicate from a
            # past round would log a bogus cross-round latency and skew
            # which silos a non-uniform strategy benches.
            self.aggregator.observe_upload(sender, recv_t - self._bcast_t0)
        if not self.aggregator.check_whether_all_receive():
            # elastic rounds (capability beyond the reference, SURVEY §5.3):
            # a dead silo must not stall the barrier forever — arm a
            # timeout that aggregates whatever arrived
            self._arm_round_timer()
            return
        self._complete_round()

    def _arm_round_timer(self) -> None:
        """Idempotent per round: arm the elastic-round timeout (legacy
        seam: the first upload; chaos seam: the broadcast itself, because
        under injected dropout/link loss a round can produce ZERO uploads
        and a timer armed only by uploads would never fire)."""
        if self.round_timeout_s > 0 and self._round_timer is None:
            this_round = self.round_idx
            self._round_timer = threading.Timer(
                self.round_timeout_s,
                lambda: self._on_round_timeout(this_round))
            self._round_timer.daemon = True
            self._round_timer.start()

    def _on_round_timeout(self, round_when_armed: int) -> None:
        # round-validity is re-checked inside _complete_round under the SAME
        # lock acquisition that aggregates — checking here and aggregating in
        # a second acquisition would race a normal completion in the gap and
        # prematurely aggregate the next round's early arrivals.
        self._complete_round(expected_round=round_when_armed,
                             from_timeout=True)

    def _complete_round(self, expected_round: Optional[int] = None,
                        from_timeout: bool = False) -> None:
        skipped_round: Optional[int] = None
        with self._round_lock:
            if expected_round is not None and self.round_idx != expected_round:
                return  # round already completed normally
            if self._round_timer is not None:
                self._round_timer.cancel()
                self._round_timer = None
            reported = len(self.aggregator.model_dict)
            # read the aggregator's CURRENT quorum: silo selection may
            # have scaled it to this round's shrunken expected cohort
            quorum_now = getattr(getattr(self, "aggregator", None),
                                 "quorum", None) or self.quorum
            if from_timeout:
                if reported < quorum_now and not self._timeout_graced:
                    # tolerance: below quorum (or zero reports), grant ONE
                    # grace interval — stragglers and compile-skewed
                    # first rounds beat averaging a sliver of the cohort.
                    # One interval only: under injected dropout a missing
                    # silo stays missing for THIS round forever, so
                    # unbounded re-arming would stall the session.
                    self._timeout_graced = True
                    logger.warning(
                        "server round %d: timeout with %d/%d models — "
                        "below quorum %d, granting one grace interval",
                        self.round_idx, reported,
                        self.aggregator.client_num, quorum_now)
                    this_round = self.round_idx
                    self._round_timer = threading.Timer(
                        self.round_timeout_s,
                        lambda: self._on_round_timeout(this_round))
                    self._round_timer.daemon = True
                    self._round_timer.start()
                    return
                if reported == 0:
                    if not self.chaos.enabled:
                        # legacy seam: without chaos the timer is armed by
                        # the first upload, so a later upload will re-arm
                        # — keep waiting rather than advancing past a
                        # round nobody saw
                        return
                    # chaos: the whole round's uploads vanished (every
                    # silo dropped / every upload lost) — skip the round:
                    # the global model is unchanged, re-broadcasting the
                    # SAME round would deterministically re-drop the same
                    # silos, so advance and let the next round's plan roll
                    skipped_round = self.round_idx
                    self.chaos_ledger.record_round(
                        skipped_round,
                        injected={"dropped": sorted(
                            self.client_online_status)},
                        observed={"expected": self.aggregator.client_num,
                                  "reported": 0, "timeout": True,
                                  "skipped": True})
                    self._timeout_graced = False
                    self.round_idx += 1
                    self._end_round_trace(skipped=True, reported=0)
                else:
                    logger.warning(
                        "server round %d: timeout with %d/%d models — "
                        "aggregating the silos that reported",
                        self.round_idx, reported,
                        self.aggregator.client_num)
            if skipped_round is None:
                if not self.aggregator.model_dict:
                    return  # already aggregated by a racing path
                if self.chaos.enabled:
                    ranks = sorted(self.client_online_status)
                    faults = self.chaos.round_faults(self.round_idx, ranks)
                    self.chaos_ledger.record_round(
                        self.round_idx,
                        injected={"dropped": list(faults.dropped),
                                  "stragglers": dict(faults.work_scale)},
                        observed={"expected": self.aggregator.client_num,
                                  "reported": reported,
                                  "timeout": bool(from_timeout)})
                import jax.random as jrandom
                # quorum history for silo selection: which of the
                # SELECTED silos actually reported before the round
                # closed (benched silos losing the shrunken barrier's
                # race is not dropout evidence — but a benched silo that
                # reports anyway heals: the redemption path)
                self.aggregator.observe_round(
                    list(self.aggregator.model_dict),
                    self._round_selected
                    or sorted(self.client_online_status))
                round_key = jrandom.fold_in(self._root_key, self.round_idx)
                # the wait is over: everything from here is server work
                self._wait_span.set_attr("reported", reported)
                self._wait_span.set_attr("from_timeout",
                                         bool(from_timeout))
                self._wait_span.end()
                self._wait_span = obs_trace.NOOP_SPAN
                with obs_trace.tracer.span(
                        "aggregate", parent=self._round_span,
                        attrs={"round_idx": self.round_idx,
                               "reported": reported}):
                    self.aggregator.aggregate(round_key)
                # close the round under the SAME lock acquisition that
                # aggregates: a straggler arriving during the (slow) server
                # eval below must already see the new round_idx, or its
                # compressed delta would pass the stale check and be
                # reconstructed against the advanced base
                completed_round = self.round_idx
                self.round_idx += 1
                self._timeout_graced = False
        if skipped_round is not None:
            logger.warning("server round %d: zero uploads after grace — "
                           "skipping the round", skipped_round)
            if self.round_idx >= self.round_num:
                self.finish_session()
            else:
                self.sync_model_to_clients()
            return
        with obs_trace.tracer.span("eval", parent=self._round_span,
                                   attrs={"round_idx": completed_round}):
            stats = self.aggregator.test_on_server()
        with obs_trace.tracer.span("host.close", parent=self._round_span,
                                   attrs={"round_idx": completed_round}):
            rec = {"round": completed_round}
            if stats:
                rec.update(stats)
                logger.info("server round %d: %s", completed_round, stats)
            # bytes-on-wire this round (diff of the process-wide ledger)
            total = WIRE_STATS.total_bytes
            rec["wire_bytes"] = total - self._wire_mark
            self._wire_mark = total
            mlops.log_comm_round(completed_round, rec["wire_bytes"],
                                 compression=getattr(self.cc_spec,
                                                     "method", None))
            self.history.append(rec)
            mlops.log_round_info(self.round_num, completed_round)
            self._save_wire_state(completed_round)
        self._end_round_trace(reported=len(self._round_selected),
                              wire_bytes=rec["wire_bytes"])
        if self.round_idx >= self.round_num:
            self.finish_session()
            return
        self.sync_model_to_clients()

    def _round_ratio(self) -> Optional[float]:
        """The adaptive schedule's keep-ratio for the round about to
        broadcast (None when the knob is off — nothing rides the wire)."""
        if self._cc_adaptive is None:
            return None
        return adaptive_keep_ratio(
            self._cc_adaptive,
            getattr(self.aggregator, "silo_stats", None),
            self._round_targets or sorted(self.client_online_status))

    def _sync_payload(self):
        """Build the per-round sync payload once (shared by every client):
        list of (param_key, value) pairs added to each sync message."""
        spec = self.cc_spec
        ratio = self._round_ratio()
        extra = []
        if ratio is not None:
            import dataclasses
            spec = dataclasses.replace(spec, ratio=ratio)
            extra = [(MyMessage.MSG_ARG_KEY_CC_RATIO, float(ratio))]
        if (spec is not None and spec.broadcast == "compress"
                and self._bcast_prev_vec is not None):
            # ship the compressed delta of the global model vs what the
            # clients currently hold; the server's own error-feedback
            # residual carries the truncated mass — and _bcast_prev_vec
            # advances by the DECODED delta so it keeps tracking the
            # clients' reconstruction, not the exact global. The
            # decompress_vec of our own blob is deliberate: it is the
            # same host routine every client runs, so the tracked base
            # is BIT-identical to theirs — the algebraic shortcut
            # (comp - residual) is not bit-exact in f32 and would let
            # the bases drift apart by an accumulating rounding gap
            enc = encode_update(
                self._global_f32_vec(), base=self._bcast_prev_vec,
                spec=spec, residual=self._bcast_residual,
                rng=jax.random.fold_in(self._cc_rng, self.round_idx),
                msg_type=MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
            self._bcast_residual = enc.residual
            self._bcast_prev_vec = decode_update(enc.payload,
                                                 base=self._bcast_prev_vec)
            return [(MyMessage.MSG_ARG_KEY_MODEL_UPDATE, enc.payload)] + extra
        if spec is not None and spec.broadcast == "bf16":
            wire = tree_to_wire_bf16(self.aggregator.global_params)
            if spec.method is not None:
                # the clients reconstruct the bf16 ROUNDING of the global —
                # track that as the base their compressed deltas refer to
                # (adding deltas to the exact f32 global instead would fold
                # the broadcast's rounding gap into every aggregate).
                # Decode the wire payload with the same routine the clients
                # run, so the tracked base is definitionally what they hold
                self._bcast_prev_vec = np.asarray(tree_flatten_to_vector(
                    bf16_wire_to_tree(wire, self.aggregator.global_params)),
                    np.float32)
            return [(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, wire),
                    (MyMessage.MSG_ARG_KEY_WIRE_DTYPE, WIRE_DTYPE_BF16)] \
                + extra
        if spec is not None and spec.method is not None:
            # dense 'full' broadcast with compressed uplinks: the clients
            # will train from (and delta against) the exact f32 global —
            # refresh the tracked base now, before any client can reply
            self._bcast_prev_vec = self._global_f32_vec()
        return [(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                 tree_to_wire(self.aggregator.global_params))] + extra

    def sync_model_to_clients(self) -> None:
        self._begin_round_trace()
        client_indexes = self.aggregator.client_selection(
            self.round_idx, int(self.args.client_num_in_total),
            self.client_num)
        online = sorted(self.client_online_status)
        selected = self.aggregator.select_silos(online)
        if len(selected) < len(online):
            # non-uniform strategy benched flaky silos: shrink this
            # round's all-received barrier so it does not wait out the
            # timeout for silos the history says will not report. The
            # broadcast still goes to EVERYONE — a benched silo that does
            # report is aggregated and heals its posterior (redemption),
            # it just no longer holds the round hostage.
            self.aggregator.set_round_expected(len(selected))
            logger.info(
                "server round %d: silo selection benched %s (of %d online)",
                self.round_idx, sorted(set(online) - set(selected)),
                len(online))
        mlops.log_selection(
            round_idx=self.round_idx,
            strategy=self.aggregator.selection_strategy,
            sampled=selected,
            excluded=sorted(set(online) - set(selected)),
            target_n=len(selected))
        self._round_targets = online
        self._round_selected = selected
        bsp = obs_trace.tracer.start_span(
            "broadcast", parent=self._round_span,
            attrs={"round_idx": self.round_idx, "targets": len(online)})
        with bsp:  # payload build INSIDE the span: prep time is broadcast
            # time, and a prep exception still emits the span (error attr)
            payload = self._sync_payload()
            self._bcast_t0 = time.time()
            # DATA-index assignment: legacy round-robin by default; the
            # `scored` knob routes the first-sampled indices to the silos
            # the stats store scores most deliverable (assign_data_indices)
            assign = self.aggregator.assign_data_indices(online,
                                                         client_indexes)
            for rank in online:
                msg = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                              self.rank, rank)
                for key, value in payload:
                    msg.add_params(key, value)
                msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                               assign[rank])
                msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX,
                               self.round_idx)
                obs_trace.inject(msg, bsp)  # see send_init_msg
                self.send_message(msg)
        self._begin_wait_span()
        if self.chaos.enabled:
            self._arm_round_timer()  # see send_init_msg

    def _finish_step(self) -> int:
        """Step stamped on the end-of-run metrics snapshot (the async
        manager progresses by aggregator version, not round_idx)."""
        return int(self.round_idx)

    def finish_session(self) -> None:
        self._end_round_trace()  # a timeout-skipped final round leaves one
        # final metrics snapshot before the FINISH broadcast: the run log
        # must carry the whole session's instruments, not just the last
        # cadence boundary's
        from ...core.obs import metrics as obs_metrics
        obs_metrics.flush_final(step=self._finish_step())
        for rank in sorted(self.client_online_status):
            self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH,
                                      self.rank, rank))
        last_eval = next((r for r in reversed(self.history) if "test_acc" in r),
                         {})
        self.result = {"params": self.aggregator.global_params,
                       "history": self.history,
                       "final_test_acc": last_eval.get("test_acc"),
                       "rounds": self.round_num}
        mlops.log_aggregation_status("FINISHED")
        # flush pending async wire-state saves before teardown — an
        # unawaited orbax commit races interpreter shutdown and loses
        # the final round's residual state
        if self._wire_ckpt is not None:
            self._wire_ckpt.close()
        self.finish()
