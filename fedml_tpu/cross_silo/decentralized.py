"""Decentralized (gossip) FL as a REAL distributed runtime — no server;
every node trains locally and exchanges parameters with its topology
neighbors as Messages over the comm stack (INPROC threads, TCP, or gRPC
across OS processes).

Parity target: reference ``simulation/mpi/decentralized_framework/``
(``decentralized_worker.py`` send-to-neighbors / wait-for-neighbors over
MPI) driving ``core/distributed/topology/symmetric_topology_manager.py:7``.
Here each node derives the SAME row-stochastic mixing matrix from the
shared (deterministic) topology manager, ships its locally-trained
parameters to every neighbor, and applies ``p_i <- sum_j W[i,j] p_j``
once all in-neighbor parameters for the round have arrived — out-of-order
rounds are buffered, so a fast neighbor can run ahead by a round without
stalling anyone.

The SP simulator (``simulation/sp/decentralized.py``) fuses the same
round into one jitted program (vmapped local SGD + one einsum mix) and on
a mesh the mix is ``ppermute`` per edge; this module is the identical
protocol in its message-passing form — the parity test asserts the same
trajectory. Node-local math here is jitted JAX: the local training step
and the weighted mix are each one compiled program per node.

Rank 0 doubles as the session's reporter: after the last round every node
sends it their final model; it publishes the average-model accuracy and
consensus distance as the run result (the reference's eval worker role).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.algframe.client_trainer import make_trainer_spec
from ..core.algframe.local_training import evaluate
from ..core.algframe.types import TrainHyper
from ..core.chaos import FaultPlan
from ..core.collectives import tree_flatten_to_vector, vector_to_tree_like
from ..core.distributed.communication.backoff import backoff_delays
from ..core.distributed.communication.message import (Message, tree_to_wire,
                                                      wire_to_tree)
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..core.distributed.topology import SymmetricTopologyManager
from ..core.wire import decode_update, encode_update
from ..utils.compression import CommCompressionSpec, is_compressed_payload

logger = logging.getLogger(__name__)


class GossipMsg:
    N2N_PARAMS = 301   # trained params -> each neighbor, tagged with round
    N2Z_FINAL = 302    # final params -> rank 0 for the session result
    Z2N_FINISH = 303   # rank 0 -> all: session done

    K_PARAMS = "params"
    K_ROUND = "round_idx"


class GossipNodeManager(FedMLCommManager):
    """One gossip node (rank == node index == data silo index)."""

    def __init__(self, args, fed, bundle, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.fed = fed
        self.n = size
        self.rounds = int(getattr(args, "comm_round", 1))
        spec = make_trainer_spec(fed, bundle)
        self.spec = spec
        import copy
        from ..optimizers.registry import create_optimizer
        inner = copy.copy(args)
        inner.federated_optimizer = "FedAvg"  # local step is plain SGD
        self.opt = create_optimizer(inner, spec)
        tm = SymmetricTopologyManager(
            self.n, neighbor_num=int(getattr(args, "topology_neighbors", 2)
                                     or 2))
        tm.generate_topology()
        self.W = np.asarray(tm.mixing_matrix())
        # peers i mixes FROM (row) == peers that need i's params (symmetric)
        self.neighbors = sorted(
            j for j in range(self.n)
            if self.W[self.rank, j] > 0 and j != self.rank)
        self._neighbor_w = [float(self.W[self.rank, j])
                            for j in self.neighbors]
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(rng)
        p0 = bundle.init(init_rng, fed.train.x[0, 0])
        self.params = p0
        self._template = p0
        cid = min(self.rank, fed.num_clients - 1)
        self.cdata = jax.tree_util.tree_map(lambda a: a[cid], fed.train)
        self.hyper = TrainHyper(
            learning_rate=jnp.float32(args.learning_rate),
            epochs=int(getattr(args, "epochs", 1)))
        self._train = jax.jit(self._train_impl)
        self._mix = jax.jit(self._mix_impl)
        self._evaluate = jax.jit(
            lambda p, x, y, m: evaluate(spec, p, x, y, m))
        self.round_idx = 0
        # round -> {sender: params}; buffers early arrivals from fast peers
        self._inbox: Dict[int, Dict[int, Any]] = {}
        self._trained: Optional[Any] = None
        self._finals: Dict[int, Any] = {}
        self.history: List[Dict[str, Any]] = []
        self.result: Optional[dict] = None
        # chaos tolerance: gossip has no server to time out a round, so a
        # lost N2N_PARAMS frame would deadlock BOTH endpoints. Under
        # injected link faults a monitor thread retransmits recent-round
        # params whenever progress stalls (backoff-paced via the shared
        # helper; receivers are idempotent, so duplicates are free).
        self.chaos_plan = FaultPlan.from_args(args)
        self._stop_resend = threading.Event()
        self._sent_wires: Dict[int, Any] = {}  # recent rounds' own params
        self._final_wire: Optional[Any] = None
        # gossip compression (core/wire, ISSUE 19): after a dense round-0
        # seed, each node ships ONE compressed delta per round vs its own
        # previous broadcast reconstruction; receivers keep a per-sender
        # reconstruction and decode in round order at mix time. Off by
        # default: dense N2N wires, byte-identical. The chaos resend loop
        # replays cached blobs safely — decode is keyed by round and a
        # round's delta is applied exactly once (at mix).
        method = getattr(args, "gossip_compression", None)
        self.gc_spec: Optional[CommCompressionSpec] = None
        if method:
            self.gc_spec = CommCompressionSpec(
                method=str(method),
                ratio=float(getattr(args, "comm_compression_ratio", 0.1)),
                levels=int(getattr(args, "comm_quantize_levels", 127)))
        self._gc_sent_recon: Optional[np.ndarray] = None  # neighbors' copy of ME
        self._gc_residual: Optional[np.ndarray] = None
        self._gc_recv_recon: Dict[int, np.ndarray] = {}   # my copy of each peer
        self._gc_rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 131),
            self.rank)

    # --- jitted math --------------------------------------------------------
    def _train_impl(self, params, round_key, hyper):
        key = jax.random.fold_in(round_key, self.rank)
        out = self.opt.local_train(params, {}, {}, self.cdata, key, hyper)
        return jax.tree_util.tree_map(jnp.add, params, out.update)

    def _mix_impl(self, own, neighbor_params):
        """p_i <- W[i,i]*own + sum_j W[i,j]*p_j, accumulated in f32 over
        neighbors in ascending-j order (matching the SP sim's einsum
        contraction up to float reassociation)."""
        w_self = float(self.W[self.rank, self.rank])
        acc = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32) * w_self, own)
        for pj, w in zip(neighbor_params, self._neighbor_w):
            acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32) * w, acc, pj)
        return jax.tree_util.tree_map(
            lambda a, t: a.astype(t.dtype), acc, own)

    # --- FSM ----------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(GossipMsg.N2N_PARAMS,
                                              self._on_params)
        self.register_message_receive_handler(GossipMsg.N2Z_FINAL,
                                              self._on_final)
        self.register_message_receive_handler(GossipMsg.Z2N_FINISH,
                                              self._on_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        if self.chaos_plan.injects_link_faults:
            t = threading.Thread(target=self._resend_loop, daemon=True)
            t.start()
        try:
            self._kick_round()
            self.com_manager.handle_receive_message()
        finally:
            self._stop_resend.set()

    def _kick_round(self) -> None:
        """Train locally and ship the trained params to every neighbor."""
        round_key = jax.random.fold_in(self.rng, self.round_idx)
        self._trained = self._train(
            self.params, round_key,
            self.hyper.replace(round_idx=jnp.int32(self.round_idx)))
        if self.gc_spec is not None and self._gc_sent_recon is not None:
            # compressed rounds: the wire is the EF-compressed delta of
            # this round's trained params vs what the neighbors hold; our
            # tracked copy advances by DECODING our own blob (the same
            # host routine every receiver runs — bit-identical bases)
            enc = encode_update(
                np.asarray(tree_flatten_to_vector(self._trained),
                           np.float32),
                base=self._gc_sent_recon, spec=self.gc_spec,
                residual=self._gc_residual,
                rng=jax.random.fold_in(self._gc_rng, self.round_idx),
                msg_type=GossipMsg.N2N_PARAMS)
            self._gc_residual = enc.residual
            self._gc_sent_recon = decode_update(enc.payload,
                                                base=self._gc_sent_recon)
            wire = enc.payload
        else:
            wire = tree_to_wire(self._trained)
            if self.gc_spec is not None:
                # dense seed round: every neighbor now holds exactly this
                self._gc_sent_recon = np.asarray(
                    tree_flatten_to_vector(self._trained), np.float32)
        # retransmission cache: a SLOW neighbor may still need our round-r
        # params after we advanced to r+1 (its copy was lost) — keep the
        # last few rounds' wires so the resend loop can replay them
        self._sent_wires[self.round_idx] = wire
        for r in sorted(self._sent_wires):
            if r < self.round_idx - 2:
                del self._sent_wires[r]
        for j in self.neighbors:
            m = Message(GossipMsg.N2N_PARAMS, self.rank, j)
            m.add_params(GossipMsg.K_PARAMS, wire)
            m.add_params(GossipMsg.K_ROUND, self.round_idx)
            self._send_with_retry(m)
        self._try_mix()

    def _resend_loop(self) -> None:
        """Chaos-link tolerance: when no progress happens for a (jittered,
        backoff-growing) interval, retransmit the cached recent-round
        params to every neighbor — fresh sends draw fresh link-fault
        decisions, so seeded loss eventually lets a copy through. Resets
        to the fast cadence whenever progress resumes."""
        def fresh():
            return backoff_delays(base_s=0.5, factor=2.0, max_s=4.0,
                                  seed=(self.rank + 1) * 7919)

        delays = fresh()
        marker = None
        while not self._stop_resend.wait(next(delays)):
            cur = (self.round_idx, len(self._inbox.get(self.round_idx, {})),
                   self._final_wire is not None, len(self._finals))
            if cur != marker:
                marker = cur
                delays = fresh()
                continue
            try:
                # ALWAYS replay the cached round wires — even after this
                # node finalized, a slower neighbor may still be waiting
                # on our round-r params (skipping them here deadlocked
                # the pair: we only nagged rank 0 while the neighbor
                # could never finish its round)
                # snapshot: _kick_round trims this dict on the main
                # thread — iterating it live would raise mid-cycle and
                # the broad except below would silently skip the whole
                # retransmission pass
                for r, wire in sorted(list(self._sent_wires.items())):
                    for j in self.neighbors:
                        m = Message(GossipMsg.N2N_PARAMS, self.rank, j)
                        m.add_params(GossipMsg.K_PARAMS, wire)
                        m.add_params(GossipMsg.K_ROUND, r)
                        self.send_message(m)
                if self._final_wire is not None and self.rank != 0:
                    m = Message(GossipMsg.N2Z_FINAL, self.rank, 0)
                    m.add_params(GossipMsg.K_PARAMS, self._final_wire)
                    self.send_message(m)
                logger.info("gossip node %d: stalled at round %d — "
                            "retransmitted params to neighbors", self.rank,
                            self.round_idx)
            except Exception as e:
                logger.debug("gossip node %d resend failed: %s", self.rank,
                             e)

    def _send_with_retry(self, msg: Message, timeout_s: float = 60.0) -> None:
        """Peer processes come up at their own pace and there is no server
        to sequence the handshake — round-0 sends retry until the
        neighbor's listener is reachable. Rides the shared transport
        backoff helper (deadline-bound, jittered) instead of the old
        hand-rolled sleep loop."""
        from ..core.distributed.communication.backoff import \
            retry_with_backoff
        retry_with_backoff(
            lambda: self.send_message(msg),
            max_attempts=1_000_000,  # deadline-bound, not attempt-bound
            base_s=0.2, max_s=2.0, deadline_s=timeout_s,
            describe=f"gossip node {self.rank} send to "
                     f"{msg.get_receiver_id()}")

    def _on_params(self, msg: Message) -> None:
        r = int(msg.get(GossipMsg.K_ROUND))
        if r < self.round_idx:
            return  # stale retransmission of a round we already mixed
        sender = msg.get_sender_id()
        # the RAW wire is buffered and decoded at mix time: compressed
        # deltas form a per-sender chain that must be applied in round
        # order exactly once — mix time is the only point with both
        # guarantees (duplicates within a round overwrite the same blob)
        self._inbox.setdefault(r, {})[sender] = msg.get(GossipMsg.K_PARAMS)
        self._try_mix()

    def _decode_neighbor(self, sender: int, wire) -> Any:
        """Inbox wire -> params tree, advancing the per-sender
        reconstruction when the sender ships compressed deltas."""
        if is_compressed_payload(wire):
            base = self._gc_recv_recon.get(sender)
            if base is None:
                raise RuntimeError(
                    f"gossip node {self.rank}: compressed params from "
                    f"{sender} before its dense seed round")
            vec = decode_update(wire, base=base)
            self._gc_recv_recon[sender] = vec
            return vector_to_tree_like(vec, self._template)
        params = wire_to_tree(wire, self._template)
        if self.gc_spec is not None:
            self._gc_recv_recon[sender] = np.asarray(
                tree_flatten_to_vector(params), np.float32)
        return params

    def _try_mix(self) -> None:
        box = self._inbox.get(self.round_idx, {})
        if self._trained is None or len(box) < len(self.neighbors):
            return
        ordered = [self._decode_neighbor(j, box[j]) for j in sorted(box)]
        self.params = self._mix(self._trained, ordered)
        del self._inbox[self.round_idx]
        self._trained = None
        if self.rank == 0 and self.round_idx < self.rounds - 1:
            # the last round's record is written by the final report (it
            # carries the avg-model accuracy)
            self.history.append({"round": self.round_idx})
        self.round_idx += 1
        if self.round_idx >= self.rounds:
            self._finalize()
            return
        self._kick_round()

    def _finalize(self) -> None:
        if self.rank != 0:
            self._final_wire = tree_to_wire(self.params)
            m = Message(GossipMsg.N2Z_FINAL, self.rank, 0)
            m.add_params(GossipMsg.K_PARAMS, self._final_wire)
            self.send_message(m)
            return  # wait for FINISH (the resend loop replays a lost one)
        self._finals[0] = self.params
        self._maybe_report()

    def _on_final(self, msg: Message) -> None:
        self._finals[msg.get_sender_id()] = wire_to_tree(
            msg.get(GossipMsg.K_PARAMS), self._template)
        self._maybe_report()

    def _maybe_report(self) -> None:
        if self.rank != 0 or len(self._finals) < self.n:
            return
        if self.result is not None:
            return  # duplicated final frame after the report went out
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves),
            *[self._finals[i] for i in range(self.n)])
        avg = jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0), stacked)
        stats = self._evaluate(avg, self.fed.test["x"], self.fed.test["y"],
                               self.fed.test["mask"])
        cnt = max(float(stats["count"]), 1.0)
        acc = float(stats["correct"]) / cnt
        mean = avg
        sq = jax.tree_util.tree_map(
            lambda a, m: jnp.sum((a - m[None]) ** 2,
                                 axis=tuple(range(1, a.ndim))),
            stacked, mean)
        consensus = float(jnp.mean(jnp.sqrt(
            sum(jax.tree_util.tree_leaves(sq)))))
        logger.info("gossip session: avg-model acc=%.4f consensus=%.4f",
                    acc, consensus)
        self.history.append({"round": self.rounds - 1, "test_acc": acc,
                             "consensus_dist": consensus})
        self.result = {"params": avg, "history": self.history,
                       "final_test_acc": acc,
                       "consensus_dist": consensus, "rounds": self.rounds}
        for j in range(1, self.n):
            self.send_message(Message(GossipMsg.Z2N_FINISH, self.rank, j))
        self.finish()

    def _on_finish(self, msg: Message) -> None:
        logger.info("gossip node %d: finish", self.rank)
        self.finish()


def run_gossip_inproc(args, fed, bundle) -> Dict[str, Any]:
    """All N gossip nodes over the in-proc broker (parity test /
    `backend: INPROC`); node 0 reports the session result."""
    from . import run_inproc_session
    n = int(getattr(args, "client_num_in_total", fed.num_clients))
    return run_inproc_session(args, lambda: [
        GossipNodeManager(args, fed, bundle, rank=r, size=n,
                          backend="INPROC")
        for r in range(n)])
