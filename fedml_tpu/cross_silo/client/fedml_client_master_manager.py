"""Cross-silo FL client FSM.

Parity target: reference ``cross_silo/client/fedml_client_master_manager.py:22``
— send ONLINE on start, handle S2C_INIT (:100), train, C2S model (:164),
S2C_SYNC loop, S2C_FINISH. Local training runs on this silo's accelerator
slice (the whole silo step is one jitted program; intra-silo data parallelism
is a pjit sharding, not a process group — the TrainerDistAdapter/DDP
machinery of the reference collapses into the trainer's mesh).
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from ...core import mlops
from ...core.distributed.communication.message import (Message, tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class ClientMasterManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank: int = 1,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.round_idx = 0
        self.server_rank = 0

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def run(self) -> None:
        # announce (reference: CONNECTION_READY -> ONLINE status)
        self.send_client_status(self.server_rank,
                                MyMessage.MSG_CLIENT_STATUS_ONLINE)
        mlops.log_training_status("ONLINE")
        super().run()

    def send_client_status(self, receiver_id: int, status: str) -> None:
        msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank,
                      receiver_id)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, status)
        self.send_message(msg)

    def handle_message_init(self, msg: Message) -> None:
        self._train_and_report(msg)

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        self._train_and_report(msg)

    def _train_and_report(self, msg: Message) -> None:
        wire = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        client_idx = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, 0))
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        params = wire_to_tree(wire, self.trainer.params_template)
        with mlops.event("train", round_idx=self.round_idx):
            new_params, n_samples, metrics = self.trainer.train(
                params, client_idx, self.round_idx)
        out = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                      self.server_rank)
        out.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                       tree_to_wire(new_params))
        out.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n_samples))
        out.add_params(MyMessage.MSG_ARG_KEY_CLIENT_METRICS,
                       {k: float(v) for k, v in (metrics or {}).items()})
        self.send_message(out)

    def handle_message_finish(self, msg: Message) -> None:
        logger.info("client rank %d: finish", self.rank)
        mlops.log_training_status("FINISHED")
        self.finish()
