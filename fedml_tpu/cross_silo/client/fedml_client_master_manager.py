"""Cross-silo FL client FSM.

Parity target: reference ``cross_silo/client/fedml_client_master_manager.py:22``
— send ONLINE on start, handle S2C_INIT (:100), train, C2S model (:164),
S2C_SYNC loop, S2C_FINISH. Local training runs on this silo's accelerator
slice (the whole silo step is one jitted program; intra-silo data parallelism
is a pjit sharding, not a process group — the TrainerDistAdapter/DDP
machinery of the reference collapses into the trainer's mesh).
"""

from __future__ import annotations

import logging
import threading

import jax
import numpy as np

from ...core import mlops
from ...core.obs import trace as obs_trace
from ...core.chaos import FaultPlan
from ...core.distributed.communication.message import (WIRE_DTYPE_BF16,
                                                       Message,
                                                       bf16_wire_to_tree,
                                                       tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.wire import (decode_update, encode_update, pack_optional_vec,
                          unpack_optional_vec, wire_checkpointer,
                          wire_state_template)
from ...utils.compression import is_compressed_payload, spec_from_args
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class ClientMasterManager(FedMLCommManager):
    # class-level fallbacks: a disabled plan + sync mode, so FSM methods
    # stay callable on partially-constructed instances (tests via __new__)
    chaos = FaultPlan()
    _async_mode = False
    _wire_ckpt = None

    def __init__(self, args, trainer, comm=None, rank: int = 1,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.round_idx = 0
        self.server_rank = 0
        # wire-efficient updates: when a spec is configured the upload is
        # the compressed delta vs the RECEIVED global model, with this
        # client's error-feedback residual carried across rounds so the
        # biased sparsifier still converges. None = dense path, unchanged.
        self.cc_spec = spec_from_args(args)
        # chaos: seeded per-(round, rank) dropout/straggler schedule —
        # a dropped silo silently skips its report (the server's
        # timeout/quorum tolerance takes it from there); a straggler
        # trains a reduced fraction of its local steps
        self.chaos = FaultPlan.from_args(args)
        # buffered-async sessions: every upload must echo the model
        # version it trained from (the sync's round tag) — that tag IS
        # the server's staleness signal, for dense uploads too
        from ...core.async_rounds import round_mode_from_args
        self._async_mode = round_mode_from_args(args) == "async_buffered"
        self._cc_residual = None
        self._global_vec = None   # f32 vector of the last received global
        self._cc_rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 97),
            self.rank)
        # crash-resume: the EF residual and the broadcast base join the
        # round checkpoint (core/wire/state) — losing either silently
        # drops accumulated compression error or corrupts later deltas.
        # Gated on the session's checkpoint knobs AND an active spec.
        self._wire_ckpt = None
        if self.cc_spec is not None and self.cc_spec.method is not None:
            self._wire_ckpt = wire_checkpointer(args, f"client_{self.rank}")
            self._restore_wire_state()

    # --- wire-state checkpointing (ISSUE 19 satellite) ----------------------
    def _wire_dim(self) -> int:
        return int(np.asarray(self.trainer.params_to_vec(
            self.trainer.params_template)).shape[0])

    def _wire_state(self, d: int) -> dict:
        rf, res = pack_optional_vec(self._cc_residual, d)
        gf, gv = pack_optional_vec(self._global_vec, d)
        return {"round": np.asarray(self.round_idx, np.int32),
                "residual_set": rf, "residual": res,
                "global_vec_set": gf, "global_vec": gv}

    def _save_wire_state(self) -> None:
        if self._wire_ckpt is None or not self._wire_ckpt.enabled:
            return
        d = self._wire_dim()
        self._wire_ckpt.maybe_save(self.round_idx, self._wire_state(d))

    def _restore_wire_state(self) -> None:
        if self._wire_ckpt is None or not self._wire_ckpt.enabled:
            return
        got = self._wire_ckpt.latest(
            wire_state_template(self._wire_dim(), ("residual", "global_vec")))
        if got is None:
            return
        step, st = got
        self._cc_residual = unpack_optional_vec(st["residual_set"],
                                                st["residual"])
        self._global_vec = unpack_optional_vec(st["global_vec_set"],
                                               st["global_vec"])
        logger.info("client rank %d: restored wire state from round %d",
                    self.rank, step)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def run(self) -> None:
        # announce (reference: CONNECTION_READY -> ONLINE status). The
        # handshake is re-announced with backoff until the server's first
        # message arrives: a single lost ONLINE frame (flaky WAN, chaos
        # link loss) must degrade to a late join, not a stalled session.
        self._server_heard = threading.Event()
        self.send_client_status(self.server_rank,
                                MyMessage.MSG_CLIENT_STATUS_ONLINE)
        mlops.log_training_status("ONLINE")

        def reannounce():
            delay = 2.0
            while not self._server_heard.wait(timeout=delay):
                logger.info("client rank %d: re-announcing ONLINE "
                            "(no server message yet)", self.rank)
                try:
                    self.send_client_status(
                        self.server_rank, MyMessage.MSG_CLIENT_STATUS_ONLINE)
                except Exception as e:
                    logger.debug("rank %d ONLINE re-announce failed: %s",
                                 self.rank, e)
                delay = min(delay * 2.0, 15.0)

        t = threading.Thread(target=reannounce, daemon=True)
        t.start()
        super().run()
        self._server_heard.set()  # release the re-announce thread

    def send_client_status(self, receiver_id: int, status: str) -> None:
        msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank,
                      receiver_id)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, status)
        self.send_message(msg)

    def handle_message_init(self, msg: Message) -> None:
        self._train_and_report(msg)

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        self._train_and_report(msg)

    def _receive_global(self, msg: Message):
        """Reassemble the server's sync payload: dense f32 (default),
        dense bf16 (``wire_dtype`` tag), or a compressed delta vs the last
        received global (``comm_compression_broadcast: compress``)."""
        update = msg.get(MyMessage.MSG_ARG_KEY_MODEL_UPDATE)
        if is_compressed_payload(update):
            if self._global_vec is None:
                raise RuntimeError(
                    "compressed sync before a dense init model")
            self._global_vec = decode_update(update, base=self._global_vec)
            return self.trainer.vec_to_params(self._global_vec)
        wire = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        if msg.get(MyMessage.MSG_ARG_KEY_WIRE_DTYPE) == WIRE_DTYPE_BF16:
            params = bf16_wire_to_tree(wire, self.trainer.params_template)
        else:
            params = wire_to_tree(wire, self.trainer.params_template)
        if self.cc_spec is not None and self.cc_spec.method is not None:
            self._global_vec = self.trainer.params_to_vec(params)
        return params

    def _train_and_report(self, msg: Message) -> None:
        if hasattr(self, "_server_heard"):
            self._server_heard.set()
        client_idx = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, 0))
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        # join the server's round trace: the sync carried the broadcast
        # span's traceparent, so this silo's train/upload spans nest
        # under it — ONE tree per round across processes
        with obs_trace.tracer.span(
                "silo.round", parent=obs_trace.extract(msg),
                attrs={"role": "client", "rank": self.rank,
                       "round_idx": self.round_idx}) as rsp:
            self._train_and_report_traced(msg, client_idx, rsp)

    def _train_and_report_traced(self, msg: Message, client_idx: int,
                                 rsp) -> None:
        # ALWAYS consume the broadcast, even when dropping out below: a
        # compressed sync is a delta vs the last reconstruction — skipping
        # it would leave _global_vec one delta behind and corrupt every
        # later round's base (and round-0 init must seed _global_vec)
        params = self._receive_global(msg)
        if self.chaos.is_dropped(self.round_idx, self.rank):
            # injected dropout: stay reachable (and base-synced) for the
            # next round but train/report nothing this round
            logger.warning("chaos: silo %d drops out of round %d",
                           self.rank, self.round_idx)
            rsp.set_attr("dropped", True)
            mlops.log_chaos(round_idx=self.round_idx,
                            injected={"dropped": [self.rank]})
            return
        work_scale = self.chaos.work_scale(self.round_idx, self.rank)
        with mlops.event("train", round_idx=self.round_idx):
            if work_scale < 1.0:
                new_params, n_samples, metrics = self.trainer.train(
                    params, client_idx, self.round_idx,
                    work_scale=work_scale)
            else:
                # healthy path: the pre-chaos trainer call signature, so
                # user trainer subclasses without the kwarg keep working
                new_params, n_samples, metrics = self.trainer.train(
                    params, client_idx, self.round_idx)
        out = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                      self.server_rank)
        if self.cc_spec is not None and self.cc_spec.method is not None:
            # broadcast-only specs (method None, e.g. bf16 downlink) keep
            # the dense uplink below. The uplink runs through the shared
            # core/wire pipeline: delta vs the received global, then EF
            # sparsify/quantize. When the server's adaptive schedule
            # tagged the sync with a keep-ratio, this round honors it.
            spec = self.cc_spec
            ratio = msg.get(MyMessage.MSG_ARG_KEY_CC_RATIO)
            if ratio is not None:
                import dataclasses
                spec = dataclasses.replace(spec, ratio=float(ratio))
            enc = encode_update(
                self.trainer.params_to_vec(new_params),
                base=self._global_vec, spec=spec,
                residual=self._cc_residual,
                rng=jax.random.fold_in(self._cc_rng, self.round_idx),
                msg_type=MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER)
            self._cc_residual = enc.residual
            out.add_params(MyMessage.MSG_ARG_KEY_MODEL_UPDATE, enc.payload)
            # a delta is only meaningful against the round's broadcast
            # base — tag it so the server can drop stragglers from a
            # timed-out round instead of reconstructing against the
            # wrong base (dense path omits this: byte-identical wire)
            out.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        else:
            out.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                           tree_to_wire(new_params))
            if self.chaos.enabled or self._async_mode:
                # under chaos an upload can outlive its round (delayed or
                # duplicated link copies, post-grace degraded aggregation
                # racing a straggler) — tag it so the server can drop the
                # stale copy instead of polluting the next round's pool.
                # Async sessions tag unconditionally: the version echo is
                # the server's per-update staleness signal. Otherwise the
                # default wire stays byte-identical.
                out.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX,
                               self.round_idx)
        out.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n_samples))
        out.add_params(MyMessage.MSG_ARG_KEY_CLIENT_METRICS,
                       {k: float(v) for k, v in (metrics or {}).items()})
        with obs_trace.tracer.span(
                "upload", attrs={"rank": self.rank,
                                 "round_idx": self.round_idx}) as usp:
            # the UPLOAD span's context rides the upload: the async
            # server's pour links exactly these spans (staleness per
            # link); the sync server links them off its wait span
            obs_trace.inject(out, usp)
            self.send_message(out)
        self._save_wire_state()

    def handle_message_finish(self, msg: Message) -> None:
        if hasattr(self, "_server_heard"):
            self._server_heard.set()
        logger.info("client rank %d: finish", self.rank)
        mlops.log_training_status("FINISHED")
        if self._wire_ckpt is not None:
            self._wire_ckpt.close()
        self.finish()
