"""Cross-silo FL client FSM.

Parity target: reference ``cross_silo/client/fedml_client_master_manager.py:22``
— send ONLINE on start, handle S2C_INIT (:100), train, C2S model (:164),
S2C_SYNC loop, S2C_FINISH. Local training runs on this silo's accelerator
slice (the whole silo step is one jitted program; intra-silo data parallelism
is a pjit sharding, not a process group — the TrainerDistAdapter/DDP
machinery of the reference collapses into the trainer's mesh).
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from ...core import mlops
from ...core.distributed.communication.message import (WIRE_DTYPE_BF16,
                                                       Message,
                                                       bf16_wire_to_tree,
                                                       tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...utils.compression import (decompress_vec, ef_compress_vec,
                                  is_compressed_payload, spec_from_args)
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class ClientMasterManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank: int = 1,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.round_idx = 0
        self.server_rank = 0
        # wire-efficient updates: when a spec is configured the upload is
        # the compressed delta vs the RECEIVED global model, with this
        # client's error-feedback residual carried across rounds so the
        # biased sparsifier still converges. None = dense path, unchanged.
        self.cc_spec = spec_from_args(args)
        self._cc_residual = None
        self._global_vec = None   # f32 vector of the last received global
        self._cc_rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 97),
            self.rank)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def run(self) -> None:
        # announce (reference: CONNECTION_READY -> ONLINE status)
        self.send_client_status(self.server_rank,
                                MyMessage.MSG_CLIENT_STATUS_ONLINE)
        mlops.log_training_status("ONLINE")
        super().run()

    def send_client_status(self, receiver_id: int, status: str) -> None:
        msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank,
                      receiver_id)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, status)
        self.send_message(msg)

    def handle_message_init(self, msg: Message) -> None:
        self._train_and_report(msg)

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        self._train_and_report(msg)

    def _receive_global(self, msg: Message):
        """Reassemble the server's sync payload: dense f32 (default),
        dense bf16 (``wire_dtype`` tag), or a compressed delta vs the last
        received global (``comm_compression_broadcast: compress``)."""
        update = msg.get(MyMessage.MSG_ARG_KEY_MODEL_UPDATE)
        if is_compressed_payload(update):
            if self._global_vec is None:
                raise RuntimeError(
                    "compressed sync before a dense init model")
            self._global_vec = self._global_vec + decompress_vec(update)
            return self.trainer.vec_to_params(self._global_vec)
        wire = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        if msg.get(MyMessage.MSG_ARG_KEY_WIRE_DTYPE) == WIRE_DTYPE_BF16:
            params = bf16_wire_to_tree(wire, self.trainer.params_template)
        else:
            params = wire_to_tree(wire, self.trainer.params_template)
        if self.cc_spec is not None and self.cc_spec.method is not None:
            self._global_vec = self.trainer.params_to_vec(params)
        return params

    def _train_and_report(self, msg: Message) -> None:
        client_idx = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, 0))
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        params = self._receive_global(msg)
        with mlops.event("train", round_idx=self.round_idx):
            new_params, n_samples, metrics = self.trainer.train(
                params, client_idx, self.round_idx)
        out = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                      self.server_rank)
        if self.cc_spec is not None and self.cc_spec.method is not None:
            # broadcast-only specs (method None, e.g. bf16 downlink) keep
            # the dense uplink below
            delta = self.trainer.params_to_vec(new_params) - self._global_vec
            blob, self._cc_residual = ef_compress_vec(
                delta, self._cc_residual, self.cc_spec,
                jax.random.fold_in(self._cc_rng, self.round_idx))
            out.add_params(MyMessage.MSG_ARG_KEY_MODEL_UPDATE, blob)
            # a delta is only meaningful against the round's broadcast
            # base — tag it so the server can drop stragglers from a
            # timed-out round instead of reconstructing against the
            # wrong base (dense path omits this: byte-identical wire)
            out.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        else:
            out.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                           tree_to_wire(new_params))
        out.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n_samples))
        out.add_params(MyMessage.MSG_ARG_KEY_CLIENT_METRICS,
                       {k: float(v) for k, v in (metrics or {}).items()})
        self.send_message(out)

    def handle_message_finish(self, msg: Message) -> None:
        logger.info("client rank %d: finish", self.rank)
        mlops.log_training_status("FINISHED")
        self.finish()
