"""Silo-local trainer for the cross-silo runtime.

Parity target: reference ``cross_silo/client/fedml_trainer.py`` +
``fedml_trainer_dist_adapter.py`` (DDP wrap): one silo's local training step.
TPU-native: the local epochs run as the same jitted ``run_local_sgd`` scan
the simulators use; intra-silo data parallelism is expressed by jitting over
this host's device mesh (data sharded on the batch axis) rather than a
torch process group.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.algframe.local_training import run_local_sgd
from ...core.algframe.types import TrainHyper


class SiloTrainer:
    """Owns this silo's shard of the federated dataset and the jitted local
    step."""

    def __init__(self, args, fed_dataset, bundle, spec, optimizer):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.spec = spec
        self.opt = optimizer
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(rng)
        sample = fed_dataset.train.x[0, 0]
        self.params_template = bundle.init(init_rng, sample)
        self._train_jit = jax.jit(self._train_impl)

    def _train_impl(self, params, cdata, rng, hyper):
        inner_opt = self.opt.make_inner_opt(hyper)
        new_params, _, metrics = run_local_sgd(
            self.spec, inner_opt, params, cdata, rng, hyper,
            grad_transform=self.opt.grad_transform,
            ctx={"global_params": params, "server_state": {},
                 "client_state": {}, "hyper": hyper})
        return new_params, metrics

    # --- flat-vector views (wire-efficient update path) ---------------------
    def params_to_vec(self, params):
        """Host float32 vector view of a params tree (leaf order is the
        template's — both FL sides flatten the same structure)."""
        import numpy as np

        from ...core.collectives import tree_flatten_to_vector
        return np.asarray(tree_flatten_to_vector(params), np.float32)

    def vec_to_params(self, vec):
        from ...core.collectives import vector_to_tree_like
        return vector_to_tree_like(jnp.asarray(vec, jnp.float32),
                                   self.params_template)

    def train(self, params, client_idx: int, round_idx: int,
              work_scale: float = 1.0
              ) -> Tuple[dict, float, Dict[str, float]]:
        cdata = jax.tree_util.tree_map(lambda a: a[client_idx],
                                       self.fed.train)
        # work_scale < 1 is the chaos straggler knob: it truncates the
        # dynamic local-step count (data, not shape — no recompile)
        hyper = TrainHyper(
            learning_rate=jnp.float32(self.args.learning_rate),
            epochs=int(self.args.epochs),
            round_idx=jnp.int32(round_idx),
            work_scale=jnp.float32(work_scale))
        key = jax.random.fold_in(jax.random.fold_in(self.rng, round_idx),
                                 client_idx)
        new_params, metrics = self._train_jit(params, cdata, key, hyper)
        n = float(cdata.num_samples)
        cnt = max(float(metrics["count"]), 1.0)
        return new_params, n, {"train_loss": float(metrics["loss_sum"]) / cnt,
                               "train_acc": float(metrics["correct"]) / cnt}
