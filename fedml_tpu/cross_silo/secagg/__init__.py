"""Secure-aggregation cross-silo runtime (the ``SA`` federated optimizer).

Parity target: reference ``cross_silo/secagg/`` (~1.4k LoC:
``sa_fedml_server_manager.py``, ``sa_fedml_client_manager.py``,
``sa_message_define.py``) — the Bonawitz-style protocol driven through extra
WAN message rounds: advertise keys -> share secrets -> masked input ->
unmask. Field math (p = 2^31 - 1, uint32 lanes; SURVEY §7 requantization
note) lives in ``core/mpc``; this module is the FSM.

Bonawitz et al. is a PER-AGGREGATION protocol: every FL round runs its own
key advertisement + secret sharing with FRESH mask material. Reusing one
key set across rounds (as earlier revisions here did) is unsound — a
client that survives round r (its self-seed legitimately reconstructed)
and drops in round r' (its mask key legitimately reconstructed) has handed
the server both masks of round r, i.e. its round-r individual update. So,
per FL round r:

  train -> C2S_ROUND_PK   (fresh X25519 mask key + fresh 128-bit self-seed)
        -> S2C_ROUND_PKS  (the round cohort = clients that advertised)
        -> C2S_SHARES     (Shamir shares of self-seed limbs + mask-key
                           limbs, AEAD-sealed per recipient, AAD-bound to
                           (sender, receiver, round))
        -> S2C_ROUTED     (mask cohort = clients whose shares arrived)
        -> C2S_MASKED     masked_k = quantize(n_k * delta_k)
                            + PRG(b_k) + sum_{j>k} PRG(s_kj)
                            - sum_{j<k} PRG(s_jk)   over the mask cohort
        -> S2C_UNMASK_REQUEST / C2S_UNMASK_SHARES -> aggregate.

Dropout recovery at every phase: the server proceeds with the >= threshold
respondents of each phase (the cohort shrinks monotonically within a
round); a client dropping after the share phase is recovered by
reconstructing its mask key from Shamir shares and cancelling its residual
pairwise masks. Clients wipe a round's secrets after answering its unmask
request, and answer at most once per round.

Confidentiality against the server: each client holds a session-scoped
X25519 *channel* keypair (``core/mpc/channels.py``) that seals routed
shares with ChaCha20-Poly1305 under per-pair keys — the server relays only
ciphertext (``test_secagg_runtime.py`` asserts the relayed bytes reveal no
share and fail AEAD authentication under any other pair's key). The
per-round *mask* keypairs seed the pairwise PRG masks via real ECDH; mask
secrets and 128-bit self-seeds are Shamir-shared as 24-bit limbs over
GF(2^31-1). At unmask time survivors reveal exactly what Bonawitz
prescribes — dropped clients' mask-key shares OR survivors' self-seed
shares, never both for one index: overlapping surviving/dropped lists (the
active-server attack) are refused outright.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import jax
import msgpack
import numpy as np

from ...core.distributed.communication.message import (Message, tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.collectives import (tree_flatten_to_vector, vector_to_tree_like)
from ...core.mpc import (P, dequantize, expand_mask, quantize,
                         shamir_reconstruct, shamir_share)
from ...core.mpc import channels
from ...core.wire import (LanePlan, field_encode, lane_dequantize_sum,
                          plan_for, record_update_stages, suggest_scale)

logger = logging.getLogger(__name__)
_P_I = int(P)


def _round_tag(round_idx: int) -> bytes:
    """AAD domain tag binding sealed share blobs to one FL round — a blob
    recorded in round r fails authentication if replayed in round r'."""
    return b"sa-round-%d" % int(round_idx)


def _refuse_sparsified_wire(args) -> None:
    """Masked summation needs every client on the same dense coordinate
    set — a per-client top-k/rand-k support set would leak exactly the
    coordinates masking hides AND misalign the mod-p sums. Lane
    quantization (``secagg_compress_bits``) is the SecAgg-compatible
    compression path; sparsifiers are refused outright."""
    if getattr(args, "comm_compression", None):
        raise ValueError(
            "comm_compression=%r cannot compose with SecAgg: per-client "
            "sparsification support sets leak masked coordinates and "
            "break masked-sum alignment. Use secagg_compress_bits "
            "(4|8|16-bit field lanes) instead."
            % getattr(args, "comm_compression"))


def _checked_threshold(args, n_clients: int) -> int:
    """Shamir threshold, enforced > n/2. The per-request overlap guard only
    sees ONE request; with t <= n/2 a deviating server could give disjoint
    halves of the cohort split views (i 'surviving' to one half, 'dropped'
    to the other) and still collect >= t shares of BOTH of i's secrets.
    t > n/2 makes the two >= t responder sets intersect, and the
    intersection client would have had to answer both views — which the
    once-per-round response guard forbids."""
    t = int(getattr(args, "secagg_threshold", 0) or 0)
    if not t:
        return max(2, n_clients // 2 + 1)
    if t <= n_clients // 2:
        raise ValueError(
            f"secagg_threshold={t} is <= n/2 for {n_clients} clients; a "
            f"majority threshold (>= {n_clients // 2 + 1}) is required to "
            "block split-view active-server attacks")
    return t


class SAMessage:
    # session setup (channel keys only — transport encryption)
    C2S_CHANNEL_PK = "sa_cpk"
    S2C_CHANNEL_PKS = "sa_cpks"
    # per-round protocol
    S2C_TRAIN = "sa_train"
    C2S_ROUND_PK = "sa_round_pk"
    S2C_ROUND_PKS = "sa_round_pks"
    C2S_SHARES = "sa_shares"
    S2C_ROUTED_SHARES = "sa_routed"
    C2S_MASKED_MODEL = "sa_masked"
    S2C_UNMASK_REQUEST = "sa_unmask_req"
    C2S_UNMASK_SHARES = "sa_unmask_shares"
    S2C_FINISH = "sa_finish"

    KEY_PK = "pk"
    KEY_PKS = "pks"
    KEY_COHORT = "cohort"
    KEY_SHARES = "shares"
    KEY_MODEL = "model"
    KEY_MASKED = "masked"
    KEY_N = "n"
    KEY_ROUND = "round"
    KEY_SURVIVING = "surviving"
    KEY_DROPPED = "dropped"
    KEY_SEED_SHARES = "seed_shares"
    KEY_KEY_SHARES = "key_shares"
    # lane-compressed field quantization (core/wire, ISSUE 19): the train
    # broadcast carries {bits, k_max, scale} when secagg_compress_bits is
    # on; absent otherwise (dense field vectors, byte-identical wire)
    KEY_WIRE = "wire"


class SecAggClientManager(FedMLCommManager):
    """Client side: channel-key setup once, then per round
    (train -> fresh keys -> share -> mask -> unmask-assist)."""

    def __init__(self, args, trainer, comm=None, rank: int = 1, size: int = 0,
                 backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.n_clients = int(getattr(args, "client_num_per_round", size - 1))
        _refuse_sparsified_wire(args)
        self.threshold = _checked_threshold(args, self.n_clients)
        self.idx = self.rank - 1  # client index 0..n-1
        # ALL secret material comes from OS entropy, never from the public
        # random_seed config (the server holds the same args and could
        # regenerate anything derived from it)
        self._rng = channels.secret_rng()
        # session-scoped channel keypair: seals routed shares; never shared
        self.enc_sk, self.enc_pk = channels.keygen()
        self.peer_enc: Dict[int, bytes] = {}  # peer_idx -> channel pk
        self.round_idx = 0
        self._round: Optional[Dict[str, Any]] = None  # this round's secrets
        self._responded_rounds: set = set()
        # lane compression (core/wire): error-feedback residual carrying
        # this client's quantization + clip error across rounds. Committed
        # only when the masked vector is actually SENT — a round sat out
        # (not in the cohort) must not advance the residual for mass that
        # was never shipped.
        self._ef_residual: Optional[np.ndarray] = None

    def register_message_receive_handlers(self) -> None:
        h = self.register_message_receive_handler
        h(SAMessage.S2C_CHANNEL_PKS, self.on_channel_pks)
        h(SAMessage.S2C_TRAIN, self.on_train)
        h(SAMessage.S2C_ROUND_PKS, self.on_round_pks)
        h(SAMessage.S2C_ROUTED_SHARES, self.on_routed_shares)
        h(SAMessage.S2C_UNMASK_REQUEST, self.on_unmask_request)
        h(SAMessage.S2C_FINISH, self.on_finish)

    def run(self) -> None:
        msg = Message(SAMessage.C2S_CHANNEL_PK, self.rank, 0)
        msg.add_params(SAMessage.KEY_PK, self.enc_pk)
        self.send_message(msg)
        super().run()

    def on_channel_pks(self, msg: Message) -> None:
        self.peer_enc = {int(k): bytes(v)
                         for k, v in msg.get(SAMessage.KEY_PKS).items()}

    # -- per-round phases ---------------------------------------------------

    def on_train(self, msg: Message) -> None:
        self.round_idx = int(msg.get(SAMessage.KEY_ROUND, 0))
        params = wire_to_tree(msg.get(SAMessage.KEY_MODEL),
                              self.trainer.params_template)
        new_params, n, _ = self.trainer.train(params, self.idx,
                                              self.round_idx)
        delta = jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), new_params, params)
        vec = np.asarray(tree_flatten_to_vector(delta), np.float32)
        wire_cfg = msg.get(SAMessage.KEY_WIRE)
        residual_next = None
        if wire_cfg is not None:
            # lane-compressed field path (core/wire): EF-compensate, clip,
            # stochastically round into b-bit lanes and pack L per uint32 —
            # the masked vector shrinks by L while the masked SUM stays
            # bit-exact (lane headroom covers k_max summands below p).
            # Rounding randomness need not be secret; seeded per
            # (client, round) so sessions replay deterministically.
            plan = LanePlan.from_wire(wire_cfg)
            scale = float(wire_cfg["scale"])
            packed, residual_next = field_encode(
                vec * np.float32(n), scale, plan, self._ef_residual,
                np.random.default_rng(((self.idx + 1) << 20)
                                      ^ self.round_idx))
            q = packed.astype(np.uint64)
        else:
            q = np.asarray(quantize(vec * np.float32(n))).astype(np.uint64)
        # fresh mask material for THIS round only (see module docstring)
        mask_sk, mask_pk = channels.keygen()
        self._round = {
            "round": self.round_idx,
            "q": q, "n": float(n),
            "d_model": int(vec.shape[0]),
            "residual_next": residual_next,
            "mask_sk": mask_sk, "mask_pk": mask_pk,
            "self_seed": self._rng.randbits(channels.SEED_BITS),
            "pks": {}, "held": {},
        }
        out = Message(SAMessage.C2S_ROUND_PK, self.rank, 0)
        out.add_params(SAMessage.KEY_ROUND, self.round_idx)
        out.add_params(SAMessage.KEY_PK, mask_pk)
        self.send_message(out)

    def on_round_pks(self, msg: Message) -> None:
        r = self._round
        if r is None or int(msg.get(SAMessage.KEY_ROUND)) != r["round"]:
            return
        r["pks"] = {int(k): bytes(v)
                    for k, v in msg.get(SAMessage.KEY_PKS).items()}
        cohort = sorted(r["pks"])
        if self.idx not in cohort:
            logger.warning("secagg client %d: not in round %d cohort — "
                           "sitting this round out", self.idx, r["round"])
            self._round = None
            return
        # Shamir-share the 128-bit self-seed and the mask secret key, both
        # as 24-bit limbs (each limb its own Shamir instance over
        # GF(2^31-1)); the j-th share set is sealed FOR cohort member j
        # under the pairwise channel key and AAD-bound to this round — the
        # server routes only ciphertext it cannot open or replay.
        n_sh = len(cohort)
        seed_sh = [shamir_share(limb, n_sh, self.threshold, self._rng)
                   for limb in channels.int_to_limbs(r["self_seed"],
                                                     channels.SEED_LIMBS)]
        key_sh = [shamir_share(limb, n_sh, self.threshold, self._rng)
                  for limb in channels.key_to_limbs(r["mask_sk"])]
        out = Message(SAMessage.C2S_SHARES, self.rank, 0)
        out.add_params(SAMessage.KEY_ROUND, r["round"])
        sealed = {}
        for pos, j in enumerate(cohort):
            payload = msgpack.packb(
                [[list(ls[pos]) for ls in seed_sh],
                 [list(ls[pos]) for ls in key_sh]])
            sealed[str(j)] = channels.seal(
                self.enc_sk, self.peer_enc[j], payload,
                aad=channels.pair_aad(self.idx, j, _round_tag(r["round"])))
        out.add_params(SAMessage.KEY_SHARES, sealed)
        self.send_message(out)

    def on_routed_shares(self, msg: Message) -> None:
        r = self._round
        if r is None or int(msg.get(SAMessage.KEY_ROUND)) != r["round"]:
            return
        mask_cohort = [int(i) for i in msg.get(SAMessage.KEY_COHORT)]
        if self.idx not in mask_cohort:
            self._round = None
            return
        for k, blob in msg.get(SAMessage.KEY_SHARES).items():
            i = int(k)
            # the whole parse stays in the try: AEAD authenticates whatever
            # the SENDER sealed, so a malicious peer can deliver
            # authentically-sealed garbage — that must drop the share, not
            # kill the receive loop
            try:
                payload = channels.open_sealed(
                    self.enc_sk, self.peer_enc[i], bytes(blob),
                    aad=channels.pair_aad(i, self.idx,
                                          _round_tag(r["round"])))
                seed_shares, key_shares = msgpack.unpackb(payload)
            except (channels.DecryptError, ValueError, TypeError) as e:
                logger.warning("secagg client %d: dropping share from %d: "
                               "%s", self.idx, i, e)
                continue
            r["held"][i] = (seed_shares, key_shares)
        # mask and submit: pairwise masks over the mask cohort only
        q = r["q"]
        d = len(q)
        total = expand_mask(r["self_seed"], d).astype(np.uint64)
        for j in mask_cohort:
            if j == self.idx:
                continue
            s = channels.mask_seed(r["mask_sk"], r["pks"][j])
            m = expand_mask(s, d).astype(np.uint64)
            if self.idx < j:
                total = (total + m) % _P_I
            else:
                total = (total + _P_I - m) % _P_I
        masked = ((q + total) % _P_I).astype(np.uint32)
        out = Message(SAMessage.C2S_MASKED_MODEL, self.rank, 0)
        out.add_params(SAMessage.KEY_ROUND, r["round"])
        out.add_params(SAMessage.KEY_MASKED, masked)
        out.add_params(SAMessage.KEY_N, r["n"])
        # per-stage byte ledger: dense-equivalent vs post-mask field bytes
        record_update_stages(SAMessage.C2S_MASKED_MODEL,
                             raw=int(r["d_model"]) * 4,
                             masked=int(masked.nbytes))
        if r["residual_next"] is not None:
            # the quantized vector ships now — commit the EF residual
            self._ef_residual = r["residual_next"]
        self.send_message(out)

    def on_unmask_request(self, msg: Message) -> None:
        r = self._round
        rnd = int(msg.get(SAMessage.KEY_ROUND))
        if r is None or rnd != r["round"] or rnd in self._responded_rounds:
            logger.warning("secagg client %d: ignoring unmask request for "
                           "round %s (stale/duplicate)", self.idx, rnd)
            return
        surviving = [int(i) for i in msg.get(SAMessage.KEY_SURVIVING)]
        dropped = [int(i) for i in msg.get(SAMessage.KEY_DROPPED)]
        # Active-server guard (Bonawitz et al. §6.2): a server listing
        # client i as BOTH surviving and dropped would collect >= threshold
        # shares of i's self-mask seed AND mask secret key, strip both
        # masks from i's masked vector, and recover i's individual update.
        # Per-round fresh keys already confine any reveal to this round;
        # within the round, refuse overlapping lists outright.
        overlap = set(surviving) & set(dropped)
        if overlap:
            logger.error(
                "secagg client %d: REFUSING unmask request — clients %s "
                "listed as both surviving and dropped (active-server "
                "attack); aborting session", self.idx, sorted(overlap))
            self.finish()
            return
        out = Message(SAMessage.C2S_UNMASK_SHARES, self.rank, 0)
        out.add_params(SAMessage.KEY_ROUND, rnd)
        out.add_params(SAMessage.KEY_SEED_SHARES,
                       {str(i): r["held"][i][0] for i in surviving
                        if i in r["held"]})
        out.add_params(SAMessage.KEY_KEY_SHARES,
                       {str(i): r["held"][i][1] for i in dropped
                        if i in r["held"]})
        # answer once, then wipe this round's secrets (forward secrecy: a
        # later replayed/forged request can reveal nothing)
        self._responded_rounds.add(rnd)
        self._round = None
        self.send_message(out)

    def on_finish(self, msg: Message) -> None:
        self.finish()


class SecAggServerManager(FedMLCommManager):
    """Server side: per-round key/share routing, sums masked vectors mod p,
    runs the unmask round, dequantizes, applies the aggregated delta."""

    def __init__(self, args, global_params, eval_fn=None, comm=None,
                 rank: int = 0, size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.global_params = global_params
        self.eval_fn = eval_fn
        self.n_clients = int(getattr(args, "client_num_per_round", size - 1))
        _refuse_sparsified_wire(args)
        self.threshold = _checked_threshold(args, self.n_clients)
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_timeout = float(getattr(args, "round_timeout_s", 0) or 0)
        self.round_idx = 0
        self.channel_pks: Dict[int, bytes] = {}
        # per-round state
        self.round_pks: Dict[int, bytes] = {}
        self.cohort: List[int] = []        # advertisers of this round
        self.share_matrix: Dict[int, Dict[str, Any]] = {}  # sealed blobs
        self.mask_cohort: List[int] = []   # share senders of this round
        self.masked: Dict[int, np.ndarray] = {}
        self.weights: Dict[int, float] = {}
        self.unmask_responses: Dict[int, Message] = {}
        self._surviving: List[int] = []
        self._dropped: List[int] = []
        self.history: List[Dict[str, Any]] = []
        self.result: Optional[dict] = None
        self._template_vec = np.asarray(
            tree_flatten_to_vector(global_params))
        # lane-compressed field quantization (core/wire): pack L b-bit
        # lanes per uint32 field element so the masked wire drops from
        # 4 B/coord to 4/L. k_max = the full client count — the lane
        # headroom must cover every summand the protocol could admit.
        bits = int(getattr(args, "secagg_compress_bits", 0) or 0)
        self._wire_plan: Optional[LanePlan] = None
        self._wire_scale = 0.0
        self._round_scale = 0.0
        if bits:
            self._wire_plan = plan_for(bits, self.n_clients)
            self._wire_scale = suggest_scale(
                float(getattr(args, "secagg_compress_clip", 4.0)),
                self._wire_plan)
        self._lock = threading.Lock()
        # setup -> (pk -> shares -> collect -> unmask -> aggregate)* -> done
        self._phase = "setup"
        self._timer: Optional[threading.Timer] = None
        # liveness floor: even with round_timeout_s unset, a crashed peer
        # must eventually abort the session instead of deadlocking it —
        # 60s floor: first-round jit compiles stall ~40s on the tunneled
        # chip; a 3x leash on a tight operator timeout must not abort a
        # healthy session mid-compile
        self._leash_s = (max(3.0 * self.round_timeout, 60.0)
                         if self.round_timeout > 0 else 300.0)

    def register_message_receive_handlers(self) -> None:
        h = self.register_message_receive_handler
        h(SAMessage.C2S_CHANNEL_PK, self.on_channel_pk)
        h(SAMessage.C2S_ROUND_PK, self.on_round_pk)
        h(SAMessage.C2S_SHARES, self.on_shares)
        h(SAMessage.C2S_MASKED_MODEL, self.on_masked_model)
        h(SAMessage.C2S_UNMASK_SHARES, self.on_unmask_shares)

    def run(self) -> None:
        # setup leash: a client crashing before its channel-pk send must
        # not hang the setup barrier forever
        self._arm_timer(self._leash_s, "setup")
        super().run()

    # -- timer plumbing -----------------------------------------------------

    def _arm_timer(self, seconds: float, phase: str) -> None:
        """(Re)arm the single phase timer. Caller may or may not hold the
        lock; threading.Timer start/cancel are thread-safe."""
        if self._timer is not None:
            self._timer.cancel()
        self._timer = threading.Timer(seconds, self._on_phase_timeout,
                                      args=(phase, self.round_idx))
        self._timer.daemon = True
        self._timer.start()

    def _abort(self, error: str, **extra) -> None:
        """Common abort: record the error, tell every client, stop."""
        with self._lock:
            self._phase = "done"
            self.result = {"error": error, "round": self.round_idx, **extra}
        for rank in range(1, self.n_clients + 1):
            self.send_message(Message(SAMessage.S2C_FINISH, 0, rank))
        self.finish()

    def _on_phase_timeout(self, phase: str, armed_round: int) -> None:
        """One handler for every phase leash: proceed with the >= threshold
        respondents of the phase, abort below threshold."""
        with self._lock:
            if self._phase != phase or self.round_idx != armed_round:
                return
            if phase == "setup":
                n, need = len(self.channel_pks), self.n_clients
                action = "abort"  # setup needs everyone (channel keys)
            elif phase == "pk":
                n, need = len(self.round_pks), self.threshold
                action = "pks" if n >= need else "abort"
            elif phase == "shares":
                n, need = len(self.share_matrix), self.threshold
                action = "route" if n >= need else "abort"
            elif phase == "collect":
                n, need = len(self.masked), self.threshold
                action = "unmask" if n >= need else "abort"
            elif phase == "unmask":
                n, need = len(self.unmask_responses), self.threshold
                action = "aggregate" if n >= need else "abort"
            else:
                return
            if action != "abort":
                logger.warning("secagg round %d: proceeding past phase %r "
                               "at timeout with %d respondents",
                               self.round_idx, phase, n)
                if action == "pks":
                    self._broadcast_round_pks_locked()
                elif action == "route":
                    self._route_shares_locked()
                elif action == "unmask":
                    self._begin_unmask_locked()
                elif action == "aggregate":
                    self._phase = "aggregate"
        if action == "abort":
            logger.error("secagg round %d: phase %r incomplete at timeout "
                         "(%d respondents < %d) — aborting session",
                         armed_round, phase, n, need)
            self._abort(f"secagg_{phase}_timeout")
        elif action == "aggregate":
            self._unmask_guarded()

    # -- session setup ------------------------------------------------------

    def on_channel_pk(self, msg: Message) -> None:
        with self._lock:
            if self._phase != "setup":
                return
            self.channel_pks[msg.get_sender_id() - 1] = bytes(
                msg.get(SAMessage.KEY_PK))
            if len(self.channel_pks) < self.n_clients:
                return
            self._phase = "pk"  # claimed; _start_round rebroadcasts state
        for rank in range(1, self.n_clients + 1):
            out = Message(SAMessage.S2C_CHANNEL_PKS, 0, rank)
            out.add_params(SAMessage.KEY_PKS,
                           {str(k): v for k, v in self.channel_pks.items()})
            self.send_message(out)
        self._start_round()

    # -- per-round phases ---------------------------------------------------

    def _start_round(self) -> None:
        with self._lock:
            self._phase = "pk"
            self.round_pks = {}
            self.cohort = []
            self.share_matrix = {}
            self.mask_cohort = []
            self.masked.clear()
            self.weights.clear()
            self.unmask_responses = {}
            self._surviving = []
            self._dropped = []
            self._arm_timer(self._leash_s, "pk")
        wire = tree_to_wire(self.global_params)
        wire_cfg = None
        if self._wire_plan is not None:
            # freeze this round's scale: every client must quantize with
            # the exact value the server will dequantize the sum with
            self._round_scale = float(self._wire_scale)
            wire_cfg = dict(self._wire_plan.to_wire(),
                            scale=self._round_scale)
        for rank in range(1, self.n_clients + 1):
            out = Message(SAMessage.S2C_TRAIN, 0, rank)
            out.add_params(SAMessage.KEY_MODEL, wire)
            out.add_params(SAMessage.KEY_ROUND, self.round_idx)
            if wire_cfg is not None:
                out.add_params(SAMessage.KEY_WIRE, wire_cfg)
            self.send_message(out)

    def on_round_pk(self, msg: Message) -> None:
        idx = msg.get_sender_id() - 1
        with self._lock:
            if (self._phase != "pk" or
                    int(msg.get(SAMessage.KEY_ROUND)) != self.round_idx):
                return
            self.round_pks[idx] = bytes(msg.get(SAMessage.KEY_PK))
            if len(self.round_pks) == self.n_clients:
                self._broadcast_round_pks_locked()
            elif self.round_timeout > 0 and len(self.round_pks) == 1:
                # first arrival (training time dominates this phase): swap
                # the dead-round leash for the tight straggler timer
                self._arm_timer(self.round_timeout, "pk")

    def _broadcast_round_pks_locked(self) -> None:
        """pk -> shares. Caller holds the lock."""
        self._phase = "shares"
        self.cohort = sorted(self.round_pks)
        self._arm_timer(self._leash_s, "shares")
        pks = {str(k): self.round_pks[k] for k in self.cohort}
        for j in self.cohort:
            out = Message(SAMessage.S2C_ROUND_PKS, 0, j + 1)
            out.add_params(SAMessage.KEY_ROUND, self.round_idx)
            out.add_params(SAMessage.KEY_PKS, pks)
            self.send_message(out)

    def on_shares(self, msg: Message) -> None:
        owner = msg.get_sender_id() - 1
        with self._lock:
            if (self._phase != "shares" or owner not in self.cohort or
                    int(msg.get(SAMessage.KEY_ROUND)) != self.round_idx):
                return
            self.share_matrix[owner] = msg.get(SAMessage.KEY_SHARES)
            if len(self.share_matrix) == len(self.cohort):
                self._route_shares_locked()

    def _route_shares_locked(self) -> None:
        """shares -> collect. Caller holds the lock. The mask cohort is the
        set whose shares arrived — only they mask and submit."""
        self._phase = "collect"
        self.mask_cohort = sorted(self.share_matrix)
        self._arm_timer(self._leash_s, "collect")
        for j in self.mask_cohort:
            routed = {str(i): self.share_matrix[i][str(j)]
                      for i in self.mask_cohort}
            out = Message(SAMessage.S2C_ROUTED_SHARES, 0, j + 1)
            out.add_params(SAMessage.KEY_ROUND, self.round_idx)
            out.add_params(SAMessage.KEY_COHORT, self.mask_cohort)
            out.add_params(SAMessage.KEY_SHARES, routed)
            self.send_message(out)

    def on_masked_model(self, msg: Message) -> None:
        idx = msg.get_sender_id() - 1
        with self._lock:
            if (self._phase != "collect" or idx not in self.mask_cohort or
                    int(msg.get(SAMessage.KEY_ROUND)) != self.round_idx):
                logger.warning("secagg: late/foreign masked input from "
                               "client %d ignored (phase=%s)", idx,
                               self._phase)
                return
            self.masked[idx] = np.asarray(msg.get(SAMessage.KEY_MASKED),
                                          np.uint32)
            self.weights[idx] = float(msg.get(SAMessage.KEY_N))
            if len(self.masked) == len(self.mask_cohort):
                self._begin_unmask_locked()
            elif self.round_timeout > 0 and len(self.masked) == 1:
                # first arrival: swap the dead-round leash for the tight
                # straggler timer
                self._arm_timer(self.round_timeout, "collect")

    def _begin_unmask_locked(self) -> None:
        """collect -> unmask. Caller holds self._lock."""
        self._phase = "unmask"
        self._surviving = sorted(self.masked)
        self._dropped = [i for i in self.mask_cohort
                         if i not in self.masked]
        self.unmask_responses = {}
        # a survivor dying between masked upload and unmask response must
        # not hang the session: proceed with >= threshold responses at the
        # leash, abort below threshold
        self._arm_timer(self._leash_s, "unmask")
        for rank in [i + 1 for i in self._surviving]:
            out = Message(SAMessage.S2C_UNMASK_REQUEST, 0, rank)
            out.add_params(SAMessage.KEY_ROUND, self.round_idx)
            out.add_params(SAMessage.KEY_SURVIVING, self._surviving)
            out.add_params(SAMessage.KEY_DROPPED, self._dropped)
            self.send_message(out)

    def on_unmask_shares(self, msg: Message) -> None:
        sender = msg.get_sender_id() - 1
        with self._lock:
            if (self._phase != "unmask" or sender not in self._surviving or
                    int(msg.get(SAMessage.KEY_ROUND)) != self.round_idx):
                return
            # key by sender: a duplicated response must not satisfy the
            # count early, and feeding the same Shamir x-coordinate twice
            # into Lagrange reconstruction silently yields a wrong secret
            # (duplicate x -> zero denominator -> pow(0, p-2) = 0)
            self.unmask_responses[sender] = msg
            if len(self.unmask_responses) < len(self._surviving):
                return  # wait for all surviving (simplest consistent point)
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._phase = "aggregate"
        self._unmask_guarded()

    # -- reconstruction + aggregation ---------------------------------------

    def _collect_shares(self, key: str, idx: int) -> List[Any]:
        shares = []
        for resp in self.unmask_responses.values():
            sh = resp.get(key).get(str(idx))
            if sh is not None:
                shares.append(sh)
            if len(shares) >= self.threshold:
                break
        if len(shares) < self.threshold:
            raise RuntimeError(
                f"secagg: {len(shares)} shares < threshold {self.threshold} "
                f"for client {idx} ({key})")
        return shares

    def _reconstruct_limbs(self, key: str, idx: int,
                           n_limbs: int) -> List[int]:
        """Reconstruct a limb-shared wide secret for ``idx`` from the first
        >= threshold unmask responses under ``key`` (each 24-bit limb is
        its own Shamir instance over GF(2^31-1))."""
        per_resp = self._collect_shares(key, idx)
        return [shamir_reconstruct([tuple(resp[limb]) for resp in per_resp])
                for limb in range(n_limbs)]

    def _reconstruct_seed(self, idx: int) -> int:
        """Client ``idx``'s 128-bit self-mask seed from its limb shares."""
        return channels.limbs_to_int(self._reconstruct_limbs(
            SAMessage.KEY_SEED_SHARES, idx, channels.SEED_LIMBS))

    def _reconstruct_mask_key(self, idx: int):
        """Reconstruct client ``idx``'s X25519 mask secret from its 24-bit
        limb shares (each limb is its own Shamir instance)."""
        return channels.limbs_to_key(self._reconstruct_limbs(
            SAMessage.KEY_KEY_SHARES, idx, channels.KEY_LIMBS))

    def _unmask_guarded(self) -> None:
        """Run _unmask_and_advance, routing ANY failure to the abort path.
        _collect_shares can legitimately raise when the >= threshold
        responders happen not to hold >= threshold decryptable shares of
        some client (a peer's setup share failed AEAD and was dropped),
        and a byzantine responder can send structurally malformed shares
        (wrong limb count -> IndexError/TypeError). On the timer thread an
        escaping exception would kill the timer and wedge the session in
        'aggregate' with no leash armed — a deadlock instead of the
        intended abort."""
        try:
            self._unmask_and_advance()
        except Exception as e:
            logger.error("secagg round %d: unmask failed (%s) — aborting "
                         "session", self.round_idx, e)
            self._abort("secagg_unmask_failed", detail=str(e))

    def _unmask_and_advance(self) -> None:
        surviving = self._surviving
        d_model = len(self._template_vec)
        # with lanes on, the whole protocol (masks, Shamir-recovered mask
        # cancellation, the mod-p sum) runs over the PACKED length — both
        # sides derive masks from expand_mask(seed, d) with the same d
        d = (self._wire_plan.packed_len(d_model)
             if self._wire_plan is not None else d_model)
        total = np.zeros(d, np.uint64)
        for m in self.masked.values():
            total = (total + m.astype(np.uint64)) % _P_I
        # reconstruct each surviving client's self-mask seed and subtract
        for i in surviving:
            seed = self._reconstruct_seed(i)
            mask = expand_mask(seed, d).astype(np.uint64)
            total = (total + _P_I - mask) % _P_I
        # cancel residual pairwise masks between survivors and dropped
        # clients: reconstruct each dropped j's mask secret key, re-derive
        # the symmetric ECDH pairwise seeds, and invert what each survivor
        # added.
        for j in self._dropped:
            sk_j = self._reconstruct_mask_key(j)
            for i in surviving:
                s = channels.mask_seed(sk_j, self.round_pks[i])
                m = expand_mask(s, d).astype(np.uint64)
                if i < j:   # survivor i added +m (i<j) -> subtract
                    total = (total + _P_I - m) % _P_I
                else:       # survivor i added -m (i>j) -> add back
                    total = (total + m) % _P_I
        if self._wire_plan is not None:
            # exact masked-sum decode: the unmasked total IS the integer
            # sum of the survivors' packed vectors (overflow bound in
            # core/wire/field_quant), so lane extraction + the K*offset
            # correction is bit-identical to summing unmasked quantized
            # vectors directly — the acceptance property test_wire pins
            vec = lane_dequantize_sum(
                np.asarray(total, np.uint64).astype(np.uint32),
                len(surviving), self._round_scale, self._wire_plan,
                d_model)
            # auto-scale: track the observed per-client aggregate
            # magnitude with 2x margin (clip error lands in each client's
            # EF residual, so a transiently tight scale self-corrects)
            per_client = float(np.abs(vec).max()) / max(len(surviving), 1)
            new_scale = suggest_scale(max(2.0 * per_client, 1e-8),
                                      self._wire_plan)
            self._wire_scale = 0.5 * self._wire_scale + 0.5 * new_scale
        else:
            vec = np.asarray(dequantize(total.astype(np.uint32)))
        wsum = sum(self.weights[i] for i in surviving)
        agg_delta_vec = vec / max(wsum, 1e-12)
        agg_delta = vector_to_tree_like(agg_delta_vec.astype(np.float32),
                                        self.global_params)
        self.global_params = jax.tree_util.tree_map(
            lambda g, u: np.asarray(g) + np.asarray(u), self.global_params,
            agg_delta)
        rec = {"round": self.round_idx}
        if self.eval_fn is not None:
            rec.update(self.eval_fn(self.global_params))
            logger.info("secagg round %d: %s", self.round_idx, rec)
        self.history.append(rec)
        with self._lock:
            self.round_idx += 1
            done = self.round_idx >= self.round_num
            if done:
                self._phase = "done"
        if done:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(SAMessage.S2C_FINISH, 0, rank))
            last = next((r for r in reversed(self.history)
                         if "test_acc" in r), {})
            self.result = {"params": self.global_params,
                           "history": self.history,
                           "final_test_acc": last.get("test_acc"),
                           "rounds": self.round_num}
            self.finish()
            return
        self._start_round()


def run_secagg_inproc(args, fed, bundle, spec=None,
                      client_factory=None) -> Dict[str, Any]:
    """Server + N SecAgg clients as threads over the in-proc broker.

    ``client_factory(rank, args, trainer) -> SecAggClientManager`` lets tests
    inject faulty clients (dropout / fault injection)."""
    import threading as _threading
    from ...core.distributed.communication.inproc import InProcBroker
    from ..horizontal.runner import _build_spec, _make_eval_fn
    from ..client.trainer import SiloTrainer
    from ...optimizers.registry import create_optimizer

    broker = InProcBroker()
    args.inproc_broker = broker
    spec = _build_spec(fed, bundle, spec)
    n = int(getattr(args, "client_num_per_round", 2))
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    init_rng, _ = jax.random.split(rng)
    global_params = bundle.init(init_rng, fed.train.x[0, 0])
    server = SecAggServerManager(args, global_params,
                                 eval_fn=_make_eval_fn(spec, fed),
                                 rank=0, size=n + 1, backend="INPROC")
    clients = []
    for r in range(1, n + 1):
        optimizer = create_optimizer(args, spec)
        trainer = SiloTrainer(args, fed, bundle, spec, optimizer)
        if client_factory is not None:
            clients.append(client_factory(r, args, trainer))
        else:
            clients.append(SecAggClientManager(args, trainer, rank=r,
                                               size=n + 1, backend="INPROC"))
    threads = [_threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30.0)
    return server.result
