"""Secure-aggregation cross-silo runtime (the ``SA`` federated optimizer).

Parity target: reference ``cross_silo/secagg/`` (~1.4k LoC:
``sa_fedml_server_manager.py``, ``sa_fedml_client_manager.py``,
``sa_message_define.py``) — the Bonawitz-style protocol driven through extra
WAN message rounds: advertise keys -> share secrets -> masked input ->
unmask. Field math (p = 2^31 - 1, uint32 lanes; SURVEY §7 requantization
note) lives in ``core/mpc``; this module is the FSM.

Per FL round r:
  masked_k = quantize(n_k * delta_k) + PRG(salt(b_k, r))
             + sum_{j>k} PRG(salt(s_kj, r)) - sum_{j<k} PRG(salt(s_jk, r))
Dropout recovery: if a client fails to submit within the round timeout, the
server proceeds with the >= threshold survivors, reconstructs the dropped
clients' secret keys (and survivors' self-mask seeds) from Shamir shares
held by the survivors, and cancels the residual pairwise masks.

Confidentiality against the server: each client holds two X25519 keypairs
(``core/mpc/channels.py``) — pairwise PRG mask seeds come from real ECDH
agreement on the *mask* keys, and routed Shamir shares are sealed with
ChaCha20-Poly1305 under per-pair keys derived from the *channel* keys, so
the server relays only ciphertext (``test_secagg_runtime.py`` asserts the
relayed bytes reveal no share and fail AEAD authentication under any other
pair's key). The mask secret key is Shamir-shared as 24-bit limbs over
GF(2^31-1); the channel key is never shared, so reconstructing a dropped
client's mask key does not open its past routed-share ciphertexts. At
unmask time survivors reveal exactly what Bonawitz prescribes: dropped
clients' mask-key shares and survivors' self-mask seed shares.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import jax
import msgpack
import numpy as np

from ...core import mlops
from ...core.distributed.communication.message import (Message, tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.collectives import (tree_flatten_to_vector, vector_to_tree_like)
from ...core.mpc import (P, dequantize, expand_mask, quantize,
                         shamir_reconstruct, shamir_share)
from ...core.mpc import channels
from ...core.mpc.secagg import salt_seed

logger = logging.getLogger(__name__)
_P_I = int(P)


class SAMessage:
    # setup
    C2S_PUBLIC_KEY = "sa_pk"
    S2C_PUBLIC_KEYS = "sa_pks"
    C2S_SHARES = "sa_shares"
    S2C_ROUTED_SHARES = "sa_routed"
    # per-round
    S2C_TRAIN = "sa_train"
    C2S_MASKED_MODEL = "sa_masked"
    S2C_UNMASK_REQUEST = "sa_unmask_req"
    C2S_UNMASK_SHARES = "sa_unmask_shares"
    S2C_FINISH = "sa_finish"

    KEY_PK = "pk"
    KEY_PKS = "pks"
    KEY_SHARES = "shares"
    KEY_MODEL = "model"
    KEY_MASKED = "masked"
    KEY_N = "n"
    KEY_ROUND = "round"
    KEY_SURVIVING = "surviving"
    KEY_DROPPED = "dropped"
    KEY_SEED_SHARES = "seed_shares"
    KEY_KEY_SHARES = "key_shares"


class SecAggClientManager(FedMLCommManager):
    """Client side: key setup once, then (train -> mask -> unmask-assist)
    per round."""

    def __init__(self, args, trainer, comm=None, rank: int = 1, size: int = 0,
                 backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.n_clients = int(getattr(args, "client_num_per_round", size - 1))
        self.threshold = int(getattr(args, "secagg_threshold", 0) or
                             max(2, self.n_clients // 2 + 1))
        self.idx = self.rank - 1  # client index 0..n-1
        # ALL secret material comes from OS entropy, never from the public
        # random_seed config (the server holds the same args and could
        # regenerate anything derived from it)
        rng = channels.secret_rng()
        # mask keypair: ECDH seeds the pairwise masks, secret Shamir-shared
        self.mask_sk, self.mask_pk = channels.keygen()
        # channel keypair: seals routed shares; never shared
        self.enc_sk, self.enc_pk = channels.keygen()
        self.self_seed = int(rng.randint(0, _P_I))
        self._rng = rng
        # peer_idx -> {"mask": bytes, "enc": bytes}
        self.peer_publics: Dict[int, Dict[str, bytes]] = {}
        # shares this client HOLDS for each peer:
        # peer_idx -> (seed_share, [mask-key limb shares])
        self.held_shares: Dict[int, Any] = {}
        self.round_idx = 0

    def register_message_receive_handlers(self) -> None:
        h = self.register_message_receive_handler
        h(SAMessage.S2C_PUBLIC_KEYS, self.on_public_keys)
        h(SAMessage.S2C_ROUTED_SHARES, self.on_routed_shares)
        h(SAMessage.S2C_TRAIN, self.on_train)
        h(SAMessage.S2C_UNMASK_REQUEST, self.on_unmask_request)
        h(SAMessage.S2C_FINISH, self.on_finish)

    def run(self) -> None:
        msg = Message(SAMessage.C2S_PUBLIC_KEY, self.rank, 0)
        msg.add_params(SAMessage.KEY_PK,
                       {"mask": self.mask_pk, "enc": self.enc_pk})
        self.send_message(msg)
        super().run()

    def on_public_keys(self, msg: Message) -> None:
        self.peer_publics = {
            int(k): {"mask": bytes(v["mask"]), "enc": bytes(v["enc"])}
            for k, v in msg.get(SAMessage.KEY_PKS).items()}
        # Shamir-share self_seed (one field element) and the mask secret
        # key (24-bit limbs). The j-th share pair is sealed FOR client j
        # under the pairwise channel key — the server routes ciphertext.
        seed_sh = shamir_share(self.self_seed, self.n_clients, self.threshold,
                               self._rng)
        limb_sh = [shamir_share(limb, self.n_clients, self.threshold,
                                self._rng)
                   for limb in channels.key_to_limbs(self.mask_sk)]
        out = Message(SAMessage.C2S_SHARES, self.rank, 0)
        sealed = {}
        for j in range(self.n_clients):
            payload = msgpack.packb(
                [list(seed_sh[j]), [list(ls[j]) for ls in limb_sh]])
            sealed[str(j)] = channels.seal(
                self.enc_sk, self.peer_publics[j]["enc"], payload,
                aad=channels.pair_aad(self.idx, j, b"sa-setup"))
        out.add_params(SAMessage.KEY_SHARES, sealed)
        self.send_message(out)

    def on_routed_shares(self, msg: Message) -> None:
        for k, blob in msg.get(SAMessage.KEY_SHARES).items():
            i = int(k)
            # the whole parse stays in the try: AEAD authenticates whatever
            # the SENDER sealed, so a malicious peer can deliver
            # authentically-sealed garbage — that must drop the share, not
            # kill the receive loop
            try:
                payload = channels.open_sealed(
                    self.enc_sk, self.peer_publics[i]["enc"], bytes(blob),
                    aad=channels.pair_aad(i, self.idx, b"sa-setup"))
                seed_share, limb_shares = msgpack.unpackb(payload)
            except (channels.DecryptError, ValueError, TypeError) as e:
                logger.warning("secagg client %d: dropping share from %d: "
                               "%s", self.idx, i, e)
                continue
            self.held_shares[i] = (seed_share, limb_shares)

    def on_train(self, msg: Message) -> None:
        self.round_idx = int(msg.get(SAMessage.KEY_ROUND, 0))
        params = wire_to_tree(msg.get(SAMessage.KEY_MODEL),
                              self.trainer.params_template)
        new_params, n, _ = self.trainer.train(params, self.idx,
                                              self.round_idx)
        delta = jax.tree_util.tree_map(lambda a, b: np.asarray(a) - np.asarray(b),
                                       new_params, params)
        vec = np.asarray(tree_flatten_to_vector(delta), np.float32)
        q = np.asarray(quantize(vec * np.float32(n))).astype(np.uint64)
        d = len(q)
        total = expand_mask(salt_seed(self.self_seed, self.round_idx),
                            d).astype(np.uint64)
        for j, pub in self.peer_publics.items():
            if j == self.idx:
                continue
            s = channels.mask_seed(self.mask_sk, pub["mask"])
            m = expand_mask(salt_seed(s, self.round_idx), d).astype(np.uint64)
            if self.idx < j:
                total = (total + m) % _P_I
            else:
                total = (total + _P_I - m) % _P_I
        masked = ((q + total) % _P_I).astype(np.uint32)
        out = Message(SAMessage.C2S_MASKED_MODEL, self.rank, 0)
        out.add_params(SAMessage.KEY_MASKED, masked)
        out.add_params(SAMessage.KEY_N, float(n))
        self.send_message(out)

    def on_unmask_request(self, msg: Message) -> None:
        surviving = [int(i) for i in msg.get(SAMessage.KEY_SURVIVING)]
        dropped = [int(i) for i in msg.get(SAMessage.KEY_DROPPED)]
        out = Message(SAMessage.C2S_UNMASK_SHARES, self.rank, 0)
        out.add_params(SAMessage.KEY_SEED_SHARES,
                       {str(i): self.held_shares[i][0] for i in surviving
                        if i in self.held_shares})
        out.add_params(SAMessage.KEY_KEY_SHARES,
                       {str(i): self.held_shares[i][1] for i in dropped
                        if i in self.held_shares})
        self.send_message(out)

    def on_finish(self, msg: Message) -> None:
        self.finish()


class SecAggServerManager(FedMLCommManager):
    """Server side: routes setup shares, sums masked vectors mod p, runs the
    unmask round, dequantizes, applies the aggregated delta."""

    def __init__(self, args, global_params, eval_fn=None, comm=None,
                 rank: int = 0, size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.global_params = global_params
        self.eval_fn = eval_fn
        self.n_clients = int(getattr(args, "client_num_per_round", size - 1))
        self.threshold = int(getattr(args, "secagg_threshold", 0) or
                             max(2, self.n_clients // 2 + 1))
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_timeout = float(getattr(args, "round_timeout_s", 0) or 0)
        self.round_idx = 0
        # client_idx -> {"mask": bytes, "enc": bytes} (X25519 publics)
        self.publics: Dict[int, Dict[str, bytes]] = {}
        # owner_idx -> {recipient: sealed blob} — opaque to the server
        self.share_matrix: Dict[int, Dict[str, Any]] = {}
        self.masked: Dict[int, np.ndarray] = {}
        self.weights: Dict[int, float] = {}
        self.unmask_responses: List[Message] = []
        self.history: List[Dict[str, Any]] = []
        self.result: Optional[dict] = None
        self._template_vec = np.asarray(
            tree_flatten_to_vector(global_params))
        self._lock = threading.Lock()
        self._phase = "setup"  # setup -> collect -> unmask -> done
        self._keys_done = False
        self._shares_done = False
        self._surviving: List[int] = []
        self._dropped: List[int] = []
        self._timer: Optional[threading.Timer] = None
        # liveness floor: even with round_timeout_s unset, a crashed peer
        # must eventually abort the session instead of deadlocking it —
        # generous so first-compile stalls (~40s tunneled) never trip it
        # 60s floor: first-round jit compiles stall ~40s on the tunneled
        # chip; a 3x leash on a tight operator timeout must not abort a
        # healthy session mid-compile
        self._leash_s = (max(3.0 * self.round_timeout, 60.0)
                         if self.round_timeout > 0 else 300.0)

    def register_message_receive_handlers(self) -> None:
        h = self.register_message_receive_handler
        h(SAMessage.C2S_PUBLIC_KEY, self.on_public_key)
        h(SAMessage.C2S_SHARES, self.on_shares)
        h(SAMessage.C2S_MASKED_MODEL, self.on_masked_model)
        h(SAMessage.C2S_UNMASK_SHARES, self.on_unmask_shares)

    def run(self) -> None:
        # setup leash: a client crashing before its pk/shares send must not
        # hang the pk/shares barriers forever (_on_setup_timeout is a no-op
        # once _start_round has moved the phase past "setup")
        self._timer = threading.Timer(self._leash_s, self._on_setup_timeout)
        self._timer.daemon = True
        self._timer.start()
        super().run()

    def _on_setup_timeout(self) -> None:
        with self._lock:
            if self._phase != "setup":
                return
            logger.error(
                "secagg: setup incomplete at timeout (%d/%d public keys, "
                "%d/%d share sets) — aborting session", len(self.publics),
                self.n_clients, len(self.share_matrix), self.n_clients)
            self._phase = "done"
            self.result = {"error": "secagg_setup_timeout"}
        for rank in range(1, self.n_clients + 1):
            self.send_message(Message(SAMessage.S2C_FINISH, 0, rank))
        self.finish()

    def on_public_key(self, msg: Message) -> None:
        """Duplicate advertisements (client retransmits) must not re-trigger
        the broadcast once setup has moved on (mirrors the LSA guard)."""
        pk = msg.get(SAMessage.KEY_PK)
        with self._lock:
            if self._keys_done:
                return
            self.publics[msg.get_sender_id() - 1] = {
                "mask": bytes(pk["mask"]), "enc": bytes(pk["enc"])}
            if len(self.publics) < self.n_clients:
                return
            self._keys_done = True
        for rank in range(1, self.n_clients + 1):
            out = Message(SAMessage.S2C_PUBLIC_KEYS, 0, rank)
            out.add_params(SAMessage.KEY_PKS,
                           {str(k): v for k, v in self.publics.items()})
            self.send_message(out)

    def on_shares(self, msg: Message) -> None:
        owner = msg.get_sender_id() - 1
        with self._lock:
            if self._shares_done:  # retransmit must not restart the round
                return
            self.share_matrix[owner] = msg.get(SAMessage.KEY_SHARES)
            if len(self.share_matrix) < self.n_clients:
                return
            self._shares_done = True
        # route: client j receives, for every owner i, i's j-th share
        for j in range(self.n_clients):
            routed = {str(i): self.share_matrix[i][str(j)]
                      for i in range(self.n_clients)}
            out = Message(SAMessage.S2C_ROUTED_SHARES, 0, j + 1)
            out.add_params(SAMessage.KEY_SHARES, routed)
            self.send_message(out)
        self._start_round()

    def _start_round(self) -> None:
        # The straggler timer is armed on the FIRST masked arrival (see
        # on_masked_model) — arming the tight timeout at round start would
        # race long first-compile times. But zero arrivals must not hang
        # forever either: arm a generous dead-round leash here that the
        # first arrival replaces with the tight timer.
        with self._lock:
            self._phase = "collect"
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(
                self._leash_s, self._on_collect_timeout,
                args=(self.round_idx,))
            self._timer.daemon = True
            self._timer.start()
        wire = tree_to_wire(self.global_params)
        for rank in range(1, self.n_clients + 1):
            out = Message(SAMessage.S2C_TRAIN, 0, rank)
            out.add_params(SAMessage.KEY_MODEL, wire)
            out.add_params(SAMessage.KEY_ROUND, self.round_idx)
            self.send_message(out)

    def _on_collect_timeout(self, armed_round: int) -> None:
        """Proceed with >= threshold survivors if stragglers never reported."""
        with self._lock:
            if self._phase != "collect" or self.round_idx != armed_round:
                return
            if len(self.masked) < self.threshold:
                logger.error(
                    "secagg round %d: only %d/%d masked inputs (< threshold "
                    "%d) at timeout — aborting session", self.round_idx,
                    len(self.masked), self.n_clients, self.threshold)
                self._phase = "done"
                self.result = {"error": "secagg_below_threshold",
                               "round": self.round_idx}
                abort = True
            else:
                self._begin_unmask_locked()
                abort = False
        if abort:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(SAMessage.S2C_FINISH, 0, rank))
            self.finish()

    def on_masked_model(self, msg: Message) -> None:
        idx = msg.get_sender_id() - 1
        with self._lock:
            if self._phase != "collect":
                logger.warning("secagg: late masked input from client %d "
                               "ignored (phase=%s)", idx, self._phase)
                return
            self.masked[idx] = np.asarray(msg.get(SAMessage.KEY_MASKED),
                                          np.uint32)
            self.weights[idx] = float(msg.get(SAMessage.KEY_N))
            if len(self.masked) == self.n_clients:
                self._begin_unmask_locked()
            elif self.round_timeout > 0 and len(self.masked) == 1:
                # first arrival: swap the dead-round leash for the tight
                # straggler timer
                if self._timer is not None:
                    self._timer.cancel()
                self._timer = threading.Timer(
                    self.round_timeout, self._on_collect_timeout,
                    args=(self.round_idx,))
                self._timer.daemon = True
                self._timer.start()

    def _begin_unmask_locked(self) -> None:
        """Transition collect -> unmask. Caller holds self._lock."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._phase = "unmask"
        self._surviving = sorted(self.masked)
        self._dropped = [i for i in range(self.n_clients)
                         if i not in self.masked]
        self.unmask_responses = []
        # a survivor dying between masked upload and unmask response must
        # not hang the session: proceed with >= threshold responses at the
        # leash, abort below threshold
        self._timer = threading.Timer(
            self._leash_s, self._on_unmask_timeout, args=(self.round_idx,))
        self._timer.daemon = True
        self._timer.start()
        for rank in [i + 1 for i in self._surviving]:
            out = Message(SAMessage.S2C_UNMASK_REQUEST, 0, rank)
            out.add_params(SAMessage.KEY_SURVIVING, self._surviving)
            out.add_params(SAMessage.KEY_DROPPED, self._dropped)
            self.send_message(out)

    def _on_unmask_timeout(self, armed_round: int) -> None:
        with self._lock:
            if self._phase != "unmask" or self.round_idx != armed_round:
                return
            if len(self.unmask_responses) < self.threshold:
                logger.error(
                    "secagg round %d: %d/%d unmask responses (< threshold "
                    "%d) at timeout — aborting session", self.round_idx,
                    len(self.unmask_responses), len(self._surviving),
                    self.threshold)
                self._phase = "done"
                self.result = {"error": "secagg_unmask_timeout",
                               "round": self.round_idx}
                abort = True
            else:
                logger.warning(
                    "secagg round %d: unmasking with %d/%d responses at "
                    "timeout", self.round_idx, len(self.unmask_responses),
                    len(self._surviving))
                self._phase = "aggregate"
                abort = False
        if abort:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(SAMessage.S2C_FINISH, 0, rank))
            self.finish()
            return
        self._unmask_and_advance()

    def on_unmask_shares(self, msg: Message) -> None:
        with self._lock:
            if self._phase != "unmask":
                return
            self.unmask_responses.append(msg)
            if len(self.unmask_responses) < self.threshold:
                return
            if len(self.unmask_responses) < len(self._surviving):
                return  # wait for all surviving (simplest consistent point)
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._phase = "aggregate"
        self._unmask_and_advance()

    def _collect_shares(self, key: str, idx: int) -> List[Any]:
        shares = []
        for resp in self.unmask_responses:
            sh = resp.get(key).get(str(idx))
            if sh is not None:
                shares.append(sh)
            if len(shares) >= self.threshold:
                break
        if len(shares) < self.threshold:
            raise RuntimeError(
                f"secagg: {len(shares)} shares < threshold {self.threshold} "
                f"for client {idx} ({key})")
        return shares

    def _reconstruct(self, key: str, idx: int) -> int:
        """Reconstruct a single-field-element Shamir secret for ``idx``
        from the first >= threshold unmask responses under ``key``."""
        return shamir_reconstruct(
            [tuple(sh) for sh in self._collect_shares(key, idx)])

    def _reconstruct_mask_key(self, idx: int):
        """Reconstruct client ``idx``'s X25519 mask secret from its 24-bit
        limb shares (each limb is its own Shamir instance)."""
        per_resp = self._collect_shares(SAMessage.KEY_KEY_SHARES, idx)
        limbs = [shamir_reconstruct([tuple(resp[limb]) for resp in per_resp])
                 for limb in range(channels.KEY_LIMBS)]
        return channels.limbs_to_key(limbs)

    def _unmask_and_advance(self) -> None:
        surviving = self._surviving
        d = len(self._template_vec)
        total = np.zeros(d, np.uint64)
        for m in self.masked.values():
            total = (total + m.astype(np.uint64)) % _P_I
        # reconstruct each surviving client's self-mask seed and subtract
        for i in surviving:
            seed = self._reconstruct(SAMessage.KEY_SEED_SHARES, i)
            mask = expand_mask(salt_seed(seed, self.round_idx),
                               d).astype(np.uint64)
            total = (total + _P_I - mask) % _P_I
        # cancel residual pairwise masks between survivors and dropped
        # clients: reconstruct each dropped j's mask secret key, re-derive
        # the symmetric ECDH pairwise seeds, and invert what each survivor
        # added.
        for j in self._dropped:
            sk_j = self._reconstruct_mask_key(j)
            for i in surviving:
                s = channels.mask_seed(sk_j, self.publics[i]["mask"])
                m = expand_mask(salt_seed(s, self.round_idx),
                                d).astype(np.uint64)
                if i < j:   # survivor i added +m (i<j) -> subtract
                    total = (total + _P_I - m) % _P_I
                else:       # survivor i added -m (i>j) -> add back
                    total = (total + m) % _P_I
        vec = np.asarray(dequantize(total.astype(np.uint32)))
        wsum = sum(self.weights.values())
        agg_delta_vec = vec / max(wsum, 1e-12)
        agg_delta = vector_to_tree_like(agg_delta_vec.astype(np.float32),
                                        self.global_params)
        self.global_params = jax.tree_util.tree_map(
            lambda g, u: np.asarray(g) + np.asarray(u), self.global_params,
            agg_delta)
        rec = {"round": self.round_idx}
        if self.eval_fn is not None:
            rec.update(self.eval_fn(self.global_params))
            logger.info("secagg round %d: %s", self.round_idx, rec)
        self.history.append(rec)
        with self._lock:
            self.masked.clear()
            self.weights.clear()
            self.unmask_responses = []
            self._surviving = []
            self._dropped = []
            self.round_idx += 1
            done = self.round_idx >= self.round_num
            if done:
                self._phase = "done"
        if done:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(SAMessage.S2C_FINISH, 0, rank))
            last = next((r for r in reversed(self.history)
                         if "test_acc" in r), {})
            self.result = {"params": self.global_params,
                           "history": self.history,
                           "final_test_acc": last.get("test_acc"),
                           "rounds": self.round_num}
            self.finish()
            return
        self._start_round()


def run_secagg_inproc(args, fed, bundle, spec=None,
                      client_factory=None) -> Dict[str, Any]:
    """Server + N SecAgg clients as threads over the in-proc broker.

    ``client_factory(rank, args, trainer) -> SecAggClientManager`` lets tests
    inject faulty clients (dropout / fault injection)."""
    import threading as _threading
    from ...core.distributed.communication.inproc import InProcBroker
    from ..horizontal.runner import _build_spec, _make_eval_fn
    from ..client.trainer import SiloTrainer
    from ...optimizers.registry import create_optimizer

    broker = InProcBroker()
    args.inproc_broker = broker
    spec = _build_spec(fed, bundle, spec)
    n = int(getattr(args, "client_num_per_round", 2))
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    init_rng, _ = jax.random.split(rng)
    global_params = bundle.init(init_rng, fed.train.x[0, 0])
    server = SecAggServerManager(args, global_params,
                                 eval_fn=_make_eval_fn(spec, fed),
                                 rank=0, size=n + 1, backend="INPROC")
    clients = []
    for r in range(1, n + 1):
        optimizer = create_optimizer(args, spec)
        trainer = SiloTrainer(args, fed, bundle, spec, optimizer)
        if client_factory is not None:
            clients.append(client_factory(r, args, trainer))
        else:
            clients.append(SecAggClientManager(args, trainer, rank=r,
                                               size=n + 1, backend="INPROC"))
    threads = [_threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30.0)
    return server.result
