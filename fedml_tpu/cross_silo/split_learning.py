"""Split learning (SplitNN) as a REAL distributed session over the comm
stack — the model is cut at a layer; parties exchange ONLY activations
(forward) and activation-gradients (backward) across the process/WAN
boundary.

Parity target: reference ``simulation/mpi/split_nn/SplitNNAPI.py:10`` with
``SplitNNClientManager``/``SplitNNServerManager`` exchanging
activations/grads over MPI and training clients round-robin. Here the
protocol rides the repo's :class:`FedMLCommManager` (INPROC threads, TCP,
or gRPC across OS processes — same FSM), and all party-local math is
jitted JAX: the client's cut-layer forward and its vjp backward are each
one compiled program, the server's head step (loss + head grads +
activation grads) is one compiled program, so the TPU work per message is
a single dispatch on either side.

The SP simulator (``simulation/sp/split_nn.py``) fuses the same math into
one end-to-end program for speed; this module is the same protocol in its
distributed form — results are numerically identical (chain rule is chain
rule whether or not a socket sits at the cut), which the parity test
asserts.

Privacy boundary: raw features never leave the client; labels travel with
activations (the label-sharing SplitNN variant, as in the reference).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..simulation.sp.split_nn import _Bottom, _Top

logger = logging.getLogger(__name__)


class SplitMsg:
    # client -> server
    C2S_ONLINE = 101
    C2S_ACTS = 102        # one batch of cut-layer activations (+ labels)
    C2S_DONE = 103        # client finished its local epochs
    C2S_EVAL_ACTS = 104   # test-set activations for server-side eval
    # server -> client
    S2C_ACTIVATE = 111    # your turn: run local epochs
    S2C_GRADS = 112       # d(loss)/d(activations) for the batch just sent
    S2C_EVALUATE = 113    # stream your test activations
    S2C_FINISH = 114

    K_ACTS = "acts"
    K_GRADS = "grads"
    K_LABELS = "labels"
    K_MASK = "mask"
    K_ROUND = "round_idx"


class SplitNNServerManager(FedMLCommManager):
    """Rank 0 — owns the model head (top). Initializes it lazily from the
    SHAPE of the first activation (dense-stack init depends on shapes and
    rng only, so this matches the SP simulator's probe init exactly)."""

    def __init__(self, args, output_dim: int, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.top = _Top(int(output_dim))
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        _, self._kt, _ = jax.random.split(rng, 3)
        self.top_params = None
        self.lr = float(args.learning_rate)
        self.rounds = int(getattr(args, "comm_round", 1))
        self.freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
        self.round_idx = 0
        self.client_num = size - 1
        self._online: List[int] = []
        self._active_pos = 0  # index into the sorted client order
        self.history: List[Dict[str, Any]] = []
        self.result: Optional[dict] = None
        self._step = jax.jit(self._step_impl)
        self._eval = jax.jit(self._eval_impl)

    # --- jitted math --------------------------------------------------------
    def _loss(self, tp, h, y, mask):
        logits = self.top.apply(tp, h)
        labels = y.astype(jnp.int32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits,
                                                                 labels)
        mask = mask.astype(per_ex.dtype)
        loss = jnp.sum(per_ex * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        correct = jnp.sum((jnp.argmax(logits, -1) == labels) * mask)
        return loss, (correct, jnp.sum(mask))

    def _step_impl(self, tp, h, y, mask):
        (_, aux), (gt, dh) = jax.value_and_grad(
            self._loss, argnums=(0, 1), has_aux=True)(tp, h, y, mask)
        new_tp = jax.tree_util.tree_map(lambda w, g: w - self.lr * g, tp, gt)
        return new_tp, dh, aux

    def _eval_impl(self, tp, h, y, mask):
        _, (correct, count) = self._loss(tp, h, y, mask)
        return correct, count

    # --- FSM ----------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(SplitMsg.C2S_ONLINE,
                                              self._on_online)
        self.register_message_receive_handler(SplitMsg.C2S_ACTS,
                                              self._on_acts)
        self.register_message_receive_handler(SplitMsg.C2S_DONE,
                                              self._on_done)
        self.register_message_receive_handler(SplitMsg.C2S_EVAL_ACTS,
                                              self._on_eval_acts)

    def _on_online(self, msg: Message) -> None:
        rank = msg.get_sender_id()
        if rank not in self._online:
            self._online.append(rank)
        logger.info("splitnn server: %d/%d parties online",
                    len(self._online), self.client_num)
        if len(self._online) >= self.client_num:
            self._online.sort()  # round-robin in cid order, like the SP sim
            self._activate(self._online[0])

    def _activate(self, rank: int) -> None:
        m = Message(SplitMsg.S2C_ACTIVATE, self.rank, rank)
        m.add_params(SplitMsg.K_ROUND, self.round_idx)
        self.send_message(m)

    def _on_acts(self, msg: Message) -> None:
        h = jnp.asarray(msg.get(SplitMsg.K_ACTS))
        y = jnp.asarray(msg.get(SplitMsg.K_LABELS))
        mask = jnp.asarray(msg.get(SplitMsg.K_MASK))
        if self.top_params is None:
            self.top_params = self.top.init(self._kt, jnp.zeros_like(h))
        self.top_params, dh, _ = self._step(self.top_params, h, y, mask)
        out = Message(SplitMsg.S2C_GRADS, self.rank, msg.get_sender_id())
        out.add_params(SplitMsg.K_GRADS, np.asarray(dh))
        self.send_message(out)

    def _on_done(self, msg: Message) -> None:
        self._active_pos += 1
        if self._active_pos < len(self._online):
            self._activate(self._online[self._active_pos])
            return
        # round complete
        self._active_pos = 0
        if self.freq > 0 and (self.round_idx % self.freq == 0
                              or self.round_idx == self.rounds - 1):
            # evaluate with the FIRST party's bottom (SP sim evaluates
            # client 0's pair; any one pair is a valid split model)
            self.send_message(Message(SplitMsg.S2C_EVALUATE, self.rank,
                                      self._online[0]))
            return
        self.history.append({"round": self.round_idx})
        self._advance()

    def _on_eval_acts(self, msg: Message) -> None:
        h = jnp.asarray(msg.get(SplitMsg.K_ACTS))
        y = jnp.asarray(msg.get(SplitMsg.K_LABELS))
        mask = jnp.asarray(msg.get(SplitMsg.K_MASK))
        correct, count = self._eval(self.top_params, h, y, mask)
        acc = float(correct) / max(float(count), 1.0)
        logger.info("splitnn server round %d: acc=%.4f", self.round_idx, acc)
        self.history.append({"round": self.round_idx, "test_acc": acc})
        self._advance()

    def _advance(self) -> None:
        self.round_idx += 1
        if self.round_idx >= self.rounds:
            for rank in self._online:
                self.send_message(Message(SplitMsg.S2C_FINISH, self.rank,
                                          rank))
            last = next((r for r in reversed(self.history)
                         if "test_acc" in r), {})
            self.result = {"params": {"top": self.top_params},
                           "history": self.history,
                           "final_test_acc": last.get("test_acc"),
                           "rounds": self.rounds}
            self.finish()
            return
        self._activate(self._online[0])


class SplitNNClientManager(FedMLCommManager):
    """Rank k>=1 — owns the bottom (feature extractor) for data silo
    ``k-1``. A state machine, not a blocking loop: handlers run on the
    receive thread, so each GRADS reply triggers the next batch send."""

    def __init__(self, args, fed, comm=None, rank: int = 1, size: int = 0,
                 backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        hidden = int(getattr(args, "splitnn_hidden", 128) or 128)
        self.bottom = _Bottom(hidden)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kb, _, _ = jax.random.split(rng, 3)
        sample = fed.train.x[0, 0]
        self.params = self.bottom.init(kb, sample)
        self.lr = float(args.learning_rate)
        cid = min(self.rank - 1, fed.num_clients - 1)
        self.cdata = jax.tree_util.tree_map(lambda a: a[cid], fed.train)
        self.test = fed.test
        self.epochs = int(getattr(args, "epochs", 1))
        self._fwd = jax.jit(self.bottom.apply)
        self._bwd = jax.jit(self._bwd_impl)
        # batches with at least one live sample, in order (padding batches
        # are no-op updates in the SP sim — skipping them is exact parity)
        self._real = [int(i) for i in
                      np.flatnonzero(np.asarray(
                          self.cdata.mask).sum(axis=-1) > 0)]
        self._epoch = 0
        self._pos = 0

    def _bwd_impl(self, p, x, dh):
        _, vjp = jax.vjp(lambda pp: self.bottom.apply(pp, x), p)
        (gp,) = vjp(dh)
        return jax.tree_util.tree_map(lambda w, g: w - self.lr * g, p, gp)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(SplitMsg.S2C_ACTIVATE,
                                              self._on_activate)
        self.register_message_receive_handler(SplitMsg.S2C_GRADS,
                                              self._on_grads)
        self.register_message_receive_handler(SplitMsg.S2C_EVALUATE,
                                              self._on_evaluate)
        self.register_message_receive_handler(SplitMsg.S2C_FINISH,
                                              self._on_finish)

    def run(self) -> None:
        m = Message(SplitMsg.C2S_ONLINE, self.rank, 0)
        self.send_message(m)
        super().run()

    def _on_activate(self, msg: Message) -> None:
        self._epoch = 0
        self._pos = 0
        self._send_next()

    def _send_next(self) -> None:
        if self._pos >= len(self._real):
            self._epoch += 1
            self._pos = 0
        if self._epoch >= self.epochs or not self._real:
            self.send_message(Message(SplitMsg.C2S_DONE, self.rank, 0))
            return
        b = self._real[self._pos]
        h = self._fwd(self.params, self.cdata.x[b])
        out = Message(SplitMsg.C2S_ACTS, self.rank, 0)
        out.add_params(SplitMsg.K_ACTS, np.asarray(h))
        out.add_params(SplitMsg.K_LABELS, np.asarray(self.cdata.y[b]))
        out.add_params(SplitMsg.K_MASK, np.asarray(self.cdata.mask[b]))
        self.send_message(out)

    def _on_grads(self, msg: Message) -> None:
        dh = jnp.asarray(msg.get(SplitMsg.K_GRADS))
        b = self._real[self._pos]
        self.params = self._bwd(self.params, self.cdata.x[b], dh)
        self._pos += 1
        self._send_next()

    def _on_evaluate(self, msg: Message) -> None:
        tx = jnp.asarray(self.test["x"])
        flat = tx.reshape((-1,) + tx.shape[2:])
        h = self._fwd(self.params, flat)
        out = Message(SplitMsg.C2S_EVAL_ACTS, self.rank, 0)
        out.add_params(SplitMsg.K_ACTS, np.asarray(h))
        out.add_params(SplitMsg.K_LABELS,
                       np.asarray(self.test["y"]).reshape(-1))
        out.add_params(SplitMsg.K_MASK,
                       np.asarray(self.test["mask"]).reshape(-1))
        self.send_message(out)

    def _on_finish(self, msg: Message) -> None:
        logger.info("splitnn client rank %d: finish", self.rank)
        self.finish()


def run_splitnn_inproc(args, fed) -> Dict[str, Any]:
    """Server + N party clients over the in-proc broker (parity test /
    `backend: INPROC` config path)."""
    from . import run_inproc_session
    n = int(getattr(args, "client_num_per_round",
                    getattr(args, "client_num_in_total", 2)))
    return run_inproc_session(args, lambda: [
        SplitNNServerManager(args, fed.num_classes, size=n + 1,
                             backend="INPROC"),
        *[SplitNNClientManager(args, fed, rank=r, size=n + 1,
                               backend="INPROC")
          for r in range(1, n + 1)]])
