"""LightSecAgg cross-silo runtime (the ``LSA`` federated optimizer).

Parity target: reference ``cross_silo/lightsecagg/`` (~950 LoC:
``lsa_fedml_server_manager.py``, ``lsa_fedml_client_manager.py``) over the
math of ``core/mpc/lightsecagg.py`` — So et al.'s one-shot
aggregate-mask reconstruction. Where Bonawitz SecAgg (the ``SA`` runtime)
needs a per-dropout Shamir reconstruction round, LightSecAgg decodes the
*aggregate* mask in one interpolation from any ``split_t + privacy_t``
surviving responses.

Per FL round r, client i:
  1. trains; computes q_i = quantize(n_i * delta_i), zero-padded so the
     field vector length divides ``split_t``;
  2. draws a fresh random mask z_i over GF(2^31-1) and Lagrange-encodes it
     into n coded sub-masks (``mask_encoding``), one per client;
  3. uploads (q_i + z_i mod p, n_i, {j: coded sub-mask for j}).
Server: picks the surviving set U1, routes each survivor j the sub-masks
{i in U1}; j replies with their field SUM (one addition — the "light"
part); the server interpolates sum_{i in U1} z_i from the first
``split_t + privacy_t`` responses, subtracts, de-quantizes, and advances
the round.

Confidentiality against the server: a one-time key phase distributes each
client's X25519 channel public key; every coded sub-mask is sealed for its
recipient with ChaCha20-Poly1305 under the pairwise ECDH key
(``core/mpc/channels.py``), so the server routes only ciphertext and — with
fewer than ``privacy_t + 1`` colluding clients — learns nothing about any
individual mask ``z_i`` beyond the aggregate it decodes.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...core.collectives import tree_flatten_to_vector, vector_to_tree_like
from ...core.distributed.communication.message import (Message, tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc import P, dequantize, quantize
from ...core.mpc import channels
from ...core.mpc.lightsecagg import decode_aggregate_mask, mask_encoding

logger = logging.getLogger(__name__)
_P_I = int(P)


class LSAMessage:
    C2S_PUBLIC_KEY = "lsa_pk"          # one-time channel-key advertisement
    S2C_PUBLIC_KEYS = "lsa_pks"
    S2C_TRAIN = "lsa_train"
    C2S_MASKED = "lsa_masked"          # masked input + sealed coded sub-masks
    S2C_AGG_REQUEST = "lsa_agg_req"    # surviving set + routed sub-masks
    C2S_AGG_SHARE = "lsa_agg_share"    # sum of routed sub-masks
    S2C_FINISH = "lsa_finish"

    KEY_PK = "pk"
    KEY_PKS = "pks"
    KEY_MODEL = "model"
    KEY_ROUND = "round"
    KEY_MASKED = "masked"
    KEY_N = "n"
    KEY_ENCODED = "encoded"            # {str(j): sealed sub-mask for j}
    KEY_ROUTED = "routed"              # {str(i): sealed sub-mask from i}
    KEY_SURVIVING = "surviving"
    KEY_AGG = "agg"


def lsa_params(n_clients: int, privacy_t: int, threshold: int):
    """split_t such that any ``threshold`` survivors can decode:
    responses needed = split_t + privacy_t <= threshold."""
    split_t = max(threshold - privacy_t, 1)
    return split_t


def _refuse_wire_compression(args) -> None:
    """LightSecAgg cannot compose with the core/wire compressors: its
    field encoding maps negatives to ``p - |q|`` (full-field magnitudes
    that overflow any low-bit lane of ``secagg_compress_bits``), and the
    MDS-coded sub-masks split the UNPACKED ``d_pad`` vector into
    ``split_t`` chunks — packing would change the vector the coding is
    defined over. Per-client sparsification support sets additionally
    leak masked coordinates. Refused outright rather than silently
    ignored or corrupted."""
    for knob in ("secagg_compress_bits", "comm_compression"):
        if getattr(args, knob, None):
            raise ValueError(
                "%s=%r is incompatible with LightSecAgg (full-field "
                "negative encodings overflow low-bit lanes; sparsifier "
                "support sets leak masked coordinates)"
                % (knob, getattr(args, knob)))


class LSAClientManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank: int = 1, size: int = 0,
                 backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        _refuse_wire_compression(args)
        self.trainer = trainer
        self.idx = rank - 1
        self.n_clients = size - 1
        self.privacy_t = int(getattr(args, "lsa_privacy_t", 1) or 1)
        thr = int(getattr(args, "lsa_threshold", 0) or 0)
        self.threshold = thr if thr > 0 else max(self.n_clients - 1, 2)
        self.split_t = lsa_params(self.n_clients, self.privacy_t,
                                  self.threshold)
        self.round_idx = 0
        # masks z_i and Lagrange coding noise are SECRET: OS entropy only —
        # a z drawn from the public random_seed config could simply be
        # regenerated by the server, unmasking every update
        self._rng = channels.secret_rng()
        self.enc_sk, self.enc_pk = channels.keygen()
        self.peer_publics: Dict[int, bytes] = {}

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(LSAMessage.S2C_PUBLIC_KEYS,
                                              self.on_public_keys)
        self.register_message_receive_handler(LSAMessage.S2C_TRAIN,
                                              self.on_train)
        self.register_message_receive_handler(LSAMessage.S2C_AGG_REQUEST,
                                              self.on_agg_request)
        self.register_message_receive_handler(LSAMessage.S2C_FINISH,
                                              self.on_finish)

    def run(self) -> None:
        msg = Message(LSAMessage.C2S_PUBLIC_KEY, self.rank, 0)
        msg.add_params(LSAMessage.KEY_PK, self.enc_pk)
        self.send_message(msg)
        super().run()

    def on_public_keys(self, msg: Message) -> None:
        self.peer_publics = {int(k): bytes(v) for k, v in
                             msg.get(LSAMessage.KEY_PKS).items()}

    def on_train(self, msg: Message) -> None:
        self.round_idx = int(msg.get(LSAMessage.KEY_ROUND, 0))
        params = wire_to_tree(msg.get(LSAMessage.KEY_MODEL),
                              self.trainer.params_template)
        new_params, n, _ = self.trainer.train(params, self.idx,
                                              self.round_idx)
        delta = jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), new_params, params)
        vec = np.asarray(tree_flatten_to_vector(delta), np.float32)
        q = np.asarray(quantize(vec * np.float32(n))).astype(np.uint64)
        # pad so the mask length divides split_t
        d = len(q)
        d_pad = -(-d // self.split_t) * self.split_t
        q = np.pad(q, (0, d_pad - d))
        z = self._rng.randint(0, _P_I, size=d_pad).astype(np.uint64)
        masked = ((q + z) % _P_I).astype(np.uint32)
        enc = mask_encoding(z, self.n_clients, self.privacy_t, self.split_t,
                            self._rng)  # [n, d_pad // split_t]
        out = Message(LSAMessage.C2S_MASKED, self.rank, 0)
        out.add_params(LSAMessage.KEY_MASKED, masked)
        out.add_params(LSAMessage.KEY_N, float(n))
        # each coded sub-mask is sealed for its recipient — the server
        # routes ciphertext it cannot read (the aad binds sender, receiver
        # and round so blobs cannot be replayed across slots or rounds)
        out.add_params(LSAMessage.KEY_ENCODED, {
            str(j): channels.seal(
                self.enc_sk, self.peer_publics[j],
                enc[j].astype("<u4").tobytes(),
                aad=channels.pair_aad(self.idx, j,
                                      b"lsa-r%d" % self.round_idx))
            for j in range(self.n_clients)})
        self.send_message(out)

    def on_agg_request(self, msg: Message) -> None:
        routed: Dict[str, Any] = msg.get(LSAMessage.KEY_ROUTED)
        round_idx = int(msg.get(LSAMessage.KEY_ROUND, self.round_idx))
        acc = None
        for i, blob in routed.items():
            try:
                pt = channels.open_sealed(
                    self.enc_sk, self.peer_publics[int(i)], bytes(blob),
                    aad=channels.pair_aad(int(i), self.idx,
                                          b"lsa-r%d" % round_idx))
            except channels.DecryptError as e:
                # ANY failed blob poisons the sum: a partial sum is a wrong
                # Lagrange evaluation point and would silently corrupt the
                # server's one-shot decode. Refuse to respond; the server's
                # agg-phase timeout handles the missing share.
                logger.error("lsa client %d: sub-mask from %s failed "
                             "authentication (%s); not responding",
                             self.idx, i, e)
                return
            sub = np.frombuffer(pt, "<u4").astype(np.uint64)
            acc = sub if acc is None else (acc + sub) % _P_I
        if acc is None:
            return
        out = Message(LSAMessage.C2S_AGG_SHARE, self.rank, 0)
        out.add_params(LSAMessage.KEY_AGG, acc.astype(np.uint32))
        self.send_message(out)

    def on_finish(self, msg: Message) -> None:
        self.finish()


class LSAServerManager(FedMLCommManager):
    def __init__(self, args, global_params, eval_fn=None, comm=None,
                 rank: int = 0, size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        _refuse_wire_compression(args)
        self.global_params = global_params
        self.eval_fn = eval_fn
        self.n_clients = size - 1
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.privacy_t = int(getattr(args, "lsa_privacy_t", 1) or 1)
        thr = int(getattr(args, "lsa_threshold", 0) or 0)
        self.threshold = thr if thr > 0 else max(self.n_clients - 1, 2)
        self.split_t = lsa_params(self.n_clients, self.privacy_t,
                                  self.threshold)
        self.round_timeout = float(getattr(args, "round_timeout_s", 0) or 0)
        # liveness floor: even with round_timeout_s unset, a crashed or
        # non-responding peer must eventually abort the session instead of
        # deadlocking it (generous: first tunneled compiles take ~40s)
        # 60s floor: first-round jit compiles stall ~40s on the tunneled
        # chip; a 3x leash on a tight operator timeout must not abort a
        # healthy session mid-compile
        self._leash_s = (max(3.0 * self.round_timeout, 60.0)
                         if self.round_timeout > 0 else 300.0)
        self._template_vec = np.asarray(
            tree_flatten_to_vector(global_params))
        self.publics: Dict[int, bytes] = {}
        self._keys_done = False
        self.masked: Dict[int, np.ndarray] = {}
        self.weights: Dict[int, float] = {}
        # owner -> {recipient: sealed blob} — opaque to the server
        self.encoded: Dict[int, Dict[str, Any]] = {}
        self.agg_shares: List = []
        self._surviving: List[int] = []
        self._phase = "collect"
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self.history: List[Dict[str, Any]] = []
        self.result: Optional[dict] = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(LSAMessage.C2S_PUBLIC_KEY,
                                              self.on_public_key)
        self.register_message_receive_handler(LSAMessage.C2S_MASKED,
                                              self.on_masked)
        self.register_message_receive_handler(LSAMessage.C2S_AGG_SHARE,
                                              self.on_agg_share)

    def run(self) -> None:
        self.register_message_receive_handlers()
        # key-phase leash: one client crashing before its pk send must not
        # hang the session forever (rounds only arm timers once started)
        self._timer = threading.Timer(self._leash_s, self._on_setup_timeout)
        self._timer.daemon = True
        self._timer.start()
        self.com_manager.handle_receive_message()

    def _on_setup_timeout(self) -> None:
        with self._lock:
            if self._keys_done:
                return
            logger.error("lsa: only %d/%d public keys at setup timeout — "
                         "aborting session", len(self.publics),
                         self.n_clients)
            self._phase = "done"
            self.result = {"error": "lsa_setup_timeout"}
        for rank in range(1, self.n_clients + 1):
            self.send_message(Message(LSAMessage.S2C_FINISH, 0, rank))
        self.finish()

    def on_public_key(self, msg: Message) -> None:
        """One-time channel-key phase: first round starts once every
        client's public key is in (the sub-mask seals need them all).
        Duplicate advertisements (client retries) must not re-trigger the
        broadcast or restart the round mid-protocol."""
        with self._lock:
            if self._keys_done:
                return
            self.publics[msg.get_sender_id() - 1] = bytes(
                msg.get(LSAMessage.KEY_PK))
            if len(self.publics) < self.n_clients:
                return
            self._keys_done = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        for rank in range(1, self.n_clients + 1):
            out = Message(LSAMessage.S2C_PUBLIC_KEYS, 0, rank)
            out.add_params(LSAMessage.KEY_PKS,
                           {str(k): v for k, v in self.publics.items()})
            self.send_message(out)
        self._start_round()

    def _start_round(self) -> None:
        with self._lock:
            self._phase = "collect"
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(
                self._leash_s, self._on_collect_timeout,
                args=(self.round_idx,))
            self._timer.daemon = True
            self._timer.start()
        wire = tree_to_wire(self.global_params)
        for rank in range(1, self.n_clients + 1):
            out = Message(LSAMessage.S2C_TRAIN, 0, rank)
            out.add_params(LSAMessage.KEY_MODEL, wire)
            out.add_params(LSAMessage.KEY_ROUND, self.round_idx)
            self.send_message(out)

    def _on_collect_timeout(self, armed_round: int) -> None:
        with self._lock:
            if self._phase != "collect" or self.round_idx != armed_round:
                return
            if len(self.masked) < max(self.threshold,
                                      self.split_t + self.privacy_t):
                logger.error(
                    "lsa round %d: %d masked inputs < threshold %d at "
                    "timeout — aborting", self.round_idx, len(self.masked),
                    self.threshold)
                self._phase = "done"
                self.result = {"error": "lsa_below_threshold",
                               "round": self.round_idx}
                abort = True
            else:
                logger.warning(
                    "lsa round %d: proceeding with %d/%d survivors",
                    self.round_idx, len(self.masked), self.n_clients)
                self._begin_agg_locked()
                abort = False
        if abort:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(LSAMessage.S2C_FINISH, 0, rank))
            self.finish()

    def on_masked(self, msg: Message) -> None:
        idx = msg.get_sender_id() - 1
        with self._lock:
            if self._phase != "collect":
                logger.warning("lsa: late masked input from %d ignored", idx)
                return
            self.masked[idx] = np.asarray(msg.get(LSAMessage.KEY_MASKED),
                                          np.uint32)
            self.weights[idx] = float(msg.get(LSAMessage.KEY_N))
            self.encoded[idx] = msg.get(LSAMessage.KEY_ENCODED)
            if len(self.masked) == self.n_clients:
                self._begin_agg_locked()
            elif self.round_timeout > 0 and len(self.masked) == 1:
                if self._timer is not None:
                    self._timer.cancel()
                self._timer = threading.Timer(
                    self.round_timeout, self._on_collect_timeout,
                    args=(self.round_idx,))
                self._timer.daemon = True
                self._timer.start()

    def _begin_agg_locked(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._phase = "agg"
        self._surviving = sorted(self.masked)
        self.agg_shares = []
        # a survivor dying (or refusing a tampered blob) between masked
        # upload and agg response must not hang the decode phase either
        self._timer = threading.Timer(
            self._leash_s, self._on_agg_timeout, args=(self.round_idx,))
        self._timer.daemon = True
        self._timer.start()
        for j in self._surviving:
            out = Message(LSAMessage.S2C_AGG_REQUEST, 0, j + 1)
            out.add_params(LSAMessage.KEY_ROUND, self.round_idx)
            out.add_params(LSAMessage.KEY_SURVIVING,
                           [int(i) for i in self._surviving])
            out.add_params(LSAMessage.KEY_ROUTED,
                           {str(i): self.encoded[i][str(j)]
                            for i in self._surviving})
            self.send_message(out)

    def _on_agg_timeout(self, armed_round: int) -> None:
        with self._lock:
            if self._phase != "agg" or self.round_idx != armed_round:
                return
            logger.error(
                "lsa round %d: only %d/%d agg shares at timeout — decode "
                "impossible, aborting session", self.round_idx,
                len(self.agg_shares), self.split_t + self.privacy_t)
            self._phase = "done"
            self.result = {"error": "lsa_agg_timeout",
                           "round": self.round_idx}
        for rank in range(1, self.n_clients + 1):
            self.send_message(Message(LSAMessage.S2C_FINISH, 0, rank))
        self.finish()

    def on_agg_share(self, msg: Message) -> None:
        j = msg.get_sender_id() - 1
        need = self.split_t + self.privacy_t
        with self._lock:
            if self._phase != "agg":
                return
            self.agg_shares.append((j, np.asarray(
                msg.get(LSAMessage.KEY_AGG), np.uint32)))
            if len(self.agg_shares) < need:
                return
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._phase = "decode"
        self._decode_and_advance()

    def _decode_and_advance(self) -> None:
        need = self.split_t + self.privacy_t
        responders = [j for j, _ in self.agg_shares[:need]]
        responses = [s.astype(np.uint64) for _, s in self.agg_shares[:need]]
        d = len(self._template_vec)
        d_pad = -(-d // self.split_t) * self.split_t
        z_sum = decode_aggregate_mask(responses, responders, self.n_clients,
                                      self.privacy_t, self.split_t, d_pad)
        total = np.zeros(d_pad, np.uint64)
        for i in self._surviving:
            total = (total + self.masked[i].astype(np.uint64)) % _P_I
        total = (total + _P_I - z_sum % _P_I) % _P_I
        vec = np.asarray(dequantize(total[:d].astype(np.uint32)))
        wsum = sum(self.weights[i] for i in self._surviving)
        agg_delta = vector_to_tree_like(
            (vec / max(wsum, 1e-12)).astype(np.float32), self.global_params)
        self.global_params = jax.tree_util.tree_map(
            lambda g, u: np.asarray(g) + np.asarray(u),
            self.global_params, agg_delta)
        rec: Dict[str, Any] = {"round": self.round_idx,
                               "survivors": len(self._surviving)}
        if self.eval_fn is not None:
            rec.update(self.eval_fn(self.global_params))
            logger.info("lsa round %d: %s", self.round_idx, rec)
        self.history.append(rec)
        with self._lock:
            self.masked.clear()
            self.weights.clear()
            self.encoded.clear()
            self.agg_shares = []
            self._surviving = []
            self.round_idx += 1
            done = self.round_idx >= self.round_num
            if done:
                self._phase = "done"
        if done:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(LSAMessage.S2C_FINISH, 0, rank))
            last = next((r for r in reversed(self.history)
                         if "test_acc" in r), {})
            self.result = {"params": self.global_params,
                           "history": self.history,
                           "final_test_acc": last.get("test_acc"),
                           "rounds": self.round_num}
            self.finish()
            return
        self._start_round()


def run_lsa_inproc(args, fed, bundle, spec=None,
                   client_factory=None) -> Dict[str, Any]:
    """Server + N LightSecAgg clients as threads over the in-proc broker."""
    import threading as _threading

    from ...core.distributed.communication.inproc import InProcBroker
    from ...optimizers.registry import create_optimizer
    from ..client.trainer import SiloTrainer
    from ..horizontal.runner import _build_spec, _make_eval_fn

    broker = InProcBroker()
    args.inproc_broker = broker
    spec = _build_spec(fed, bundle, spec)
    n = int(getattr(args, "client_num_per_round", 2))
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    init_rng, _ = jax.random.split(rng)
    global_params = jax.device_get(bundle.init(init_rng, fed.train.x[0, 0]))
    server = LSAServerManager(args, global_params,
                              eval_fn=_make_eval_fn(spec, fed),
                              rank=0, size=n + 1, backend="INPROC")
    import copy
    inner_args = copy.copy(args)
    inner_args.federated_optimizer = "FedAvg"  # protocol rides plain FedAvg
    clients = []
    for r in range(1, n + 1):
        optimizer = create_optimizer(inner_args, spec)
        trainer = SiloTrainer(args, fed, bundle, spec, optimizer)
        if client_factory is not None:
            clients.append(client_factory(r, args, trainer))
        else:
            clients.append(LSAClientManager(args, trainer, rank=r,
                                            size=n + 1, backend="INPROC"))
    threads = [_threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30.0)
    return server.result
