"""LightSecAgg cross-silo runtime (the ``LSA`` federated optimizer).

Parity target: reference ``cross_silo/lightsecagg/`` (~950 LoC:
``lsa_fedml_server_manager.py``, ``lsa_fedml_client_manager.py``) over the
math of ``core/mpc/lightsecagg.py`` — So et al.'s one-shot
aggregate-mask reconstruction. Where Bonawitz SecAgg (the ``SA`` runtime)
needs a per-dropout Shamir reconstruction round, LightSecAgg decodes the
*aggregate* mask in one interpolation from any ``split_t + privacy_t``
surviving responses.

Per FL round r, client i:
  1. trains; computes q_i = quantize(n_i * delta_i), zero-padded so the
     field vector length divides ``split_t``;
  2. draws a fresh random mask z_i over GF(2^31-1) and Lagrange-encodes it
     into n coded sub-masks (``mask_encoding``), one per client;
  3. uploads (q_i + z_i mod p, n_i, {j: coded sub-mask for j}).
Server: picks the surviving set U1, routes each survivor j the sub-masks
{i in U1}; j replies with their field SUM (one addition — the "light"
part); the server interpolates sum_{i in U1} z_i from the first
``split_t + privacy_t`` responses, subtracts, de-quantizes, and advances
the round.

SECURITY SCOPE: protocol-shape parity only, like the SA runtime — coded
sub-masks are routed through the server in plaintext (no p2p encryption in
this environment), so the server is not an honest-but-curious adversary
the deployment defends against. The masking algebra, coding math, and
one-shot reconstruction match the paper; add transport encryption between
clients for the real privacy property.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...core.collectives import tree_flatten_to_vector, vector_to_tree_like
from ...core.distributed.communication.message import (Message, tree_to_wire,
                                                       wire_to_tree)
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc import P, dequantize, quantize
from ...core.mpc.lightsecagg import decode_aggregate_mask, mask_encoding

logger = logging.getLogger(__name__)
_P_I = int(P)


class LSAMessage:
    S2C_TRAIN = "lsa_train"
    C2S_MASKED = "lsa_masked"          # masked input + coded sub-masks
    S2C_AGG_REQUEST = "lsa_agg_req"    # surviving set + routed sub-masks
    C2S_AGG_SHARE = "lsa_agg_share"    # sum of routed sub-masks
    S2C_FINISH = "lsa_finish"

    KEY_MODEL = "model"
    KEY_ROUND = "round"
    KEY_MASKED = "masked"
    KEY_N = "n"
    KEY_ENCODED = "encoded"            # {str(j): uint32 sub-mask for j}
    KEY_ROUTED = "routed"              # {str(i): uint32 sub-mask from i}
    KEY_SURVIVING = "surviving"
    KEY_AGG = "agg"


def lsa_params(n_clients: int, privacy_t: int, threshold: int):
    """split_t such that any ``threshold`` survivors can decode:
    responses needed = split_t + privacy_t <= threshold."""
    split_t = max(threshold - privacy_t, 1)
    return split_t


class LSAClientManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank: int = 1, size: int = 0,
                 backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.idx = rank - 1
        self.n_clients = size - 1
        self.privacy_t = int(getattr(args, "lsa_privacy_t", 1) or 1)
        thr = int(getattr(args, "lsa_threshold", 0) or 0)
        self.threshold = thr if thr > 0 else max(self.n_clients - 1, 2)
        self.split_t = lsa_params(self.n_clients, self.privacy_t,
                                  self.threshold)
        self.round_idx = 0
        self._rng = np.random.RandomState(
            int(getattr(args, "random_seed", 0)) * 1009 + 77 + self.idx)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(LSAMessage.S2C_TRAIN,
                                              self.on_train)
        self.register_message_receive_handler(LSAMessage.S2C_AGG_REQUEST,
                                              self.on_agg_request)
        self.register_message_receive_handler(LSAMessage.S2C_FINISH,
                                              self.on_finish)

    def on_train(self, msg: Message) -> None:
        self.round_idx = int(msg.get(LSAMessage.KEY_ROUND, 0))
        params = wire_to_tree(msg.get(LSAMessage.KEY_MODEL),
                              self.trainer.params_template)
        new_params, n, _ = self.trainer.train(params, self.idx,
                                              self.round_idx)
        delta = jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), new_params, params)
        vec = np.asarray(tree_flatten_to_vector(delta), np.float32)
        q = np.asarray(quantize(vec * np.float32(n))).astype(np.uint64)
        # pad so the mask length divides split_t
        d = len(q)
        d_pad = -(-d // self.split_t) * self.split_t
        q = np.pad(q, (0, d_pad - d))
        z = self._rng.randint(0, _P_I, size=d_pad).astype(np.uint64)
        masked = ((q + z) % _P_I).astype(np.uint32)
        enc = mask_encoding(z, self.n_clients, self.privacy_t, self.split_t,
                            self._rng)  # [n, d_pad // split_t]
        out = Message(LSAMessage.C2S_MASKED, self.rank, 0)
        out.add_params(LSAMessage.KEY_MASKED, masked)
        out.add_params(LSAMessage.KEY_N, float(n))
        out.add_params(LSAMessage.KEY_ENCODED,
                       {str(j): enc[j].astype(np.uint32)
                        for j in range(self.n_clients)})
        self.send_message(out)

    def on_agg_request(self, msg: Message) -> None:
        routed: Dict[str, Any] = msg.get(LSAMessage.KEY_ROUTED)
        acc = None
        for _i, sub in routed.items():
            sub = np.asarray(sub, np.uint64)
            acc = sub if acc is None else (acc + sub) % _P_I
        out = Message(LSAMessage.C2S_AGG_SHARE, self.rank, 0)
        out.add_params(LSAMessage.KEY_AGG, acc.astype(np.uint32))
        self.send_message(out)

    def on_finish(self, msg: Message) -> None:
        self.finish()


class LSAServerManager(FedMLCommManager):
    def __init__(self, args, global_params, eval_fn=None, comm=None,
                 rank: int = 0, size: int = 0, backend: str = "INPROC"):
        super().__init__(args, comm, rank, size, backend)
        self.global_params = global_params
        self.eval_fn = eval_fn
        self.n_clients = size - 1
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.privacy_t = int(getattr(args, "lsa_privacy_t", 1) or 1)
        thr = int(getattr(args, "lsa_threshold", 0) or 0)
        self.threshold = thr if thr > 0 else max(self.n_clients - 1, 2)
        self.split_t = lsa_params(self.n_clients, self.privacy_t,
                                  self.threshold)
        self.round_timeout = float(getattr(args, "round_timeout_s", 0) or 0)
        self._template_vec = np.asarray(
            tree_flatten_to_vector(global_params))
        self.masked: Dict[int, np.ndarray] = {}
        self.weights: Dict[int, float] = {}
        self.encoded: Dict[int, Dict[str, np.ndarray]] = {}
        self.agg_shares: List = []
        self._surviving: List[int] = []
        self._phase = "collect"
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self.history: List[Dict[str, Any]] = []
        self.result: Optional[dict] = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(LSAMessage.C2S_MASKED,
                                              self.on_masked)
        self.register_message_receive_handler(LSAMessage.C2S_AGG_SHARE,
                                              self.on_agg_share)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self._start_round()
        self.com_manager.handle_receive_message()

    def _start_round(self) -> None:
        with self._lock:
            self._phase = "collect"
            if self.round_timeout > 0:
                leash = max(3.0 * self.round_timeout, 60.0)
                self._timer = threading.Timer(
                    leash, self._on_collect_timeout, args=(self.round_idx,))
                self._timer.daemon = True
                self._timer.start()
        wire = tree_to_wire(self.global_params)
        for rank in range(1, self.n_clients + 1):
            out = Message(LSAMessage.S2C_TRAIN, 0, rank)
            out.add_params(LSAMessage.KEY_MODEL, wire)
            out.add_params(LSAMessage.KEY_ROUND, self.round_idx)
            self.send_message(out)

    def _on_collect_timeout(self, armed_round: int) -> None:
        with self._lock:
            if self._phase != "collect" or self.round_idx != armed_round:
                return
            if len(self.masked) < max(self.threshold,
                                      self.split_t + self.privacy_t):
                logger.error(
                    "lsa round %d: %d masked inputs < threshold %d at "
                    "timeout — aborting", self.round_idx, len(self.masked),
                    self.threshold)
                self._phase = "done"
                self.result = {"error": "lsa_below_threshold",
                               "round": self.round_idx}
                abort = True
            else:
                logger.warning(
                    "lsa round %d: proceeding with %d/%d survivors",
                    self.round_idx, len(self.masked), self.n_clients)
                self._begin_agg_locked()
                abort = False
        if abort:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(LSAMessage.S2C_FINISH, 0, rank))
            self.finish()

    def on_masked(self, msg: Message) -> None:
        idx = msg.get_sender_id() - 1
        with self._lock:
            if self._phase != "collect":
                logger.warning("lsa: late masked input from %d ignored", idx)
                return
            self.masked[idx] = np.asarray(msg.get(LSAMessage.KEY_MASKED),
                                          np.uint32)
            self.weights[idx] = float(msg.get(LSAMessage.KEY_N))
            self.encoded[idx] = msg.get(LSAMessage.KEY_ENCODED)
            if len(self.masked) == self.n_clients:
                self._begin_agg_locked()
            elif self.round_timeout > 0 and len(self.masked) == 1:
                if self._timer is not None:
                    self._timer.cancel()
                self._timer = threading.Timer(
                    self.round_timeout, self._on_collect_timeout,
                    args=(self.round_idx,))
                self._timer.daemon = True
                self._timer.start()

    def _begin_agg_locked(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._phase = "agg"
        self._surviving = sorted(self.masked)
        self.agg_shares = []
        if self.round_timeout > 0:
            # a survivor dying between masked upload and agg response must
            # not hang the decode phase either
            self._timer = threading.Timer(
                max(self.round_timeout, 10.0), self._on_agg_timeout,
                args=(self.round_idx,))
            self._timer.daemon = True
            self._timer.start()
        for j in self._surviving:
            out = Message(LSAMessage.S2C_AGG_REQUEST, 0, j + 1)
            out.add_params(LSAMessage.KEY_SURVIVING,
                           [int(i) for i in self._surviving])
            out.add_params(LSAMessage.KEY_ROUTED,
                           {str(i): self.encoded[i][str(j)]
                            for i in self._surviving})
            self.send_message(out)

    def _on_agg_timeout(self, armed_round: int) -> None:
        with self._lock:
            if self._phase != "agg" or self.round_idx != armed_round:
                return
            logger.error(
                "lsa round %d: only %d/%d agg shares at timeout — decode "
                "impossible, aborting session", self.round_idx,
                len(self.agg_shares), self.split_t + self.privacy_t)
            self._phase = "done"
            self.result = {"error": "lsa_agg_timeout",
                           "round": self.round_idx}
        for rank in range(1, self.n_clients + 1):
            self.send_message(Message(LSAMessage.S2C_FINISH, 0, rank))
        self.finish()

    def on_agg_share(self, msg: Message) -> None:
        j = msg.get_sender_id() - 1
        need = self.split_t + self.privacy_t
        with self._lock:
            if self._phase != "agg":
                return
            self.agg_shares.append((j, np.asarray(
                msg.get(LSAMessage.KEY_AGG), np.uint32)))
            if len(self.agg_shares) < need:
                return
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._phase = "decode"
        self._decode_and_advance()

    def _decode_and_advance(self) -> None:
        need = self.split_t + self.privacy_t
        responders = [j for j, _ in self.agg_shares[:need]]
        responses = [s.astype(np.uint64) for _, s in self.agg_shares[:need]]
        d = len(self._template_vec)
        d_pad = -(-d // self.split_t) * self.split_t
        z_sum = decode_aggregate_mask(responses, responders, self.n_clients,
                                      self.privacy_t, self.split_t, d_pad)
        total = np.zeros(d_pad, np.uint64)
        for i in self._surviving:
            total = (total + self.masked[i].astype(np.uint64)) % _P_I
        total = (total + _P_I - z_sum % _P_I) % _P_I
        vec = np.asarray(dequantize(total[:d].astype(np.uint32)))
        wsum = sum(self.weights[i] for i in self._surviving)
        agg_delta = vector_to_tree_like(
            (vec / max(wsum, 1e-12)).astype(np.float32), self.global_params)
        self.global_params = jax.tree_util.tree_map(
            lambda g, u: np.asarray(g) + np.asarray(u),
            self.global_params, agg_delta)
        rec: Dict[str, Any] = {"round": self.round_idx,
                               "survivors": len(self._surviving)}
        if self.eval_fn is not None:
            rec.update(self.eval_fn(self.global_params))
            logger.info("lsa round %d: %s", self.round_idx, rec)
        self.history.append(rec)
        with self._lock:
            self.masked.clear()
            self.weights.clear()
            self.encoded.clear()
            self.agg_shares = []
            self._surviving = []
            self.round_idx += 1
            done = self.round_idx >= self.round_num
            if done:
                self._phase = "done"
        if done:
            for rank in range(1, self.n_clients + 1):
                self.send_message(Message(LSAMessage.S2C_FINISH, 0, rank))
            last = next((r for r in reversed(self.history)
                         if "test_acc" in r), {})
            self.result = {"params": self.global_params,
                           "history": self.history,
                           "final_test_acc": last.get("test_acc"),
                           "rounds": self.round_num}
            self.finish()
            return
        self._start_round()


def run_lsa_inproc(args, fed, bundle, spec=None,
                   client_factory=None) -> Dict[str, Any]:
    """Server + N LightSecAgg clients as threads over the in-proc broker."""
    import threading as _threading

    from ...core.distributed.communication.inproc import InProcBroker
    from ...optimizers.registry import create_optimizer
    from ..client.trainer import SiloTrainer
    from ..horizontal.runner import _build_spec, _make_eval_fn

    broker = InProcBroker()
    args.inproc_broker = broker
    spec = _build_spec(fed, bundle, spec)
    n = int(getattr(args, "client_num_per_round", 2))
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    init_rng, _ = jax.random.split(rng)
    global_params = jax.device_get(bundle.init(init_rng, fed.train.x[0, 0]))
    server = LSAServerManager(args, global_params,
                              eval_fn=_make_eval_fn(spec, fed),
                              rank=0, size=n + 1, backend="INPROC")
    import copy
    inner_args = copy.copy(args)
    inner_args.federated_optimizer = "FedAvg"  # protocol rides plain FedAvg
    clients = []
    for r in range(1, n + 1):
        optimizer = create_optimizer(inner_args, spec)
        trainer = SiloTrainer(args, fed, bundle, spec, optimizer)
        if client_factory is not None:
            clients.append(client_factory(r, args, trainer))
        else:
            clients.append(LSAClientManager(args, trainer, rank=r,
                                            size=n + 1, backend="INPROC"))
    threads = [_threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30.0)
    return server.result
