"""Federated optimizer protocol: a (client transform, server transform) pair.

This is the functional re-design of the reference's algorithm layer — one
class per federated optimizer replaces the reference's per-optimizer
trainer/aggregator/manager triples (``ml/trainer/fedprox_trainer.py``,
``simulation/sp/*``, ``simulation/mpi/*``). The engine (SP golden loop or TPU
mesh) is optimizer-agnostic: it calls ``local_train`` per scheduled client,
reduces ``update * weight`` (and ``extras``) with a weighted psum, then calls
``server_update`` — exactly the NCCL simulator's pre-scaled SUM-reduce shape
(``nccl/base_framework/LocalAggregator.py:85-96``, ``Server.py:192-198``)
generalized to every optimizer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.algframe.types import ClientData, ClientOutput, TrainHyper
from ..core.algframe.client_trainer import TrainerSpec, make_inner_optimizer
from ..core.algframe.local_training import run_local_sgd
from ..core.collectives import tree_add, tree_sub

PyTree = Any


class FedOptimizer:
    """Base = FedAvg semantics (``sp/fedavg/fedavg_api.py:144``: weighted
    average of client models with post-sampling ``n_k/Σn`` weights; in delta
    form: ``w ← w + Σ n_k Δ_k / Σ n_k``)."""

    name = "FedAvg"
    has_client_state = False
    # True only for optimizers whose client pass evaluates the SHARED
    # global params with no per-client trajectory (FedSGD): the engine may
    # then fold the [S] client-slot axis into the batch axis
    # (``client_slot_fold``) because the weighted update sum is exactly
    # additive over samples. Local-SGD optimizers iterate per-client
    # params and can never fold.
    folds_client_slots = False

    def __init__(self, args, spec: TrainerSpec):
        self.args = args
        self.spec = spec
        self.inner_opt_name = getattr(args, "client_optimizer", "sgd")
        self.momentum = getattr(args, "momentum", 0.0) or 0.0
        self.weight_decay = getattr(args, "weight_decay", 0.0) or 0.0

    # --- state constructors -------------------------------------------------
    def server_init(self, params: PyTree) -> PyTree:
        return {}

    def client_state_init(self, params: PyTree) -> PyTree:
        """Per-client persistent state (one client's worth; engines stack it
        over all clients)."""
        return {}

    def server_extras_zero(self, params: PyTree) -> Dict[str, Any]:
        """Zero-valued pytree matching ``ClientOutput.extras`` — engines need
        it to initialize the weighted-psum accumulator."""
        return {}

    # --- client transform ---------------------------------------------------
    def make_inner_opt(self, hyper: TrainHyper):
        return make_inner_optimizer(
            self.inner_opt_name, hyper.learning_rate,
            momentum=self.momentum, weight_decay=self.weight_decay)

    def grad_transform(self, grads: PyTree, params: PyTree,
                       ctx: Dict[str, Any]) -> PyTree:
        return grads

    def local_train(
        self,
        global_params: PyTree,
        server_state: PyTree,
        client_state: PyTree,
        cdata: ClientData,
        rng: jax.Array,
        hyper: TrainHyper,
    ) -> ClientOutput:
        inner_opt = self.make_inner_opt(hyper)
        ctx = {"global_params": global_params, "server_state": server_state,
               "client_state": client_state, "hyper": hyper}
        params, _, metrics = run_local_sgd(
            self.spec, inner_opt, global_params, cdata, rng, hyper,
            grad_transform=self.grad_transform, ctx=ctx)
        update = tree_sub(params, global_params)
        return ClientOutput(
            update=update,
            weight=cdata.num_samples.astype(jnp.float32),
            client_state=client_state,
            extras={},
            metrics=metrics,
        )

    # --- server transform ---------------------------------------------------
    def server_update(
        self,
        params: PyTree,
        server_state: PyTree,
        agg_update: PyTree,
        agg_extras: Dict[str, Any],
        round_idx: jnp.ndarray,
    ) -> Tuple[PyTree, PyTree]:
        """``agg_update`` and ``agg_extras`` are already weight-averaged by
        the engine (Σ n_k x_k / Σ n_k)."""
        return tree_add(params, agg_update), server_state

    def server_update_async(
        self,
        params: PyTree,
        server_state: PyTree,
        agg_update: PyTree,
        agg_extras: Dict[str, Any],
        round_idx: jnp.ndarray,
        merge_scale: jnp.ndarray,
        pour_frac: jnp.ndarray,
    ) -> Tuple[PyTree, PyTree]:
        """Buffered-async server transform (``round_mode: async_buffered``).

        ``agg_update``/``agg_extras`` are the staleness-weighted average of
        one poured buffer; ``merge_scale`` is the pour's absolute damping
        (FedAsync's ``alpha * s(staleness)`` generalized to a K-buffer:
        ``alpha * Σ w·s / Σ w``) and ``pour_frac`` the poured fraction of
        the population (``K / N`` — what replaces the sync cohort fraction
        in participation-scaled state updates). Both are traced scalars
        (DATA), so per-pour staleness never recompiles the program.

        Default correction: damp the aggregate (and extras) by
        ``merge_scale`` and reuse the sync transform — exact for
        linear-in-the-update transforms (FedAvg/FedProx/FedSGD, SCAFFOLD's
        ``c`` update via the damped extras). Optimizers whose server step
        is NOT linear in its input override this (FedOpt's adaptive
        optimizers normalize away input scale)."""
        del pour_frac  # linear transforms need no separate fraction
        scaled = jax.tree_util.tree_map(
            lambda u: u * merge_scale.astype(u.dtype), agg_update)
        scaled_ex = jax.tree_util.tree_map(
            lambda e: e * merge_scale.astype(e.dtype), agg_extras)
        return self.server_update(params, server_state, scaled, scaled_ex,
                                  round_idx)
