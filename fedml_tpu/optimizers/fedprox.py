"""FedProx — proximal-regularized local training.

Parity target: the reference's FedProx trainer (``ml/trainer/fedprox_trainer.py``,
``simulation/sp/fedprox/``): local objective ``F_k(w) + (mu/2)||w - w_t||^2``.
TPU-native form: the proximal term is a ``grad_transform`` hook on the shared
scanned local-SGD loop — ``g <- g + mu (w - w_t)`` — so the whole client step
stays one fused XLA program; server transform is plain FedAvg.
"""

from __future__ import annotations

import jax

from .base import FedOptimizer
from .registry import register


@register
class FedProx(FedOptimizer):
    name = "FedProx"

    def __init__(self, args, spec):
        super().__init__(args, spec)
        self.mu = float(getattr(args, "fedprox_mu", 0.1))

    def grad_transform(self, grads, params, ctx):
        mu = self.mu
        gp = ctx["global_params"]
        return jax.tree_util.tree_map(
            lambda g, w, w0: g + mu * (w - w0), grads, params, gp)
