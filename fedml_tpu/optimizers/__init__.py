from .base import FedOptimizer
from .registry import create_optimizer, available_optimizers, register

# importing registers each optimizer under its reference name
from . import fedprox, fedopt, scaffold, fednova, feddyn, mime  # noqa: F401,E402

__all__ = ["FedOptimizer", "create_optimizer", "available_optimizers",
           "register"]
