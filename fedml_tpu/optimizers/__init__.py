from .base import FedOptimizer
from .registry import create_optimizer, available_optimizers, register

__all__ = ["FedOptimizer", "create_optimizer", "available_optimizers",
           "register"]
