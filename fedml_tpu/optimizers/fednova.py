"""FedNova — normalized averaging for heterogeneous local work.

Parity target: ``ml/trainer/fednova_trainer.py`` + ``simulation/sp/fednova``
(Wang et al.): each client normalizes its accumulated update by its own
effective step budget ``a_i``, the server rescales the average by
``tau_eff = sum_k p_k a_i`` so objective-inconsistency from unequal local
steps cancels:

    w+ = w + tau_eff * sum_k p_k (Delta_k / a_i).

For momentum-SGD clients (factor rho), ``a_i = (tau - rho(1-rho^tau)/(1-rho))
/ (1-rho)``; for plain SGD ``a_i = tau``. The normalized delta is the
``ClientOutput.update`` and ``a_i`` rides the weighted psum via ``extras``,
so the server transform needs no extra communication round.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.algframe.local_training import effective_steps, run_local_sgd
from ..core.algframe.types import ClientOutput
from ..core.collectives import tree_sub
from .base import FedOptimizer, PyTree
from .registry import register


@register
class FedNova(FedOptimizer):
    name = "FedNova"

    def _a_i(self, tau: jnp.ndarray) -> jnp.ndarray:
        rho = jnp.float32(self.momentum)
        plain = tau
        mom = (tau - rho * (1.0 - jnp.power(rho, tau)) / (1.0 - rho)) / (1.0 - rho)
        return jnp.where(rho > 0, mom, plain)

    def local_train(self, global_params, server_state, client_state, cdata,
                    rng, hyper) -> ClientOutput:
        inner_opt = self.make_inner_opt(hyper)
        params, _, metrics = run_local_sgd(
            self.spec, inner_opt, global_params, cdata, rng, hyper)
        delta = tree_sub(params, global_params)
        tau = effective_steps(cdata, hyper.epochs,
                              getattr(hyper, "work_scale", 1.0))
        a_i = self._a_i(tau)
        normalized = jax.tree_util.tree_map(
            lambda d: d / a_i.astype(d.dtype), delta)
        return ClientOutput(
            update=normalized,
            weight=cdata.num_samples.astype(jnp.float32),
            client_state=client_state,
            extras={"a": a_i},
            metrics=metrics)

    def server_extras_zero(self, params: PyTree):
        return {"a": jnp.float32(0.0)}

    def server_update(self, params, server_state, agg_update, agg_extras,
                      round_idx) -> Tuple[PyTree, PyTree]:
        tau_eff = agg_extras["a"]  # sum_k p_k a_i (weighted psum average)
        new_params = jax.tree_util.tree_map(
            lambda w, u: w + tau_eff.astype(w.dtype) * u, params, agg_update)
        return new_params, server_state
