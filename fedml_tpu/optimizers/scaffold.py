"""SCAFFOLD — stochastic controlled averaging with control variates.

Parity target: ``ml/trainer/scaffold_trainer.py`` + ``simulation/sp/scaffold``
(client drift correction ``g <- g - c_i + c``; option-II control-variate
update ``c_i+ = c_i - c + (w_t - w_local)/(K * lr)``; server
``x <- x + lr_g * avg(dx)``, ``c <- c + (|S|/N) * avg(dc)``).

TPU-native form: ``c`` lives in the replicated server state, each client's
``c_i`` in the per-client sharded state, the correction is a
``grad_transform`` on the shared scanned loop, and ``dc_i`` rides the same
weighted psum as the model delta (``ClientOutput.extras``).

Math note: the control-variate update assumes a plain-SGD inner optimizer;
use ``client_optimizer: sgd`` with zero momentum.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.algframe.local_training import effective_steps, run_local_sgd
from ..core.algframe.types import ClientOutput
from ..core.collectives import tree_sub, tree_zeros_like
from .base import FedOptimizer, PyTree
from .registry import register


@register
class SCAFFOLD(FedOptimizer):
    name = "SCAFFOLD"
    has_client_state = True

    def __init__(self, args, spec):
        super().__init__(args, spec)
        self.server_lr = float(getattr(args, "server_lr", 1.0))
        n_total = int(getattr(args, "client_num_in_total", 1))
        n_round = int(getattr(args, "client_num_per_round", n_total))
        self.participation = float(n_round) / float(max(n_total, 1))

    def server_init(self, params: PyTree) -> PyTree:
        return {"c": tree_zeros_like(params)}

    def client_state_init(self, params: PyTree) -> PyTree:
        return {"c_i": tree_zeros_like(params)}

    def server_extras_zero(self, params: PyTree):
        return {"delta_c": tree_zeros_like(params)}

    def grad_transform(self, grads, params, ctx):
        c = ctx["server_state"]["c"]
        c_i = ctx["client_state"]["c_i"]
        return jax.tree_util.tree_map(
            lambda g, cc, ci: g + cc - ci, grads, c, c_i)

    def local_train(self, global_params, server_state, client_state, cdata,
                    rng, hyper) -> ClientOutput:
        inner_opt = self.make_inner_opt(hyper)
        ctx = {"global_params": global_params, "server_state": server_state,
               "client_state": client_state, "hyper": hyper}
        params, _, metrics = run_local_sgd(
            self.spec, inner_opt, global_params, cdata, rng, hyper,
            grad_transform=self.grad_transform, ctx=ctx)
        update = tree_sub(params, global_params)
        k = effective_steps(cdata, hyper.epochs,
                            getattr(hyper, "work_scale", 1.0))
        inv_klr = 1.0 / (k * hyper.learning_rate)
        c, c_i = server_state["c"], client_state["c_i"]
        # option II: c_i+ = c_i - c - update/(K*lr)
        new_c_i = jax.tree_util.tree_map(
            lambda ci, cc, u: ci - cc - u * inv_klr.astype(u.dtype),
            c_i, c, update)
        delta_c = tree_sub(new_c_i, c_i)
        return ClientOutput(
            update=update,
            weight=cdata.num_samples.astype(jnp.float32),
            client_state={"c_i": new_c_i},
            extras={"delta_c": delta_c},
            metrics=metrics)

    def server_update(self, params, server_state, agg_update, agg_extras,
                      round_idx) -> Tuple[PyTree, PyTree]:
        lr_g = jnp.float32(self.server_lr)
        frac = jnp.float32(self.participation)
        new_params = jax.tree_util.tree_map(
            lambda w, u: w + lr_g.astype(w.dtype) * u, params, agg_update)
        new_c = jax.tree_util.tree_map(
            lambda cc, dc: cc + frac.astype(cc.dtype) * dc,
            server_state["c"], agg_extras["delta_c"])
        return new_params, {"c": new_c}

    def server_update_async(self, params, server_state, agg_update,
                            agg_extras, round_idx, merge_scale, pour_frac):
        """Staleness correction: the params step is the damped aggregate
        (linear — same as the base default), but the control variate must
        advance by the POURED population fraction (``K / N``), not the
        sync cohort fraction baked into ``self.participation`` — a K-sized
        pour carries K clients' worth of drift evidence regardless of how
        many are concurrently in flight. ``delta_c`` is damped by the same
        ``merge_scale`` as the update: stale drift estimates are as
        outdated as stale updates."""
        lr_g = jnp.float32(self.server_lr)
        new_params = jax.tree_util.tree_map(
            lambda w, u: w + (lr_g * merge_scale).astype(w.dtype) * u,
            params, agg_update)
        new_c = jax.tree_util.tree_map(
            lambda cc, dc: cc + (pour_frac * merge_scale).astype(cc.dtype)
            * dc, server_state["c"], agg_extras["delta_c"])
        return new_params, {"c": new_c}
