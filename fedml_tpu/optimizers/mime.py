"""Mime (MimeLite variant) — server statistics applied, not updated, locally.

Parity target: ``ml/trainer/mime_trainer.py`` + ``simulation/sp/mime``
(Karimireddy et al.): clients take SGD steps using the *server's* momentum
buffer ``m`` held fixed (``g' = (1-beta) g + beta m``), and return the
full-batch gradient at the global parameters; the server refreshes
``m <- (1-beta) avg_full_grad + beta m`` and averages parameters as usual.

TPU-native form: ``m`` is replicated server state; the fixed-momentum step is
a ``grad_transform``; the full-batch gradient rides the weighted psum via
``extras`` — one round stays one XLA program.

Math note: assumes a plain-SGD inner optimizer (``client_optimizer: sgd``,
zero client momentum); the momentum blending is Mime's own.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.algframe.local_training import full_batch_grad, run_local_sgd
from ..core.algframe.types import ClientOutput
from ..core.collectives import tree_sub, tree_zeros_like
from .base import FedOptimizer, PyTree
from .registry import register


@register
class Mime(FedOptimizer):
    name = "Mime"

    def __init__(self, args, spec):
        super().__init__(args, spec)
        self.beta = float(getattr(args, "server_momentum", 0.9))

    def server_init(self, params: PyTree) -> PyTree:
        return {"m": tree_zeros_like(params)}

    def server_extras_zero(self, params: PyTree):
        return {"full_grad": tree_zeros_like(params)}

    def grad_transform(self, grads, params, ctx):
        beta = self.beta
        m = ctx["server_state"]["m"]
        return jax.tree_util.tree_map(
            lambda g, mm: (1.0 - beta) * g + beta * mm, grads, m)

    def local_train(self, global_params, server_state, client_state, cdata,
                    rng, hyper) -> ClientOutput:
        inner_opt = self.make_inner_opt(hyper)
        ctx = {"global_params": global_params, "server_state": server_state,
               "client_state": client_state, "hyper": hyper}
        sgd_rng, grad_rng = jax.random.split(rng)
        params, _, metrics = run_local_sgd(
            self.spec, inner_opt, global_params, cdata, sgd_rng, hyper,
            grad_transform=self.grad_transform, ctx=ctx)
        full_grad, _ = full_batch_grad(self.spec, global_params, cdata, grad_rng)
        return ClientOutput(
            update=tree_sub(params, global_params),
            weight=cdata.num_samples.astype(jnp.float32),
            client_state=client_state,
            extras={"full_grad": full_grad},
            metrics=metrics)

    def server_update(self, params, server_state, agg_update, agg_extras,
                      round_idx) -> Tuple[PyTree, PyTree]:
        beta = jnp.float32(self.beta)
        new_m = jax.tree_util.tree_map(
            lambda mm, g: (1.0 - beta).astype(mm.dtype) * g
            + beta.astype(mm.dtype) * mm,
            server_state["m"], agg_extras["full_grad"])
        new_params = jax.tree_util.tree_map(jnp.add, params, agg_update)
        return new_params, {"m": new_m}
