"""FedOpt family — adaptive *server* optimization over the aggregated
pseudo-gradient, plus the degenerate FedSGD / FedLocalSGD variants.

Parity targets: ``simulation/sp/fedopt/`` (server optimizer applied to the
averaged client delta; reference defaults to momentum SGD), reference
optimizer names ``FedOpt``/``FedOpt_seq``/``FedSGD``/``FedLocalSGD``
(``constants.py:40-60``). TPU-native form: the server transform is an optax
``GradientTransformation`` whose state is part of the replicated
``server_state`` pytree, so the FedOpt step runs inside the same jitted
round program as the psum aggregation.

``server_optimizer`` options: sgd (momentum = ``server_momentum``), adam,
adagrad, yogi — the four from Reddi et al., "Adaptive Federated
Optimization", which the reference's FedOpt implements.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from ..core.algframe.local_training import (full_batch_grad,
                                            full_batch_grad_sum)
from ..core.algframe.types import ClientOutput
from .base import FedOptimizer, PyTree
from .registry import register


def make_server_optimizer(name: str, lr: float, momentum: float = 0.9
                          ) -> optax.GradientTransformation:
    name = (name or "sgd").lower()
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum or None)
    if name == "adam":
        return optax.adam(lr)
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "yogi":
        return optax.yogi(lr)
    raise ValueError(f"unknown server_optimizer {name!r}")


@register
class FedOpt(FedOptimizer):
    name = "FedOpt"

    def __init__(self, args, spec):
        super().__init__(args, spec)
        self.server_opt = make_server_optimizer(
            getattr(args, "server_optimizer", "sgd"),
            float(getattr(args, "server_lr", 1.0)),
            float(getattr(args, "server_momentum", 0.9)))

    def server_init(self, params: PyTree) -> PyTree:
        return {"opt_state": self.server_opt.init(params)}

    def server_update(self, params, server_state, agg_update, agg_extras,
                      round_idx) -> Tuple[PyTree, PyTree]:
        # pseudo-gradient = -averaged delta (Reddi et al. Eq. 2)
        pseudo_grad = jax.tree_util.tree_map(lambda u: -u, agg_update)
        updates, opt_state = self.server_opt.update(
            pseudo_grad, server_state["opt_state"], params)
        return optax.apply_updates(params, updates), {"opt_state": opt_state}

    def server_update_async(self, params, server_state, agg_update,
                            agg_extras, round_idx, merge_scale, pour_frac):
        """Staleness correction for ADAPTIVE server optimizers: adam/yogi
        normalize the step by running second moments, so scaling the
        pseudo-gradient (the base-class default) would be erased by the
        normalization — a pour of ancient updates would move the model at
        full rate. Damp the APPLIED STEP instead: moments accumulate the
        undamped pseudo-gradient (they estimate its statistics, which
        staleness does not change), the parameter step is scaled by
        ``merge_scale``."""
        del pour_frac
        pseudo_grad = jax.tree_util.tree_map(lambda u: -u, agg_update)
        updates, opt_state = self.server_opt.update(
            pseudo_grad, server_state["opt_state"], params)
        damped = jax.tree_util.tree_map(
            lambda u: u * merge_scale.astype(u.dtype), updates)
        return optax.apply_updates(params, damped), {"opt_state": opt_state}


@register
class FedSGD(FedOptimizer):
    """One aggregated gradient step per round: clients return their
    full-batch gradient (no local SGD), the server applies it with
    ``server_lr`` — the communication-maximal baseline
    (``FedML_FEDERATED_OPTIMIZER_FEDSGD``, ``constants.py:59``)."""

    name = "FedSGD"
    # every client's gradient is taken at the SAME global params, and the
    # engine aggregate Σ_k n_k·upd_k = -Σ over all reporting samples g_i
    # is additive over samples — so the [S] client-slot axis may fold
    # into the batch axis (ISSUE 16 client_slot_fold)
    folds_client_slots = True

    def __init__(self, args, spec):
        super().__init__(args, spec)
        self.server_lr = float(getattr(args, "server_lr", 1.0))

    def local_train(self, global_params, server_state, client_state, cdata,
                    rng, hyper) -> ClientOutput:
        grads, metrics = full_batch_grad(self.spec, global_params, cdata, rng)
        update = jax.tree_util.tree_map(lambda g: -g, grads)
        return ClientOutput(update=update,
                            weight=cdata.num_samples.astype(jnp.float32),
                            client_state=client_state, extras={},
                            metrics=metrics)

    def local_train_folded(self, global_params, folded_cdata, rng
                           ) -> Tuple[PyTree, Dict[str, Any]]:
        """One pass over a CLIENT-FOLDED batch (the engine reshapes the
        [S] slot axis into the batch axis): returns the weight-scaled
        update SUM ``-Σ_i g_i`` plus the summed metrics — exactly what the
        slot scan's ``Σ_k w_k·upd_k`` accumulator would hold, computed
        with S-times-larger per-op batches."""
        grad_sum, metrics = full_batch_grad_sum(
            self.spec, global_params, folded_cdata, rng)
        update_sum = jax.tree_util.tree_map(lambda g: -g, grad_sum)
        return update_sum, metrics

    def server_update(self, params, server_state, agg_update, agg_extras,
                      round_idx):
        lr = jnp.float32(self.server_lr)
        new = jax.tree_util.tree_map(
            lambda w, u: w + lr.astype(w.dtype) * u, params, agg_update)
        return new, server_state


@register
class FedLocalSGD(FedOptimizer):
    """Local SGD with periodic (uniform) parameter averaging — FedAvg with
    equal client weights (``FedML_FEDERATED_OPTIMIZER_FEDLOCALSGD``)."""

    name = "FedLocalSGD"

    def local_train(self, global_params, server_state, client_state, cdata,
                    rng, hyper) -> ClientOutput:
        out = super().local_train(global_params, server_state, client_state,
                                  cdata, rng, hyper)
        return out.replace(weight=jnp.float32(1.0))
