"""Registry mapping ``federated_optimizer`` names to optimizer classes —
the dispatch analogue of ``simulation/simulator.py:27-216`` (SP: 11
optimizers, MPI: 14) without the per-backend duplication: one optimizer class
serves every engine."""

from __future__ import annotations

from typing import Dict, Type

from .base import FedOptimizer

_REGISTRY: Dict[str, Type[FedOptimizer]] = {}


def register(cls: Type[FedOptimizer]) -> Type[FedOptimizer]:
    _REGISTRY[cls.name.lower()] = cls
    return cls


def create_optimizer(args, spec) -> FedOptimizer:
    name = str(getattr(args, "federated_optimizer", "FedAvg"))
    # "_seq" suffixes pick the same math; sequential multi-client-per-chip
    # scheduling is an engine concern here (schedule tensor), not a separate
    # algorithm (reference has FedAvg_seq/FedOpt_seq as distinct stacks).
    key = name.lower().removesuffix("_seq")
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown federated_optimizer {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key](args, spec)


def available_optimizers():
    return sorted(_REGISTRY)


register(FedOptimizer)  # FedAvg
