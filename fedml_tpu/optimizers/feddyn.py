"""FedDyn — dynamic regularization (Acar et al.).

Parity target: ``ml/trainer/feddyn_trainer.py`` + ``simulation/sp/feddyn``.
Client k minimizes ``F_k(w) - <h_k, w> + (alpha/2)||w - w_t||^2`` where
``h_k`` is its accumulated first-order correction; after training
``h_k <- h_k - alpha * (w_k - w_t)``. The server keeps
``h = -(alpha/N) * sum_k accumulated deltas``:

    h+ = h - alpha * (|S|/N) * avg_update,   w+ = (w_t + avg_update) - h+/alpha.

TPU-native form: ``h_k`` is per-client sharded state, the linear + proximal
terms are a ``grad_transform``, and the server correction is part of the
replicated server state inside the jitted round.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.algframe.local_training import run_local_sgd
from ..core.algframe.types import ClientOutput
from ..core.collectives import tree_sub, tree_zeros_like
from .base import FedOptimizer, PyTree
from .registry import register


@register
class FedDyn(FedOptimizer):
    name = "FedDyn"
    has_client_state = True

    def __init__(self, args, spec):
        super().__init__(args, spec)
        self.alpha = float(getattr(args, "feddyn_alpha", 0.01))
        n_total = int(getattr(args, "client_num_in_total", 1))
        n_round = int(getattr(args, "client_num_per_round", n_total))
        self.participation = float(n_round) / float(max(n_total, 1))

    def server_init(self, params: PyTree) -> PyTree:
        return {"h": tree_zeros_like(params)}

    def client_state_init(self, params: PyTree) -> PyTree:
        return {"h_i": tree_zeros_like(params)}

    def grad_transform(self, grads, params, ctx):
        alpha = self.alpha
        gp = ctx["global_params"]
        h_i = ctx["client_state"]["h_i"]
        return jax.tree_util.tree_map(
            lambda g, w, w0, h: g + alpha * (w - w0) - h, grads, params, gp, h_i)

    def local_train(self, global_params, server_state, client_state, cdata,
                    rng, hyper) -> ClientOutput:
        inner_opt = self.make_inner_opt(hyper)
        ctx = {"global_params": global_params, "server_state": server_state,
               "client_state": client_state, "hyper": hyper}
        params, _, metrics = run_local_sgd(
            self.spec, inner_opt, global_params, cdata, rng, hyper,
            grad_transform=self.grad_transform, ctx=ctx)
        update = tree_sub(params, global_params)
        alpha = jnp.float32(self.alpha)
        new_h_i = jax.tree_util.tree_map(
            lambda h, u: h - alpha.astype(u.dtype) * u,
            client_state["h_i"], update)
        return ClientOutput(
            update=update,
            weight=cdata.num_samples.astype(jnp.float32),
            client_state={"h_i": new_h_i},
            extras={},
            metrics=metrics)

    def server_update(self, params, server_state, agg_update, agg_extras,
                      round_idx) -> Tuple[PyTree, PyTree]:
        alpha = jnp.float32(self.alpha)
        frac = jnp.float32(self.participation)
        new_h = jax.tree_util.tree_map(
            lambda h, u: h - (alpha * frac).astype(u.dtype) * u,
            server_state["h"], agg_update)
        new_params = jax.tree_util.tree_map(
            lambda w, u, h: w + u - h / alpha.astype(w.dtype),
            params, agg_update, new_h)
        return new_params, {"h": new_h}
