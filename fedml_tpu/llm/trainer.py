"""Causal-LM trainer spec — plugs the LLM into the algorithm frame.

Parity target: ``HFTrainer`` (reference ``train/llm/hf_trainer.py:28``) and
the completion-only collator (``modeling_utils.py:28``): per-token
cross-entropy where prompt/padding positions are excluded from the loss.
Here ignored positions are encoded as label ``-1`` inside the standard
``{"x", "y", "mask"}`` batch, so the spec composes with ``run_local_sgd``
and therefore with the whole federated-optimizer zoo, the defense/DP hook
chain, and both simulators — the LLM is not a special case of the runtime,
just another TrainerSpec.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

from ..core.algframe.client_trainer import TrainerSpec

PyTree = Any


class CausalLMTrainer(TrainerSpec):
    """Next-token CE. Batch: ``x`` [bs, L] int tokens, ``y`` [bs, L] labels
    with ``-1`` = ignore (prompt tokens under completion-only masking,
    right-padding), ``mask`` [bs] per-sample realness."""

    def _stats(self, params, batch, rng, train):
        kwargs = {"train": train}
        if rng is not None:
            kwargs["rng"] = rng
        logits = self.apply_fn(params, batch["x"], **kwargs)
        labels = batch["y"].astype(jnp.int32)
        tok_w = ((labels >= 0).astype(jnp.float32)
                 * batch["mask"].astype(jnp.float32)[:, None])
        safe = jnp.maximum(labels, 0)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
        loss_sum = jnp.sum(per_tok * tok_w)
        correct = jnp.sum((jnp.argmax(logits, -1) == safe) * tok_w)
        count = jnp.sum(tok_w)
        return loss_sum, correct, count

    def loss(self, params, batch, rng):
        loss_sum, correct, count = self._stats(params, batch, rng, True)
        loss = loss_sum / jnp.maximum(count, 1.0)
        return loss, {"loss_sum": loss_sum, "correct": correct,
                      "count": count}

    def eval_stats(self, params, batch):
        loss_sum, correct, count = self._stats(params, batch, None, False)
        return {"loss_sum": loss_sum, "correct": correct, "count": count}
