"""Federated LLM fine-tuning — the UnitedLLM/FedLLM analogue.

Parity target: ``spotlight_prj/unitedllm/src/unitedllm_trainer.py:57``
(HFTrainer used as the FedML ClientTrainer in a cross-silo job) and the
BASELINE.md ``FedLLM LoRA`` config. TPU-native: the trainable pytree each
silo ships is the LoRA adapter tree alone (base weights frozen and never
communicated), so a federated round aggregates kilobytes instead of the
full model — the design SURVEY §7 calls for ("get_model_params … cheap
all_gather on the LoRA adapters only").

``build_llm(args)`` wires the pieces into the standard (fed, bundle, spec)
triple, so every runner — SP golden, jitted TPU engine, cross-silo WAN
FSM — fine-tunes the LLM with zero special-casing.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .data import ByteTokenizer, build_llm_federated
from .lora import lora_init, make_lora_apply
from .model import CausalLM, LLMConfig, init_llm
from .trainer import CausalLMTrainer

logger = logging.getLogger(__name__)
PyTree = Any


def llm_config_from_args(args) -> LLMConfig:
    """Map the flat config namespace onto LLMConfig (reference
    ``ModelArguments``, ``train/llm/configurations.py:156``)."""
    precision = str(getattr(args, "precision", "float32")).lower()
    dtype = "bfloat16" if precision in ("bf16", "bfloat16") else "float32"
    return LLMConfig(
        vocab_size=int(getattr(args, "llm_vocab_size", ByteTokenizer.vocab_size)),
        hidden_size=int(getattr(args, "llm_hidden_size", 128)),
        intermediate_size=int(getattr(args, "llm_intermediate_size", 352)),
        num_layers=int(getattr(args, "llm_num_layers", 2)),
        num_heads=int(getattr(args, "llm_num_heads", 4)),
        num_kv_heads=getattr(args, "llm_num_kv_heads", None),
        max_seq_len=int(getattr(args, "llm_max_seq_len", 128)),
        dtype=dtype,
        # default: the fused Pallas flash kernels on TPU (O(s·block) memory
        # in both directions), dense elsewhere (interpret-mode flash is for
        # tests, not training)
        attention_impl=str(getattr(args, "llm_attention_impl", None)
                           or ("flash" if jax.default_backend() == "tpu"
                               else "dense")),
    )


@dataclasses.dataclass
class LLMBundle:
    """ModelBundle-compatible wrapper whose trainable pytree is the LoRA
    adapter tree (or the full params when ``lora_rank == 0``)."""

    module: CausalLM
    cfg: LLMConfig
    base_params: Optional[PyTree]  # None = full fine-tune
    lora_rank: int
    lora_alpha: float
    name: str = "causal_lm"

    def __post_init__(self):
        if self.base_params is not None:
            self._apply = make_lora_apply(self._raw_apply, self.base_params,
                                          self.lora_alpha)
        else:
            self._apply = self._raw_apply

    def _raw_apply(self, params, x, rng=None, train=False):
        del rng  # no dropout in the decoder
        return self.module.apply({"params": params}, x, train=train)

    def init(self, rng: jax.Array, sample_input: jnp.ndarray) -> PyTree:
        if self.base_params is not None:
            return lora_init(rng, self.base_params, rank=self.lora_rank)
        return self.module.init(rng, sample_input[:1])["params"]

    def apply(self, params, x, rng=None, train=False):
        return self._apply(params, x, rng=rng, train=train)


def build_llm_bundle(args) -> Tuple[LLMBundle, ByteTokenizer]:
    """Model-only build (no dataset): what serving replicas need — a
    replica restart must not pay corpus construction just to rebuild the
    bundle an artifact's params plug into."""
    cfg = llm_config_from_args(args)
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    module, base_params = init_llm(cfg, rng)
    rank = int(getattr(args, "lora_rank", 8))
    alpha = float(getattr(args, "lora_alpha", 16.0))
    bundle = LLMBundle(module, cfg,
                       base_params if rank > 0 else None, rank, alpha)
    return bundle, ByteTokenizer()


def build_llm(args) -> Tuple[Any, LLMBundle, CausalLMTrainer, ByteTokenizer]:
    """→ (fed_dataset, bundle, trainer_spec, tokenizer)."""
    bundle, _ = build_llm_bundle(args)
    n_silos = int(getattr(args, "client_num_in_total", 2))
    fed, tokenizer = build_llm_federated(args, n_silos,
                                         bundle.cfg.max_seq_len)
    spec = CausalLMTrainer(bundle.apply)
    return fed, bundle, spec, tokenizer


def run_federated_llm(args) -> dict:
    """Run a federated LoRA fine-tune with the standard runner dispatch
    (simulation backend or cross-silo per ``args.training_type``)."""
    from ..runner import FedMLRunner

    fed, bundle, spec, _ = build_llm(args)
    runner = FedMLRunner(args, dataset=fed, model=bundle,
                         client_trainer=spec)
    return runner.run()
