"""Federated LLM fine-tuning — the UnitedLLM/FedLLM analogue.

Parity target: ``spotlight_prj/unitedllm/src/unitedllm_trainer.py:57``
(HFTrainer used as the FedML ClientTrainer in a cross-silo job) and the
BASELINE.md ``FedLLM LoRA`` config. TPU-native: the trainable pytree each
silo ships is the LoRA adapter tree alone (base weights frozen and never
communicated), so a federated round aggregates kilobytes instead of the
full model — the design SURVEY §7 calls for ("get_model_params … cheap
all_gather on the LoRA adapters only").

``build_llm(args)`` wires the pieces into the standard (fed, bundle, spec)
triple, so every runner — SP golden, jitted TPU engine, cross-silo WAN
FSM — fine-tunes the LLM with zero special-casing.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .data import ByteTokenizer, build_llm_federated
from .lora import lora_init, make_lora_apply
from .model import CausalLM, LLMConfig, init_llm
from .trainer import CausalLMTrainer

logger = logging.getLogger(__name__)
PyTree = Any


def llm_config_from_args(args) -> LLMConfig:
    """Map the flat config namespace onto LLMConfig (reference
    ``ModelArguments``, ``train/llm/configurations.py:156``)."""
    precision = str(getattr(args, "precision", "float32")).lower()
    dtype = "bfloat16" if precision in ("bf16", "bfloat16") else "float32"
    return LLMConfig(
        vocab_size=int(getattr(args, "llm_vocab_size", ByteTokenizer.vocab_size)),
        hidden_size=int(getattr(args, "llm_hidden_size", 128)),
        intermediate_size=int(getattr(args, "llm_intermediate_size", 352)),
        num_layers=int(getattr(args, "llm_num_layers", 2)),
        num_heads=int(getattr(args, "llm_num_heads", 4)),
        num_kv_heads=getattr(args, "llm_num_kv_heads", None),
        max_seq_len=int(getattr(args, "llm_max_seq_len", 128)),
        dtype=dtype,
        # default: the fused Pallas flash kernels on TPU (O(s·block) memory
        # in both directions), dense elsewhere (interpret-mode flash is for
        # tests, not training)
        attention_impl=str(getattr(args, "llm_attention_impl", None)
                           or ("flash" if jax.default_backend() == "tpu"
                               else "dense")),
    )


@dataclasses.dataclass
class LLMBundle:
    """ModelBundle-compatible wrapper whose trainable pytree is the LoRA
    adapter tree (or the full params when ``lora_rank == 0``)."""

    module: CausalLM
    cfg: LLMConfig
    base_params: Optional[PyTree]  # None = full fine-tune
    lora_rank: int
    lora_alpha: float
    name: str = "causal_lm"

    def __post_init__(self):
        if self.base_params is not None:
            self._apply = make_lora_apply(self._raw_apply, self.base_params,
                                          self.lora_alpha)
        else:
            self._apply = self._raw_apply

    def _raw_apply(self, params, x, rng=None, train=False):
        del rng  # no dropout in the decoder
        return self.module.apply({"params": params}, x, train=train)

    def init(self, rng: jax.Array, sample_input: jnp.ndarray) -> PyTree:
        if self.base_params is not None:
            return lora_init(rng, self.base_params, rank=self.lora_rank)
        return self.module.init(rng, sample_input[:1])["params"]

    def apply(self, params, x, rng=None, train=False):
        return self._apply(params, x, rng=rng, train=train)


def build_llm_bundle(args) -> Tuple[LLMBundle, ByteTokenizer]:
    """Model-only build (no dataset): what serving replicas need — a
    replica restart must not pay corpus construction just to rebuild the
    bundle an artifact's params plug into."""
    cfg = llm_config_from_args(args)
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    module, base_params = init_llm(cfg, rng)
    rank = int(getattr(args, "lora_rank", 8))
    alpha = float(getattr(args, "lora_alpha", 16.0))
    bundle = LLMBundle(module, cfg,
                       base_params if rank > 0 else None, rank, alpha)
    return bundle, ByteTokenizer()


def build_llm(args) -> Tuple[Any, LLMBundle, CausalLMTrainer, ByteTokenizer]:
    """→ (fed_dataset, bundle, trainer_spec, tokenizer)."""
    bundle, _ = build_llm_bundle(args)
    n_silos = int(getattr(args, "client_num_in_total", 2))
    fed, tokenizer = build_llm_federated(args, n_silos,
                                         bundle.cfg.max_seq_len)
    spec = CausalLMTrainer(bundle.apply)
    return fed, bundle, spec, tokenizer


def run_federated_llm(args) -> dict:
    """Run a federated LoRA fine-tune with the standard runner dispatch
    (simulation backend or cross-silo per ``args.training_type``).
    ``llm_adapter_export_dir`` additionally writes the global + per-silo
    personalized adapters as named artifacts the serving adapter bank
    (``serving/batch/``) loads."""
    from ..runner import FedMLRunner

    fed, bundle, spec, _ = build_llm(args)
    export_dir = getattr(args, "llm_adapter_export_dir", None)
    if export_dir and int(getattr(args, "lora_rank", 8)) <= 0:
        # fail BEFORE the (possibly hours-long) run, not after it
        raise ValueError("llm_adapter_export_dir needs lora_rank > 0 "
                         "(the adapter bank serves adapters over a "
                         "frozen base)")
    runner = FedMLRunner(args, dataset=fed, model=bundle,
                         client_trainer=spec)
    result = runner.run()
    export_dir = getattr(args, "llm_adapter_export_dir", None)
    if export_dir and isinstance(result, dict) and "params" in result:
        export_silo_adapters(args, export_dir, result=result,
                             prebuilt=(fed, bundle, spec))
    return result


# --- adapter-bank artifacts -------------------------------------------------
# The serving side of the federated-personalization loop: named LoRA
# adapter trees (kilobytes each) written with the msgpack artifact codec,
# plus a manifest the AdapterBank loads. One gateway then serves every
# silo's personalization side by side over a shared base model.

_MANIFEST = "manifest.json"


def _safe_name(name: str) -> str:
    import re
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", str(name))
    if not safe:
        raise ValueError(f"adapter name {name!r} is empty after "
                         "sanitization")
    return safe


def save_adapter_artifacts(adapters, out_dir: str, *,
                           lora_rank: Optional[int] = None,
                           lora_alpha: Optional[float] = None) -> str:
    """Write ``{name: adapter_tree}`` as one msgpack artifact per adapter
    plus ``manifest.json``; returns the manifest path."""
    import json
    import os

    from ..serving import save_model

    os.makedirs(out_dir, exist_ok=True)
    manifest = {"format": "fedml_tpu_adapter_bank_v1", "adapters": {}}
    if lora_rank is not None:
        manifest["lora_rank"] = int(lora_rank)
    if lora_alpha is not None:
        manifest["lora_alpha"] = float(lora_alpha)
    for name, tree in adapters.items():
        fname = _safe_name(name) + ".fmtpu"
        save_model(tree, os.path.join(out_dir, fname))
        manifest["adapters"][str(name)] = fname
    path = os.path.join(out_dir, _MANIFEST)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
    os.replace(tmp, path)
    logger.info("adapter artifacts: %d adapters -> %s",
                len(manifest["adapters"]), out_dir)
    return path


def load_adapter_artifacts(manifest_dir: str) -> dict:
    """Manifest dir → ``{name: adapter_tree}`` (msgpack artifacts only —
    same trust story as every served model)."""
    import json
    import os

    from ..serving import load_model

    with open(os.path.join(manifest_dir, _MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format") != "fedml_tpu_adapter_bank_v1":
        raise ValueError(f"{manifest_dir}: not an adapter-bank manifest")
    return {name: load_model(os.path.join(manifest_dir, fname))
            for name, fname in manifest["adapters"].items()}


def personalize_adapter(spec, global_adapter, silo_data, *,
                        learning_rate: float = 1e-3, steps: int = 4,
                        step_fn=None):
    """A few local SGD steps from the global adapter over one silo's
    batches — the cheap per-silo personalization pass whose output the
    adapter bank serves. ``silo_data``: ``{"x": [nb, bs, L], "y", "mask"}``
    numpy/jnp arrays. Returns ``(adapter, step_fn)`` so callers
    personalizing many silos reuse the compiled step."""
    import optax

    opt = optax.sgd(float(learning_rate))
    if step_fn is None:
        def _step(params, opt_state, batch):
            grads, _ = jax.grad(spec.loss, has_aux=True)(params, batch,
                                                         None)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state
        step_fn = jax.jit(_step)
    params = global_adapter
    opt_state = opt.init(params)
    n_batches = int(silo_data["x"].shape[0])
    for s in range(int(steps)):
        j = s % n_batches
        batch = {"x": jnp.asarray(silo_data["x"][j]),
                 "y": jnp.asarray(silo_data["y"][j]),
                 "mask": jnp.asarray(silo_data["mask"][j])}
        params, opt_state = step_fn(params, opt_state, batch)
    return params, step_fn


def export_silo_adapters(args, out_dir: str, result: Optional[dict] = None,
                         prebuilt=None) -> str:
    """Federated LoRA → a served adapter bank: run (or reuse) the
    federated fine-tune, personalize the global adapter per silo with a
    few local steps on that silo's shard, and write ``global`` +
    ``silo_<i>`` named artifacts. Returns the manifest path."""
    if prebuilt is not None:
        fed, bundle, spec = prebuilt
    else:
        fed, bundle, spec, _ = build_llm(args)
    if int(getattr(args, "lora_rank", 8)) <= 0:
        raise ValueError("adapter export needs lora_rank > 0 (the bank "
                         "serves adapters over a frozen base)")
    if result is None:
        from ..runner import FedMLRunner
        result = FedMLRunner(args, dataset=fed, model=bundle,
                             client_trainer=spec).run()
    global_adapter = result["params"]
    adapters = {"global": global_adapter}
    steps = int(getattr(args, "llm_adapter_personalize_steps", 4))
    step_fn = None
    import numpy as np
    for i in range(fed.num_clients):
        silo = {"x": np.asarray(fed.train.x[i]),
                "y": np.asarray(fed.train.y[i]),
                "mask": np.asarray(fed.train.mask[i])}
        adapters[f"silo_{i}"], step_fn = personalize_adapter(
            spec, global_adapter, silo,
            learning_rate=float(getattr(args, "learning_rate", 1e-3)),
            steps=steps, step_fn=step_fn)
    return save_adapter_artifacts(
        adapters, out_dir,
        lora_rank=int(getattr(args, "lora_rank", 8)),
        lora_alpha=float(getattr(args, "lora_alpha", 16.0)))
