"""Import HF/torch checkpoints into the flax CausalLM.

Parity target: the reference loads base models from the HF hub
(``ModelArguments.get_model_kwargs`` → ``AutoModelForCausalLM``,
``train/llm/configurations.py:271-341``). This environment has no network
egress, so the importer consumes a *local* checkpoint: a torch state dict
(``pytorch_model.bin`` / ``.pt``) or a directory containing one, with
Llama-style parameter naming (``model.layers.N.self_attn.q_proj.weight``).

torch Linear stores weights [out, in]; flax kernels are [in, out] (and
[in, heads, head_dim] for the fused attention projections) — the transpose
and reshape happen here, once, at import time.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping

import jax.numpy as jnp
import numpy as np

from .model import LLMConfig

PyTree = Any


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def load_torch_state_dict(path: str) -> Mapping[str, Any]:
    import torch

    if os.path.isdir(path):
        for name in ("pytorch_model.bin", "model.pt", "checkpoint.pt"):
            cand = os.path.join(path, name)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"no torch checkpoint (pytorch_model.bin / model.pt) in {path}")
    state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, dict) and "state_dict" in state:
        state = state["state_dict"]
    return state


def convert_llama_state_dict(state: Mapping[str, Any],
                             cfg: LLMConfig) -> PyTree:
    """Llama-naming torch state dict → CausalLM param tree."""
    h, nh, kvh, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                      cfg.head_dim)

    def lin(key: str) -> np.ndarray:          # [out, in] → [in, out]
        return _to_np(state[key]).T

    params: Dict[str, Any] = {
        "embed": {"embedding": _to_np(state["model.embed_tokens.weight"])},
        "ln_f": {"scale": _to_np(state["model.norm.weight"])},
    }
    if cfg.tie_embeddings and "lm_head.weight" in state:
        head = _to_np(state["lm_head.weight"])
        if not np.allclose(head, params["embed"]["embedding"], atol=1e-6):
            raise ValueError(
                "checkpoint has an untied lm_head but cfg.tie_embeddings "
                "is True — importing would silently drop the head; set "
                "tie_embeddings=False on the LLMConfig")
    if not cfg.tie_embeddings and "lm_head.weight" in state:
        params["lm_head"] = {"kernel": lin("lm_head.weight")}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        params[f"layer_{i}"] = {
            "ln_attn": {"scale": _to_np(state[p + "input_layernorm.weight"])},
            "ln_mlp": {"scale": _to_np(
                state[p + "post_attention_layernorm.weight"])},
            "attn": {
                "q": {"kernel": lin(p + "self_attn.q_proj.weight")
                      .reshape(h, nh, hd)},
                "k": {"kernel": lin(p + "self_attn.k_proj.weight")
                      .reshape(h, kvh, hd)},
                "v": {"kernel": lin(p + "self_attn.v_proj.weight")
                      .reshape(h, kvh, hd)},
                "o": {"kernel": lin(p + "self_attn.o_proj.weight")},
            },
            "mlp": {
                "gate": {"kernel": lin(p + "mlp.gate_proj.weight")},
                "up": {"kernel": lin(p + "mlp.up_proj.weight")},
                "down": {"kernel": lin(p + "mlp.down_proj.weight")},
            },
        }
    return _tree_to_jnp(params)


def _tree_to_jnp(tree):
    import jax

    return jax.tree_util.tree_map(jnp.asarray, tree)


def load_hf_llama(path: str, cfg: LLMConfig) -> PyTree:
    """Local HF-Llama checkpoint → flax params ready for ``CausalLM``."""
    return convert_llama_state_dict(load_torch_state_dict(path), cfg)
