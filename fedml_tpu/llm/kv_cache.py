"""Paged (block-allocated) KV cache for continuous-batching decode.

The serving template's original decode loop re-ran the FULL forward over a
padded ``[1, max_seq_len]`` buffer for every token — O(s) attention work
per emitted token and one request at a time. This module gives the decode
step a vLLM-style paged cache (Kwon et al. 2023) so each step is
one-token work per slot and S requests share one compiled program:

* the physical cache is a fixed pool of ``num_blocks`` blocks of
  ``block_size`` key/value rows per layer (one trailing TRASH block
  absorbs writes from inactive slots and padded prefill rows, so the
  jitted step never branches on occupancy);
* each slot owns a **block table** ``[max_blocks]`` of physical block ids
  mapping logical position ``p`` to ``table[p // block_size]`` — tables,
  positions, and occupancy are DATA, so admit/evict never recompiles;
* the jitted decode/prefill programs *gather* a slot's blocks into a
  position-ordered dense view ``[T = max_seq_len]`` (bit-compatible with
  the full-forward attention: same key-axis length, masked tail
  contributes exact zeros) and *scatter* the step's new K/V rows back into
  the pool at ``(table[p // bs], p % bs)``.

Block allocation/free is host-side bookkeeping (a free list); admission
reserves the request's worst-case block count up front so decode can never
hit out-of-memory mid-stream.

Shared-prefix caching (vLLM block sharing / SGLang RadixAttention):
blocks are REFCOUNTED — a fully-written prompt block can be aliased into
another slot's table (both tables point at the same physical block) and
``free()`` only returns a block to the free list when its last reference
drops. The :class:`PrefixIndex` is the host-side map from prompt content
(exact ``(parent_block, token_tuple)`` chain keys — no hash collisions
can alias wrong content) to resident physical blocks; it holds its own
reference on every cached block so a released slot's prompt prefix stays
warm for the next request, and under pool pressure cold chains are
cascade-evicted (a child whose parent is gone could never be matched
again, so the whole subtree goes at once). Aliased blocks are READ-ONLY
by construction: a slot's novel prefill and decode writes land only at
positions past its matched prefix, i.e. in blocks it exclusively owns;
the one partially-reusable block is copied first (:func:`copy_block_rows`
— copy-on-write) and only its tail is prefilled.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

PyTree = Any


def _stable_items(d: Dict, tries: int = 8) -> List[Tuple[Any, Any]]:
    """Snapshot a dict the engine worker mutates concurrently: /metrics
    and /debug/state read refcounts and index metadata from HTTP handler
    threads, and iterating a dict whose size changes mid-iteration
    raises RuntimeError in CPython — exactly under the load the operator
    is trying to inspect. Retry a few times; an empty read beats a 500."""
    for _ in range(tries):
        try:
            return list(d.items())
        except RuntimeError:
            continue
    return []


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static geometry of the paged pool (baked into the compiled step)."""

    num_layers: int
    kv_heads: int
    head_dim: int
    max_seq_len: int
    block_size: int = 16
    num_blocks: int = 256   # physical pool, shared across slots

    def __post_init__(self):
        if self.max_seq_len % self.block_size:
            raise ValueError(
                f"block_size {self.block_size} must divide max_seq_len "
                f"{self.max_seq_len}: the gathered view must be exactly "
                "max_seq_len keys for full-forward bit-compatibility")

    @property
    def max_blocks_per_slot(self) -> int:
        return self.max_seq_len // self.block_size

    @property
    def trash_block(self) -> int:
        """Sacrificial physical block: writes from inactive slots and
        padded prefill rows land here; unallocated table entries read it."""
        return self.num_blocks

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)


def init_pools(cfg: KVCacheConfig, dtype=jnp.float32
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Zeroed K and V pools ``[L, num_blocks + 1, block_size, H, D]``
    (the +1 is the trash block)."""
    shape = (cfg.num_layers, cfg.num_blocks + 1, cfg.block_size,
             cfg.kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def gather_view(pool_layer: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """``[NB+1, bs, H, D]`` pool + ``[..., max_blocks]`` tables →
    position-ordered dense view ``[..., T, H, D]`` (T = max_blocks * bs).
    Pure; safe under jit — tables are data."""
    v = pool_layer[tables]                      # [..., max_blocks, bs, H, D]
    shape = v.shape[:-4] + (v.shape[-4] * v.shape[-3],) + v.shape[-2:]
    return v.reshape(shape)


def scatter_token(pool: jnp.ndarray, layer: int, tables: jnp.ndarray,
                  positions: jnp.ndarray, values: jnp.ndarray,
                  active: jnp.ndarray, block_size: int,
                  trash_block: int) -> jnp.ndarray:
    """Write one new K or V row per slot at its logical position.

    pool ``[L, NB+1, bs, H, D]`` (the FULL stacked pool); writes go to
    layer ``layer`` as one coordinate scatter — under buffer donation
    XLA applies it in place, so the cost is O(slots), independent of
    pool size. (The per-layer form — slice ``pool[layer]``, scatter,
    write back with ``pool.at[layer].set`` — materializes the whole
    pool twice per dispatch: ~300 ms/step at an 8k-block pool on CPU vs
    ~0.02 ms for this form.) tables ``[S, max_blocks]``; positions
    ``[S]``; values ``[S, H, D]``; active ``[S]`` bool. Inactive slots'
    writes are routed to the trash block. Active slots own disjoint
    blocks, so the scatter has no cross-slot conflicts.
    """
    s = tables.shape[0]
    pos = jnp.clip(positions, 0, tables.shape[1] * block_size - 1)
    blk = tables[jnp.arange(s), pos // block_size]
    blk = jnp.where(active, blk, trash_block)
    return pool.at[jnp.full_like(blk, layer), blk,
                   pos % block_size].set(values)


def scatter_chunk(pool: jnp.ndarray, layer: int, table_row: jnp.ndarray,
                  positions: jnp.ndarray, values: jnp.ndarray,
                  valid: jnp.ndarray, block_size: int,
                  trash_block: int) -> jnp.ndarray:
    """Write a prefill chunk's K or V rows for ONE slot into layer
    ``layer`` of the stacked pool (coordinate scatter, in place under
    donation — see :func:`scatter_token`).

    table_row ``[max_blocks]``; positions ``[C]`` (logical); values
    ``[C, H, D]``; valid ``[C]`` bool (padded chunk tail → trash)."""
    pos = jnp.clip(positions, 0, table_row.shape[0] * block_size - 1)
    blk = jnp.where(valid, table_row[pos // block_size], trash_block)
    return pool.at[jnp.full_like(blk, layer), blk,
                   pos % block_size].set(values)


def scatter_chunk_batch(pool: jnp.ndarray, layer: int,
                        table_rows: jnp.ndarray,
                        positions: jnp.ndarray, values: jnp.ndarray,
                        valid: jnp.ndarray, block_size: int,
                        trash_block: int) -> jnp.ndarray:
    """Write B slots' prefill chunks in ONE scatter (piggybacked prefill)
    into layer ``layer`` of the stacked pool (coordinate scatter, in
    place under donation — see :func:`scatter_token`).

    table_rows ``[B, max_blocks]``; positions ``[B, C]``; values
    ``[B, C, H, D]``; valid ``[B, C]``. Rows in an admission wave own
    disjoint fresh blocks (aliased prefix blocks are never written —
    every valid position is past its row's matched prefix), so the
    flattened scatter has no cross-row conflicts; invalid rows land in
    the trash block."""
    b, c = positions.shape
    pos = jnp.clip(positions, 0, table_rows.shape[1] * block_size - 1)
    blk = jnp.take_along_axis(table_rows, pos // block_size, axis=1)
    blk = jnp.where(valid, blk, trash_block).reshape(-1)
    flat = values.reshape((b * c,) + values.shape[2:])
    return pool.at[jnp.full_like(blk, layer), blk,
                   (pos % block_size).reshape(-1)].set(flat)


def copy_block_rows(pool: jnp.ndarray, src, dst, n_rows) -> jnp.ndarray:
    """Copy the first ``n_rows`` rows of physical block ``src`` into
    block ``dst`` across every layer — the admission-time copy-on-write:
    a partially matched cached block's reusable rows move into a block
    the new slot OWNS, and the shared source is never written.

    pool ``[L, num_blocks + 1, block_size, H, D]``; ``src``/``dst``/
    ``n_rows`` are DATA (int32), so one compiled program covers every
    COW copy."""
    bs = pool.shape[2]
    keep = (jnp.arange(bs) < n_rows)[None, :, None, None]
    merged = jnp.where(keep, pool[:, src], pool[:, dst])
    return pool.at[:, dst].set(merged)


class BlockAllocator:
    """Host-side refcounted free-list over the physical pool. Admission
    reserves the request's worst-case block count up front (prompt +
    max_new_tokens, clamped to max_seq_len), so a decoding slot can never
    fail to grow. A block may be referenced by several holders at once —
    multiple slots aliasing a shared prefix plus the prefix index's own
    pin — and returns to the free list only when the LAST reference
    drops (``free()`` on an aliased block while a reader still holds it
    merely decrements)."""

    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        self._free: List[int] = list(range(cfg.num_blocks))
        self._owned: dict = {}   # slot -> list of physical block ids
        self._refs: Dict[int, int] = {}   # block -> live reference count

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._refs.get(int(block), 0)

    def refcounts(self) -> Dict[int, int]:
        """Per-block live reference counts (the /debug/state payload;
        safe to call from handler threads)."""
        return dict(_stable_items(self._refs))

    def aliased_blocks(self) -> int:
        """Blocks held by more than one reference (shared prefix)."""
        return sum(1 for _, c in _stable_items(self._refs) if c >= 2)

    def can_alloc(self, n_tokens: int) -> bool:
        return self.cfg.blocks_needed(n_tokens) <= len(self._free)

    def alloc(self, slot: int, n_tokens: int,
              shared: Sequence[int] = ()) -> np.ndarray:
        """Reserve blocks for ``n_tokens`` positions; returns the slot's
        table row ``[max_blocks_per_slot]`` (unused entries = trash).

        ``shared``: already-written physical blocks aliased as the row's
        LEADING entries (their refcount is bumped; the slot must never
        write them) — only the remainder comes off the free list."""
        shared = [int(b) for b in shared]
        need = self.cfg.blocks_needed(n_tokens) - len(shared)
        if need < 0:
            raise ValueError(
                f"{len(shared)} shared blocks exceed the "
                f"{self.cfg.blocks_needed(n_tokens)} needed for "
                f"{n_tokens} tokens")
        if need > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {need} blocks, "
                f"{len(self._free)} free")
        if slot in self._owned:
            raise RuntimeError(f"slot {slot} already holds blocks")
        for b in shared:
            if self._refs.get(b, 0) <= 0:
                raise RuntimeError(
                    f"block {b} aliased while unreferenced (stale "
                    "prefix-index entry?)")
            self._refs[b] += 1
        fresh = []
        for _ in range(need):
            b = self._free.pop()
            self._refs[b] = 1
            fresh.append(b)
        blocks = shared + fresh
        self._owned[slot] = blocks
        row = np.full((self.cfg.max_blocks_per_slot,),
                      self.cfg.trash_block, np.int32)
        row[:len(blocks)] = blocks
        return row

    def retain(self, block: int) -> None:
        """Extra pin on a live block (the prefix index's hold on a cached
        block: the block survives its writer slot's release)."""
        b = int(block)
        if self._refs.get(b, 0) <= 0:
            raise RuntimeError(f"retain of unreferenced block {b}")
        self._refs[b] += 1

    def release_block(self, block: int) -> bool:
        """Drop one reference; the block returns to the free list only at
        zero. Returns True when the block was actually freed."""
        b = int(block)
        n = self._refs.get(b, 0)
        if n <= 0:
            raise RuntimeError(f"block {b} over-freed")
        if n == 1:
            del self._refs[b]
            self._free.append(b)
            return True
        self._refs[b] = n - 1
        return False

    def free(self, slot: int) -> None:
        for b in self._owned.pop(slot, []):
            self.release_block(b)


class PrefixIndex:
    """Host-side shared-prefix index: prompt content → resident blocks.

    One entry per cached physical block, keyed by the EXACT
    ``(parent_block_id, tuple(block_tokens))`` pair — token equality, not
    a hash, decides a match, so a collision can never alias wrong KV.
    Only FULL blocks are indexed: prompt blocks at admit (complete after
    prefill, never rewritten — decode writes land past the prompt) and,
    under suffix caching, decode blocks at slot release (complete once
    the slot stops writing — a released slot never scatters again). The
    causal argument is the same for both origins: the KV at position p
    is a pure function of tokens 0..p, so an exact token-chain match
    aliases bit-identical KV regardless of who wrote it. Every entry
    holds one allocator reference so the cached chain outlives the slot
    that wrote it. ``last-used`` ordering is a logical tick, not wall
    time — eviction order is deterministic for a given admit sequence."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        # (parent_block, tokens) -> block; meta: block -> {key, parent,
        # tick, origin ("prompt" | "decode")}
        self._entries: Dict[Tuple[int, Tuple[int, ...]], int] = {}
        self._meta: Dict[int, Dict[str, Any]] = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.evictions = 0
        # suffix-cache counters: matches that aliased at least one
        # decode-origin block, and the decode-origin tokens they reused
        self.suffix_hits = 0
        self.suffix_tokens_reused = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def cached_blocks(self) -> int:
        return len(self._meta)

    def match(self, ids: Sequence[int]) -> List[int]:
        """Longest indexed chain of full blocks prefixing ``ids`` →
        physical block ids, oldest first (the caller caps actual reuse at
        ``len(ids) - 1`` so the last prompt token is always prefilled and
        yields the first-token logits). Bumps the chain's recency."""
        bs = self.block_size
        self._tick += 1
        chain: List[int] = []
        parent = -1
        for i in range(len(ids) // bs):
            key = (parent, tuple(int(t) for t in ids[i * bs:(i + 1) * bs]))
            blk = self._entries.get(key)
            if blk is None:
                break
            self._meta[blk]["tick"] = self._tick
            chain.append(blk)
            parent = blk
        return chain

    def insert(self, ids: Sequence[int], row: np.ndarray, n_tokens: int,
               alloc: BlockAllocator, origin: str = "prompt") -> int:
        """Register every full block of ``ids[:n_tokens]`` (now fully
        written in the pool) under an allocator pin; blocks whose chain
        key already exists are skipped (never double-pinned — the chain
        continues through the block already indexed). ``origin`` tags
        newly indexed blocks for the suffix-cache accounting ("decode" =
        inserted at release from generated tokens). Returns the number
        of newly indexed blocks."""
        bs = self.block_size
        self._tick += 1
        parent = -1
        added = 0
        for i in range(int(n_tokens) // bs):
            key = (parent, tuple(int(t) for t in ids[i * bs:(i + 1) * bs]))
            blk = self._entries.get(key)
            if blk is None:
                blk = int(row[i])
                if blk in self._meta:
                    # same block already indexed under another key is
                    # impossible (a block is written by one slot under
                    # one content); guard anyway rather than double-pin
                    parent = blk
                    continue
                alloc.retain(blk)
                self._entries[key] = blk
                self._meta[blk] = {"key": key, "parent": parent,
                                   "tick": self._tick, "origin": origin}
                added += 1
            else:
                self._meta[blk]["tick"] = self._tick
            parent = blk
        return added

    def origin_of(self, block: int) -> str:
        """The indexed origin of a cached block ("prompt" / "decode");
        entries from before the origin tag read as "prompt"."""
        meta = self._meta.get(int(block))
        return "prompt" if meta is None else meta.get("origin", "prompt")

    def count_suffix_reuse(self, chain: Sequence[int]) -> int:
        """Decode-origin blocks in a matched chain — the blocks whose
        tokens the engine generated itself and is now NOT re-prefilling.
        Callers bump ``suffix_hits``/``suffix_tokens_reused`` from this
        at admission commit (not here: an abandoned admission must not
        count)."""
        return sum(1 for b in chain if self.origin_of(b) == "decode")

    def reclaimable(self, alloc: BlockAllocator) -> int:
        """Cached blocks only the index still references — the blocks an
        eviction sweep could actually return to the free list. Read from
        handler threads too (kv_pool_stats), so snapshot defensively."""
        return sum(1 for b, _ in _stable_items(self._meta)
                   if alloc.refcount(b) == 1)

    def _subtree(self, root: int) -> List[int]:
        out: List[int] = []
        frontier = {root}
        while frontier:
            out.extend(sorted(frontier))
            frontier = {b for b, m in self._meta.items()
                        if m["parent"] in frontier and b not in out}
        return out

    def evict(self, alloc: BlockAllocator, need_free: int,
              protect: Sequence[int] = ()) -> int:
        """Cascade-evict least-recently-used chains until the allocator
        has ``need_free`` free blocks (or nothing evictable remains).
        Evicting an entry drops the INDEX pin only — a block a reader
        slot still aliases stays resident until the reader releases.
        ``protect``: blocks the in-progress admission just matched (about
        to be aliased) — their subtrees are skipped. Returns the number
        of blocks actually freed."""
        protect_set = {int(b) for b in protect}
        skipped: set = set()
        freed = 0
        while alloc.free_blocks < need_free:
            candidates = [b for b in self._meta if b not in skipped]
            if not candidates:
                break
            victim = min(candidates,
                         key=lambda b: (self._meta[b]["tick"], b))
            sub = self._subtree(victim)
            if protect_set.intersection(sub):
                skipped.add(victim)
                continue
            for blk in sub:
                key = self._meta.pop(blk)["key"]
                del self._entries[key]
                self.evictions += 1
                if alloc.release_block(blk):
                    freed += 1
        return freed

    def debug_state(self) -> Dict[str, Any]:
        return {"entries": len(self._entries),
                "cached_blocks": len(self._meta),
                "decode_blocks": sum(
                    1 for _, m in _stable_items(self._meta)
                    if m.get("origin", "prompt") == "decode"),
                "hits": int(self.hits), "misses": int(self.misses),
                "tokens_reused": int(self.tokens_reused),
                "suffix_hits": int(self.suffix_hits),
                "suffix_tokens_reused": int(self.suffix_tokens_reused),
                "evictions": int(self.evictions)}
