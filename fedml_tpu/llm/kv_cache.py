"""Paged (block-allocated) KV cache for continuous-batching decode.

The serving template's original decode loop re-ran the FULL forward over a
padded ``[1, max_seq_len]`` buffer for every token — O(s) attention work
per emitted token and one request at a time. This module gives the decode
step a vLLM-style paged cache (Kwon et al. 2023) so each step is
one-token work per slot and S requests share one compiled program:

* the physical cache is a fixed pool of ``num_blocks`` blocks of
  ``block_size`` key/value rows per layer (one trailing TRASH block
  absorbs writes from inactive slots and padded prefill rows, so the
  jitted step never branches on occupancy);
* each slot owns a **block table** ``[max_blocks]`` of physical block ids
  mapping logical position ``p`` to ``table[p // block_size]`` — tables,
  positions, and occupancy are DATA, so admit/evict never recompiles;
* the jitted decode/prefill programs *gather* a slot's blocks into a
  position-ordered dense view ``[T = max_seq_len]`` (bit-compatible with
  the full-forward attention: same key-axis length, masked tail
  contributes exact zeros) and *scatter* the step's new K/V rows back into
  the pool at ``(table[p // bs], p % bs)``.

Block allocation/free is host-side bookkeeping (a free list); admission
reserves the request's worst-case block count up front so decode can never
hit out-of-memory mid-stream.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

PyTree = Any


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static geometry of the paged pool (baked into the compiled step)."""

    num_layers: int
    kv_heads: int
    head_dim: int
    max_seq_len: int
    block_size: int = 16
    num_blocks: int = 256   # physical pool, shared across slots

    def __post_init__(self):
        if self.max_seq_len % self.block_size:
            raise ValueError(
                f"block_size {self.block_size} must divide max_seq_len "
                f"{self.max_seq_len}: the gathered view must be exactly "
                "max_seq_len keys for full-forward bit-compatibility")

    @property
    def max_blocks_per_slot(self) -> int:
        return self.max_seq_len // self.block_size

    @property
    def trash_block(self) -> int:
        """Sacrificial physical block: writes from inactive slots and
        padded prefill rows land here; unallocated table entries read it."""
        return self.num_blocks

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)


def init_pools(cfg: KVCacheConfig, dtype=jnp.float32
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Zeroed K and V pools ``[L, num_blocks + 1, block_size, H, D]``
    (the +1 is the trash block)."""
    shape = (cfg.num_layers, cfg.num_blocks + 1, cfg.block_size,
             cfg.kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def gather_view(pool_layer: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """``[NB+1, bs, H, D]`` pool + ``[..., max_blocks]`` tables →
    position-ordered dense view ``[..., T, H, D]`` (T = max_blocks * bs).
    Pure; safe under jit — tables are data."""
    v = pool_layer[tables]                      # [..., max_blocks, bs, H, D]
    shape = v.shape[:-4] + (v.shape[-4] * v.shape[-3],) + v.shape[-2:]
    return v.reshape(shape)


def scatter_token(pool_layer: jnp.ndarray, tables: jnp.ndarray,
                  positions: jnp.ndarray, values: jnp.ndarray,
                  active: jnp.ndarray, block_size: int,
                  trash_block: int) -> jnp.ndarray:
    """Write one new K or V row per slot at its logical position.

    pool_layer ``[NB+1, bs, H, D]``; tables ``[S, max_blocks]``; positions
    ``[S]``; values ``[S, H, D]``; active ``[S]`` bool. Inactive slots'
    writes are routed to the trash block. Active slots own disjoint blocks,
    so the scatter has no cross-slot conflicts.
    """
    s = tables.shape[0]
    pos = jnp.clip(positions, 0, tables.shape[1] * block_size - 1)
    blk = tables[jnp.arange(s), pos // block_size]
    blk = jnp.where(active, blk, trash_block)
    return pool_layer.at[blk, pos % block_size].set(values)


def scatter_chunk(pool_layer: jnp.ndarray, table_row: jnp.ndarray,
                  positions: jnp.ndarray, values: jnp.ndarray,
                  valid: jnp.ndarray, block_size: int,
                  trash_block: int) -> jnp.ndarray:
    """Write a prefill chunk's K or V rows for ONE slot.

    table_row ``[max_blocks]``; positions ``[C]`` (logical); values
    ``[C, H, D]``; valid ``[C]`` bool (padded chunk tail → trash)."""
    pos = jnp.clip(positions, 0, table_row.shape[0] * block_size - 1)
    blk = jnp.where(valid, table_row[pos // block_size], trash_block)
    return pool_layer.at[blk, pos % block_size].set(values)


class BlockAllocator:
    """Host-side free-list over the physical pool. Admission reserves the
    request's worst-case block count up front (prompt + max_new_tokens,
    clamped to max_seq_len), so a decoding slot can never fail to grow."""

    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        self._free: List[int] = list(range(cfg.num_blocks))
        self._owned: dict = {}   # slot -> list of physical block ids

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def can_alloc(self, n_tokens: int) -> bool:
        return self.cfg.blocks_needed(n_tokens) <= len(self._free)

    def alloc(self, slot: int, n_tokens: int) -> np.ndarray:
        """Reserve blocks for ``n_tokens`` positions; returns the slot's
        table row ``[max_blocks_per_slot]`` (unused entries = trash)."""
        need = self.cfg.blocks_needed(n_tokens)
        if need > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {need} blocks, "
                f"{len(self._free)} free")
        if slot in self._owned:
            raise RuntimeError(f"slot {slot} already holds blocks")
        blocks = [self._free.pop() for _ in range(need)]
        self._owned[slot] = blocks
        row = np.full((self.cfg.max_blocks_per_slot,),
                      self.cfg.trash_block, np.int32)
        row[:need] = blocks
        return row

    def free(self, slot: int) -> None:
        for b in self._owned.pop(slot, []):
            self._free.append(b)
