"""FedLLM — the LLM fine-tuning pillar (reference ``train/llm/`` +
``spotlight_prj/unitedllm/``), rebuilt TPU-first:

- ``model``: flax Llama-style decoder (RMSNorm/rotary/SwiGLU), bf16
  compute, MXU-shaped matmuls.
- ``attention``: dense golden + Pallas flash kernel + ring attention over
  the ``sp`` mesh axis for long context.
- ``lora``: adapters as a pure pytree transform; federated rounds ship
  adapters only.
- ``sharding``: FSDP/TP partition specs (XLA-FSDP, the DeepSpeed ZeRO
  analogue) + sequence-parallel forward.
- ``trainer``: completion-only causal-LM TrainerSpec that composes with the
  whole algorithm frame.
- ``federated``: ``build_llm`` / ``run_federated_llm`` — UnitedLLM parity.
- ``hf``: local HF/Llama torch-checkpoint import.
"""

from .model import CausalLM, LLMConfig, init_llm
from .lora import lora_init, lora_merge, make_lora_apply, lora_param_count
from .trainer import CausalLMTrainer
from .federated import LLMBundle, build_llm, llm_config_from_args, run_federated_llm

__all__ = [
    "CausalLM", "LLMConfig", "init_llm",
    "lora_init", "lora_merge", "make_lora_apply", "lora_param_count",
    "CausalLMTrainer",
    "LLMBundle", "build_llm", "llm_config_from_args", "run_federated_llm",
]
