"""Attention implementations for the LLM path.

The reference's only long-context machinery is a CUDA flash-attn
monkey-patch (``train/llm/models/attention.py:30``). The TPU-native
counterparts here are first-class:

- ``dense``: plain causal attention — XLA fuses this well for short
  sequences; the numerical golden for the other two.
- ``flash``: a Pallas online-softmax kernel, blocked over the KV axis so
  the [s, s] score matrix never materializes in HBM (the flash-attn
  analogue on the MXU). Backward currently recomputes through the dense
  path (documented trade-off; fine at the fine-tune lengths the reference
  targets, ``DEFAULT_MAX_SEQ_LENGTH=1024``).
- ``ring``: ring attention over the ``sp`` mesh axis — sequence shards
  rotate K/V via ``ppermute`` while accumulating online-softmax state, so
  context length scales with the number of chips (capability beyond the
  reference; SURVEY §5.7 flags this as the TPU equivalent to build).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# (axis_name, axis_size) for ring attention; set by the sequence-parallel
# wrapper (sharding.py) around the shard_map'd forward.
_RING_AXIS: contextvars.ContextVar[Optional[Tuple[str, int]]] = \
    contextvars.ContextVar("fedml_tpu_ring_axis", default=None)


@contextlib.contextmanager
def ring_axis(name: str, size: int):
    token = _RING_AXIS.set((name, size))
    try:
        yield
    finally:
        _RING_AXIS.reset(token)


def causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     impl: str = "dense",
                     attn_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Dispatch. q/k/v: [b, s, h, d] → [b, s, h, d]."""
    if impl in ("ring", "flash") and attn_mask is not None:
        raise NotImplementedError(
            f"attention_impl={impl!r} does not support key-padding masks "
            "yet — use impl='dense', or pack sequences without padding")
    if impl == "ring":
        ax = _RING_AXIS.get()
        if ax is None:
            raise RuntimeError(
                "attention_impl='ring' requires the sequence-parallel "
                "context (fedml_tpu.llm.attention.ring_axis) — wrap the "
                "forward in shard_map over the 'sp' axis")
        return ring_causal_attention(q, k, v, axis_name=ax[0],
                                     axis_size=ax[1])
    if impl == "flash":
        return flash_causal_attention(q, k, v)
    return dense_causal_attention(q, k, v, attn_mask=attn_mask)


def dense_causal_attention(q, k, v, attn_mask=None):
    """[b, s, h, d] — reference semantics, scores in f32."""
    _, s, _, d = q.shape
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    causal = jnp.tril(jnp.ones((s, s), bool))
    mask = causal[None, None]
    if attn_mask is not None:  # [b, s] key padding
        mask = mask & attn_mask[:, None, None, :].astype(bool)
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------- flash ----

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      seq_len: int, scale: float):
    """One (batch*head, q-block) program: online softmax over KV blocks.

    q_ref: [block_q, d]; k_ref/v_ref: [s, d]; o_ref: [block_q, d].
    """
    import jax.experimental.pallas as pl

    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    q_blk_idx = pl.program_id(1)
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)

    q = q_ref[:].astype(jnp.float32) * scale

    def body(i, carry):
        o_acc, m, l = carry
        k_blk = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s_blk = jnp.dot(q, k_blk.T,
                        preferred_element_type=jnp.float32)  # [bq, bk]
        k_pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s_blk = jnp.where(q_pos >= k_pos, s_blk, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_blk, -1, keepdims=True))
        p = jnp.exp(s_blk - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
        o_new = o_acc * alpha + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    n_k = pl.cdiv(seq_len, block_k)
    # causal: kv blocks strictly after this q block contribute nothing;
    # the last live block is the one containing this q block's final query
    n_live = jnp.minimum(
        n_k, ((q_blk_idx + 1) * block_q + block_k - 1) // block_k)
    o_acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o_acc, m, l = jax.lax.fori_loop(0, n_live, body, (o_acc, m0, l0))
    o_ref[:] = (o_acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, block_q: int, block_k: int):
    import jax.experimental.pallas as pl

    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    grid = (b * h, pl.cdiv(s, block_q))
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, seq_len=s,
                          scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=jax.default_backend() != "tpu",
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_causal_attention(q, k, v, block_q: int = 128, block_k: int = 128):
    """Pallas flash-attention forward; backward recomputes via the dense
    path (activation-memory trade documented in the module docstring)."""
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    return _flash_fwd(q, k, v, block_q, block_k)


def _flash_fwd_rule(q, k, v, block_q, block_k):
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    return _flash_fwd(q, k, v, bq, bk), (q, k, v)


def _flash_bwd_rule(block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(dense_causal_attention, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv


flash_causal_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ----------------------------------------------------------------- ring ----

def ring_causal_attention(q, k, v, axis_name: str = "sp",
                          axis_size: int = 1) -> jnp.ndarray:
    """Causal attention with the sequence sharded over ``axis_name``.

    Must be traced inside ``shard_map``: q/k/v are the local shards
    [b, s_loc, h, d]; K/V rotate around the ring via ``ppermute`` while each
    device folds the visiting block into its online-softmax accumulator.
    Communication rides ICI; peak memory per device is O(s_loc² + s_loc·d).
    """
    b, s_loc, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    my_idx = jax.lax.axis_index(axis_name)
    q_pos = my_idx * s_loc + jnp.arange(s_loc, dtype=jnp.int32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def fold(carry, xs):
        o_acc, m, l, k_cur, v_cur = carry
        step = xs
        kv_idx = (my_idx - step) % axis_size
        kv_pos = kv_idx * s_loc + jnp.arange(s_loc, dtype=jnp.int32)
        s_blk = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                           k_cur.astype(jnp.float32)) * scale
        mask = q_pos[:, None] >= kv_pos[None, :]
        s_blk = jnp.where(mask[None, None], s_blk, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_blk, -1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_blk - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, -1)
        o_new = (o_acc * alpha[..., None] +
                 jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), ()

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        fold, (o0, m0, l0, k, v), jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
