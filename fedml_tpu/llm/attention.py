"""Attention implementations for the LLM path.

The reference's only long-context machinery is a CUDA flash-attn
monkey-patch (``train/llm/models/attention.py:30``). The TPU-native
counterparts here are first-class:

- ``dense``: plain causal attention — XLA fuses this well for short
  sequences; the numerical golden for the other two.
- ``flash``: Pallas online-softmax kernels for BOTH directions — the
  forward emits O and the per-query logsumexp; the backward recomputes
  probabilities blockwise from (Q, K, LSE) in two kernels (dQ; dK/dV), so
  the [s, s] score matrix never materializes in HBM in either direction
  and training memory is O(s·d + s·block). Key-padding masks are
  supported. This is the fwd+bwd fused flash-attn the reference gets from
  its CUDA monkey-patch (``train/llm/models/attention.py:30-67``), built
  for the MXU.
- ``ring``: ring attention over the ``sp`` mesh axis — sequence shards
  rotate K/V (and the key-padding mask) via ``ppermute`` while
  accumulating online-softmax state, so context length scales with the
  number of chips (capability beyond the reference; SURVEY §5.7 flags
  this as the TPU equivalent to build).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# (axis_name, axis_size) for ring attention; set by the sequence-parallel
# wrapper (sharding.py) around the shard_map'd forward.
_RING_AXIS: contextvars.ContextVar[Optional[Tuple[str, int]]] = \
    contextvars.ContextVar("fedml_tpu_ring_axis", default=None)


@contextlib.contextmanager
def ring_axis(name: str, size: int):
    token = _RING_AXIS.set((name, size))
    try:
        yield
    finally:
        _RING_AXIS.reset(token)


def causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     impl: str = "dense",
                     attn_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Dispatch. q/k/v: [b, s, h, d] → [b, s, h, d]."""
    if impl == "ring":
        ax = _RING_AXIS.get()
        if ax is None:
            raise RuntimeError(
                "attention_impl='ring' requires the sequence-parallel "
                "context (fedml_tpu.llm.attention.ring_axis) — wrap the "
                "forward in shard_map over the 'sp' axis")
        return ring_causal_attention(q, k, v, axis_name=ax[0],
                                     axis_size=ax[1], attn_mask=attn_mask)
    if impl == "flash":
        return flash_causal_attention(q, k, v, attn_mask=attn_mask)
    return dense_causal_attention(q, k, v, attn_mask=attn_mask)


def dense_causal_attention(q, k, v, attn_mask=None):
    """[b, s, h, d] — reference semantics, scores in f32."""
    _, s, _, d = q.shape
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    causal = jnp.tril(jnp.ones((s, s), bool))
    mask = causal[None, None]
    if attn_mask is not None:  # [b, s] key padding
        mask = mask & attn_mask[:, None, None, :].astype(bool)
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def cached_attention(q, k_all, v_all, q_positions):
    """Decode/prefill attention over a position-ordered cached K/V view.

    q: [b, s, h, d] (s = 1 for decode, chunk length for prefill);
    k_all/v_all: [b, T, h, d] — the slot's gathered cache view with the
    current tokens already written at their logical positions;
    q_positions: [b, s] absolute positions of the query rows.

    The live mask is ``key_index <= q_position``: the view is position-
    ordered, every position <= q_pos holds a genuinely written key, and
    everything after is masked to NEG_INF (exact-zero probability). The
    math mirrors :func:`dense_causal_attention` term for term — f32
    scores, NEG_INF masking, softmax over a T-long key axis — so a decode
    step over a ``T == max_seq_len`` view is bit-compatible with the
    full-forward step on the padded ``[1, max_seq_len]`` buffer (masked
    positions contribute exact 0.0 in both).
    """
    _, _, _, d = q.shape
    t = k_all.shape[1]
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k_all.astype(jnp.float32)) * scale
    key_idx = jnp.arange(t, dtype=jnp.int32)
    live = key_idx[None, None, None, :] <= q_positions[:, None, :, None]
    scores = jnp.where(live, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v_all.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------- flash ----
# FlashAttention-2 style: the forward saves only (O, LSE); both backward
# kernels recompute P = exp(QK^T·scale − LSE) blockwise in VMEM, so neither
# direction materializes [s, s] in HBM. Key padding rides a [b, s] mask.

def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *,
                      block_k: int, seq_len: int, scale: float):
    """One (batch*head, q-block) program: online softmax over KV blocks.

    q_ref: [block_q, d]; k_ref/v_ref: [s, d]; mask_ref: [s, 1];
    o_ref: [block_q, d]; lse_ref: [block_q, 1].
    """
    import jax.experimental.pallas as pl

    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    q_blk_idx = pl.program_id(1)
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)

    q = q_ref[:].astype(jnp.float32) * scale

    def body(i, carry):
        o_acc, m, l = carry
        k_blk = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s_blk = jnp.dot(q, k_blk.T,
                        preferred_element_type=jnp.float32)  # [bq, bk]
        k_pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        live = q_pos >= k_pos
        kmask = mask_ref[pl.ds(i * block_k, block_k), 0]
        live = jnp.logical_and(live, (kmask > 0)[None, :])
        s_blk = jnp.where(live, s_blk, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_blk, -1, keepdims=True))
        # gate on `live`, not just the exp: for a row with NO live keys
        # m_new stays NEG_INF, so exp(s_blk - m_new) = exp(0) = 1 at every
        # masked position and O would silently become an unmasked average
        # of V; gating keeps l = 0 so the row's output is exactly zero and
        # its stored LSE ≈ NEG_INF (flagging the row) instead
        p = jnp.where(live, jnp.exp(s_blk - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
        o_new = o_acc * alpha + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    n_k = pl.cdiv(seq_len, block_k)
    # causal: kv blocks strictly after this q block contribute nothing;
    # the last live block is the one containing this q block's final query
    n_live = jnp.minimum(
        n_k, ((q_blk_idx + 1) * block_q + block_k - 1) // block_k)
    o_acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o_acc, m, l = jax.lax.fori_loop(0, n_live, body, (o_acc, m0, l0))
    o_ref[:] = (o_acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(jnp.maximum(l, 1e-30))


def _flash_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, dd_ref,
                     dq_ref, *, block_k: int, seq_len: int, scale: float):
    """dQ for one q block: dS = P ∘ (dO·Vᵀ − D); dQ = scale · dS·K."""
    import jax.experimental.pallas as pl

    block_q, d = q_ref.shape
    q_blk_idx = pl.program_id(1)
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)
    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]                      # [block_q, 1]
    dd = dd_ref[:]                        # [block_q, 1]

    def body(i, dq_acc):
        k_blk = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s_blk = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        live = q_pos >= k_pos
        kmask = mask_ref[pl.ds(i * block_k, block_k), 0]
        live = jnp.logical_and(live, (kmask > 0)[None, :])
        p = jnp.where(live, jnp.exp(s_blk - lse), 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dd)
        return dq_acc + jnp.dot(ds, k_blk,
                                preferred_element_type=jnp.float32)

    n_k = pl.cdiv(seq_len, block_k)
    n_live = jnp.minimum(
        n_k, ((q_blk_idx + 1) * block_q + block_k - 1) // block_k)
    dq = jax.lax.fori_loop(0, n_live, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(k_ref, v_ref, q_ref, mask_ref, do_ref, lse_ref,
                      dd_ref, dk_ref, dv_ref, *, block_q: int, seq_len: int,
                      scale: float):
    """dK/dV for one kv block: dV = Pᵀ·dO; dK = scale · dSᵀ·Q."""
    import jax.experimental.pallas as pl

    block_k, d = k_ref.shape
    k_blk_idx = pl.program_id(1)
    k_pos = k_blk_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    kmask = (mask_ref[:, 0] > 0)[None, :]  # this kv block's slice via BlockSpec

    def body(j, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[pl.ds(j * block_q, block_q), :].astype(
            jnp.float32) * scale
        do_blk = do_ref[pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(j * block_q, block_q), :]
        dd = dd_ref[pl.ds(j * block_q, block_q), :]
        s_blk = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32)
        q_pos = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        live = jnp.logical_and(q_pos >= k_pos, kmask)
        p = jnp.where(live, jnp.exp(s_blk - lse), 0.0)       # [bq, bk]
        dv_acc = dv_acc + jnp.dot(p.T, do_blk,
                                  preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dd)
        dk_acc = dk_acc + jnp.dot(ds.T, q_blk,
                                  preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    n_q = pl.cdiv(seq_len, block_q)
    # causal: q blocks strictly before this kv block see none of it
    j0 = (k_blk_idx * block_k) // block_q
    dk, dv = jax.lax.fori_loop(
        j0, n_q, body, (jnp.zeros((block_k, d), jnp.float32),
                        jnp.zeros((block_k, d), jnp.float32)))
    # dk absorbs the q-side scale (q was pre-scaled), which equals the
    # symmetric scale on s = scale·q·kᵀ
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _interp():
    return jax.default_backend() != "tpu"


def _compiler_params():
    """Raise the Mosaic scoped-VMEM cap above the 16 MiB default: the
    kernels keep the full-length K/V refs resident, and at seq 8192 with
    d=128 that sits a few hundred KiB over the default cap. v5e/v4 chips
    have 128 MiB of VMEM; 64 MiB keeps headroom for double-buffering and
    admits sequences to ~64k on one chip (ring attention shards beyond
    that). None in interpret mode (TPU-only knob)."""
    if _interp():
        return None
    import jax.experimental.pallas.tpu as pltpu
    return pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)


def _flash_fwd(q, k, v, mask, block_q: int, block_k: int):
    import jax.experimental.pallas as pl

    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    grid = (b * h, pl.cdiv(s, block_q))
    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, seq_len=s,
                          scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, 1), lambda i, j, h=h: (i // h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ],
        interpret=_interp(),
        compiler_params=_compiler_params(),
    )(qf, kf, vf, mask)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3), lse


def _flash_bwd(q, k, v, mask, o, lse, g, block_q: int, block_k: int):
    import jax.experimental.pallas as pl

    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    gf = g.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    of = o.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    # D_i = Σ_d dO_i ∘ O_i — one cheap elementwise pass in XLA
    dd = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                 axis=-1, keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_k=block_k, seq_len=s,
                          scale=scale),
        grid=(b * h, pl.cdiv(s, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, 1), lambda i, j, h=h: (i // h, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=_interp(),
        compiler_params=_compiler_params(),
    )(qf, kf, vf, mask, gf, lse, dd)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q, seq_len=s,
                          scale=scale),
        grid=(b * h, pl.cdiv(s, block_k)),
        in_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, block_k, 1), lambda i, j, h=h: (i // h, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        ],
        interpret=_interp(),
        compiler_params=_compiler_params(),
    )(kf, vf, qf, mask, gf, lse, dd)

    unflat = lambda a: a.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return unflat(dq), unflat(dk), unflat(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, mask, block_q: int, block_k: int):
    return _flash_fwd(q, k, v, mask, block_q, block_k)[0]


def _flash_fwd_rule(q, k, v, mask, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, mask, block_q, block_k)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd_rule(block_q, block_k, res, g):
    q, k, v, mask, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, mask, out, lse, g, block_q, block_k)
    return dq, dk, dv, jnp.zeros_like(mask)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_causal_attention(q, k, v, block_q: int = 512, block_k: int = 512,
                           attn_mask: Optional[jnp.ndarray] = None):
    """Pallas flash attention, fused fwd+bwd (see module docstring).
    ``attn_mask``: optional [b, s] key-padding mask (1 = real).

    Default blocks are 512x512 — measured on v5e (h=8, d=128): 1.5x
    faster than 128x128 at s=4096 and 2.7x at s=8192 (bigger MXU tiles,
    fewer grid programs); ``_fit_block`` shrinks them automatically for
    shorter sequences.

    Sequences are padded up to a multiple of 128 so every Pallas block is
    lane/sublane-aligned on real TPU hardware (a non-power-of-two s like
    1000 would otherwise pick a 125-row block). Pallas dynamic slices
    CLAMP out-of-bounds starts, so blocks MUST divide the padded length
    exactly — padding then slicing is the safe shape-independent recipe.
    Padded keys are masked out; padded query rows are sliced away.
    """
    b, s, h, d = q.shape
    s_pad = -(-s // 128) * 128
    if attn_mask is None:
        mask = jnp.ones((b, s, 1), jnp.float32)
    else:
        mask = attn_mask.astype(jnp.float32)[:, :, None]
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        mask = jnp.pad(mask, [(0, 0), (0, s_pad - s), (0, 0)])
    out = _flash(q, k, v, mask, _fit_block(s_pad, block_q),
                 _fit_block(s_pad, block_k))
    return out[:, :s] if s_pad != s else out


def _fit_block(s_pad: int, want: int) -> int:
    """Largest 128-multiple block <= ``want`` that divides ``s_pad``
    (itself a 128-multiple) — lane-aligned AND exactly tiling."""
    b = max(128, (min(want, s_pad) // 128) * 128)
    while s_pad % b:
        b -= 128
    return b


# ----------------------------------------------------------------- ring ----

def ring_causal_attention(q, k, v, axis_name: str = "sp",
                          axis_size: int = 1,
                          attn_mask: Optional[jnp.ndarray] = None
                          ) -> jnp.ndarray:
    """Causal attention with the sequence sharded over ``axis_name``.

    Must be traced inside ``shard_map``: q/k/v are the local shards
    [b, s_loc, h, d]; K/V rotate around the ring via ``ppermute`` while each
    device folds the visiting block into its online-softmax accumulator.
    Communication rides ICI; peak memory per device is O(s_loc² + s_loc·d).

    ``attn_mask``: optional [b, s_loc] key-padding shard (1 = real key),
    sharded over ``axis_name`` the same way as k/v. It rotates around the
    ring alongside the K/V block it describes, so every device masks the
    *visiting* block's padded keys (the varlen/unpad story of the
    reference's flash patch, ``train/llm/models/attention.py:68``).
    A query row whose visible keys are all padded yields exactly zero.
    """
    b, s_loc, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    my_idx = jax.lax.axis_index(axis_name)
    q_pos = my_idx * s_loc + jnp.arange(s_loc, dtype=jnp.int32)
    kmask0 = (jnp.ones((b, s_loc), bool) if attn_mask is None
              else attn_mask.astype(bool))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def fold(carry, xs):
        o_acc, m, l, k_cur, v_cur, km_cur = carry
        step = xs
        kv_idx = (my_idx - step) % axis_size
        kv_pos = kv_idx * s_loc + jnp.arange(s_loc, dtype=jnp.int32)
        s_blk = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                           k_cur.astype(jnp.float32)) * scale
        causal = q_pos[:, None] >= kv_pos[None, :]          # [s_loc, s_loc]
        live = causal[None, None] & km_cur[:, None, None, :]  # [b,1,q,k]
        s_blk = jnp.where(live, s_blk, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_blk, -1))
        alpha = jnp.exp(m - m_new)
        # gate on `live` (not just the exp): a row with no live keys has
        # m_new = NEG_INF and exp(NEG_INF - NEG_INF) = 1 everywhere, which
        # would silently average V; gating keeps l = 0 -> output 0
        p = jnp.where(live, jnp.exp(s_blk - m_new[..., None]), 0.0)
        l_new = l * alpha + jnp.sum(p, -1)
        o_new = (o_acc * alpha[..., None] +
                 jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        km_nxt = jax.lax.ppermute(km_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt, km_nxt), ()

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    # TRAINING-MEMORY CONTRACT: the fold is rematerialized. Plain autodiff
    # through the scan would save each step's [b, h, s_loc, s_loc]
    # probability block as a residual — s_loc²·axis_size memory, erasing
    # ring attention's point at exactly the context lengths it exists for.
    # With remat the backward recomputes the block from the step's carry
    # (K/V shards, O(s_loc·d)), so saved state stays O(axis_size·s_loc·d)
    # and the s_loc² working block lives only transiently per step — the
    # same guarantee the flash kernels give single-chip
    # (test_ring_bwd_residuals_stay_linear_in_s).
    (o, m, l, _, _, _), _ = jax.lax.scan(
        jax.checkpoint(fold), (o0, m0, l0, k, v, kmask0),
        jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
