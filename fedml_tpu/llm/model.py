"""TPU-native causal-LM for the FedLLM path.

Parity target: the reference's LLM stack builds on HF transformers
(``train/llm/configurations.py:156`` ``ModelArguments`` → ``AutoModel``
with optional flash-attn patch ``train/llm/models/attention.py:30``).
Here the model is a from-scratch flax decoder in the Llama style
(RMSNorm / rotary / SwiGLU) designed for the MXU: all hot ops are large
batched matmuls, compute dtype is configurable (bf16 by default on TPU),
and every kernel carries a partition spec over the ``fsdp`` / ``tensor``
mesh axes (the XLA-FSDP analogue of the reference's DeepSpeed ZeRO path,
``train/llm/distributed.py:21-70``).

HF checkpoint import for weight parity lives in ``hf.py``; attention
variants (Pallas flash kernel, ring attention over the ``sp`` axis) live
in ``attention.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


@dataclasses.dataclass
class LLMConfig:
    """Static architecture config (reference ``ModelArguments``,
    ``configurations.py:156``, minus the HF-hub plumbing)."""

    vocab_size: int = 512
    hidden_size: int = 128
    intermediate_size: int = 352
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: Optional[int] = None  # grouped-query attention; None = MHA
    max_seq_len: int = 256
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    # compute dtype for activations/matmuls; params stay float32 masters
    dtype: str = "float32"
    # attention implementation: "dense" | "flash" (Pallas) | "ring"
    attention_impl: str = "dense"
    # tie input embedding and LM head (small models)
    tie_embeddings: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    def flops_per_token(self) -> float:
        """Approximate fwd+bwd FLOPs per token (6 * params + attention),
        used by the bench's MFU report."""
        p = self.param_count()
        attn = 12 * self.num_layers * self.hidden_size * self.max_seq_len
        return 6.0 * p + attn

    def param_count(self) -> int:
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        per_layer = (h * h * 2 +                       # q, o
                     2 * h * self.kv_heads * self.head_dim +  # k, v
                     3 * h * i +                       # gate, up, down
                     2 * h)                            # 2 rmsnorms
        emb = v * h if self.tie_embeddings else 2 * v * h
        return self.num_layers * per_layer + emb + h


def _rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary position embedding. x: [b, s, heads, head_dim]."""
    half = x.shape[-1] // 2
    freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions[..., None].astype(jnp.float32) * freq  # [b, s, half]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        normed = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)
        return (normed * scale).astype(x.dtype)


def _lora_delta(x: jnp.ndarray, pair, scale: float) -> jnp.ndarray:
    """Low-rank side path ``(x @ a) @ b * scale`` (the S-LoRA batched
    apply: adapters stay factored instead of being merged into W, so a
    per-slot adapter gather is two small einsums, not a weight copy).

    ``pair = {"lora_a", "lora_b"}`` with leaves either shared
    ``[d_in, r]`` / ``[r, d_out]`` or per-slot ``[b, d_in, r]`` /
    ``[b, r, d_out]`` (gathered from a stacked adapter bank)."""
    a, bb = pair["lora_a"], pair["lora_b"]
    xf = x.astype(jnp.float32)
    if a.ndim == 3:   # per-slot adapters
        h = jnp.einsum("bsd,bdr->bsr", xf, a)
        return jnp.einsum("bsr,bro->bso", h, bb) * scale
    return ((xf @ a) @ bb) * scale


class Attention(nn.Module):
    cfg: LLMConfig

    @nn.compact
    def __call__(self, x, positions, attn_mask=None, kv_view=None,
                 adapter=None, lora_scale: float = 1.0):
        """Default path (``kv_view=None``): full causal self-attention,
        returns ``(out, None)``. Cache path: ``kv_view = (k_all, v_all)``
        position-ordered dense views ``[b, T, kv_heads, head_dim]`` of the
        slot's cached keys/values; the current tokens' K/V are written
        into the view at ``positions`` before attending, and returned as
        ``(out, (k_cur, v_cur))`` for the caller to scatter into the
        paged pool. ``adapter``: optional ``{q,k,v,o: {lora_a, lora_b}}``
        low-rank side paths (per-slot when leaves carry a leading batch
        axis)."""
        cfg = self.cfg
        b, s, _ = x.shape
        dense = lambda feats, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, name=name,
            dtype=cfg.compute_dtype, param_dtype=jnp.float32)

        def proj(name, feats):
            y = dense(feats, name)(x)
            if adapter is not None and name in adapter:
                delta = _lora_delta(x, adapter[name], lora_scale)
                y = y + delta.reshape(y.shape).astype(y.dtype)
            return y

        q = proj("q", (cfg.num_heads, cfg.head_dim))
        k = proj("k", (cfg.kv_heads, cfg.head_dim))
        v = proj("v", (cfg.kv_heads, cfg.head_dim))
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        from .attention import cached_attention, causal_attention
        if kv_view is not None:
            k_all, v_all = kv_view
            new_kv = (k, v)
            # write the current tokens into the gathered view at their
            # logical positions (out-of-range sentinel positions — padded
            # prefill rows, inactive slots — are dropped)
            bidx = jnp.arange(b)[:, None]
            k_all = k_all.at[bidx, positions].set(k, mode="drop")
            v_all = v_all.at[bidx, positions].set(v, mode="drop")
            if cfg.kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.kv_heads
                k_all = jnp.repeat(k_all, rep, axis=2)
                v_all = jnp.repeat(v_all, rep, axis=2)
            out = cached_attention(q, k_all, v_all, positions)
        else:
            new_kv = None
            if cfg.kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.kv_heads
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            out = causal_attention(q, k, v, impl=cfg.attention_impl,
                                   attn_mask=attn_mask)
        out = out.reshape(b, s, cfg.num_heads * cfg.head_dim)
        y = nn.DenseGeneral(cfg.hidden_size, use_bias=False, name="o",
                            dtype=cfg.compute_dtype,
                            param_dtype=jnp.float32)(out)
        if adapter is not None and "o" in adapter:
            y = y + _lora_delta(out, adapter["o"],
                                lora_scale).reshape(y.shape).astype(y.dtype)
        return y, new_kv


class MLP(nn.Module):
    cfg: LLMConfig

    @nn.compact
    def __call__(self, x, adapter=None, lora_scale: float = 1.0):
        cfg = self.cfg
        dense = lambda feats, name: nn.DenseGeneral(  # noqa: E731
            feats, use_bias=False, name=name, dtype=cfg.compute_dtype,
            param_dtype=jnp.float32)

        def proj(name, feats, inp):
            y = dense(feats, name)(inp)
            if adapter is not None and name in adapter:
                delta = _lora_delta(inp, adapter[name], lora_scale)
                y = y + delta.reshape(y.shape).astype(y.dtype)
            return y

        gate = proj("gate", cfg.intermediate_size, x)
        up = proj("up", cfg.intermediate_size, x)
        return proj("down", cfg.hidden_size, nn.silu(gate) * up)


class DecoderLayer(nn.Module):
    cfg: LLMConfig

    @nn.compact
    def __call__(self, x, positions, attn_mask=None, kv_view=None,
                 adapter=None, lora_scale: float = 1.0):
        attn = adapter.get("attn") if adapter is not None else None
        mlp = adapter.get("mlp") if adapter is not None else None
        a_out, new_kv = Attention(self.cfg, name="attn")(
            RMSNorm(self.cfg.rms_eps, name="ln_attn")(x), positions,
            attn_mask, kv_view=kv_view, adapter=attn,
            lora_scale=lora_scale)
        h = x + a_out
        h = h + MLP(self.cfg, name="mlp")(
            RMSNorm(self.cfg.rms_eps, name="ln_mlp")(h), adapter=mlp,
            lora_scale=lora_scale)
        return h, new_kv


class CausalLM(nn.Module):
    """Decoder-only LM. ``__call__(tokens [b, s]) -> logits [b, s, vocab]``.

    Cache-aware path (continuous-batching serving): pass ``positions``
    ([b, s] absolute positions; out-of-range values mark padded/inactive
    rows whose cache writes are dropped) and ``kv_view`` (per-layer
    ``(k_all, v_all)`` gathered cache views) — returns
    ``(logits, [(k_cur, v_cur), ...])`` so the caller can scatter the new
    rows into its paged pool. ``adapters``: a LoRA tree shaped like
    :func:`~fedml_tpu.llm.lora.lora_init`'s output, optionally with a
    leading per-slot batch axis on every leaf (gathered from a stacked
    adapter bank) — applied as factored side paths, never merged."""

    cfg: LLMConfig

    @nn.compact
    def __call__(self, tokens, train: bool = False, attn_mask=None,
                 positions=None, kv_view=None, adapters=None,
                 lora_scale: float = 1.0):
        cfg = self.cfg
        emb = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed",
                       dtype=cfg.compute_dtype, param_dtype=jnp.float32)
        x = emb(tokens)
        if positions is None:
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            if cfg.attention_impl == "ring":
                # sequence is sharded over the ring axis: offset to global
                # positions so RoPE and the causal mask stay correct per
                # shard
                from .attention import _RING_AXIS
                ax = _RING_AXIS.get()
                if ax is not None:
                    pos = pos + jax.lax.axis_index(ax[0]) * tokens.shape[1]
            positions = jnp.broadcast_to(pos[None, :], tokens.shape)
        new_kvs = []
        for i in range(cfg.num_layers):
            x, new_kv = DecoderLayer(cfg, name=f"layer_{i}")(
                x, positions, attn_mask,
                kv_view=None if kv_view is None else kv_view[i],
                adapter=None if adapters is None
                else adapters.get(f"layer_{i}"),
                lora_scale=lora_scale)
            new_kvs.append(new_kv)
        x = RMSNorm(cfg.rms_eps, name="ln_f")(x)
        if cfg.tie_embeddings:
            logits = emb.attend(x)
        else:
            logits = nn.DenseGeneral(cfg.vocab_size, use_bias=False,
                                     name="lm_head", dtype=cfg.compute_dtype,
                                     param_dtype=jnp.float32)(x)
        logits = logits.astype(jnp.float32)
        if kv_view is not None:
            return logits, new_kvs
        return logits


def init_llm(cfg: LLMConfig, rng: jax.Array) -> Tuple[CausalLM, PyTree]:
    """Build the module and init params on a tiny dummy batch."""
    model = CausalLM(cfg)
    tokens = jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)
    params = model.init(rng, tokens)["params"]
    return model, params


def count_params(params: PyTree) -> int:
    return int(sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(params)))
