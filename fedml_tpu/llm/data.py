"""LLM data utilities: tokenizer, instruction formatting, packing.

Parity target: reference ``train/llm/dataset_utils.py`` +
``modeling_utils.py:28`` (completion-only collator: loss only on response
tokens) and the UnitedLLM databricks-dolly pipeline. Without network
egress, the default tokenizer is byte-level (no vocab download) and the
default corpus is a locally generated instruction set; real corpora are
read from ``data_cache_dir`` when present (jsonl with
``instruction``/``response`` fields, the dolly schema).

Everything returns the framework-standard padded arrays so LLM federated
runs ride the same containers as every other task: ``x`` [n, L] tokens,
``y`` [n, L] next-token labels with ``-1`` on prompt/pad positions.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

PAD, BOS, EOS, SEP = 0, 1, 2, 3
SPECIAL_TOKENS = 4
BYTE_VOCAB = 256 + SPECIAL_TOKENS


class ByteTokenizer:
    """Byte-level tokenizer: token = byte value + SPECIAL_TOKENS offset.
    Zero-dependency stand-in for the HF tokenizer the reference downloads
    (``ModelArguments.get_tokenizer_kwargs``, ``configurations.py:343``)."""

    vocab_size = BYTE_VOCAB
    pad_id, bos_id, eos_id, sep_id = PAD, BOS, EOS, SEP

    def encode(self, text: str) -> List[int]:
        return [b + SPECIAL_TOKENS for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i - SPECIAL_TOKENS for i in ids
                     if i >= SPECIAL_TOKENS).decode("utf-8", "replace")


class RoundTripByteTokenizer(ByteTokenizer):
    """Round-trip-exact variant: ``encode(decode(ids)) == ids`` for every
    byte-token sequence, including invalid UTF-8. ``decode`` maps
    undecodable bytes to lone surrogates (``surrogateescape``) instead of
    U+FFFD, and ``encode`` inverts them back to the original bytes; valid
    UTF-8 text encodes identically to :class:`ByteTokenizer`. Lone
    surrogates survive the JSON wire because ``json.dumps`` (default
    ``ensure_ascii=True``) escapes them to ``\\udcXX`` and ``json.loads``
    restores them. The suffix-cache chat surface needs this exactness:
    a follow-up request re-encodes the assistant reply it was served, and
    the re-encoded ids must equal the generated ids for the stored
    decode-origin KV blocks to alias."""

    def encode(self, text: str) -> List[int]:
        return [b + SPECIAL_TOKENS
                for b in text.encode("utf-8", "surrogateescape")]

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i - SPECIAL_TOKENS for i in ids
                     if i >= SPECIAL_TOKENS).decode("utf-8",
                                                    "surrogateescape")


def synthetic_instruction_corpus(n: int, seed: int = 0
                                 ) -> List[Dict[str, str]]:
    """Deterministic toy instruction/response pairs (arithmetic, echo,
    sorting) — learnable structure so fine-tune loss curves are meaningful
    without any downloaded corpus."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        kind = i % 3
        if kind == 0:
            a, b = rng.randint(0, 50, 2)
            out.append({"instruction": f"add {a} {b}",
                        "response": str(a + b)})
        elif kind == 1:
            word = "".join(rng.choice(list("abcdef"), 5))
            out.append({"instruction": f"echo {word}", "response": word})
        else:
            nums = rng.randint(0, 9, 4)
            out.append({"instruction": "sort " + " ".join(map(str, nums)),
                        "response": " ".join(map(str, sorted(nums)))})
    return out


def shakespeare_instruction_corpus(window: int = 96,
                                   stride: int = 48
                                   ) -> List[Dict[str, str]]:
    """REAL-language instruction corpus built from the bundled
    public-domain Shakespeare passages (``data/bundled/shakespeare.py``):
    each row asks the model to continue a text window — a completion task
    over genuine natural language, the zero-egress counterpart of the
    dolly corpus the reference's UnitedLLM pipeline downloads."""
    from ..data.bundled.shakespeare import PASSAGES
    rows = []
    for role, text in PASSAGES.items():
        for start in range(0, max(len(text) - window, 1), stride):
            chunk = text[start:start + window]
            cut = max(window // 3, 1)
            rows.append({"instruction": f"Continue: {chunk[:cut]}",
                         "response": chunk[cut:]})
    return rows


def load_instruction_corpus(path: Optional[str], n_fallback: int = 256,
                            seed: int = 0,
                            fallback: str = "synthetic"
                            ) -> List[Dict[str, str]]:
    """jsonl with instruction/response (dolly schema: ``instruction`` +
    ``response``). No file: ``fallback='shakespeare'`` uses the bundled
    REAL text corpus; ``'synthetic'`` (default) uses the toy generator
    with a loud notice."""
    if path and os.path.exists(path):
        rows = []
        with open(path) as f:
            for line in f:
                if line.strip():
                    r = json.loads(line)
                    rows.append({"instruction": r["instruction"],
                                 "response": r["response"]})
        return rows
    if fallback == "shakespeare":
        return shakespeare_instruction_corpus()
    import logging
    logging.getLogger(__name__).warning(
        "no instruction corpus at %r — using the SYNTHETIC fallback corpus",
        path)
    return synthetic_instruction_corpus(n_fallback, seed)


def tokenize_examples(corpus: Sequence[Dict[str, str]],
                      tokenizer: ByteTokenizer, seq_len: int,
                      completion_only: bool = True
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """→ (x [n, L], y [n, L]) with next-token labels; ``-1`` marks positions
    whose loss is excluded (prompt tokens when ``completion_only``, and all
    padding) — the collator semantics of ``modeling_utils.py:28``."""
    xs, ys = [], []
    for ex in corpus:
        prompt = tokenizer.encode(ex["instruction"]) + [SEP]
        resp = tokenizer.encode(ex["response"]) + [EOS]
        ids = ([BOS] + prompt + resp)[:seq_len + 1]
        x = ids[:-1]
        labels = ids[1:]
        if completion_only:
            # label positions that predict prompt tokens are ignored;
            # x[i] predicts labels[i], prompt spans x[0..len(prompt)]
            n_prompt = min(len(prompt), len(labels))
            labels = [-1] * n_prompt + labels[n_prompt:]
        pad = seq_len - len(x)
        xs.append(x + [PAD] * pad)
        ys.append(labels + [-1] * pad)
    return (np.asarray(xs, np.int32), np.asarray(ys, np.int32))


def build_llm_federated(args, n_silos: int, seq_len: int,
                        tokenizer: Optional[ByteTokenizer] = None):
    """Partition an instruction corpus across silos into the standard
    FederatedDataset (so simulators/cross-silo consume it unchanged)."""
    from ..data.containers import build_federated_dataset

    tokenizer = tokenizer or ByteTokenizer()
    corpus = load_instruction_corpus(
        getattr(args, "llm_corpus_path", None),
        n_fallback=int(getattr(args, "llm_corpus_size", 256)),
        seed=int(getattr(args, "random_seed", 0)),
        fallback=str(getattr(args, "llm_corpus_fallback", "synthetic")))
    x, y = tokenize_examples(corpus, tokenizer, seq_len)
    n = x.shape[0]
    rng = np.random.RandomState(int(getattr(args, "random_seed", 0)))
    order = rng.permutation(n)
    n_test = max(4, n // 10)
    test_idx, train_idx = order[:n_test], order[n_test:]
    shards = np.array_split(train_idx, n_silos)
    client_x = [x[s] for s in shards]
    client_y = [y[s] for s in shards]
    fed = build_federated_dataset(
        client_x, client_y, x[test_idx], y[test_idx],
        batch_size=int(getattr(args, "batch_size", 8)),
        num_classes=tokenizer.vocab_size, dtype=np.int32, task="llm")
    corpus_path = getattr(args, "llm_corpus_path", None)
    fed.provenance = (
        "real" if (corpus_path and os.path.exists(corpus_path))
        or str(getattr(args, "llm_corpus_fallback", "synthetic"))
        == "shakespeare" else "synthetic")
    return fed, tokenizer
