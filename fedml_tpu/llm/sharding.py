"""Parameter/activation sharding for the LLM path — the XLA-FSDP + TP
analogue of the reference's DeepSpeed ZeRO integration
(``train/llm/distributed.py:21-70``; launcher option ``deepspeed`` in the
UnitedLLM config).

Design: Megatron-style tensor parallelism over the ``tensor`` axis
(attention heads / MLP intermediate sharded; paired projections sharded on
the opposite side so each block needs one reduce), ZeRO-3-style parameter
sharding over ``fsdp`` on the remaining large axis, batch over ``data``,
and sequence over ``sp`` for ring attention. The specs are *constraints*:
XLA's SPMD partitioner inserts the all-gathers/reduce-scatters, exactly the
"annotate shardings, let XLA insert collectives" recipe.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import traverse_util
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..constants import AXIS_DATA, AXIS_FSDP, AXIS_SEQ, AXIS_TENSOR
from .attention import ring_axis

PyTree = Any


def _mesh_axis(mesh: Mesh, name: Optional[str]) -> Optional[str]:
    """Use an axis only if the mesh has it with size > 1."""
    return name if (name in mesh.shape and mesh.shape[name] > 1) else None


def llm_param_specs(params: PyTree, mesh: Mesh) -> PyTree:
    """PartitionSpec tree for CausalLM (+ LoRA) params.

    Rules (path suffix → spec over (fsdp, tensor)):
      q/k/v kernel [h, heads, hd]  → (fsdp, tensor, -)
      o kernel     [h_attn, h]     → (tensor, fsdp)
      gate/up      [h, inter]      → (fsdp, tensor)
      down         [inter, h]      → (tensor, fsdp)
      embed/lm_head [vocab, h]     → (tensor, fsdp)
      norms / biases / LoRA factors → replicated (tiny)
    """
    fsdp = _mesh_axis(mesh, AXIS_FSDP)
    tp = _mesh_axis(mesh, AXIS_TENSOR)

    def spec_for(path: Tuple[str, ...], leaf) -> P:
        name = path[-1]
        parent = path[-2] if len(path) >= 2 else ""
        if name in ("lora_a", "lora_b") or leaf.ndim <= 1:
            return P()
        if name == "kernel" and parent in ("q", "k", "v"):
            return P(fsdp, tp, *(None,) * (leaf.ndim - 2))
        if name == "kernel" and parent == "o":
            return P(tp, fsdp)
        if name == "kernel" and parent in ("gate", "up"):
            return P(fsdp, tp)
        if name == "kernel" and parent == "down":
            return P(tp, fsdp)
        if name == "embedding" or parent == "lm_head":
            return P(tp, fsdp)
        # fallback: shard the largest divisible axis over fsdp
        spec = [None] * leaf.ndim
        if fsdp is not None:
            size = mesh.shape[AXIS_FSDP]
            for i in sorted(range(leaf.ndim), key=lambda i: -leaf.shape[i]):
                if leaf.shape[i] % size == 0:
                    spec[i] = fsdp
                    break
        return P(*spec)

    flat = traverse_util.flatten_dict(params)
    specs = {path: spec_for(path, leaf) for path, leaf in flat.items()}
    return traverse_util.unflatten_dict(specs)


def shard_llm_params(params: PyTree, mesh: Mesh) -> PyTree:
    """device_put the param tree onto the mesh per ``llm_param_specs``."""
    specs = llm_param_specs(params, mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def make_sharded_train_step(loss_fn: Callable, optimizer, mesh: Mesh,
                            params_specs: PyTree):
    """jit a (params, opt_state, batch, rng) -> (params, opt_state, loss)
    step with parameter shardings constrained to ``params_specs`` and the
    batch sharded over ``data``. XLA inserts the FSDP gather/scatter and TP
    reduces."""
    data_ax = _mesh_axis(mesh, AXIS_DATA)

    def step(params, opt_state, batch, rng):
        params = jax.lax.with_sharding_constraint(
            params, jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), params_specs))
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    batch_sharding = {
        "x": NamedSharding(mesh, P(data_ax, None)),
        "y": NamedSharding(mesh, P(data_ax, None)),
        "mask": NamedSharding(mesh, P(data_ax)),
    }
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), params_specs)
    return jax.jit(
        step,
        in_shardings=(param_sh, None, batch_sharding, None),
        out_shardings=(param_sh, None, None))


def make_ring_forward(model_apply: Callable, mesh: Mesh,
                      axis_name: str = AXIS_SEQ) -> Callable:
    """Sequence-parallel forward: tokens [b, S] sharded over ``sp``; each
    shard runs the decoder on its sequence slice with ring attention
    rotating K/V over ICI. ``model_apply(params, tokens, attn_mask)`` runs
    on local shards. Returns ``fwd(params, tokens, attn_mask=None) ->
    logits`` (sharded on the sequence axis); ``attn_mask`` is a [b, S]
    key-padding mask (1 = real token) sharded over ``sp`` alongside the
    tokens — it rotates with K/V inside ring attention."""
    from ..core.jax_compat import shard_map

    size = mesh.shape[axis_name]

    def local_fwd(params, tokens, attn_mask):
        with ring_axis(axis_name, size):
            return model_apply(params, tokens, attn_mask)

    fwd = shard_map(
        local_fwd, mesh=mesh,
        in_specs=(P(), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name, None),
        check_vma=False)

    def call(params, tokens, attn_mask=None):
        if attn_mask is None:
            attn_mask = jnp.ones(tokens.shape, jnp.int32)
        return fwd(params, tokens, attn_mask)

    return call
