"""LoRA as a pure pytree transform.

Parity target: the reference's PEFT/LoRA integration
(``train/llm/configurations.py:356`` ``get_peft_config``,
``peft_utils.py`` LORA_LAYER_TYPES) which wraps torch modules in-place.
TPU-native design: LoRA is *data*, not module surgery — a small pytree of
``(lora_a, lora_b)`` factor pairs mirroring the targeted kernels. The
forward merges ``W + (a @ b) * (alpha / rank)`` inside jit (XLA fuses the
rank-r update into the matmul's producer), gradients flow only through the
adapter tree, and federated aggregation ships the adapter tree alone — the
cheap all-gather the reference approximates with ZeRO-3 gathered-parameter
contexts (``train/llm/distributed.py:54-70``).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import traverse_util

PyTree = Any

# kernel parents targeted by default: attention projections + MLP
DEFAULT_TARGETS: Tuple[str, ...] = ("q", "k", "v", "o", "gate", "up", "down")


def _target_paths(params: PyTree, targets: Sequence[str]):
    flat = traverse_util.flatten_dict(params)
    return [path for path in flat
            if path[-1] == "kernel" and len(path) >= 2
            and path[-2] in targets]


def lora_init(rng: jax.Array, params: PyTree, rank: int = 8,
              targets: Sequence[str] = DEFAULT_TARGETS) -> PyTree:
    """Create a zero-effect adapter tree for the targeted kernels.

    Each target kernel [in, ...out] gets ``lora_a`` [in, rank] (gaussian,
    std 1/rank as in the LoRA paper) and ``lora_b`` [rank, prod(out)]
    (zeros), so the initial merged model equals the base model exactly.
    """
    paths = _target_paths(params, targets)
    if not paths:
        raise ValueError(
            f"no LoRA targets found; targets={tuple(targets)}")
    flat = traverse_util.flatten_dict(params)
    out = {}
    for i, path in enumerate(paths):
        kernel = flat[path]
        d_in = kernel.shape[0]
        d_out = int(np.prod(kernel.shape[1:]))
        k = jax.random.fold_in(rng, i)
        out[path[:-1] + ("lora_a",)] = (
            jax.random.normal(k, (d_in, rank), jnp.float32) / rank)
        out[path[:-1] + ("lora_b",)] = jnp.zeros((rank, d_out), jnp.float32)
    return traverse_util.unflatten_dict(out)


def lora_merge(params: PyTree, lora: PyTree, alpha: float = 16.0) -> PyTree:
    """Return params with ``W + (a @ b) * (alpha / rank)`` at every adapted
    kernel. Pure; safe under jit and grad."""
    flat = dict(traverse_util.flatten_dict(params))
    lflat = traverse_util.flatten_dict(lora)
    a_paths = [p for p in lflat if p[-1] == "lora_a"]
    for path in a_paths:
        base_path = path[:-1] + ("kernel",)
        a = lflat[path]
        b = lflat[path[:-1] + ("lora_b",)]
        kernel = flat[base_path]
        rank = a.shape[1]
        delta = (a @ b) * (alpha / rank)
        flat[base_path] = kernel + delta.reshape(kernel.shape).astype(
            kernel.dtype)
    return traverse_util.unflatten_dict(flat)


def lora_zero_like(lora: PyTree) -> PyTree:
    """An all-zero adapter with ``lora``'s structure: zero ``lora_b``
    already means zero effect, but zeroing ``lora_a`` too makes the
    identity adapter content-independent — the bank's 'serve the base
    model' row."""
    return jax.tree_util.tree_map(jnp.zeros_like, lora)


def lora_stack(adapters: Sequence[PyTree]) -> PyTree:
    """Stack N structurally-identical adapter trees into ONE pytree whose
    leaves carry a leading ``[A]`` axis — the resident multi-LoRA bank a
    batched serving step gathers from (S-LoRA, Sheng et al. 2023).
    Structures must match exactly (same targets, same rank)."""
    if not adapters:
        raise ValueError("lora_stack needs >= 1 adapter")
    return jax.tree_util.tree_map(
        lambda *ls: jnp.stack([jnp.asarray(l, jnp.float32) for l in ls]),
        *adapters)


def lora_select(stack: PyTree, idx) -> PyTree:
    """Gather per-slot adapters out of a stacked bank: every ``[A, ...]``
    leaf becomes ``[S, ...]`` (or ``[...]`` for a scalar ``idx``). Pure
    gather — safe inside jit with ``idx`` as data, which is what keeps the
    decode step compile-once across any adapter mix."""
    return jax.tree_util.tree_map(lambda l: l[idx], stack)


def lora_param_count(lora: PyTree) -> int:
    return int(sum(np.prod(p.shape)
                   for p in jax.tree_util.tree_leaves(lora)))


def make_lora_apply(apply_fn: Callable[..., jnp.ndarray], base_params: PyTree,
                    alpha: float = 16.0) -> Callable[..., jnp.ndarray]:
    """Close over frozen base params: returns ``apply(lora, x, **kw)`` so the
    adapter tree is the *only* trainable pytree the algorithm frame sees —
    every federated optimizer / defense / DP hook then operates on adapters
    alone, which is exactly the FedLLM aggregation contract
    (UnitedLLM ships per-round adapter checkpoints,
    ``spotlight_prj/unitedllm/src/unitedllm_trainer.py``)."""

    def apply(lora: PyTree, x: jnp.ndarray, **kwargs) -> jnp.ndarray:
        merged = lora_merge(base_params, lora, alpha)
        return apply_fn(merged, x, **kwargs)

    return apply
