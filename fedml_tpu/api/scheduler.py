"""Resource allocation store + matcher + job monitor.

Parity target: the reference scheduler core —
``computing/scheduler/scheduler_core/compute_gpu_db.py:1-333`` (sqlite
device/GPU allocation tables), ``scheduler_matcher.py:1-124`` (match a
job's resource request against available devices), and
``comm_utils/job_monitor.py:338,450`` (periodic monitor that detects
dead runs/endpoints and restarts them).

Local-first redesign: one sqlite file under the runs root holds the
device table and live allocations; :func:`fedml_tpu.api.launch_job`
consults the matcher when a job yaml carries a ``computing:`` section
(``device_slots: N``), and releases the allocation when the run reaches
a terminal state. The :class:`JobMonitor` generalizes the serving
replica-set health check to training runs: a run whose process died
WITHOUT writing an exit record (SIGKILL, OOM, host crash) is a crash —
distinct from a graceful nonzero exit — and, if the job opted in
(``restart: true``), it is relaunched. Restart lineage and counts are
persisted in the run metas, so the cap survives monitor restarts.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class ResourceDB:
    """Sqlite-backed device + allocation store (reference
    ``compute_gpu_db.py``: ``ComputeGpuDatabase`` over sqlite). One file
    per deployment; safe for concurrent processes (sqlite handles the
    locking)."""

    def __init__(self, path: Optional[str] = None):
        if path is None:
            from . import _runs_root
            path = os.path.join(_runs_root(), "resources.db")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self.path = path
        with self._conn() as c:
            c.execute("""CREATE TABLE IF NOT EXISTS devices (
                device_id TEXT PRIMARY KEY,
                total_slots INTEGER NOT NULL,
                meta TEXT DEFAULT '{}')""")
            c.execute("""CREATE TABLE IF NOT EXISTS allocations (
                run_id TEXT PRIMARY KEY,
                device_id TEXT NOT NULL,
                slots INTEGER NOT NULL,
                ts REAL NOT NULL)""")

    @contextlib.contextmanager
    def _conn(self):
        conn = sqlite3.connect(self.path, timeout=10.0)
        conn.isolation_level = None  # autocommit; we use explicit BEGIN
        try:
            yield conn
        finally:
            conn.close()

    @staticmethod
    def _free_map(c) -> Dict[str, int]:
        """device_id -> free slots, in ONE query on an open connection."""
        rows = c.execute(
            "SELECT d.device_id, "
            "       d.total_slots - COALESCE(SUM(a.slots), 0) "
            "FROM devices d LEFT JOIN allocations a "
            "     ON a.device_id = d.device_id "
            "GROUP BY d.device_id, d.total_slots").fetchall()
        return {d: int(f) for d, f in rows}

    @staticmethod
    def _match_in(free: Dict[str, int], slots: int) -> Optional[str]:
        """Best-fit-by-headroom (reference ``scheduler_matcher.py``:
        order candidates by available capacity): the device with the
        most free slots that still fits; None = no capacity."""
        best, best_free = None, -1
        for dev, f in free.items():
            if f >= int(slots) and f > best_free:
                best, best_free = dev, f
        return best

    # --- device table -------------------------------------------------------
    def register_device(self, device_id: str, total_slots: int,
                        meta: Optional[dict] = None) -> None:
        with self._conn() as c:
            c.execute("INSERT OR REPLACE INTO devices VALUES (?, ?, ?)",
                      (device_id, int(total_slots),
                       json.dumps(meta or {})))

    def devices(self) -> List[Dict[str, Any]]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT device_id, total_slots, meta FROM devices"
            ).fetchall()
            free = self._free_map(c)
        return [{"device_id": d, "total_slots": s,
                 "meta": json.loads(m), "free_slots": free.get(d, 0)}
                for d, s, m in rows]

    def free_slots(self, device_id: str) -> int:
        with self._conn() as c:
            return self._free_map(c).get(device_id, 0)

    def match(self, slots: int) -> Optional[str]:
        with self._conn() as c:
            return self._match_in(self._free_map(c), slots)

    # --- allocations --------------------------------------------------------
    def allocate(self, run_id: str, slots: int,
                 device_id: Optional[str] = None) -> Optional[str]:
        """Atomically claim ``slots`` on ``device_id`` (or the matcher's
        pick). Returns the device id, or None when nothing fits."""
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")  # serialize check+insert
            try:
                free = self._free_map(c)
                target = device_id or self._match_in(free, slots)
                if target is None or free.get(target, 0) < int(slots):
                    c.execute("ROLLBACK")
                    return None
                c.execute("INSERT OR REPLACE INTO allocations "
                          "VALUES (?, ?, ?, ?)",
                          (run_id, target, int(slots), time.time()))
                c.execute("COMMIT")
                return target
            except sqlite3.Error:
                c.execute("ROLLBACK")
                raise

    def release(self, run_id: str) -> bool:
        with self._conn() as c:
            cur = c.execute("DELETE FROM allocations WHERE run_id=?",
                            (run_id,))
            return cur.rowcount > 0

    def allocations(self) -> List[Dict[str, Any]]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT run_id, device_id, slots, ts FROM allocations"
            ).fetchall()
        return [{"run_id": r, "device_id": d, "slots": s, "ts": ts}
                for r, d, s, ts in rows]


_default_db: Optional[ResourceDB] = None
_db_lock = threading.Lock()


def default_db() -> ResourceDB:
    """Process-wide ResourceDB with a 'local' device auto-registered
    (slots from ``FEDML_TPU_LOCAL_SLOTS``, default 8)."""
    global _default_db
    with _db_lock:
        if _default_db is None:
            db = ResourceDB()
            if not any(d["device_id"] == "local" for d in db.devices()):
                db.register_device(
                    "local",
                    int(os.environ.get("FEDML_TPU_LOCAL_SLOTS", "8")))
            _default_db = db
        return _default_db


def _reset_default_db() -> None:  # test isolation (runs root changes)
    global _default_db
    with _db_lock:
        _default_db = None


def _pid_dead(pid: int) -> bool:
    """True when the process is gone OR a zombie — ``kill(pid, 0)``
    succeeds on zombies (a dead child nobody reaped), but a zombie does
    no work and must count as dead. Falls back to the portable signal-0
    probe where procfs is unavailable (macOS)."""
    from . import _pid_alive
    if pid <= 0:
        return True
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
        return state == "Z"
    except FileNotFoundError:
        return not _pid_alive(pid)  # no procfs entry: gone, or non-Linux
    except (OSError, IndexError):
        return not _pid_alive(pid)


class JobMonitor:
    """Periodic run supervisor (reference ``job_monitor.py``
    ``monitor_slave_run_process_status`` :338 + endpoint restarts :450).

    Crash detection is exit-record based, NOT pid based: a terminal run
    with no ``exit_code`` file died silently (SIGKILL/OOM) no matter who
    noticed first — ``run_status`` may already have reconciled the
    registry entry to FAILED before this scan. Restart bookkeeping
    (``restart_of``, ``restart_index``, ``monitor_handled``) lives in
    the run metas, so the ``max_restarts`` cap binds across monitor
    restarts and multiple monitors."""

    def __init__(self, interval_s: float = 1.0, max_restarts: int = 3):
        self.interval_s = float(interval_s)
        self.max_restarts = int(max_restarts)
        self.restarted: Dict[str, str] = {}   # dead run -> replacement
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "JobMonitor":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scan_once()
            except Exception:
                logger.exception("job monitor scan failed")

    def scan_once(self) -> List[str]:
        """One scan; returns run ids newly detected as crashed."""
        from . import (STATUS_FAILED, STATUS_FINISHED, STATUS_KILLED,
                       STATUS_RUNNING, _finalize, _read_exit_code,
                       _read_meta, _release_allocation, _run_dir,
                       _write_meta, launch_job, run_list)
        acted = []
        for meta in run_list():  # run_list reconciles statuses itself
            run_id = meta.get("run_id")
            status = meta.get("status")
            rc_recorded = os.path.exists(
                os.path.join(_run_dir(run_id), "exit_code"))
            if status == STATUS_RUNNING:
                if not _pid_dead(int(meta.get("pid", -1))):
                    continue
                # the pid poll and the exit-record stat race the job's
                # shutdown: a run can write exit_code between run_list's
                # reconcile and our poll. Re-check the record NOW, before
                # forcing FAILED — a recorded rc means a normal exit and
                # is authoritative (finalize with it instead).
                rc = _read_exit_code(run_id)
                if rc is not None:
                    _finalize(run_id, rc)   # writes terminal meta AND
                    meta = _read_meta(run_id) or meta   # releases the
                    meta["allocation_released"] = True  # allocation
                    crashed = False
                else:
                    # dead (incl. zombie) with no exit record: a silent
                    # death (SIGKILL/OOM) — finalize it ourselves
                    fresh = _read_meta(run_id) or meta
                    fresh["status"] = STATUS_FAILED
                    fresh["error"] = "process died without exit record"
                    _write_meta(fresh["run_id"], fresh)
                    meta = fresh
                    crashed = True
            elif status == STATUS_FAILED and not rc_recorded:
                # run_status (ours or any other poller's) already marked
                # the silent death — still OUR crash to handle, once.
                # pid <= 0 = the launch itself failed (nothing ever ran):
                # not a crash to restart.
                crashed = int(meta.get("pid", -1)) > 0
            elif status in (STATUS_FINISHED, STATUS_KILLED,
                            STATUS_FAILED):
                # only runs that ever CLAIMED capacity need a release, and
                # only once — _finalize/run_stop already released them, so
                # this is a belt-and-braces sweep, not a per-scan sqlite
                # DELETE for every historical run forever
                if (meta.get("device_id")
                        and not meta.get("allocation_released")):
                    _release_allocation(run_id)
                    meta["allocation_released"] = True
                    _write_meta(run_id, meta)
                continue
            else:
                continue
            if meta.get("monitor_handled"):
                continue
            meta["monitor_handled"] = True
            if not meta.get("allocation_released"):
                _release_allocation(run_id)
                meta["allocation_released"] = True
            _write_meta(run_id, meta)
            if not crashed:
                continue
            acted.append(run_id)
            logger.warning("job monitor: run %s died (pid %s)", run_id,
                           meta.get("pid"))
            if not self._wants_restart(meta):
                continue
            n = int(meta.get("restart_index", 0))
            if n >= self.max_restarts:
                logger.error("job monitor: lineage of %s exceeded "
                             "max_restarts=%d",
                             meta.get("lineage_root", run_id),
                             self.max_restarts)
                continue
            res = launch_job(meta["yaml"])
            if res.result_code == 0:
                root = meta.get("lineage_root", run_id)
                self.restarted[run_id] = res.run_id
                new_meta = _read_meta(res.run_id) or {}
                new_meta["restart_of"] = run_id
                new_meta["lineage_root"] = root
                new_meta["restart_index"] = n + 1
                _write_meta(res.run_id, new_meta)
                logger.warning("job monitor: restarted %s as %s "
                               "(restart %d/%d)", run_id, res.run_id,
                               n + 1, self.max_restarts)
        return acted

    @staticmethod
    def _wants_restart(meta: Dict[str, Any]) -> bool:
        yaml_file = meta.get("yaml")
        if not yaml_file or not os.path.exists(yaml_file):
            return False
        try:
            import yaml as _yaml
            spec = _yaml.safe_load(open(yaml_file)) or {}
        except Exception:
            return False
        return bool(spec.get("restart"))
