"""Python API: ``fedml_tpu.api.*`` — the programmatic platform surface.

Parity target: ``api/__init__.py:29-43`` of the reference (``fedml_login``,
``launch_job``, ``run_status/run_logs/run_stop/run_list``, ``build``, model
serve). The reference's implementations are thin wrappers over a cloud
platform (MLOps REST + MQTT agents); this framework is **local-first by
design**: a job is a local subprocess, the "platform" is a run registry
under ``~/.cache/fedml_tpu/runs/<run_id>/`` (``meta.json`` + ``job.log``),
and every API call works with zero network. The call shapes — launch
returns a run id, logs/status/stop address it — are kept so user code
written against the reference maps 1:1.

Job YAML forms accepted by :func:`launch_job`:

* **task job** (reference launch yaml): has a ``job:`` shell command and
  optionally ``workspace:`` — the command runs in the workspace;
* **training config** (reference fedml_config yaml): anything else — runs
  ``python -m fedml_tpu.cli train --cf <yaml>`` so a simulation/cross-silo
  config is directly launchable.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shlex
import signal
import subprocess
import sys
import time
import uuid
import zipfile
from typing import Any, Dict, List, Optional

import yaml

logger = logging.getLogger(__name__)


def _runs_root() -> str:
    return os.path.expanduser(
        os.environ.get("FEDML_TPU_RUNS_DIR", "~/.cache/fedml_tpu/runs"))


# Run statuses (reference api/constants.py RunStatus, reduced to the
# lifecycle a local job actually has)
STATUS_RUNNING = "RUNNING"
STATUS_FINISHED = "FINISHED"
STATUS_FAILED = "FAILED"
STATUS_KILLED = "KILLED"


@dataclasses.dataclass
class LaunchResult:
    run_id: str
    result_code: int
    result_message: str
    inner_id: Optional[int] = None  # pid


def _run_dir(run_id: str) -> str:
    return os.path.join(_runs_root(), run_id)


def _write_meta(run_id: str, meta: Dict[str, Any]) -> None:
    # atomic: concurrent status pollers must never read truncated JSON
    path = os.path.join(_run_dir(run_id), "meta.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, path)


def _read_meta(run_id: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(_run_dir(run_id), "meta.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def fedml_login(api_key: Optional[str] = None) -> int:
    """Local-first stand-in for platform login: records the profile in the
    run registry so launches are attributed; never talks to a network.
    Returns 0 (success) for API-shape parity with the reference."""
    os.makedirs(_runs_root(), exist_ok=True)
    profile = os.path.join(_runs_root(), "profile.json")
    with open(profile, "w") as f:
        json.dump({"api_key_set": bool(api_key), "ts": time.time()}, f)
    return 0


def launch_job(yaml_file: str, api_key: Optional[str] = None,
               detach: bool = True, extra_env: Optional[Dict[str, str]] = None
               ) -> LaunchResult:
    """Launch a job described by ``yaml_file`` as a local subprocess."""
    yaml_file = os.path.abspath(os.path.expanduser(yaml_file))
    if not os.path.exists(yaml_file):
        return LaunchResult("", -1, f"no such job yaml: {yaml_file}")
    with open(yaml_file) as f:
        spec = yaml.safe_load(f) or {}

    run_id = time.strftime("%Y%m%d-%H%M%S-") + uuid.uuid4().hex[:6]
    rdir = _run_dir(run_id)
    os.makedirs(rdir, exist_ok=True)

    # resource matching (reference scheduler_matcher.py consulted at
    # launch): a `computing: {device_slots: N}` section claims capacity
    # in the sqlite allocation store; no fit = the launch fails loudly
    device_id = None
    slots = int((spec.get("computing") or {}).get("device_slots", 0) or 0)
    if slots > 0:
        from .scheduler import default_db
        device_id = default_db().allocate(run_id, slots)
        if device_id is None:
            _write_meta(run_id, {
                "run_id": run_id, "yaml": yaml_file,
                "status": STATUS_FAILED,
                "error": f"no device with {slots} free slots"})
            return LaunchResult(
                run_id, -1, f"no device with {slots} free slots")

    if "job" in spec:  # task job: shell command in a workspace
        workspace = os.path.expanduser(str(spec.get("workspace", ".")))
        if not os.path.isabs(workspace):
            workspace = os.path.join(os.path.dirname(yaml_file), workspace)
        # record the exit code for run_status even when detached; the user
        # command runs in a subshell so its `exit` cannot skip the record
        wrapped = (f'( {spec["job"]} ); rc=$?; '
                   f'echo $rc > {shlex.quote(rdir)}/exit_code; exit $rc')
        cmd = ["bash", "-c", wrapped]
        kind = "task"
    else:  # training config: run through the CLI trainer
        workspace = os.path.dirname(yaml_file)
        inner = (f"{shlex.quote(sys.executable)} -m fedml_tpu.cli train "
                 f"--cf {shlex.quote(yaml_file)}")
        wrapped = (f'( {inner} ); rc=$?; echo $rc > {shlex.quote(rdir)}'
                   f'/exit_code; exit $rc')
        cmd = ["bash", "-c", wrapped]
        kind = "train"

    env = dict(os.environ)
    if kind == "train":
        # the subprocess must find this package even when it is run from a
        # source tree rather than installed
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
    env["FEDML_TPU_RUN_ID"] = run_id
    env.update(extra_env or {})
    log_path = os.path.join(rdir, "job.log")
    try:
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(cmd, cwd=workspace, env=env,
                                    stdout=log_f,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True)
    except OSError as e:  # e.g. workspace directory does not exist
        _release_allocation(run_id)
        _write_meta(run_id, {
            "run_id": run_id, "kind": kind, "yaml": yaml_file,
            "workspace": workspace, "pid": -1, "started": time.time(),
            "status": STATUS_FAILED, "error": str(e),
        })
        return LaunchResult(run_id, -1, f"could not start job: {e}")
    _write_meta(run_id, {
        "run_id": run_id, "kind": kind, "yaml": yaml_file,
        "cmd": " ".join(shlex.quote(c) for c in cmd),
        "workspace": workspace, "pid": proc.pid,
        "started": time.time(), "status": STATUS_RUNNING,
        **({"device_id": device_id, "device_slots": slots}
           if device_id else {}),
    })
    # remote observability: ship this run's log to the configured log
    # server (reference mlops_runtime_log_daemon.py:333 tails + uploads)
    log_url = os.environ.get("FEDML_TPU_LOG_SERVER_URL")
    shipper = None
    if log_url:
        from ..core.mlops.log_daemon import start_log_shipper
        shipper = start_log_shipper(log_path, log_url, run_id=run_id)
    if not detach:
        rc = proc.wait()
        _finalize(run_id, rc)
        if shipper is not None:  # final flush, don't leak the poll thread
            shipper.stop()
        return LaunchResult(run_id, 0 if rc == 0 else -1,
                            f"exit code {rc}", proc.pid)
    return LaunchResult(run_id, 0, "launched", proc.pid)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _release_allocation(run_id: str) -> None:
    """Free the run's resource claim; cheap no-op when it holds none."""
    try:
        from .scheduler import default_db
        default_db().release(run_id)
    except Exception:  # the allocation store must never break run paths
        logger.exception("could not release allocation for %s", run_id)


def _finalize(run_id: str, rc: Optional[int]) -> None:
    meta = _read_meta(run_id) or {}
    meta["status"] = STATUS_FINISHED if rc == 0 else STATUS_FAILED
    meta["exit_code"] = rc
    meta["ended"] = time.time()
    _write_meta(run_id, meta)
    _release_allocation(run_id)


def _read_exit_code(run_id: str) -> Optional[int]:
    """The run's recorded exit code, or None when absent/unreadable. A
    recorded code is authoritative even if the pid has been recycled by
    an unrelated process (reboot/wraparound)."""
    rc_path = os.path.join(_run_dir(run_id), "exit_code")
    try:
        return int(open(rc_path).read().strip())
    except (OSError, ValueError):
        return None


def run_status(run_id: str) -> Optional[str]:
    """Current status; polls the pid for liveness and finalizes on exit."""
    meta = _read_meta(run_id)
    if meta is None:
        return None
    if meta.get("status") == STATUS_RUNNING:
        rc = _read_exit_code(run_id)
        if rc is None:
            pid = int(meta.get("pid", -1))
            if pid > 0 and _pid_alive(pid):
                return STATUS_RUNNING
            rc = -1  # process gone without recording a code
        _finalize(run_id, rc)
        meta = _read_meta(run_id)
    return meta.get("status")


def run_logs(run_id: str, tail: Optional[int] = None) -> List[str]:
    path = os.path.join(_run_dir(run_id), "job.log")
    if not os.path.exists(path):
        return []
    with open(path, errors="replace") as f:
        lines = f.read().splitlines()
    return lines[-tail:] if tail else lines


def run_stop(run_id: str) -> bool:
    # resolve liveness first so stopping an already-finished run does not
    # clobber its FINISHED/FAILED record
    status = run_status(run_id)
    if status is None:
        return False
    if status != STATUS_RUNNING:
        return True
    meta = _read_meta(run_id)
    pid = int(meta.get("pid", -1))
    if pid > 0 and _pid_alive(pid):
        try:  # kill the whole session (job may have children)
            os.killpg(os.getpgid(pid), signal.SIGTERM)
        except OSError:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
    meta["status"] = STATUS_KILLED
    meta["ended"] = time.time()
    _write_meta(run_id, meta)
    _release_allocation(run_id)
    return True


def run_wait(run_id: str, timeout_s: Optional[float] = None,
             poll_s: float = 0.5, kill_on_timeout: bool = True
             ) -> Optional[str]:
    """Job-monitor primitive (reference ``comm_utils/job_monitor.py`` role):
    block until the run reaches a terminal status; on timeout optionally
    stop the run. Returns the final status."""
    deadline = (time.time() + timeout_s) if timeout_s is not None else None
    while True:
        status = run_status(run_id)
        if status not in (STATUS_RUNNING,):
            return status
        if deadline is not None and time.time() > deadline:
            if kill_on_timeout:
                run_stop(run_id)
            return run_status(run_id)
        time.sleep(poll_s)


def run_list() -> List[Dict[str, Any]]:
    root = _runs_root()
    if not os.path.isdir(root):
        return []
    out = []
    for rid in sorted(os.listdir(root)):
        meta = _read_meta(rid)
        if meta:
            meta["status"] = run_status(rid)
            out.append(meta)
    return out


def build(source_dir: str, dest_zip: Optional[str] = None,
          config_yaml: Optional[str] = None) -> str:
    """Package a job workspace into a distributable zip (reference
    ``fedml build``): the workspace tree + the config under ``conf/``."""
    source_dir = os.path.abspath(os.path.expanduser(source_dir))
    dest_zip = dest_zip or (os.path.basename(source_dir.rstrip("/"))
                            + "_job.zip")
    dest_abs = os.path.abspath(dest_zip)
    with zipfile.ZipFile(dest_zip, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(source_dir):
            for fn in files:
                full = os.path.join(root, fn)
                if os.path.abspath(full) == dest_abs:
                    continue  # never zip the archive into itself
                zf.write(full, os.path.relpath(full, source_dir))
        if config_yaml:
            zf.write(os.path.abspath(os.path.expanduser(config_yaml)),
                     os.path.join("conf", os.path.basename(config_yaml)))
    return os.path.abspath(dest_zip)


def model_serve(params_path: str, model: str, output_dim: int,
                port: int = 0, dataset: str = "", block: bool = False):
    """Serve a saved model artifact over HTTP; returns the (started) runner.
    The CLI's ``serve`` command and the reference's model-deploy flow both
    funnel here."""
    from ..arguments import Arguments
    from ..serving import CheckpointPredictor, FedMLInferenceRunner

    args = Arguments(model=model, dataset=dataset or "synthetic_mnist")
    predictor = CheckpointPredictor.from_files(args, params_path, output_dim)
    runner = FedMLInferenceRunner(predictor, port=port)
    if block:
        runner.run()
    else:
        runner.start()
    return runner
