"""Centralized (non-FL) baseline trainer.

Parity target: reference ``centralized/centralized_trainer.py`` (plain
trainer over the pooled dataset, used to baseline FL results). TPU-native:
pools every client's real samples and runs the same jitted local-SGD scan
the FL engines use — so "FL vs centralized" comparisons differ only in the
protocol, not the training code.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.algframe.client_trainer import make_trainer_spec
from ..core.algframe.local_training import evaluate, run_local_sgd
from ..core.algframe.types import ClientData, TrainHyper

logger = logging.getLogger(__name__)


class CentralizedTrainer:
    """Train one model on the union of all clients' data."""

    def __init__(self, args, fed_dataset, bundle, spec=None):
        self.args = args
        self.fed = fed_dataset
        self.bundle = bundle
        self.spec = spec or make_trainer_spec(fed_dataset, bundle)
        # pool real samples across clients into one padded batch stream
        x = np.asarray(fed_dataset.train.x)
        y = np.asarray(fed_dataset.train.y)
        m = np.asarray(fed_dataset.train.mask)
        bs = x.shape[2]
        real = m.reshape(-1) > 0
        flat_x = x.reshape((-1,) + x.shape[3:])[real]
        flat_y = y.reshape((-1,) + y.shape[3:])[real]
        n = len(flat_x)
        nb = max(1, -(-n // bs))
        pad = nb * bs - n
        mask = np.concatenate([np.ones(n, np.float32),
                               np.zeros(pad, np.float32)])
        if pad:
            flat_x = np.concatenate(
                [flat_x, np.zeros((pad,) + flat_x.shape[1:], flat_x.dtype)])
            flat_y = np.concatenate(
                [flat_y, np.zeros((pad,) + flat_y.shape[1:], flat_y.dtype)])
        self.data = ClientData(
            x=jnp.asarray(flat_x.reshape((nb, bs) + flat_x.shape[1:])),
            y=jnp.asarray(flat_y.reshape((nb, bs) + flat_y.shape[1:])),
            mask=jnp.asarray(mask.reshape(nb, bs)),
            num_samples=jnp.float32(n))
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        init_rng, self.rng = jax.random.split(rng)
        self.params = bundle.init(init_rng, fed_dataset.train.x[0, 0])
        import optax
        lr = float(getattr(args, "learning_rate", 0.03))
        momentum = float(getattr(args, "momentum", 0.0) or 0.0)
        self._opt = (optax.sgd(lr, momentum=momentum) if momentum
                     else optax.sgd(lr))

        def epoch(params, opt_state, rng):
            hyper = TrainHyper(learning_rate=jnp.float32(lr), epochs=1)
            return run_local_sgd(self.spec, self._opt, params, self.data,
                                 rng, hyper, init_opt_state=opt_state)

        self._epoch = jax.jit(epoch)
        self._evaluate = jax.jit(
            lambda p: evaluate(self.spec, p, self.fed.test["x"],
                               self.fed.test["y"], self.fed.test["mask"]))
        self.history = []

    def run(self, comm_round=None) -> Dict[str, Any]:
        epochs = int(comm_round if comm_round is not None
                     else getattr(self.args, "epochs", 1)
                     * getattr(self.args, "comm_round", 1))
        t0 = time.time()
        opt_state = self._opt.init(self.params)
        for e in range(epochs):
            key = jax.random.fold_in(self.rng, e)
            self.params, opt_state, metrics = self._epoch(
                self.params, opt_state, key)
            cnt = max(float(metrics["count"]), 1.0)
            rec = {"epoch": e,
                   "train_loss": float(metrics["loss_sum"]) / cnt,
                   "train_acc": float(metrics["correct"]) / cnt}
            freq = int(getattr(self.args, "frequency_of_the_test", 5) or 5)
            if e % freq == 0 or e == epochs - 1:
                stats = self._evaluate(self.params)
                nte = max(float(stats["count"]), 1.0)
                rec["test_acc"] = float(stats["correct"]) / nte
                logger.info("centralized epoch %d: acc=%.4f", e,
                            rec["test_acc"])
            self.history.append(rec)
        last = next((h for h in reversed(self.history) if "test_acc" in h),
                    {})
        return {"params": self.params, "history": self.history,
                "final_test_acc": last.get("test_acc"),
                "wall_time_s": time.time() - t0, "rounds": epochs}
