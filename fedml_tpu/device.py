"""Device discovery (reference ``fedml.device.get_device`` →
``ml/engine/ml_engine_adapter.py:118,198``). On TPU the "device" handed to
user code is the mesh itself; single-device callers get ``jax.devices()[0]``.
"""

from __future__ import annotations

import jax

from .core.mesh import build_mesh


def get_device(args=None):
    if args is not None and getattr(args, "mesh_shape", None):
        return build_mesh(args.mesh_shape)
    return jax.devices()[0]


def device_count() -> int:
    return jax.device_count()
