"""Compat namespace mirroring the reference's ``fedml.ml`` layout.

The reference splits the ML layer into ``ml/aggregator`` (FedMLAggOperator),
``ml/trainer`` (concrete local trainers), and ``ml/engine`` (multi-engine
adapter). In this framework those roles live in first-class modules — the
agg operator is :func:`fedml_tpu.core.collectives.tree_weighted_average`,
trainers are the pure-function specs of
:mod:`fedml_tpu.core.algframe.client_trainer`, and there is exactly one
engine (JAX/XLA) by design, so the adapter layer is gone. This package
re-exports them under the reference's names so ``fedml.ml``-style imports
port mechanically.
"""

from ..core.algframe.client_trainer import (  # noqa: F401
    ClassificationTrainer, MultiLabelTrainer, RegressionTrainer,
    SequenceTrainer, TrainerSpec, make_trainer_spec)
from ..core.collectives import tree_weighted_average  # noqa: F401


class FedMLAggOperator:
    """Reference ``ml/aggregator/agg_operator.py:8`` shape: ``agg(args,
    raw_grad_list)`` with (n_k, params) pairs -> weighted average."""

    @staticmethod
    def agg(args, raw_grad_list):
        import jax.numpy as jnp

        from ..core.collectives import stack_trees
        weights = jnp.asarray([float(n) for n, _ in raw_grad_list],
                              jnp.float32)
        stacked = stack_trees([p for _, p in raw_grad_list])
        return tree_weighted_average(stacked, weights)
