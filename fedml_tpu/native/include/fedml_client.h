/* fedml_client.h — C API of the native device SDK.
 *
 * Mirrors the reference's on-device surface so a real app can bind it the
 * way the Android app binds JNI:
 *
 *   reference JNI (JniFedMLClientManager.cpp)        this C ABI
 *   ------------------------------------------       -------------------
 *   NativeFedMLClientManager_create          :15  -> fedml_client_create
 *   NativeFedMLClientManager_release         :26  -> fedml_client_release
 *   NativeFedMLClientManager_init            :43  -> fedml_client_init
 *                                                    (+ _set_callbacks)
 *   NativeFedMLClientManager_train           :103 -> fedml_client_train
 *   NativeFedMLClientManager_getEpochAndLoss :116 -> fedml_client_get_epoch_and_loss
 *   NativeFedMLClientManager_stopTraining    :129 -> fedml_client_stop_training
 *   (MNN serialized-model handling)               -> artifact_* family
 *   (on-device test/eval)                         -> fedml_client_evaluate
 *
 * Model artifacts are the framework's msgpack format ("FMTPU1\n" magic;
 * serving.save_model/load_model) — the device consumes the server's
 * global model and produces an update the server loads with no Python on
 * the device. Implementation: ../mobilenn.cpp (link the shared object the
 * package builds, libmobilenn-<hash>.so).
 */

#ifndef FEDML_TPU_NATIVE_FEDML_CLIENT_H
#define FEDML_TPU_NATIVE_FEDML_CLIENT_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- client manager session (FedMLClientManager analogue) ----------- */

typedef void (*fedml_progress_cb)(float pct);
typedef void (*fedml_loss_cb)(int32_t epoch, float loss);

/* Opaque session handle. */
void* fedml_client_create(void);
void  fedml_client_release(void* client);

/* Load the global model artifact and this device's CSV data shard
 * (label in the last column). Returns 0, or <0 on artifact/data errors. */
int32_t fedml_client_init(void* client, const char* model_path,
                          const char* data_path, int32_t batch_size,
                          float learning_rate, int32_t epoch_num,
                          uint64_t seed);

void fedml_client_set_callbacks(void* client, fedml_progress_cb progress,
                                fedml_loss_cb loss);

/* Run the local epochs; honors fedml_client_stop_training between
 * epochs; returns final-epoch mean loss (NaN on error). */
float fedml_client_train(void* client);

/* Most recent (epoch, loss) pair — the getEpochAndLoss analogue. */
int32_t fedml_client_get_epoch_and_loss(void* client, int32_t* epoch,
                                        float* loss);

int32_t fedml_client_stop_training(void* client);

/* On-device evaluation (accuracy in [0,1]) of the current params on the
 * loaded shard; -1 on error. */
float fedml_client_evaluate(void* client);

/* Persist the trained params as a server-loadable artifact. */
int32_t fedml_client_save_model(void* client, const char* path);

/* ---- model artifact access (serialized-model handling) -------------- */

void*   artifact_open(const char* path);            /* NULL on error   */
int32_t artifact_count(void* artifact);
int32_t artifact_key(void* artifact, int32_t i, char* out, int32_t cap);
int64_t artifact_elems(void* artifact, const char* key);  /* -1 missing */
int32_t artifact_shape(void* artifact, const char* key, int32_t* dims,
                       int32_t cap);
int64_t artifact_read_f32(void* artifact, const char* key, float* out,
                          int64_t cap);
void    artifact_close(void* artifact);
int32_t artifact_save(const char* path, const char** keys,
                      const float** data, const int32_t* ndims,
                      const int32_t* shapes, int32_t n_leaves);

/* ---- raw trainers / masking / data (see mobilenn.cpp) --------------- */

float train_linear_sgd(float* W, float* b, const float* x,
                       const int32_t* y, int32_t n, int32_t d, int32_t k,
                       int32_t epochs, int32_t batch, float lr,
                       uint64_t seed);
float eval_linear(const float* W, const float* b, const float* x,
                  const int32_t* y, int32_t n, int32_t d, int32_t k);
void gen_mask(uint32_t* out, int64_t n, uint64_t seed);
void mask_vector(uint32_t* out, const float* v, int64_t n, float scale,
                 uint64_t seed);
void unmask_vector(float* out, const uint32_t* masked, int64_t n,
                   float scale, uint64_t seed);
int32_t lsa_mask_encode(uint32_t* out, const uint32_t* z, int32_t d,
                        int32_t n_clients, int32_t privacy_t,
                        int32_t split_t, uint64_t seed);
int32_t csv_probe(const char* path, int32_t* rows, int32_t* cols);
int32_t csv_read(const char* path, float* x, int32_t* y, int32_t rows,
                 int32_t cols);
int32_t mobilenn_abi_version(void);

#ifdef __cplusplus
}
#endif

#endif /* FEDML_TPU_NATIVE_FEDML_CLIENT_H */
