"""Native device core bindings (MobileNN analogue).

The reference ships a C++ on-device SDK (``android/fedmlsdk/MobileNN``:
``FedMLBaseTrainer`` + MNN/torch engines + native LightSecAgg,
``src/security/LightSecAgg.cpp``) bridged to the app through JNI. Here the
native core is :mod:`mobilenn.cpp` (softmax-regression SGD + GF(2^31-1)
masking) compiled on demand with ``g++`` and bridged through ``ctypes`` —
the JNI analogue for a Python host. The simulated device client
(:mod:`fedml_tpu.cross_device.client`) selects it with
``device_engine: native``.

``available()`` is False when no toolchain/binary exists; callers fall back
to the JAX engine.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "mobilenn.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

PRIME = 2147483647  # 2^31 - 1, matches core/mpc/field_ops.py


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    root = os.path.expanduser(os.environ.get(
        "FEDML_TPU_NATIVE_DIR", "~/.cache/fedml_tpu/native"))
    return os.path.join(root, f"libmobilenn-{digest}.so")


def _build() -> Optional[str]:
    so = _cache_path()
    if os.path.exists(so):
        return so
    os.makedirs(os.path.dirname(so), exist_ok=True)
    tmp = so + ".tmp.so"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        logger.warning("native build failed (%s): %s", e,
                       detail.decode(errors="replace")[:500])
        return None
    os.replace(tmp, so)
    logger.info("built native core -> %s", so)
    return so


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        so = _build()
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.train_linear_sgd.restype = ctypes.c_float
        lib.train_linear_sgd.argtypes = [
            f32p, f32p, f32p, i32p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_float,
            ctypes.c_uint64]
        lib.eval_linear.restype = ctypes.c_float
        lib.eval_linear.argtypes = [f32p, f32p, f32p, i32p, ctypes.c_int32,
                                    ctypes.c_int32, ctypes.c_int32]
        lib.gen_mask.restype = None
        lib.gen_mask.argtypes = [u32p, ctypes.c_int64, ctypes.c_uint64]
        lib.mask_vector.restype = None
        lib.mask_vector.argtypes = [u32p, f32p, ctypes.c_int64,
                                    ctypes.c_float, ctypes.c_uint64]
        lib.unmask_vector.restype = None
        lib.unmask_vector.argtypes = [f32p, u32p, ctypes.c_int64,
                                      ctypes.c_float, ctypes.c_uint64]
        lib.mobilenn_abi_version.restype = ctypes.c_int32
        lib.mobilenn_abi_version.argtypes = []
        assert lib.mobilenn_abi_version() == 1
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


class NativeLinearTrainer:
    """Device-side trainer over the native core. Param layout matches the
    flax ``LogisticRegression`` bundle ({'Dense_0': {'kernel','bias'}}), so
    the server aggregates native and JAX device updates interchangeably."""

    def __init__(self):
        self.lib = _load()
        if self.lib is None:
            raise RuntimeError("native core unavailable (no g++?)")

    def train(self, params: Dict, x: np.ndarray, y: np.ndarray,
              epochs: int, batch_size: int, lr: float, seed: int):
        dense = params["Dense_0"]
        W = np.ascontiguousarray(np.asarray(dense["kernel"], np.float32))
        b = np.ascontiguousarray(np.asarray(dense["bias"], np.float32))
        x2 = np.ascontiguousarray(x.reshape(len(x), -1).astype(np.float32))
        y2 = np.ascontiguousarray(np.asarray(y, np.int32))
        d, k = W.shape
        loss = self.lib.train_linear_sgd(
            _f32p(W), _f32p(b), _f32p(x2), _i32p(y2),
            np.int32(len(x2)), np.int32(d), np.int32(k),
            np.int32(epochs), np.int32(batch_size), np.float32(lr),
            np.uint64(seed))
        return {"Dense_0": {"kernel": W, "bias": b}}, float(loss)

    def evaluate(self, params: Dict, x: np.ndarray, y: np.ndarray) -> float:
        dense = params["Dense_0"]
        W = np.ascontiguousarray(np.asarray(dense["kernel"], np.float32))
        b = np.ascontiguousarray(np.asarray(dense["bias"], np.float32))
        x2 = np.ascontiguousarray(x.reshape(len(x), -1).astype(np.float32))
        y2 = np.ascontiguousarray(np.asarray(y, np.int32))
        d, k = W.shape
        return float(self.lib.eval_linear(
            _f32p(W), _f32p(b), _f32p(x2), _i32p(y2),
            np.int32(len(x2)), np.int32(d), np.int32(k)))


def gen_mask(n: int, seed: int) -> np.ndarray:
    lib = _load()
    out = np.empty(n, np.uint32)
    lib.gen_mask(_u32p(out), np.int64(n), np.uint64(seed))
    return out


def mask_vector(v: np.ndarray, scale: float, seed: int) -> np.ndarray:
    lib = _load()
    v = np.ascontiguousarray(v, np.float32)
    out = np.empty(v.size, np.uint32)
    lib.mask_vector(_u32p(out), _f32p(v), np.int64(v.size),
                    np.float32(scale), np.uint64(seed))
    return out


def unmask_vector(masked: np.ndarray, scale: float, seed: int) -> np.ndarray:
    lib = _load()
    masked = np.ascontiguousarray(masked, np.uint32)
    out = np.empty(masked.size, np.float32)
    lib.unmask_vector(_f32p(out), _u32p(masked), np.int64(masked.size),
                      np.float32(scale), np.uint64(seed))
    return out
