"""Native device core bindings (MobileNN analogue).

The reference ships a C++ on-device SDK (``android/fedmlsdk/MobileNN``:
``FedMLBaseTrainer`` + MNN/torch engines + native LightSecAgg,
``src/security/LightSecAgg.cpp``) bridged to the app through JNI. Here the
native core is :mod:`mobilenn.cpp` (softmax-regression SGD + GF(2^31-1)
masking) compiled on demand with ``g++`` and bridged through ``ctypes`` —
the JNI analogue for a Python host. The simulated device client
(:mod:`fedml_tpu.cross_device.client`) selects it with
``device_engine: native``.

``available()`` is False when no toolchain/binary exists; callers fall back
to the JAX engine.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "mobilenn.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

PRIME = 2147483647  # 2^31 - 1, matches core/mpc/field_ops.py

# callback signatures of the C ABI (include/fedml_client.h)
PROGRESS_CB = ctypes.CFUNCTYPE(None, ctypes.c_float)
LOSS_CB = ctypes.CFUNCTYPE(None, ctypes.c_int32, ctypes.c_float)


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    root = os.path.expanduser(os.environ.get(
        "FEDML_TPU_NATIVE_DIR", "~/.cache/fedml_tpu/native"))
    return os.path.join(root, f"libmobilenn-{digest}.so")


def _build() -> Optional[str]:
    so = _cache_path()
    if os.path.exists(so):
        return so
    os.makedirs(os.path.dirname(so), exist_ok=True)
    tmp = so + ".tmp.so"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        logger.warning("native build failed (%s): %s", e,
                       detail.decode(errors="replace")[:500])
        return None
    os.replace(tmp, so)
    logger.info("built native core -> %s", so)
    return so


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        so = _build()
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.train_linear_sgd.restype = ctypes.c_float
        lib.train_linear_sgd.argtypes = [
            f32p, f32p, f32p, i32p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_float,
            ctypes.c_uint64]
        lib.eval_linear.restype = ctypes.c_float
        lib.eval_linear.argtypes = [f32p, f32p, f32p, i32p, ctypes.c_int32,
                                    ctypes.c_int32, ctypes.c_int32]
        lib.gen_mask.restype = None
        lib.gen_mask.argtypes = [u32p, ctypes.c_int64, ctypes.c_uint64]
        lib.mask_vector.restype = None
        lib.mask_vector.argtypes = [u32p, f32p, ctypes.c_int64,
                                    ctypes.c_float, ctypes.c_uint64]
        lib.unmask_vector.restype = None
        lib.unmask_vector.argtypes = [f32p, u32p, ctypes.c_int64,
                                      ctypes.c_float, ctypes.c_uint64]
        lib.train_cnn_sgd.restype = ctypes.c_float
        lib.train_cnn_sgd.argtypes = (
            [f32p] * 6 + [f32p, i32p] + [ctypes.c_int32] * 9
            + [ctypes.c_float, ctypes.c_uint64])
        lib.eval_cnn.restype = ctypes.c_float
        lib.eval_cnn.argtypes = ([f32p] * 6 + [f32p, i32p]
                                 + [ctypes.c_int32] * 7)
        lib.lsa_mask_encode.restype = ctypes.c_int32
        lib.lsa_mask_encode.argtypes = [u32p, u32p, ctypes.c_int32,
                                        ctypes.c_int32, ctypes.c_int32,
                                        ctypes.c_int32, ctypes.c_uint64]
        lib.csv_probe.restype = ctypes.c_int32
        lib.csv_probe.argtypes = [ctypes.c_char_p, i32p, i32p]
        lib.csv_read.restype = ctypes.c_int32
        lib.csv_read.argtypes = [ctypes.c_char_p, f32p, i32p,
                                 ctypes.c_int32, ctypes.c_int32]
        # model artifact codec (serialized-model handling)
        lib.artifact_open.restype = ctypes.c_void_p
        lib.artifact_open.argtypes = [ctypes.c_char_p]
        lib.artifact_count.restype = ctypes.c_int32
        lib.artifact_count.argtypes = [ctypes.c_void_p]
        lib.artifact_key.restype = ctypes.c_int32
        lib.artifact_key.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                     ctypes.c_char_p, ctypes.c_int32]
        lib.artifact_elems.restype = ctypes.c_int64
        lib.artifact_elems.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.artifact_shape.restype = ctypes.c_int32
        lib.artifact_shape.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       i32p, ctypes.c_int32]
        lib.artifact_read_f32.restype = ctypes.c_int64
        lib.artifact_read_f32.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          f32p, ctypes.c_int64]
        lib.artifact_close.restype = None
        lib.artifact_close.argtypes = [ctypes.c_void_p]
        lib.artifact_save.restype = ctypes.c_int32
        lib.artifact_save.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(f32p), i32p, i32p, ctypes.c_int32]
        # client manager session (FedMLClientManager analogue)
        lib.fedml_client_create.restype = ctypes.c_void_p
        lib.fedml_client_create.argtypes = []
        lib.fedml_client_release.restype = None
        lib.fedml_client_release.argtypes = [ctypes.c_void_p]
        lib.fedml_client_init.restype = ctypes.c_int32
        lib.fedml_client_init.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int32, ctypes.c_float, ctypes.c_int32,
            ctypes.c_uint64]
        lib.fedml_client_set_callbacks.restype = None
        lib.fedml_client_set_callbacks.argtypes = [ctypes.c_void_p,
                                                   PROGRESS_CB, LOSS_CB]
        lib.fedml_client_train.restype = ctypes.c_float
        lib.fedml_client_train.argtypes = [ctypes.c_void_p]
        lib.fedml_client_get_epoch_and_loss.restype = ctypes.c_int32
        lib.fedml_client_get_epoch_and_loss.argtypes = [
            ctypes.c_void_p, i32p, f32p]
        lib.fedml_client_stop_training.restype = ctypes.c_int32
        lib.fedml_client_stop_training.argtypes = [ctypes.c_void_p]
        lib.fedml_client_evaluate.restype = ctypes.c_float
        lib.fedml_client_evaluate.argtypes = [ctypes.c_void_p]
        lib.fedml_client_save_model.restype = ctypes.c_int32
        lib.fedml_client_save_model.argtypes = [ctypes.c_void_p,
                                                ctypes.c_char_p]
        lib.mobilenn_abi_version.restype = ctypes.c_int32
        lib.mobilenn_abi_version.argtypes = []
        assert lib.mobilenn_abi_version() == 3
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


class NativeLinearTrainer:
    """Device-side trainer over the native core. Param layout matches the
    flax ``LogisticRegression`` bundle ({'Dense_0': {'kernel','bias'}}), so
    the server aggregates native and JAX device updates interchangeably."""

    def __init__(self):
        self.lib = _load()
        if self.lib is None:
            raise RuntimeError("native core unavailable (no g++?)")

    def train(self, params: Dict, x: np.ndarray, y: np.ndarray,
              epochs: int, batch_size: int, lr: float, seed: int):
        dense = params["Dense_0"]
        W = np.ascontiguousarray(np.asarray(dense["kernel"], np.float32))
        b = np.ascontiguousarray(np.asarray(dense["bias"], np.float32))
        x2 = np.ascontiguousarray(x.reshape(len(x), -1).astype(np.float32))
        y2 = np.ascontiguousarray(np.asarray(y, np.int32))
        d, k = W.shape
        loss = self.lib.train_linear_sgd(
            _f32p(W), _f32p(b), _f32p(x2), _i32p(y2),
            np.int32(len(x2)), np.int32(d), np.int32(k),
            np.int32(epochs), np.int32(batch_size), np.float32(lr),
            np.uint64(seed))
        return {"Dense_0": {"kernel": W, "bias": b}}, float(loss)

    def evaluate(self, params: Dict, x: np.ndarray, y: np.ndarray) -> float:
        dense = params["Dense_0"]
        W = np.ascontiguousarray(np.asarray(dense["kernel"], np.float32))
        b = np.ascontiguousarray(np.asarray(dense["bias"], np.float32))
        x2 = np.ascontiguousarray(x.reshape(len(x), -1).astype(np.float32))
        y2 = np.ascontiguousarray(np.asarray(y, np.int32))
        d, k = W.shape
        return float(self.lib.eval_linear(
            _f32p(W), _f32p(b), _f32p(x2), _i32p(y2),
            np.int32(len(x2)), np.int32(d), np.int32(k)))


def gen_mask(n: int, seed: int) -> np.ndarray:
    lib = _load()
    out = np.empty(n, np.uint32)
    lib.gen_mask(_u32p(out), np.int64(n), np.uint64(seed))
    return out


def mask_vector(v: np.ndarray, scale: float, seed: int) -> np.ndarray:
    lib = _load()
    v = np.ascontiguousarray(v, np.float32)
    out = np.empty(v.size, np.uint32)
    lib.mask_vector(_u32p(out), _f32p(v), np.int64(v.size),
                    np.float32(scale), np.uint64(seed))
    return out


def unmask_vector(masked: np.ndarray, scale: float, seed: int) -> np.ndarray:
    lib = _load()
    masked = np.ascontiguousarray(masked, np.uint32)
    out = np.empty(masked.size, np.float32)
    lib.unmask_vector(_f32p(out), _u32p(masked), np.int64(masked.size),
                      np.float32(scale), np.uint64(seed))
    return out


class NativeCNNTrainer:
    """Device-side CNN trainer over the native core — the MNN-LeNet-engine
    analogue (reference ``FedMLMNNTrainer.cpp``). Param tree matches the
    flax ``DeviceCNN`` bundle ({'Conv_0','Conv_1','Dense_0'}), so the
    server aggregates native-CNN and JAX-CNN device updates
    interchangeably."""

    def __init__(self):
        self.lib = _load()
        if self.lib is None:
            raise RuntimeError("native core unavailable (no g++?)")

    @staticmethod
    def _unpack(params: Dict):
        k1 = np.ascontiguousarray(
            np.asarray(params["Conv_0"]["kernel"], np.float32))
        b1 = np.ascontiguousarray(
            np.asarray(params["Conv_0"]["bias"], np.float32))
        k2 = np.ascontiguousarray(
            np.asarray(params["Conv_1"]["kernel"], np.float32))
        b2 = np.ascontiguousarray(
            np.asarray(params["Conv_1"]["bias"], np.float32))
        wd = np.ascontiguousarray(
            np.asarray(params["Dense_0"]["kernel"], np.float32))
        bd = np.ascontiguousarray(
            np.asarray(params["Dense_0"]["bias"], np.float32))
        return k1, b1, k2, b2, wd, bd

    @staticmethod
    def _image(x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        if x.ndim == 2:  # flat -> square single-channel (DeviceCNN parity)
            side = int(round(x.shape[-1] ** 0.5))
            x = x.reshape(len(x), side, side, 1)
        return np.ascontiguousarray(x)

    def train(self, params: Dict, x: np.ndarray, y: np.ndarray,
              epochs: int, batch_size: int, lr: float, seed: int):
        k1, b1, k2, b2, wd, bd = self._unpack(params)
        x4 = self._image(x)
        y2 = np.ascontiguousarray(np.asarray(y, np.int32))
        n, H, W, cin = x4.shape
        c1, c2, k = k1.shape[-1], k2.shape[-1], bd.shape[0]
        loss = self.lib.train_cnn_sgd(
            _f32p(k1), _f32p(b1), _f32p(k2), _f32p(b2), _f32p(wd),
            _f32p(bd), _f32p(x4), _i32p(y2),
            np.int32(n), np.int32(H), np.int32(W), np.int32(cin),
            np.int32(c1), np.int32(c2), np.int32(k), np.int32(epochs),
            np.int32(batch_size), np.float32(lr), np.uint64(seed))
        return ({"Conv_0": {"kernel": k1, "bias": b1},
                 "Conv_1": {"kernel": k2, "bias": b2},
                 "Dense_0": {"kernel": wd, "bias": bd}}, float(loss))

    def evaluate(self, params: Dict, x: np.ndarray, y: np.ndarray) -> float:
        k1, b1, k2, b2, wd, bd = self._unpack(params)
        x4 = self._image(x)
        y2 = np.ascontiguousarray(np.asarray(y, np.int32))
        n, H, W, cin = x4.shape
        return float(self.lib.eval_cnn(
            _f32p(k1), _f32p(b1), _f32p(k2), _f32p(b2), _f32p(wd),
            _f32p(bd), _f32p(x4), _i32p(y2),
            np.int32(n), np.int32(H), np.int32(W), np.int32(cin),
            np.int32(k1.shape[-1]), np.int32(k2.shape[-1]),
            np.int32(bd.shape[0])))


def lsa_mask_encode(z: np.ndarray, n_clients: int, privacy_t: int,
                    split_t: int, seed: int) -> np.ndarray:
    """Native LightSecAgg Lagrange encoding of a field mask ``z`` into
    ``n_clients`` coded sub-masks — decodes with the Python
    ``core.mpc.lightsecagg.decode_aggregate_mask`` (same points, same
    field)."""
    lib = _load()
    z = np.ascontiguousarray(z, np.uint32)
    if len(z) % split_t:
        raise ValueError("mask length must divide split_t")
    out = np.empty((n_clients, len(z) // split_t), np.uint32)
    rc = lib.lsa_mask_encode(_u32p(out), _u32p(z), np.int32(len(z)),
                             np.int32(n_clients), np.int32(privacy_t),
                             np.int32(split_t), np.uint64(seed))
    if rc != 0:
        raise ValueError(f"lsa_mask_encode failed (rc={rc})")
    return out


def read_csv(path: str):
    """Native CSV dataset reader (label in the last column); returns
    (x [n, d] float32, y [n] int32)."""
    lib = _load()
    rows = np.zeros(1, np.int32)
    cols = np.zeros(1, np.int32)
    rc = lib.csv_probe(path.encode(), _i32p(rows), _i32p(cols))
    if rc != 0:
        raise OSError(f"csv_probe({path!r}) failed (rc={rc})")
    r, c = int(rows[0]), int(cols[0])
    x = np.empty((r, c - 1), np.float32)
    y = np.empty(r, np.int32)
    rc = lib.csv_read(path.encode(), _f32p(x), _i32p(y), np.int32(r),
                      np.int32(c))
    if rc != 0:
        raise OSError(f"csv_read({path!r}) failed (rc={rc})")
    return x, y


# ---------------------------------------------------------------------------
# model artifact access (serialized-model handling) — the native codec for
# the framework's msgpack artifact format (serving.save_model/load_model)


def load_artifact_native(path: str) -> Dict[str, np.ndarray]:
    """Parse a model artifact with the NATIVE codec (no Python msgpack):
    returns {slash/path: float32 ndarray}. Raises on parse failure."""
    lib = _load()
    h = lib.artifact_open(path.encode())
    if not h:
        raise ValueError(f"{path}: not a parseable fedml_tpu artifact")
    try:
        out: Dict[str, np.ndarray] = {}
        buf = ctypes.create_string_buffer(4096)
        for i in range(lib.artifact_count(h)):
            lib.artifact_key(h, np.int32(i), buf, np.int32(len(buf)))
            key = buf.value.decode()
            dims = np.zeros(16, np.int32)
            nd = lib.artifact_shape(h, key.encode(), _i32p(dims),
                                    np.int32(16))
            shape = tuple(int(d) for d in dims[:nd])
            n = lib.artifact_elems(h, key.encode())
            arr = np.empty(int(n), np.float32)
            got = lib.artifact_read_f32(h, key.encode(), _f32p(arr),
                                        np.int64(n))
            if got != n:
                raise ValueError(f"{path}: short read on {key}")
            out[key] = arr.reshape(shape)
        return out
    finally:
        lib.artifact_close(h)


def save_artifact_native(leaves: Dict[str, np.ndarray], path: str) -> None:
    """Write {slash/path: float32 array} as a nested model artifact,
    byte-compatible with ``serving.load_model``."""
    lib = _load()
    items = sorted(leaves.items())
    keys = (ctypes.c_char_p * len(items))(
        *[k.encode() for k, _ in items])
    arrays = [np.ascontiguousarray(v, np.float32) for _, v in items]
    data = (ctypes.POINTER(ctypes.c_float) * len(items))(
        *[_f32p(a) for a in arrays])
    ndims = np.asarray([a.ndim for a in arrays], np.int32)
    shapes = np.asarray(sum([list(a.shape) for a in arrays], []), np.int32)
    rc = lib.artifact_save(path.encode(), keys, data, _i32p(ndims),
                           _i32p(shapes), np.int32(len(items)))
    if rc != 0:
        raise OSError(f"artifact_save({path!r}) failed (rc={rc})")


class NativeClientManager:
    """The FedMLClientManager analogue over the C ABI
    (``include/fedml_client.h``; reference
    ``MobileNN/includes/FedMLClientManager.h`` +
    ``JniFedMLClientManager.cpp``): init(model artifact, CSV shard) ->
    train -> evaluate/save, with progress/loss callbacks."""

    def __init__(self):
        self.lib = _load()
        if self.lib is None:
            raise RuntimeError("native core unavailable (no g++?)")
        self._h = self.lib.fedml_client_create()
        self._cbs = []  # keep ctypes callbacks alive for the session

    def init(self, model_path: str, data_path: str, batch_size: int = 32,
             learning_rate: float = 0.1, epochs: int = 1,
             seed: int = 0) -> None:
        rc = self.lib.fedml_client_init(
            self._h, model_path.encode(), data_path.encode(),
            np.int32(batch_size), np.float32(learning_rate),
            np.int32(epochs), np.uint64(seed))
        if rc != 0:
            raise RuntimeError(f"fedml_client_init failed (rc={rc})")

    def set_callbacks(self, on_progress=None, on_loss=None) -> None:
        p = PROGRESS_CB(on_progress) if on_progress else PROGRESS_CB()
        l = LOSS_CB(on_loss) if on_loss else LOSS_CB()
        self._cbs = [p, l]  # keep alive: C holds these pointers
        self.lib.fedml_client_set_callbacks(self._h, p, l)

    def train(self) -> float:
        return float(self.lib.fedml_client_train(self._h))

    def get_epoch_and_loss(self):
        e = np.zeros(1, np.int32)
        lo = np.zeros(1, np.float32)
        self.lib.fedml_client_get_epoch_and_loss(self._h, _i32p(e),
                                                 _f32p(lo))
        return int(e[0]), float(lo[0])

    def stop_training(self) -> None:
        self.lib.fedml_client_stop_training(self._h)

    def evaluate(self) -> float:
        return float(self.lib.fedml_client_evaluate(self._h))

    def save_model(self, path: str) -> None:
        rc = self.lib.fedml_client_save_model(self._h, path.encode())
        if rc != 0:
            raise OSError(f"fedml_client_save_model failed (rc={rc})")

    def close(self) -> None:
        if self._h:
            self.lib.fedml_client_release(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
