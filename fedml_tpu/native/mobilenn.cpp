// Native on-device training core — the MobileNN analogue.
//
// Parity target: the reference's C++ device SDK
// (android/fedmlsdk/MobileNN: FedMLBaseTrainer + MNN/torch engine
// implementations, ~2.6k LoC C++) and its native secure-aggregation masking
// (MobileNN/src/security/LightSecAgg.cpp). Devices in that stack train a
// small model locally in native code and exchange *masked* updates.
//
// This is a fresh implementation sized to what a TPU-federated deployment
// actually needs on-device: a softmax-regression SGD trainer (the
// cross-device reference workload is LR/LeNet-class models) and
// finite-field masking over GF(p), p = 2^31 - 1 — the same field the
// Python SecAgg math uses (core/mpc/field_ops.py), so natively-masked
// updates unmask server-side with the existing Python pipeline.
//
// Deterministic by construction: shuffling and mask generation use
// explicit splitmix64 streams seeded by the caller, so device results are
// reproducible across runs and platforms.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

// splitmix64: tiny, high-quality, seedable PRG (public-domain algorithm)
inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

constexpr uint64_t kPrime = 2147483647ULL;  // 2^31 - 1 (Mersenne)

}  // namespace

extern "C" {

// Softmax-regression SGD: logits = x·W + b, cross-entropy loss, plain SGD.
// x: [n, d] row-major, y: [n] labels in [0, k). W: [d, k], b: [k] updated
// in place. Runs `epochs` passes over batches of `batch` with per-epoch
// Fisher-Yates shuffling from `seed`. Returns mean loss of the LAST epoch.
float train_linear_sgd(float* W, float* b, const float* x, const int32_t* y,
                       int32_t n, int32_t d, int32_t k, int32_t epochs,
                       int32_t batch, float lr, uint64_t seed) {
  if (n <= 0 || d <= 0 || k <= 0 || batch <= 0) return -1.0f;
  std::vector<int32_t> order(n);
  for (int32_t i = 0; i < n; ++i) order[i] = i;
  std::vector<float> logits(k), probs(k);
  std::vector<float> gW(static_cast<size_t>(d) * k), gb(k);
  float last_epoch_loss = 0.0f;

  for (int32_t e = 0; e < epochs; ++e) {
    uint64_t rng = seed + static_cast<uint64_t>(e) * 0x51ED2701ULL;
    for (int32_t i = n - 1; i > 0; --i) {  // Fisher-Yates
      int32_t j = static_cast<int32_t>(splitmix64(rng) % (i + 1));
      int32_t t = order[i]; order[i] = order[j]; order[j] = t;
    }
    float epoch_loss = 0.0f;
    int32_t seen = 0;
    for (int32_t start = 0; start < n; start += batch) {
      int32_t bs = (start + batch <= n) ? batch : (n - start);
      std::memset(gW.data(), 0, gW.size() * sizeof(float));
      std::memset(gb.data(), 0, gb.size() * sizeof(float));
      for (int32_t bi = 0; bi < bs; ++bi) {
        const float* xi = x + static_cast<size_t>(order[start + bi]) * d;
        int32_t yi = y[order[start + bi]];
        // forward
        float maxl = -1e30f;
        for (int32_t c = 0; c < k; ++c) {
          float acc = b[c];
          for (int32_t f = 0; f < d; ++f) acc += xi[f] * W[f * k + c];
          logits[c] = acc;
          if (acc > maxl) maxl = acc;
        }
        float denom = 0.0f;
        for (int32_t c = 0; c < k; ++c) {
          probs[c] = std::exp(logits[c] - maxl);
          denom += probs[c];
        }
        for (int32_t c = 0; c < k; ++c) probs[c] /= denom;
        epoch_loss += -std::log(probs[yi] > 1e-12f ? probs[yi] : 1e-12f);
        ++seen;
        // backward: dlogit = probs - onehot(y)
        for (int32_t c = 0; c < k; ++c) {
          float dl = probs[c] - (c == yi ? 1.0f : 0.0f);
          gb[c] += dl;
          for (int32_t f = 0; f < d; ++f) gW[f * k + c] += xi[f] * dl;
        }
      }
      const float scale = lr / static_cast<float>(bs);
      for (size_t idx = 0; idx < gW.size(); ++idx) W[idx] -= scale * gW[idx];
      for (int32_t c = 0; c < k; ++c) b[c] -= scale * gb[c];
    }
    last_epoch_loss = seen ? epoch_loss / seen : 0.0f;
  }
  return last_epoch_loss;
}

// Accuracy of the current W, b on (x, y) — the device-side eval hook.
float eval_linear(const float* W, const float* b, const float* x,
                  const int32_t* y, int32_t n, int32_t d, int32_t k) {
  if (n <= 0) return 0.0f;
  int32_t correct = 0;
  for (int32_t i = 0; i < n; ++i) {
    const float* xi = x + static_cast<size_t>(i) * d;
    int32_t best = 0;
    float bestv = -1e30f;
    for (int32_t c = 0; c < k; ++c) {
      float acc = b[c];
      for (int32_t f = 0; f < d; ++f) acc += xi[f] * W[f * k + c];
      if (acc > bestv) { bestv = acc; best = c; }
    }
    if (best == y[i]) ++correct;
  }
  return static_cast<float>(correct) / static_cast<float>(n);
}

// Generate a PRG mask stream over GF(2^31-1) from `seed` (LightSecAgg
// device-side primitive; server unmasks with the Python field ops).
void gen_mask(uint32_t* out, int64_t n, uint64_t seed) {
  uint64_t rng = seed;
  for (int64_t i = 0; i < n; ++i)
    out[i] = static_cast<uint32_t>(splitmix64(rng) % kPrime);
}

// Quantize float vector v into the field (fixed-point, `scale` ticks per
// unit, offset so negatives map into the field) and add the PRG mask from
// `seed`: out[i] = (q(v[i]) + mask[i]) mod p.
void mask_vector(uint32_t* out, const float* v, int64_t n, float scale,
                 uint64_t seed) {
  uint64_t rng = seed;
  const int64_t half = static_cast<int64_t>(kPrime / 2);
  for (int64_t i = 0; i < n; ++i) {
    double q = std::llround(static_cast<double>(v[i]) * scale);
    int64_t qi = static_cast<int64_t>(q);
    // clamp into (-p/2, p/2) then shift into [0, p)
    if (qi > half - 1) qi = half - 1;
    if (qi < -half) qi = -half;
    uint64_t f = static_cast<uint64_t>(qi + half);
    uint64_t m = splitmix64(rng) % kPrime;
    out[i] = static_cast<uint32_t>((f + m) % kPrime);
  }
}

// Remove the PRG mask and de-quantize: the server-side inverse of
// mask_vector for a SINGLE device (aggregate unmasking sums masked vectors
// and subtracts the sum of masks — done by the Python pipeline; this
// single-vector form is used in tests and point-to-point checks).
void unmask_vector(float* out, const uint32_t* masked, int64_t n,
                   float scale, uint64_t seed) {
  uint64_t rng = seed;
  const int64_t half = static_cast<int64_t>(kPrime / 2);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t m = splitmix64(rng) % kPrime;
    uint64_t f = (static_cast<uint64_t>(masked[i]) + kPrime - m) % kPrime;
    out[i] = static_cast<float>(static_cast<int64_t>(f) - half) / scale;
  }
}

int32_t mobilenn_abi_version() { return 2; }

}  // extern "C"

// ===================== CNN trainer (LeNet-class) ============================
//
// Mirror of the flax DeviceCNN (model/cv/cnn.py): conv3x3 SAME (C1) + relu +
// maxpool2 + conv3x3 SAME (C2) + relu + maxpool2 + dense + softmax CE.
// Layouts match flax exactly: x NHWC, conv kernels [3,3,Cin,Cout], dense
// kernel [features, k], flatten order (h*W + w)*C + c — so native and JAX
// devices train the SAME param tree and the server aggregates them
// interchangeably (reference: MobileNN's MNN LeNet engine,
// FedMLMNNTrainer.cpp).

namespace {

struct ConvShape {
  int32_t H, W, Cin, Cout;
};

// y[b] = relu(conv3x3_same(x)) ; x: [H,W,Cin], k: [3,3,Cin,Cout]
void conv3x3_fwd(const float* x, const float* k, const float* bias, float* y,
                 const ConvShape& s) {
  for (int32_t h = 0; h < s.H; ++h)
    for (int32_t w = 0; w < s.W; ++w)
      for (int32_t co = 0; co < s.Cout; ++co) {
        float acc = bias[co];
        for (int32_t dh = -1; dh <= 1; ++dh)
          for (int32_t dw = -1; dw <= 1; ++dw) {
            int32_t ih = h + dh, iw = w + dw;
            if (ih < 0 || ih >= s.H || iw < 0 || iw >= s.W) continue;
            const float* xp = x + (ih * s.W + iw) * s.Cin;
            const float* kp = k + (((dh + 1) * 3 + (dw + 1)) * s.Cin) * s.Cout
                              + co;
            for (int32_t ci = 0; ci < s.Cin; ++ci)
              acc += xp[ci] * kp[ci * s.Cout];
          }
        y[(h * s.W + w) * s.Cout + co] = acc;
      }
}

// backward of conv3x3_same: accumulates gk/gb, writes gx (may be null)
void conv3x3_bwd(const float* x, const float* k, const float* gy, float* gx,
                 float* gk, float* gb, const ConvShape& s) {
  if (gx) std::memset(gx, 0, sizeof(float) * s.H * s.W * s.Cin);
  for (int32_t h = 0; h < s.H; ++h)
    for (int32_t w = 0; w < s.W; ++w)
      for (int32_t co = 0; co < s.Cout; ++co) {
        float g = gy[(h * s.W + w) * s.Cout + co];
        if (g == 0.0f) continue;
        gb[co] += g;
        for (int32_t dh = -1; dh <= 1; ++dh)
          for (int32_t dw = -1; dw <= 1; ++dw) {
            int32_t ih = h + dh, iw = w + dw;
            if (ih < 0 || ih >= s.H || iw < 0 || iw >= s.W) continue;
            const float* xp = x + (ih * s.W + iw) * s.Cin;
            size_t kbase = (((dh + 1) * 3 + (dw + 1)) * s.Cin) * s.Cout + co;
            for (int32_t ci = 0; ci < s.Cin; ++ci) {
              gk[kbase + static_cast<size_t>(ci) * s.Cout] += xp[ci] * g;
              if (gx)
                gx[(ih * s.W + iw) * s.Cin + ci] +=
                    k[kbase + static_cast<size_t>(ci) * s.Cout] * g;
            }
          }
      }
}

// 2x2 maxpool stride 2 (floor); argmax saved for backward
void pool2_fwd(const float* x, float* y, int32_t* arg, int32_t H, int32_t W,
               int32_t C) {
  int32_t Ho = H / 2, Wo = W / 2;
  for (int32_t h = 0; h < Ho; ++h)
    for (int32_t w = 0; w < Wo; ++w)
      for (int32_t c = 0; c < C; ++c) {
        float best = -1e30f;
        int32_t bi = 0;
        for (int32_t dh = 0; dh < 2; ++dh)
          for (int32_t dw = 0; dw < 2; ++dw) {
            int32_t idx = ((h * 2 + dh) * W + (w * 2 + dw)) * C + c;
            if (x[idx] > best) { best = x[idx]; bi = idx; }
          }
        y[(h * Wo + w) * C + c] = best;
        arg[(h * Wo + w) * C + c] = bi;
      }
}

}  // namespace

extern "C" {

// Train the DeviceCNN with SGD. Params updated in place:
//   k1 [3,3,Cin,C1] b1 [C1]  k2 [3,3,C1,C2] b2 [C2]
//   Wd [feat, k]    bd [k]   with feat = (H/4)*(W/4)*C2
// x: [n, H, W, Cin] NHWC, y: [n]. Returns mean loss of the last epoch.
float train_cnn_sgd(float* k1, float* b1, float* k2, float* b2, float* Wd,
                    float* bd, const float* x, const int32_t* y, int32_t n,
                    int32_t H, int32_t W, int32_t Cin, int32_t C1, int32_t C2,
                    int32_t nclass, int32_t epochs, int32_t batch, float lr,
                    uint64_t seed) {
  if (n <= 0 || H < 4 || W < 4 || batch <= 0) return -1.0f;
  const int32_t H2 = H / 2, W2 = W / 2, H4 = H2 / 2, W4 = W2 / 2;
  const int32_t feat = H4 * W4 * C2;
  ConvShape s1{H, W, Cin, C1}, s2{H2, W2, C1, C2};
  std::vector<int32_t> order(n);
  for (int32_t i = 0; i < n; ++i) order[i] = i;

  // activations (per sample)
  std::vector<float> a1(H * W * C1), p1(H2 * W2 * C1);
  std::vector<int32_t> arg1(H2 * W2 * C1);
  std::vector<float> a2(H2 * W2 * C2), p2(feat);
  std::vector<int32_t> arg2(feat);
  std::vector<float> logits(nclass), probs(nclass);
  // grads (per batch)
  std::vector<float> gk1(9 * static_cast<size_t>(Cin) * C1), gb1(C1);
  std::vector<float> gk2(9 * static_cast<size_t>(C1) * C2), gb2(C2);
  std::vector<float> gWd(static_cast<size_t>(feat) * nclass), gbd(nclass);
  // per-sample backward scratch
  std::vector<float> gp2(feat), ga2(H2 * W2 * C2), gp1(H2 * W2 * C1),
      ga1(H * W * C1);

  float last_epoch_loss = 0.0f;
  for (int32_t e = 0; e < epochs; ++e) {
    uint64_t rng = seed + static_cast<uint64_t>(e) * 0x51ED2701ULL;
    for (int32_t i = n - 1; i > 0; --i) {
      int32_t j = static_cast<int32_t>(splitmix64(rng) % (i + 1));
      int32_t t = order[i]; order[i] = order[j]; order[j] = t;
    }
    float epoch_loss = 0.0f;
    int32_t seen = 0;
    for (int32_t start = 0; start < n; start += batch) {
      int32_t bs = (start + batch <= n) ? batch : (n - start);
      std::memset(gk1.data(), 0, gk1.size() * sizeof(float));
      std::memset(gb1.data(), 0, gb1.size() * sizeof(float));
      std::memset(gk2.data(), 0, gk2.size() * sizeof(float));
      std::memset(gb2.data(), 0, gb2.size() * sizeof(float));
      std::memset(gWd.data(), 0, gWd.size() * sizeof(float));
      std::memset(gbd.data(), 0, gbd.size() * sizeof(float));
      for (int32_t bi = 0; bi < bs; ++bi) {
        const float* xi = x + static_cast<size_t>(order[start + bi]) * H * W
                          * Cin;
        int32_t yi = y[order[start + bi]];
        // ---- forward
        conv3x3_fwd(xi, k1, b1, a1.data(), s1);
        for (auto& v : a1) v = v > 0 ? v : 0;
        pool2_fwd(a1.data(), p1.data(), arg1.data(), H, W, C1);
        conv3x3_fwd(p1.data(), k2, b2, a2.data(), s2);
        for (auto& v : a2) v = v > 0 ? v : 0;
        pool2_fwd(a2.data(), p2.data(), arg2.data(), H2, W2, C2);
        float maxl = -1e30f;
        for (int32_t c = 0; c < nclass; ++c) {
          float acc = bd[c];
          for (int32_t f = 0; f < feat; ++f)
            acc += p2[f] * Wd[static_cast<size_t>(f) * nclass + c];
          logits[c] = acc;
          if (acc > maxl) maxl = acc;
        }
        float denom = 0.0f;
        for (int32_t c = 0; c < nclass; ++c) {
          probs[c] = std::exp(logits[c] - maxl);
          denom += probs[c];
        }
        for (int32_t c = 0; c < nclass; ++c) probs[c] /= denom;
        epoch_loss += -std::log(probs[yi] > 1e-12f ? probs[yi] : 1e-12f);
        ++seen;
        // ---- backward
        std::memset(gp2.data(), 0, gp2.size() * sizeof(float));
        for (int32_t c = 0; c < nclass; ++c) {
          float dl = probs[c] - (c == yi ? 1.0f : 0.0f);
          gbd[c] += dl;
          for (int32_t f = 0; f < feat; ++f) {
            gWd[static_cast<size_t>(f) * nclass + c] += p2[f] * dl;
            gp2[f] += Wd[static_cast<size_t>(f) * nclass + c] * dl;
          }
        }
        std::memset(ga2.data(), 0, ga2.size() * sizeof(float));
        for (int32_t i2 = 0; i2 < feat; ++i2) ga2[arg2[i2]] = gp2[i2];
        for (size_t i2 = 0; i2 < ga2.size(); ++i2)
          if (a2[i2] <= 0) ga2[i2] = 0;  // relu'
        conv3x3_bwd(p1.data(), k2, ga2.data(), gp1.data(), gk2.data(),
                    gb2.data(), s2);
        std::memset(ga1.data(), 0, ga1.size() * sizeof(float));
        for (int32_t i1 = 0; i1 < H2 * W2 * C1; ++i1)
          ga1[arg1[i1]] = gp1[i1];
        for (size_t i1 = 0; i1 < ga1.size(); ++i1)
          if (a1[i1] <= 0) ga1[i1] = 0;
        conv3x3_bwd(xi, k1, ga1.data(), nullptr, gk1.data(), gb1.data(), s1);
      }
      const float scale = lr / static_cast<float>(bs);
      for (size_t i2 = 0; i2 < gk1.size(); ++i2) k1[i2] -= scale * gk1[i2];
      for (int32_t c = 0; c < C1; ++c) b1[c] -= scale * gb1[c];
      for (size_t i2 = 0; i2 < gk2.size(); ++i2) k2[i2] -= scale * gk2[i2];
      for (int32_t c = 0; c < C2; ++c) b2[c] -= scale * gb2[c];
      for (size_t i2 = 0; i2 < gWd.size(); ++i2) Wd[i2] -= scale * gWd[i2];
      for (int32_t c = 0; c < nclass; ++c) bd[c] -= scale * gbd[c];
    }
    last_epoch_loss = seen ? epoch_loss / seen : 0.0f;
  }
  return last_epoch_loss;
}

// Forward-only accuracy for the DeviceCNN.
float eval_cnn(const float* k1, const float* b1, const float* k2,
               const float* b2, const float* Wd, const float* bd,
               const float* x, const int32_t* y, int32_t n, int32_t H,
               int32_t W, int32_t Cin, int32_t C1, int32_t C2,
               int32_t nclass) {
  if (n <= 0) return 0.0f;
  const int32_t H2 = H / 2, W2 = W / 2, H4 = H2 / 2, W4 = W2 / 2;
  const int32_t feat = H4 * W4 * C2;
  ConvShape s1{H, W, Cin, C1}, s2{H2, W2, C1, C2};
  std::vector<float> a1(H * W * C1), p1(H2 * W2 * C1), a2(H2 * W2 * C2),
      p2(feat);
  std::vector<int32_t> arg1(H2 * W2 * C1), arg2(feat);
  int32_t correct = 0;
  for (int32_t i = 0; i < n; ++i) {
    const float* xi = x + static_cast<size_t>(i) * H * W * Cin;
    conv3x3_fwd(xi, k1, b1, a1.data(), s1);
    for (auto& v : a1) v = v > 0 ? v : 0;
    pool2_fwd(a1.data(), p1.data(), arg1.data(), H, W, C1);
    conv3x3_fwd(p1.data(), k2, b2, a2.data(), s2);
    for (auto& v : a2) v = v > 0 ? v : 0;
    pool2_fwd(a2.data(), p2.data(), arg2.data(), H2, W2, C2);
    int32_t best = 0;
    float bestv = -1e30f;
    for (int32_t c = 0; c < nclass; ++c) {
      float acc = bd[c];
      for (int32_t f = 0; f < feat; ++f)
        acc += p2[f] * Wd[static_cast<size_t>(f) * nclass + c];
      if (acc > bestv) { bestv = acc; best = c; }
    }
    if (best == y[i]) ++correct;
  }
  return static_cast<float>(correct) / static_cast<float>(n);
}

// ================= LightSecAgg Lagrange mask encoding =======================
//
// Native counterpart of core/mpc/lightsecagg.py mask_encoding (reference
// MobileNN/src/security/LightSecAgg.cpp): identical evaluation points and
// field math, so natively-encoded sub-masks decode with the Python
// decode_aggregate_mask. The privacy padding rows come from the device's own
// splitmix64 stream (padding values are arbitrary randomness; only the
// coding must match).

namespace {

inline uint64_t gf_mul(uint64_t a, uint64_t b) { return (a * b) % kPrime; }

uint64_t gf_pow(uint64_t base, uint64_t exp) {
  uint64_t r = 1;
  base %= kPrime;
  while (exp) {
    if (exp & 1) r = gf_mul(r, base);
    base = gf_mul(base, base);
    exp >>= 1;
  }
  return r;
}

inline uint64_t gf_inv(uint64_t a) { return gf_pow(a, kPrime - 2); }

// Lagrange basis coefficients l_k(xq) on source points src[0..m)
void lagrange_at(const uint64_t* src, int32_t m, uint64_t xq, uint64_t* out) {
  for (int32_t k = 0; k < m; ++k) {
    uint64_t num = 1, den = 1;
    for (int32_t j = 0; j < m; ++j) {
      if (j == k) continue;
      num = gf_mul(num, (xq + kPrime - src[j]) % kPrime);
      den = gf_mul(den, (src[k] + kPrime - src[j]) % kPrime);
    }
    out[k] = gf_mul(num, gf_inv(den));
  }
}

}  // namespace

// z: [d] field elements (uint32 < p), d % split_t == 0.
// out: [n_clients, d / split_t]. Returns 0 on success.
int32_t lsa_mask_encode(uint32_t* out, const uint32_t* z, int32_t d,
                        int32_t n_clients, int32_t privacy_t, int32_t split_t,
                        uint64_t seed) {
  if (d <= 0 || split_t <= 0 || d % split_t != 0) return -1;
  const int32_t l = d / split_t;
  const int32_t m = split_t + privacy_t;
  // source points: betas 1..split_t, gammas split_t+1..split_t+privacy_t
  std::vector<uint64_t> src(m);
  for (int32_t i = 0; i < m; ++i) src[i] = static_cast<uint64_t>(i + 1);
  // data rows: z split into split_t rows, then privacy_t random rows
  std::vector<uint64_t> pad(static_cast<size_t>(privacy_t) * l);
  uint64_t rng = seed;
  for (auto& v : pad) v = splitmix64(rng) % kPrime;
  std::vector<uint64_t> coeff(m);
  for (int32_t c = 0; c < n_clients; ++c) {
    uint64_t alpha = static_cast<uint64_t>(m + 1 + c);
    lagrange_at(src.data(), m, alpha, coeff.data());
    uint32_t* dst = out + static_cast<size_t>(c) * l;
    for (int32_t col = 0; col < l; ++col) {
      uint64_t acc = 0;
      for (int32_t row = 0; row < split_t; ++row)
        acc = (acc + gf_mul(coeff[row],
                            z[static_cast<size_t>(row) * l + col])) % kPrime;
      for (int32_t row = 0; row < privacy_t; ++row)
        acc = (acc + gf_mul(coeff[split_t + row],
                            pad[static_cast<size_t>(row) * l + col]))
              % kPrime;
      dst[col] = static_cast<uint32_t>(acc);
    }
  }
  return 0;
}

// ========================= native dataset reader ============================
//
// CSV reader (label in the LAST column — the reference device SDK ships
// per-engine dataset readers; this is the transport-agnostic one). Two-call
// pattern: probe for shape, then fill caller-allocated buffers.

#include <cstdio>
#include <cstdlib>

int32_t csv_probe(const char* path, int32_t* rows, int32_t* cols) {
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  int32_t r = 0, c = 0, cur_cols = 1;
  int ch, prev = '\n';
  while ((ch = std::fgetc(f)) != EOF) {
    if (ch == ',') ++cur_cols;
    if (ch == '\n') {
      if (prev != '\n') {  // skip blank lines
        if (c == 0) c = cur_cols;
        else if (cur_cols != c) { std::fclose(f); return -2; }
        ++r;
      }
      cur_cols = 1;
    }
    prev = ch;
  }
  if (prev != '\n' && prev != EOF) { if (c == 0) c = cur_cols; ++r; }
  std::fclose(f);
  *rows = r;
  *cols = c;
  return 0;
}

// x: [rows, cols-1] features; y: [rows] labels from the last column.
int32_t csv_read(const char* path, float* x, int32_t* y, int32_t rows,
                 int32_t cols) {
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  for (int32_t r = 0; r < rows; ++r) {
    for (int32_t c = 0; c < cols; ++c) {
      double v;
      if (std::fscanf(f, "%lf", &v) != 1) { std::fclose(f); return -2; }
      if (c < cols - 1) x[static_cast<size_t>(r) * (cols - 1) + c] =
          static_cast<float>(v);
      else y[r] = static_cast<int32_t>(v);
      int ch = std::fgetc(f);  // consume , or newline
      (void)ch;
    }
  }
  std::fclose(f);
  return 0;
}

}  // extern "C"
