// Native on-device training core — the MobileNN analogue.
//
// Parity target: the reference's C++ device SDK
// (android/fedmlsdk/MobileNN: FedMLBaseTrainer + MNN/torch engine
// implementations, ~2.6k LoC C++) and its native secure-aggregation masking
// (MobileNN/src/security/LightSecAgg.cpp). Devices in that stack train a
// small model locally in native code and exchange *masked* updates.
//
// This is a fresh implementation sized to what a TPU-federated deployment
// actually needs on-device: a softmax-regression SGD trainer (the
// cross-device reference workload is LR/LeNet-class models) and
// finite-field masking over GF(p), p = 2^31 - 1 — the same field the
// Python SecAgg math uses (core/mpc/field_ops.py), so natively-masked
// updates unmask server-side with the existing Python pipeline.
//
// Deterministic by construction: shuffling and mask generation use
// explicit splitmix64 streams seeded by the caller, so device results are
// reproducible across runs and platforms.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

// splitmix64: tiny, high-quality, seedable PRG (public-domain algorithm)
inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

constexpr uint64_t kPrime = 2147483647ULL;  // 2^31 - 1 (Mersenne)

}  // namespace

extern "C" {

// Softmax-regression SGD: logits = x·W + b, cross-entropy loss, plain SGD.
// x: [n, d] row-major, y: [n] labels in [0, k). W: [d, k], b: [k] updated
// in place. Runs `epochs` passes over batches of `batch` with per-epoch
// Fisher-Yates shuffling from `seed`. Returns mean loss of the LAST epoch.
float train_linear_sgd(float* W, float* b, const float* x, const int32_t* y,
                       int32_t n, int32_t d, int32_t k, int32_t epochs,
                       int32_t batch, float lr, uint64_t seed) {
  if (n <= 0 || d <= 0 || k <= 0 || batch <= 0) return -1.0f;
  std::vector<int32_t> order(n);
  for (int32_t i = 0; i < n; ++i) order[i] = i;
  std::vector<float> logits(k), probs(k);
  std::vector<float> gW(static_cast<size_t>(d) * k), gb(k);
  float last_epoch_loss = 0.0f;

  for (int32_t e = 0; e < epochs; ++e) {
    uint64_t rng = seed + static_cast<uint64_t>(e) * 0x51ED2701ULL;
    for (int32_t i = n - 1; i > 0; --i) {  // Fisher-Yates
      int32_t j = static_cast<int32_t>(splitmix64(rng) % (i + 1));
      int32_t t = order[i]; order[i] = order[j]; order[j] = t;
    }
    float epoch_loss = 0.0f;
    int32_t seen = 0;
    for (int32_t start = 0; start < n; start += batch) {
      int32_t bs = (start + batch <= n) ? batch : (n - start);
      std::memset(gW.data(), 0, gW.size() * sizeof(float));
      std::memset(gb.data(), 0, gb.size() * sizeof(float));
      for (int32_t bi = 0; bi < bs; ++bi) {
        const float* xi = x + static_cast<size_t>(order[start + bi]) * d;
        int32_t yi = y[order[start + bi]];
        // forward
        float maxl = -1e30f;
        for (int32_t c = 0; c < k; ++c) {
          float acc = b[c];
          for (int32_t f = 0; f < d; ++f) acc += xi[f] * W[f * k + c];
          logits[c] = acc;
          if (acc > maxl) maxl = acc;
        }
        float denom = 0.0f;
        for (int32_t c = 0; c < k; ++c) {
          probs[c] = std::exp(logits[c] - maxl);
          denom += probs[c];
        }
        for (int32_t c = 0; c < k; ++c) probs[c] /= denom;
        epoch_loss += -std::log(probs[yi] > 1e-12f ? probs[yi] : 1e-12f);
        ++seen;
        // backward: dlogit = probs - onehot(y)
        for (int32_t c = 0; c < k; ++c) {
          float dl = probs[c] - (c == yi ? 1.0f : 0.0f);
          gb[c] += dl;
          for (int32_t f = 0; f < d; ++f) gW[f * k + c] += xi[f] * dl;
        }
      }
      const float scale = lr / static_cast<float>(bs);
      for (size_t idx = 0; idx < gW.size(); ++idx) W[idx] -= scale * gW[idx];
      for (int32_t c = 0; c < k; ++c) b[c] -= scale * gb[c];
    }
    last_epoch_loss = seen ? epoch_loss / seen : 0.0f;
  }
  return last_epoch_loss;
}

// Accuracy of the current W, b on (x, y) — the device-side eval hook.
float eval_linear(const float* W, const float* b, const float* x,
                  const int32_t* y, int32_t n, int32_t d, int32_t k) {
  if (n <= 0) return 0.0f;
  int32_t correct = 0;
  for (int32_t i = 0; i < n; ++i) {
    const float* xi = x + static_cast<size_t>(i) * d;
    int32_t best = 0;
    float bestv = -1e30f;
    for (int32_t c = 0; c < k; ++c) {
      float acc = b[c];
      for (int32_t f = 0; f < d; ++f) acc += xi[f] * W[f * k + c];
      if (acc > bestv) { bestv = acc; best = c; }
    }
    if (best == y[i]) ++correct;
  }
  return static_cast<float>(correct) / static_cast<float>(n);
}

// Generate a PRG mask stream over GF(2^31-1) from `seed` (LightSecAgg
// device-side primitive; server unmasks with the Python field ops).
void gen_mask(uint32_t* out, int64_t n, uint64_t seed) {
  uint64_t rng = seed;
  for (int64_t i = 0; i < n; ++i)
    out[i] = static_cast<uint32_t>(splitmix64(rng) % kPrime);
}

// Quantize float vector v into the field (fixed-point, `scale` ticks per
// unit, offset so negatives map into the field) and add the PRG mask from
// `seed`: out[i] = (q(v[i]) + mask[i]) mod p.
void mask_vector(uint32_t* out, const float* v, int64_t n, float scale,
                 uint64_t seed) {
  uint64_t rng = seed;
  const int64_t half = static_cast<int64_t>(kPrime / 2);
  for (int64_t i = 0; i < n; ++i) {
    double q = std::llround(static_cast<double>(v[i]) * scale);
    int64_t qi = static_cast<int64_t>(q);
    // clamp into (-p/2, p/2) then shift into [0, p)
    if (qi > half - 1) qi = half - 1;
    if (qi < -half) qi = -half;
    uint64_t f = static_cast<uint64_t>(qi + half);
    uint64_t m = splitmix64(rng) % kPrime;
    out[i] = static_cast<uint32_t>((f + m) % kPrime);
  }
}

// Remove the PRG mask and de-quantize: the server-side inverse of
// mask_vector for a SINGLE device (aggregate unmasking sums masked vectors
// and subtracts the sum of masks — done by the Python pipeline; this
// single-vector form is used in tests and point-to-point checks).
void unmask_vector(float* out, const uint32_t* masked, int64_t n,
                   float scale, uint64_t seed) {
  uint64_t rng = seed;
  const int64_t half = static_cast<int64_t>(kPrime / 2);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t m = splitmix64(rng) % kPrime;
    uint64_t f = (static_cast<uint64_t>(masked[i]) + kPrime - m) % kPrime;
    out[i] = static_cast<float>(static_cast<int64_t>(f) - half) / scale;
  }
}

int32_t mobilenn_abi_version() { return 1; }

}  // extern "C"
