// Native on-device training core — the MobileNN analogue.
//
// Parity target: the reference's C++ device SDK
// (android/fedmlsdk/MobileNN: FedMLBaseTrainer + MNN/torch engine
// implementations, ~2.6k LoC C++) and its native secure-aggregation masking
// (MobileNN/src/security/LightSecAgg.cpp). Devices in that stack train a
// small model locally in native code and exchange *masked* updates.
//
// This is a fresh implementation sized to what a TPU-federated deployment
// actually needs on-device: a softmax-regression SGD trainer (the
// cross-device reference workload is LR/LeNet-class models) and
// finite-field masking over GF(p), p = 2^31 - 1 — the same field the
// Python SecAgg math uses (core/mpc/field_ops.py), so natively-masked
// updates unmask server-side with the existing Python pipeline.
//
// Deterministic by construction: shuffling and mask generation use
// explicit splitmix64 streams seeded by the caller, so device results are
// reproducible across runs and platforms.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

// splitmix64: tiny, high-quality, seedable PRG (public-domain algorithm)
inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

constexpr uint64_t kPrime = 2147483647ULL;  // 2^31 - 1 (Mersenne)

}  // namespace

extern "C" {

// Softmax-regression SGD: logits = x·W + b, cross-entropy loss, plain SGD.
// x: [n, d] row-major, y: [n] labels in [0, k). W: [d, k], b: [k] updated
// in place. Runs `epochs` passes over batches of `batch` with per-epoch
// Fisher-Yates shuffling from `seed`. Returns mean loss of the LAST epoch.
float train_linear_sgd(float* W, float* b, const float* x, const int32_t* y,
                       int32_t n, int32_t d, int32_t k, int32_t epochs,
                       int32_t batch, float lr, uint64_t seed) {
  if (n <= 0 || d <= 0 || k <= 0 || batch <= 0) return -1.0f;
  std::vector<int32_t> order(n);
  for (int32_t i = 0; i < n; ++i) order[i] = i;
  std::vector<float> logits(k), probs(k);
  std::vector<float> gW(static_cast<size_t>(d) * k), gb(k);
  float last_epoch_loss = 0.0f;

  for (int32_t e = 0; e < epochs; ++e) {
    uint64_t rng = seed + static_cast<uint64_t>(e) * 0x51ED2701ULL;
    for (int32_t i = n - 1; i > 0; --i) {  // Fisher-Yates
      int32_t j = static_cast<int32_t>(splitmix64(rng) % (i + 1));
      int32_t t = order[i]; order[i] = order[j]; order[j] = t;
    }
    float epoch_loss = 0.0f;
    int32_t seen = 0;
    for (int32_t start = 0; start < n; start += batch) {
      int32_t bs = (start + batch <= n) ? batch : (n - start);
      std::memset(gW.data(), 0, gW.size() * sizeof(float));
      std::memset(gb.data(), 0, gb.size() * sizeof(float));
      for (int32_t bi = 0; bi < bs; ++bi) {
        const float* xi = x + static_cast<size_t>(order[start + bi]) * d;
        int32_t yi = y[order[start + bi]];
        // forward
        float maxl = -1e30f;
        for (int32_t c = 0; c < k; ++c) {
          float acc = b[c];
          for (int32_t f = 0; f < d; ++f) acc += xi[f] * W[f * k + c];
          logits[c] = acc;
          if (acc > maxl) maxl = acc;
        }
        float denom = 0.0f;
        for (int32_t c = 0; c < k; ++c) {
          probs[c] = std::exp(logits[c] - maxl);
          denom += probs[c];
        }
        for (int32_t c = 0; c < k; ++c) probs[c] /= denom;
        epoch_loss += -std::log(probs[yi] > 1e-12f ? probs[yi] : 1e-12f);
        ++seen;
        // backward: dlogit = probs - onehot(y)
        for (int32_t c = 0; c < k; ++c) {
          float dl = probs[c] - (c == yi ? 1.0f : 0.0f);
          gb[c] += dl;
          for (int32_t f = 0; f < d; ++f) gW[f * k + c] += xi[f] * dl;
        }
      }
      const float scale = lr / static_cast<float>(bs);
      for (size_t idx = 0; idx < gW.size(); ++idx) W[idx] -= scale * gW[idx];
      for (int32_t c = 0; c < k; ++c) b[c] -= scale * gb[c];
    }
    last_epoch_loss = seen ? epoch_loss / seen : 0.0f;
  }
  return last_epoch_loss;
}

// Accuracy of the current W, b on (x, y) — the device-side eval hook.
float eval_linear(const float* W, const float* b, const float* x,
                  const int32_t* y, int32_t n, int32_t d, int32_t k) {
  if (n <= 0) return 0.0f;
  int32_t correct = 0;
  for (int32_t i = 0; i < n; ++i) {
    const float* xi = x + static_cast<size_t>(i) * d;
    int32_t best = 0;
    float bestv = -1e30f;
    for (int32_t c = 0; c < k; ++c) {
      float acc = b[c];
      for (int32_t f = 0; f < d; ++f) acc += xi[f] * W[f * k + c];
      if (acc > bestv) { bestv = acc; best = c; }
    }
    if (best == y[i]) ++correct;
  }
  return static_cast<float>(correct) / static_cast<float>(n);
}

// Generate a PRG mask stream over GF(2^31-1) from `seed` (LightSecAgg
// device-side primitive; server unmasks with the Python field ops).
void gen_mask(uint32_t* out, int64_t n, uint64_t seed) {
  uint64_t rng = seed;
  for (int64_t i = 0; i < n; ++i)
    out[i] = static_cast<uint32_t>(splitmix64(rng) % kPrime);
}

// Quantize float vector v into the field (fixed-point, `scale` ticks per
// unit, offset so negatives map into the field) and add the PRG mask from
// `seed`: out[i] = (q(v[i]) + mask[i]) mod p.
void mask_vector(uint32_t* out, const float* v, int64_t n, float scale,
                 uint64_t seed) {
  uint64_t rng = seed;
  const int64_t half = static_cast<int64_t>(kPrime / 2);
  for (int64_t i = 0; i < n; ++i) {
    double q = std::llround(static_cast<double>(v[i]) * scale);
    int64_t qi = static_cast<int64_t>(q);
    // clamp into (-p/2, p/2) then shift into [0, p)
    if (qi > half - 1) qi = half - 1;
    if (qi < -half) qi = -half;
    uint64_t f = static_cast<uint64_t>(qi + half);
    uint64_t m = splitmix64(rng) % kPrime;
    out[i] = static_cast<uint32_t>((f + m) % kPrime);
  }
}

// Remove the PRG mask and de-quantize: the server-side inverse of
// mask_vector for a SINGLE device (aggregate unmasking sums masked vectors
// and subtracts the sum of masks — done by the Python pipeline; this
// single-vector form is used in tests and point-to-point checks).
void unmask_vector(float* out, const uint32_t* masked, int64_t n,
                   float scale, uint64_t seed) {
  uint64_t rng = seed;
  const int64_t half = static_cast<int64_t>(kPrime / 2);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t m = splitmix64(rng) % kPrime;
    uint64_t f = (static_cast<uint64_t>(masked[i]) + kPrime - m) % kPrime;
    out[i] = static_cast<float>(static_cast<int64_t>(f) - half) / scale;
  }
}

int32_t mobilenn_abi_version() { return 3; }

}  // extern "C"

// ===================== CNN trainer (LeNet-class) ============================
//
// Mirror of the flax DeviceCNN (model/cv/cnn.py): conv3x3 SAME (C1) + relu +
// maxpool2 + conv3x3 SAME (C2) + relu + maxpool2 + dense + softmax CE.
// Layouts match flax exactly: x NHWC, conv kernels [3,3,Cin,Cout], dense
// kernel [features, k], flatten order (h*W + w)*C + c — so native and JAX
// devices train the SAME param tree and the server aggregates them
// interchangeably (reference: MobileNN's MNN LeNet engine,
// FedMLMNNTrainer.cpp).

namespace {

struct ConvShape {
  int32_t H, W, Cin, Cout;
};

// y[b] = relu(conv3x3_same(x)) ; x: [H,W,Cin], k: [3,3,Cin,Cout]
void conv3x3_fwd(const float* x, const float* k, const float* bias, float* y,
                 const ConvShape& s) {
  for (int32_t h = 0; h < s.H; ++h)
    for (int32_t w = 0; w < s.W; ++w)
      for (int32_t co = 0; co < s.Cout; ++co) {
        float acc = bias[co];
        for (int32_t dh = -1; dh <= 1; ++dh)
          for (int32_t dw = -1; dw <= 1; ++dw) {
            int32_t ih = h + dh, iw = w + dw;
            if (ih < 0 || ih >= s.H || iw < 0 || iw >= s.W) continue;
            const float* xp = x + (ih * s.W + iw) * s.Cin;
            const float* kp = k + (((dh + 1) * 3 + (dw + 1)) * s.Cin) * s.Cout
                              + co;
            for (int32_t ci = 0; ci < s.Cin; ++ci)
              acc += xp[ci] * kp[ci * s.Cout];
          }
        y[(h * s.W + w) * s.Cout + co] = acc;
      }
}

// backward of conv3x3_same: accumulates gk/gb, writes gx (may be null)
void conv3x3_bwd(const float* x, const float* k, const float* gy, float* gx,
                 float* gk, float* gb, const ConvShape& s) {
  if (gx) std::memset(gx, 0, sizeof(float) * s.H * s.W * s.Cin);
  for (int32_t h = 0; h < s.H; ++h)
    for (int32_t w = 0; w < s.W; ++w)
      for (int32_t co = 0; co < s.Cout; ++co) {
        float g = gy[(h * s.W + w) * s.Cout + co];
        if (g == 0.0f) continue;
        gb[co] += g;
        for (int32_t dh = -1; dh <= 1; ++dh)
          for (int32_t dw = -1; dw <= 1; ++dw) {
            int32_t ih = h + dh, iw = w + dw;
            if (ih < 0 || ih >= s.H || iw < 0 || iw >= s.W) continue;
            const float* xp = x + (ih * s.W + iw) * s.Cin;
            size_t kbase = (((dh + 1) * 3 + (dw + 1)) * s.Cin) * s.Cout + co;
            for (int32_t ci = 0; ci < s.Cin; ++ci) {
              gk[kbase + static_cast<size_t>(ci) * s.Cout] += xp[ci] * g;
              if (gx)
                gx[(ih * s.W + iw) * s.Cin + ci] +=
                    k[kbase + static_cast<size_t>(ci) * s.Cout] * g;
            }
          }
      }
}

// 2x2 maxpool stride 2 (floor); argmax saved for backward
void pool2_fwd(const float* x, float* y, int32_t* arg, int32_t H, int32_t W,
               int32_t C) {
  int32_t Ho = H / 2, Wo = W / 2;
  for (int32_t h = 0; h < Ho; ++h)
    for (int32_t w = 0; w < Wo; ++w)
      for (int32_t c = 0; c < C; ++c) {
        float best = -1e30f;
        int32_t bi = 0;
        for (int32_t dh = 0; dh < 2; ++dh)
          for (int32_t dw = 0; dw < 2; ++dw) {
            int32_t idx = ((h * 2 + dh) * W + (w * 2 + dw)) * C + c;
            if (x[idx] > best) { best = x[idx]; bi = idx; }
          }
        y[(h * Wo + w) * C + c] = best;
        arg[(h * Wo + w) * C + c] = bi;
      }
}

}  // namespace

extern "C" {

// Train the DeviceCNN with SGD. Params updated in place:
//   k1 [3,3,Cin,C1] b1 [C1]  k2 [3,3,C1,C2] b2 [C2]
//   Wd [feat, k]    bd [k]   with feat = (H/4)*(W/4)*C2
// x: [n, H, W, Cin] NHWC, y: [n]. Returns mean loss of the last epoch.
float train_cnn_sgd(float* k1, float* b1, float* k2, float* b2, float* Wd,
                    float* bd, const float* x, const int32_t* y, int32_t n,
                    int32_t H, int32_t W, int32_t Cin, int32_t C1, int32_t C2,
                    int32_t nclass, int32_t epochs, int32_t batch, float lr,
                    uint64_t seed) {
  if (n <= 0 || H < 4 || W < 4 || batch <= 0) return -1.0f;
  const int32_t H2 = H / 2, W2 = W / 2, H4 = H2 / 2, W4 = W2 / 2;
  const int32_t feat = H4 * W4 * C2;
  ConvShape s1{H, W, Cin, C1}, s2{H2, W2, C1, C2};
  std::vector<int32_t> order(n);
  for (int32_t i = 0; i < n; ++i) order[i] = i;

  // activations (per sample)
  std::vector<float> a1(H * W * C1), p1(H2 * W2 * C1);
  std::vector<int32_t> arg1(H2 * W2 * C1);
  std::vector<float> a2(H2 * W2 * C2), p2(feat);
  std::vector<int32_t> arg2(feat);
  std::vector<float> logits(nclass), probs(nclass);
  // grads (per batch)
  std::vector<float> gk1(9 * static_cast<size_t>(Cin) * C1), gb1(C1);
  std::vector<float> gk2(9 * static_cast<size_t>(C1) * C2), gb2(C2);
  std::vector<float> gWd(static_cast<size_t>(feat) * nclass), gbd(nclass);
  // per-sample backward scratch
  std::vector<float> gp2(feat), ga2(H2 * W2 * C2), gp1(H2 * W2 * C1),
      ga1(H * W * C1);

  float last_epoch_loss = 0.0f;
  for (int32_t e = 0; e < epochs; ++e) {
    uint64_t rng = seed + static_cast<uint64_t>(e) * 0x51ED2701ULL;
    for (int32_t i = n - 1; i > 0; --i) {
      int32_t j = static_cast<int32_t>(splitmix64(rng) % (i + 1));
      int32_t t = order[i]; order[i] = order[j]; order[j] = t;
    }
    float epoch_loss = 0.0f;
    int32_t seen = 0;
    for (int32_t start = 0; start < n; start += batch) {
      int32_t bs = (start + batch <= n) ? batch : (n - start);
      std::memset(gk1.data(), 0, gk1.size() * sizeof(float));
      std::memset(gb1.data(), 0, gb1.size() * sizeof(float));
      std::memset(gk2.data(), 0, gk2.size() * sizeof(float));
      std::memset(gb2.data(), 0, gb2.size() * sizeof(float));
      std::memset(gWd.data(), 0, gWd.size() * sizeof(float));
      std::memset(gbd.data(), 0, gbd.size() * sizeof(float));
      for (int32_t bi = 0; bi < bs; ++bi) {
        const float* xi = x + static_cast<size_t>(order[start + bi]) * H * W
                          * Cin;
        int32_t yi = y[order[start + bi]];
        // ---- forward
        conv3x3_fwd(xi, k1, b1, a1.data(), s1);
        for (auto& v : a1) v = v > 0 ? v : 0;
        pool2_fwd(a1.data(), p1.data(), arg1.data(), H, W, C1);
        conv3x3_fwd(p1.data(), k2, b2, a2.data(), s2);
        for (auto& v : a2) v = v > 0 ? v : 0;
        pool2_fwd(a2.data(), p2.data(), arg2.data(), H2, W2, C2);
        float maxl = -1e30f;
        for (int32_t c = 0; c < nclass; ++c) {
          float acc = bd[c];
          for (int32_t f = 0; f < feat; ++f)
            acc += p2[f] * Wd[static_cast<size_t>(f) * nclass + c];
          logits[c] = acc;
          if (acc > maxl) maxl = acc;
        }
        float denom = 0.0f;
        for (int32_t c = 0; c < nclass; ++c) {
          probs[c] = std::exp(logits[c] - maxl);
          denom += probs[c];
        }
        for (int32_t c = 0; c < nclass; ++c) probs[c] /= denom;
        epoch_loss += -std::log(probs[yi] > 1e-12f ? probs[yi] : 1e-12f);
        ++seen;
        // ---- backward
        std::memset(gp2.data(), 0, gp2.size() * sizeof(float));
        for (int32_t c = 0; c < nclass; ++c) {
          float dl = probs[c] - (c == yi ? 1.0f : 0.0f);
          gbd[c] += dl;
          for (int32_t f = 0; f < feat; ++f) {
            gWd[static_cast<size_t>(f) * nclass + c] += p2[f] * dl;
            gp2[f] += Wd[static_cast<size_t>(f) * nclass + c] * dl;
          }
        }
        std::memset(ga2.data(), 0, ga2.size() * sizeof(float));
        for (int32_t i2 = 0; i2 < feat; ++i2) ga2[arg2[i2]] = gp2[i2];
        for (size_t i2 = 0; i2 < ga2.size(); ++i2)
          if (a2[i2] <= 0) ga2[i2] = 0;  // relu'
        conv3x3_bwd(p1.data(), k2, ga2.data(), gp1.data(), gk2.data(),
                    gb2.data(), s2);
        std::memset(ga1.data(), 0, ga1.size() * sizeof(float));
        for (int32_t i1 = 0; i1 < H2 * W2 * C1; ++i1)
          ga1[arg1[i1]] = gp1[i1];
        for (size_t i1 = 0; i1 < ga1.size(); ++i1)
          if (a1[i1] <= 0) ga1[i1] = 0;
        conv3x3_bwd(xi, k1, ga1.data(), nullptr, gk1.data(), gb1.data(), s1);
      }
      const float scale = lr / static_cast<float>(bs);
      for (size_t i2 = 0; i2 < gk1.size(); ++i2) k1[i2] -= scale * gk1[i2];
      for (int32_t c = 0; c < C1; ++c) b1[c] -= scale * gb1[c];
      for (size_t i2 = 0; i2 < gk2.size(); ++i2) k2[i2] -= scale * gk2[i2];
      for (int32_t c = 0; c < C2; ++c) b2[c] -= scale * gb2[c];
      for (size_t i2 = 0; i2 < gWd.size(); ++i2) Wd[i2] -= scale * gWd[i2];
      for (int32_t c = 0; c < nclass; ++c) bd[c] -= scale * gbd[c];
    }
    last_epoch_loss = seen ? epoch_loss / seen : 0.0f;
  }
  return last_epoch_loss;
}

// Forward-only accuracy for the DeviceCNN.
float eval_cnn(const float* k1, const float* b1, const float* k2,
               const float* b2, const float* Wd, const float* bd,
               const float* x, const int32_t* y, int32_t n, int32_t H,
               int32_t W, int32_t Cin, int32_t C1, int32_t C2,
               int32_t nclass) {
  if (n <= 0) return 0.0f;
  const int32_t H2 = H / 2, W2 = W / 2, H4 = H2 / 2, W4 = W2 / 2;
  const int32_t feat = H4 * W4 * C2;
  ConvShape s1{H, W, Cin, C1}, s2{H2, W2, C1, C2};
  std::vector<float> a1(H * W * C1), p1(H2 * W2 * C1), a2(H2 * W2 * C2),
      p2(feat);
  std::vector<int32_t> arg1(H2 * W2 * C1), arg2(feat);
  int32_t correct = 0;
  for (int32_t i = 0; i < n; ++i) {
    const float* xi = x + static_cast<size_t>(i) * H * W * Cin;
    conv3x3_fwd(xi, k1, b1, a1.data(), s1);
    for (auto& v : a1) v = v > 0 ? v : 0;
    pool2_fwd(a1.data(), p1.data(), arg1.data(), H, W, C1);
    conv3x3_fwd(p1.data(), k2, b2, a2.data(), s2);
    for (auto& v : a2) v = v > 0 ? v : 0;
    pool2_fwd(a2.data(), p2.data(), arg2.data(), H2, W2, C2);
    int32_t best = 0;
    float bestv = -1e30f;
    for (int32_t c = 0; c < nclass; ++c) {
      float acc = bd[c];
      for (int32_t f = 0; f < feat; ++f)
        acc += p2[f] * Wd[static_cast<size_t>(f) * nclass + c];
      if (acc > bestv) { bestv = acc; best = c; }
    }
    if (best == y[i]) ++correct;
  }
  return static_cast<float>(correct) / static_cast<float>(n);
}

// ================= LightSecAgg Lagrange mask encoding =======================
//
// Native counterpart of core/mpc/lightsecagg.py mask_encoding (reference
// MobileNN/src/security/LightSecAgg.cpp): identical evaluation points and
// field math, so natively-encoded sub-masks decode with the Python
// decode_aggregate_mask. The privacy padding rows come from the device's own
// splitmix64 stream (padding values are arbitrary randomness; only the
// coding must match).

namespace {

inline uint64_t gf_mul(uint64_t a, uint64_t b) { return (a * b) % kPrime; }

uint64_t gf_pow(uint64_t base, uint64_t exp) {
  uint64_t r = 1;
  base %= kPrime;
  while (exp) {
    if (exp & 1) r = gf_mul(r, base);
    base = gf_mul(base, base);
    exp >>= 1;
  }
  return r;
}

inline uint64_t gf_inv(uint64_t a) { return gf_pow(a, kPrime - 2); }

// Lagrange basis coefficients l_k(xq) on source points src[0..m)
void lagrange_at(const uint64_t* src, int32_t m, uint64_t xq, uint64_t* out) {
  for (int32_t k = 0; k < m; ++k) {
    uint64_t num = 1, den = 1;
    for (int32_t j = 0; j < m; ++j) {
      if (j == k) continue;
      num = gf_mul(num, (xq + kPrime - src[j]) % kPrime);
      den = gf_mul(den, (src[k] + kPrime - src[j]) % kPrime);
    }
    out[k] = gf_mul(num, gf_inv(den));
  }
}

}  // namespace

// z: [d] field elements (uint32 < p), d % split_t == 0.
// out: [n_clients, d / split_t]. Returns 0 on success.
int32_t lsa_mask_encode(uint32_t* out, const uint32_t* z, int32_t d,
                        int32_t n_clients, int32_t privacy_t, int32_t split_t,
                        uint64_t seed) {
  if (d <= 0 || split_t <= 0 || d % split_t != 0) return -1;
  const int32_t l = d / split_t;
  const int32_t m = split_t + privacy_t;
  // source points: betas 1..split_t, gammas split_t+1..split_t+privacy_t
  std::vector<uint64_t> src(m);
  for (int32_t i = 0; i < m; ++i) src[i] = static_cast<uint64_t>(i + 1);
  // data rows: z split into split_t rows, then privacy_t random rows
  std::vector<uint64_t> pad(static_cast<size_t>(privacy_t) * l);
  uint64_t rng = seed;
  for (auto& v : pad) v = splitmix64(rng) % kPrime;
  std::vector<uint64_t> coeff(m);
  for (int32_t c = 0; c < n_clients; ++c) {
    uint64_t alpha = static_cast<uint64_t>(m + 1 + c);
    lagrange_at(src.data(), m, alpha, coeff.data());
    uint32_t* dst = out + static_cast<size_t>(c) * l;
    for (int32_t col = 0; col < l; ++col) {
      uint64_t acc = 0;
      for (int32_t row = 0; row < split_t; ++row)
        acc = (acc + gf_mul(coeff[row],
                            z[static_cast<size_t>(row) * l + col])) % kPrime;
      for (int32_t row = 0; row < privacy_t; ++row)
        acc = (acc + gf_mul(coeff[split_t + row],
                            pad[static_cast<size_t>(row) * l + col]))
              % kPrime;
      dst[col] = static_cast<uint32_t>(acc);
    }
  }
  return 0;
}

// ========================= native dataset reader ============================
//
// CSV reader (label in the LAST column — the reference device SDK ships
// per-engine dataset readers; this is the transport-agnostic one). Two-call
// pattern: probe for shape, then fill caller-allocated buffers.

#include <cstdio>
#include <cstdlib>

int32_t csv_probe(const char* path, int32_t* rows, int32_t* cols) {
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  int32_t r = 0, c = 0, cur_cols = 1;
  int ch, prev = '\n';
  while ((ch = std::fgetc(f)) != EOF) {
    if (ch == ',') ++cur_cols;
    if (ch == '\n') {
      if (prev != '\n') {  // skip blank lines
        if (c == 0) c = cur_cols;
        else if (cur_cols != c) { std::fclose(f); return -2; }
        ++r;
      }
      cur_cols = 1;
    }
    prev = ch;
  }
  if (prev != '\n' && prev != EOF) { if (c == 0) c = cur_cols; ++r; }
  std::fclose(f);
  *rows = r;
  *cols = c;
  return 0;
}

// x: [rows, cols-1] features; y: [rows] labels from the last column.
int32_t csv_read(const char* path, float* x, int32_t* y, int32_t rows,
                 int32_t cols) {
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  for (int32_t r = 0; r < rows; ++r) {
    for (int32_t c = 0; c < cols; ++c) {
      double v;
      if (std::fscanf(f, "%lf", &v) != 1) { std::fclose(f); return -2; }
      if (c < cols - 1) x[static_cast<size_t>(r) * (cols - 1) + c] =
          static_cast<float>(v);
      else y[r] = static_cast<int32_t>(v);
      int ch = std::fgetc(f);  // consume , or newline
      (void)ch;
    }
  }
  std::fclose(f);
  return 0;
}

}  // extern "C"

// ===================== model artifact codec (msgpack) =======================
//
// Reads/writes the framework's model artifact format natively: the
// "FMTPU1\n" magic followed by a msgpack map tree whose leaves are
// ext-42 numpy arrays (head = packed (dtype_str, shape), then raw bytes)
// — the exact bytes `serving.save_model` / `load_model` produce, so a
// device can consume the server's global model and produce an update the
// server loads with zero Python on the device (reference counterpart: the
// MNN/torch serialized-model handling in FedMLMNNTrainer.cpp /
// FedMLTorchTrainer.cpp). Subset codec: maps, strings, arrays,
// non-negative ints, ext — everything a param-tree artifact contains.

#include <map>
#include <memory>
#include <string>

namespace artifact {

constexpr char kMagic[] = "FMTPU1\n";
constexpr size_t kMagicLen = 7;
constexpr int8_t kNpExt = 42;

struct Leaf {
  std::vector<int32_t> shape;
  std::vector<float> data;
};

struct Store {
  std::map<std::string, Leaf> leaves;  // "a/b/c" slash paths, sorted
};

// ---- reader ----------------------------------------------------------------

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  uint8_t u8() {
    if (p >= end) { fail = true; return 0; }
    return *p++;
  }
  uint64_t be(int n) {
    uint64_t v = 0;
    for (int i = 0; i < n; ++i) v = (v << 8) | u8();
    return v;
  }
  const uint8_t* raw(size_t n) {
    // compare against the remaining size, not p + n (a crafted huge n
    // would overflow the pointer arithmetic — UB — before the check)
    if (n > static_cast<size_t>(end - p)) { fail = true; return nullptr; }
    const uint8_t* r = p;
    p += n;
    return r;
  }
};

bool parse_uint(Cursor& c, uint64_t* out) {
  uint8_t t = c.u8();
  if (t <= 0x7f) { *out = t; return true; }
  if (t == 0xcc) { *out = c.be(1); return true; }
  if (t == 0xcd) { *out = c.be(2); return true; }
  if (t == 0xce) { *out = c.be(4); return true; }
  if (t == 0xcf) { *out = c.be(8); return true; }
  return false;
}

bool parse_str(Cursor& c, std::string* out) {
  uint8_t t = c.u8();
  size_t n;
  if ((t & 0xe0) == 0xa0) n = t & 0x1f;
  else if (t == 0xd9) n = c.be(1);
  else if (t == 0xda) n = c.be(2);
  else if (t == 0xdb) n = c.be(4);
  else return false;
  const uint8_t* r = c.raw(n);
  if (!r) return false;
  out->assign(reinterpret_cast<const char*>(r), n);
  return true;
}

// ext leaf -> Leaf (head tuple [dtype_str, [shape...]] + raw data).
// `len` is ATTACKER-CONTROLLED (artifacts cross trust boundaries — device
// uploads, served model pulls): it must be bounded by the remaining
// buffer before any sub-cursor is built, and allocation is deferred until
// the payload length has been checked against the declared shape.
bool parse_ext_leaf(Cursor& c, size_t len, int8_t type, Leaf* leaf) {
  if (type != kNpExt) return false;
  if (len > static_cast<size_t>(c.end - c.p)) return false;  // truncated
  Cursor h{c.p, c.p + len};
  const uint8_t* payload_end = c.p + len;
  uint8_t t = h.u8();
  size_t tuple_n;
  if ((t & 0xf0) == 0x90) tuple_n = t & 0x0f;
  else if (t == 0xdc) tuple_n = h.be(2);
  else return false;
  if (tuple_n != 2) return false;
  std::string dtype;
  if (!parse_str(h, &dtype)) return false;
  uint8_t s = h.u8();
  size_t ndim;
  if ((s & 0xf0) == 0x90) ndim = s & 0x0f;
  else if (s == 0xdc) ndim = h.be(2);
  else return false;
  size_t elems = 1;
  leaf->shape.clear();
  for (size_t i = 0; i < ndim; ++i) {
    uint64_t d;
    if (!parse_uint(h, &d)) return false;
    if (d > (1ULL << 31)) return false;  // absurd dim = crafted input
    leaf->shape.push_back(static_cast<int32_t>(d));
    if (d != 0 && elems > (1ULL << 33) / d) return false;  // overflow cap
    elems *= d;
  }
  if (h.fail) return false;
  const uint8_t* data = h.p;
  size_t nbytes = static_cast<size_t>(payload_end - data);
  // validate the declared shape against the ACTUAL payload bytes BEFORE
  // allocating — crafted dims must not drive a giant resize
  size_t unit;
  if (dtype == "<f4" || dtype == "<i4") unit = 4;
  else if (dtype == "<f8") unit = 8;
  else return false;  // artifact leaves are float tensors
  if (nbytes != elems * unit) return false;
  leaf->data.resize(elems);
  if (dtype == "<f4") {
    std::memcpy(leaf->data.data(), data, nbytes);
  } else if (dtype == "<f8") {
    // per-element memcpy: the payload sits at an arbitrary offset inside
    // the msgpack blob, and a reinterpret_cast load of a misaligned
    // double is UB (SIGBUS on strict-alignment device targets)
    for (size_t i = 0; i < elems; ++i) {
      double v;
      std::memcpy(&v, data + i * 8, 8);
      leaf->data[i] = static_cast<float>(v);
    }
  } else {  // <i4
    for (size_t i = 0; i < elems; ++i) {
      int32_t v;
      std::memcpy(&v, data + i * 4, 4);
      leaf->data[i] = static_cast<float>(v);
    }
  }
  c.p = payload_end;
  return true;
}

// Nesting bound: artifacts are attacker-controlled, and each fixmap level
// costs ~2 bytes of input, so unbounded recursion here is a crafted-blob
// stack overflow. Real parameter trees are a handful of levels deep.
constexpr int kMaxTreeDepth = 64;

bool parse_value(Cursor& c, const std::string& prefix, Store* store,
                 int depth);

bool parse_map(Cursor& c, size_t n, const std::string& prefix,
               Store* store, int depth) {
  for (size_t i = 0; i < n; ++i) {
    std::string key;
    if (!parse_str(c, &key)) return false;
    std::string path = prefix.empty() ? key : prefix + "/" + key;
    if (!parse_value(c, path, store, depth)) return false;
  }
  return true;
}

bool parse_value(Cursor& c, const std::string& prefix, Store* store,
                 int depth) {
  if (depth > kMaxTreeDepth) return false;
  if (c.p >= c.end) return false;
  uint8_t t = *c.p;
  if ((t & 0xf0) == 0x80) { c.u8(); return parse_map(c, t & 0x0f, prefix, store, depth + 1); }
  if (t == 0xde) { c.u8(); return parse_map(c, c.be(2), prefix, store, depth + 1); }
  if (t == 0xdf) { c.u8(); return parse_map(c, c.be(4), prefix, store, depth + 1); }
  size_t len;
  int8_t etype;
  if (t == 0xd4 || t == 0xd5 || t == 0xd6 || t == 0xd7 || t == 0xd8) {
    c.u8();
    len = 1u << (t - 0xd4);
    etype = static_cast<int8_t>(c.u8());
  } else if (t == 0xc7) { c.u8(); len = c.be(1); etype = static_cast<int8_t>(c.u8()); }
  else if (t == 0xc8) { c.u8(); len = c.be(2); etype = static_cast<int8_t>(c.u8()); }
  else if (t == 0xc9) { c.u8(); len = c.be(4); etype = static_cast<int8_t>(c.u8()); }
  else return false;  // artifact trees hold only maps and array leaves
  Leaf leaf;
  if (!parse_ext_leaf(c, len, etype, &leaf)) return false;
  store->leaves[prefix] = std::move(leaf);
  return true;
}

// ---- writer ----------------------------------------------------------------

void put_be(std::vector<uint8_t>* out, uint64_t v, int n) {
  for (int i = n - 1; i >= 0; --i)
    out->push_back(static_cast<uint8_t>((v >> (8 * i)) & 0xff));
}

void put_str(std::vector<uint8_t>* out, const std::string& s) {
  if (s.size() < 32) out->push_back(0xa0 | static_cast<uint8_t>(s.size()));
  else if (s.size() <= 0xff) { out->push_back(0xd9); put_be(out, s.size(), 1); }
  else if (s.size() <= 0xffff) { out->push_back(0xda); put_be(out, s.size(), 2); }
  else { out->push_back(0xdb); put_be(out, s.size(), 4); }
  out->insert(out->end(), s.begin(), s.end());
}

void put_uint(std::vector<uint8_t>* out, uint64_t v) {
  if (v <= 0x7f) out->push_back(static_cast<uint8_t>(v));
  else if (v <= 0xff) { out->push_back(0xcc); put_be(out, v, 1); }
  else if (v <= 0xffff) { out->push_back(0xcd); put_be(out, v, 2); }
  else if (v <= 0xffffffffULL) { out->push_back(0xce); put_be(out, v, 4); }
  else { out->push_back(0xcf); put_be(out, v, 8); }
}

void put_leaf(std::vector<uint8_t>* out, const Leaf& leaf) {
  std::vector<uint8_t> head;
  head.push_back(0x92);  // fixarray 2
  put_str(&head, "<f4");
  head.push_back(0x90 | static_cast<uint8_t>(leaf.shape.size()));
  size_t elems = 1;
  for (int32_t d : leaf.shape) { put_uint(&head, d); elems *= d; }
  size_t total = head.size() + elems * 4;
  out->push_back(0xc9);  // ext32 (simplest single form)
  put_be(out, total, 4);
  out->push_back(static_cast<uint8_t>(kNpExt));
  out->insert(out->end(), head.begin(), head.end());
  const uint8_t* data = reinterpret_cast<const uint8_t*>(leaf.data.data());
  out->insert(out->end(), data, data + elems * 4);
}

// nested emit: the sorted flat slash paths form a tree; emit maps
// recursively over the [begin, end) range sharing `prefix`
using LeafIter = std::map<std::string, Leaf>::const_iterator;

void put_tree(std::vector<uint8_t>* out, LeafIter begin, LeafIter end,
              size_t prefix_len) {
  // collect direct children
  std::vector<std::pair<std::string, std::pair<LeafIter, LeafIter>>> kids;
  for (LeafIter it = begin; it != end;) {
    const std::string& path = it->first;
    size_t slash = path.find('/', prefix_len);
    std::string child = (slash == std::string::npos)
                            ? path.substr(prefix_len)
                            : path.substr(prefix_len, slash - prefix_len);
    LeafIter run = it;
    while (run != end && run->first.compare(prefix_len, child.size(),
                                            child) == 0 &&
           (run->first.size() == prefix_len + child.size() ||
            run->first[prefix_len + child.size()] == '/'))
      ++run;
    kids.emplace_back(child, std::make_pair(it, run));
    it = run;
  }
  if (kids.size() < 16) out->push_back(0x80 | static_cast<uint8_t>(kids.size()));
  else if (kids.size() <= 0xffff) { out->push_back(0xde); put_be(out, kids.size(), 2); }
  else { out->push_back(0xdf); put_be(out, kids.size(), 4); }
  for (auto& k : kids) {
    put_str(out, k.first);
    LeafIter b = k.second.first, e = k.second.second;
    bool is_leaf = (std::next(b) == e &&
                    b->first.size() == prefix_len + k.first.size());
    if (is_leaf) put_leaf(out, b->second);
    else put_tree(out, b, e, prefix_len + k.first.size() + 1);
  }
}

}  // namespace artifact

extern "C" {

// Opens a model artifact; returns an opaque handle or NULL on parse
// failure. Pair with artifact_close.
void* artifact_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < static_cast<long>(artifact::kMagicLen)) { std::fclose(f); return nullptr; }
  std::vector<uint8_t> blob(static_cast<size_t>(size));
  if (std::fread(blob.data(), 1, blob.size(), f) != blob.size()) {
    std::fclose(f);
    return nullptr;
  }
  std::fclose(f);
  if (std::memcmp(blob.data(), artifact::kMagic, artifact::kMagicLen) != 0)
    return nullptr;
  auto store = std::make_unique<artifact::Store>();
  artifact::Cursor c{blob.data() + artifact::kMagicLen,
                     blob.data() + blob.size()};
  if (!artifact::parse_value(c, "", store.get(), 0) || c.fail) return nullptr;
  return store.release();
}

int32_t artifact_count(void* h) {
  return static_cast<int32_t>(
      static_cast<artifact::Store*>(h)->leaves.size());
}

// i-th (sorted) slash path; returns its length or -1.
int32_t artifact_key(void* h, int32_t i, char* out, int32_t cap) {
  auto& leaves = static_cast<artifact::Store*>(h)->leaves;
  if (i < 0 || i >= static_cast<int32_t>(leaves.size())) return -1;
  auto it = leaves.begin();
  std::advance(it, i);
  int32_t n = static_cast<int32_t>(it->first.size());
  if (cap > 0) {
    int32_t c = n < cap - 1 ? n : cap - 1;
    std::memcpy(out, it->first.data(), c);
    out[c] = 0;
  }
  return n;
}

int64_t artifact_elems(void* h, const char* key) {
  auto& leaves = static_cast<artifact::Store*>(h)->leaves;
  auto it = leaves.find(key);
  if (it == leaves.end()) return -1;
  return static_cast<int64_t>(it->second.data.size());
}

int32_t artifact_shape(void* h, const char* key, int32_t* dims,
                       int32_t cap) {
  auto& leaves = static_cast<artifact::Store*>(h)->leaves;
  auto it = leaves.find(key);
  if (it == leaves.end()) return -1;
  int32_t n = static_cast<int32_t>(it->second.shape.size());
  for (int32_t i = 0; i < n && i < cap; ++i) dims[i] = it->second.shape[i];
  return n;
}

int64_t artifact_read_f32(void* h, const char* key, float* out,
                          int64_t cap) {
  auto& leaves = static_cast<artifact::Store*>(h)->leaves;
  auto it = leaves.find(key);
  if (it == leaves.end()) return -1;
  int64_t n = static_cast<int64_t>(it->second.data.size());
  if (n > cap) return -2;
  std::memcpy(out, it->second.data.data(), static_cast<size_t>(n) * 4);
  return n;
}

void artifact_close(void* h) { delete static_cast<artifact::Store*>(h); }

// Save leaves as a NESTED artifact (slash paths -> map tree), bytes
// compatible with Python `serving.load_model`. shapes is the
// concatenation of each leaf's dims (ndims[i] entries each).
int32_t artifact_save(const char* path, const char** keys,
                      const float** data, const int32_t* ndims,
                      const int32_t* shapes, int32_t n_leaves) {
  artifact::Store store;
  const int32_t* sp = shapes;
  for (int32_t i = 0; i < n_leaves; ++i) {
    artifact::Leaf leaf;
    size_t elems = 1;
    for (int32_t d = 0; d < ndims[i]; ++d) {
      leaf.shape.push_back(*sp);
      elems *= static_cast<size_t>(*sp);
      ++sp;
    }
    leaf.data.assign(data[i], data[i] + elems);
    store.leaves[keys[i]] = std::move(leaf);
  }
  std::vector<uint8_t> out;
  out.insert(out.end(), artifact::kMagic,
             artifact::kMagic + artifact::kMagicLen);
  artifact::put_tree(&out, store.leaves.begin(), store.leaves.end(), 0);
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  bool ok = std::fwrite(out.data(), 1, out.size(), f) == out.size();
  std::fclose(f);
  return ok ? 0 : -2;
}

}  // extern "C"

// ===================== device client manager ================================
//
// The FedMLClientManager analogue (reference
// MobileNN/includes/FedMLClientManager.h + JniFedMLClientManager.cpp:
// create/init/train/getEpochAndLoss/stopTraining/release): one opaque
// session object a host app drives through the C ABI in
// include/fedml_client.h. init() loads the global model ARTIFACT and the
// device's CSV shard; train() runs the local epochs (linear or CNN per
// the artifact's keys) with progress/loss callbacks; the trained params
// save back as an artifact the server loads directly.

extern "C" {

typedef void (*fedml_progress_cb)(float pct);
typedef void (*fedml_loss_cb)(int32_t epoch, float loss);

struct FedMLClient {
  artifact::Store params;
  std::vector<float> x;
  std::vector<int32_t> y;
  int32_t n = 0, d = 0;
  int32_t batch = 32, epochs = 1;
  float lr = 0.1f;
  uint64_t seed = 0;
  volatile int32_t stop_flag = 0;
  int32_t last_epoch = -1;
  float last_loss = 0.0f;
  fedml_progress_cb on_progress = nullptr;
  fedml_loss_cb on_loss = nullptr;
};

void* fedml_client_create() { return new FedMLClient(); }

void fedml_client_release(void* h) {
  delete static_cast<FedMLClient*>(h);
}

// Load the global model artifact + the device's CSV data shard.
// Returns 0 on success.
int32_t fedml_client_init(void* h, const char* model_path,
                          const char* data_path, int32_t batch_size,
                          float learning_rate, int32_t epoch_num,
                          uint64_t seed) {
  auto* c = static_cast<FedMLClient*>(h);
  void* art = artifact_open(model_path);
  if (!art) return -1;
  c->params = *static_cast<artifact::Store*>(art);
  artifact_close(art);
  int32_t rows = 0, cols = 0;
  if (csv_probe(data_path, &rows, &cols) != 0 || cols < 2) return -2;
  c->x.resize(static_cast<size_t>(rows) * (cols - 1));
  c->y.resize(rows);
  if (csv_read(data_path, c->x.data(), c->y.data(), rows, cols) != 0)
    return -3;
  c->n = rows;
  c->d = cols - 1;
  c->batch = batch_size;
  c->lr = learning_rate;
  c->epochs = epoch_num;
  c->seed = seed;
  c->stop_flag = 0;
  return 0;
}

void fedml_client_set_callbacks(void* h, fedml_progress_cb progress,
                                fedml_loss_cb loss) {
  auto* c = static_cast<FedMLClient*>(h);
  c->on_progress = progress;
  c->on_loss = loss;
}

// Local training over the loaded shard; epoch-at-a-time so stopTraining
// and the progress callback have real granularity. Returns final-epoch
// mean loss (NaN on error).
// Shared precondition of train/evaluate: the artifact's linear head must
// exist, be 2-D, and match the loaded shard's feature width — a 64-wide
// kernel against an 80-column CSV would index past the weight buffer.
// Returns the class count k, or -1 when the params are unusable.
static int32_t client_linear_classes(FedMLClient* c,
                                     artifact::Leaf** W,
                                     artifact::Leaf** B) {
  auto wi = c->params.leaves.find("Dense_0/kernel");
  auto bi = c->params.leaves.find("Dense_0/bias");
  if (wi == c->params.leaves.end() || bi == c->params.leaves.end())
    return -1;  // only the linear family is artifact-driven for now
  if (wi->second.shape.size() != 2 || wi->second.shape[0] != c->d)
    return -1;
  int32_t k = wi->second.shape[1];
  if (bi->second.shape.size() != 1 || bi->second.shape[0] != k) return -1;
  *W = &wi->second;
  *B = &bi->second;
  return k;
}

float fedml_client_train(void* h) {
  auto* c = static_cast<FedMLClient*>(h);
  artifact::Leaf *W, *B;
  int32_t k = client_linear_classes(c, &W, &B);
  if (k < 0) return NAN;
  float loss = NAN;
  for (int32_t e = 0; e < c->epochs && !c->stop_flag; ++e) {
    loss = train_linear_sgd(W->data.data(), B->data.data(),
                            c->x.data(), c->y.data(), c->n, c->d, k, 1,
                            c->batch, c->lr, c->seed + e);
    c->last_epoch = e;
    c->last_loss = loss;
    if (c->on_loss) c->on_loss(e, loss);
    if (c->on_progress)
      c->on_progress(100.0f * (e + 1) / c->epochs);
  }
  return loss;
}

// "epoch,loss" of the most recent local epoch (reference getEpochAndLoss
// returns the same pair as a string; a C ABI hands back the parts).
int32_t fedml_client_get_epoch_and_loss(void* h, int32_t* epoch,
                                        float* loss) {
  auto* c = static_cast<FedMLClient*>(h);
  *epoch = c->last_epoch;
  *loss = c->last_loss;
  return c->last_epoch >= 0 ? 0 : -1;
}

int32_t fedml_client_stop_training(void* h) {
  static_cast<FedMLClient*>(h)->stop_flag = 1;
  return 0;
}

// On-device evaluation of the CURRENT params on the loaded shard.
float fedml_client_evaluate(void* h) {
  auto* c = static_cast<FedMLClient*>(h);
  artifact::Leaf *W, *B;
  int32_t k = client_linear_classes(c, &W, &B);
  if (k < 0) return -1.0f;
  return eval_linear(W->data.data(), B->data.data(),
                     c->x.data(), c->y.data(), c->n, c->d, k);
}

// Persist the trained params as an artifact the server loads directly.
int32_t fedml_client_save_model(void* h, const char* path) {
  auto* c = static_cast<FedMLClient*>(h);
  std::vector<const char*> keys;
  std::vector<const float*> data;
  std::vector<int32_t> ndims, shapes;
  for (auto& kv : c->params.leaves) {
    keys.push_back(kv.first.c_str());
    data.push_back(kv.second.data.data());
    ndims.push_back(static_cast<int32_t>(kv.second.shape.size()));
    for (int32_t dshape : kv.second.shape) shapes.push_back(dshape);
  }
  return artifact_save(path, keys.data(), data.data(), ndims.data(),
                       shapes.data(), static_cast<int32_t>(keys.size()));
}

}  // extern "C"
