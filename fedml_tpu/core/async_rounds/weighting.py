"""Staleness weighting — the ONE definition shared by every async path.

Buffered-async aggregation (FedBuff, Nguyen et al., AISTATS 2022) pours a
buffer of K client updates whenever they arrive, each down-weighted by how
many model versions elapsed since the client was handed its base model.
FedAsync (Xie et al., 2019) supplies the decay families implemented here:

* ``constant`` — ``s(t) = 1``: pure FedBuff, arrival order alone decides.
* ``polynomial`` — ``s(t) = (1 + t)^(-a)``: smooth decay, the default (and
  what the SP ``async_fedavg`` toy always used).
* ``hinge`` — ``s(t) = 1`` for ``t <= b``, else ``1 / (a * (t - b) + 1)``:
  free staleness up to ``b`` versions, hyperbolic decay past it.

Staleness is CLAMPED to ``cap`` before weighting — a stale upload is
down-weighted, never dropped (the cap saturates the decay so one
long-partitioned silo's redemption update still moves the model). All
functions are plain NumPy/host math so they are unit-testable without a
device and usable both host-side (cross-silo, SP toy) and as program DATA
(the TPU engine computes weights host-side and feeds them to the jitted
pour as a ``[K]`` array — weighting never recompiles anything).
"""

from __future__ import annotations

from typing import Callable, Tuple

import numpy as np

STALENESS_WEIGHTINGS = ("constant", "polynomial", "hinge")

# staleness caps must stay in a sane band: 1 keeps only fresh-or-one-late
# updates at full decay resolution, 1024 is "effectively uncapped" while
# still bounding the cross-silo base ring
MIN_STALENESS_CAP = 1
MAX_STALENESS_CAP = 1024


def make_staleness_fn(kind: str = "polynomial", poly_a: float = 0.5,
                      hinge_b: int = 4, cap: int = 16
                      ) -> Callable[[np.ndarray], np.ndarray]:
    """Vectorized staleness -> weight in ``(0, 1]``. ``cap`` clamps the
    input staleness (down-weight saturates; updates are never zeroed)."""
    kind = str(kind or "polynomial").lower()
    if kind not in STALENESS_WEIGHTINGS:
        raise ValueError(f"async_staleness_weighting {kind!r} unknown; "
                         f"choose from {STALENESS_WEIGHTINGS}")
    a = float(poly_a)
    if a < 0.0:
        raise ValueError("async_staleness_poly must be >= 0")
    b = max(int(hinge_b), 0)
    cap = int(np.clip(int(cap), MIN_STALENESS_CAP, MAX_STALENESS_CAP))

    def fn(staleness) -> np.ndarray:
        s = np.clip(np.asarray(staleness, np.float64), 0.0, float(cap))
        if kind == "constant":
            w = np.ones_like(s)
        elif kind == "polynomial":
            w = (1.0 + s) ** (-a)
        else:  # hinge (np.where evaluates both branches: clamp the
            # denominator so s <= b entries can't divide by <= 0)
            w = np.where(s <= b, 1.0,
                         1.0 / np.maximum(a * (s - b) + 1.0, 1e-9))
        return np.asarray(w, np.float32)

    return fn


def _num_knob(args, name: str, default: float) -> float:
    """Numeric knob with an EXPLICIT absence check: 0 is a legitimate
    value for most async knobs (poly_a=0 = no decay, alpha=0 = frozen
    control, hinge_b=0 = decay from the first stale version), so the
    usual ``or default`` idiom would silently revert it."""
    v = getattr(args, name, None)
    return float(default if v is None else v)


def weighting_knobs_from_args(args):
    """(kind, poly_a, hinge_b) — the one reading shared by every async
    surface (engine, cross-silo server, SP toy), including the adaptive
    staleness-cap rebuilds."""
    kind = str(getattr(args, "async_staleness_weighting", None)
               or "polynomial").lower()
    return (kind, _num_knob(args, "async_staleness_poly", 0.5),
            int(_num_knob(args, "async_hinge_b", 4)))


def staleness_fn_from_args(args) -> Callable[[np.ndarray], np.ndarray]:
    """The ``async_staleness_*`` knobs, read once (see arguments.py)."""
    kind, poly_a, hinge_b = weighting_knobs_from_args(args)
    return make_staleness_fn(kind=kind, poly_a=poly_a, hinge_b=hinge_b,
                             cap=staleness_cap_from_args(args))


def staleness_cap_from_args(args) -> int:
    """Static staleness cap; ``async_staleness_cap: 0`` means adaptive
    (:func:`adaptive_staleness_cap` re-derives it each pour) — callers
    still need a concrete starting value, which is the default 16."""
    cap = int(getattr(args, "async_staleness_cap", 16) or 0)
    return int(np.clip(cap if cap > 0 else 16,
                       MIN_STALENESS_CAP, MAX_STALENESS_CAP))


def merge_alpha_from_args(args) -> float:
    """The FedAsync mixing rate: the poured aggregate is applied scaled by
    ``alpha * (sample-weighted mean staleness weight)``. 0 is honored (a
    frozen-server control config), absent means the 0.6 default."""
    return _num_knob(args, "async_alpha", 0.6)


def pour_weights(weights, staleness, fn: Callable[[np.ndarray], np.ndarray],
                 alpha: float) -> Tuple[np.ndarray, float]:
    """Combine per-update sample weights with staleness decay.

    Returns ``(norm_w [K], merge_scale)``: ``norm_w`` sums to 1 (the
    relative mix WITHIN the pour — staler updates count for less against
    their peers), ``merge_scale = alpha * Σ(w·s)/Σ(w)`` is the absolute
    damping of the applied aggregate (an all-fresh pour applies
    ``alpha · Δ``, an all-stale pour a proportionally smaller step). The
    split matters: folding staleness only into the relative mix would let
    a pour of uniformly ancient updates move the model at full rate."""
    w = np.asarray(weights, np.float64)
    s = np.asarray(fn(staleness), np.float64)
    cw = w * s
    denom = max(float(np.sum(cw)), 1e-12)
    norm_w = np.asarray(cw / denom, np.float32)
    merge_scale = float(alpha) * float(np.sum(cw)) / max(float(np.sum(w)),
                                                         1e-12)
    return norm_w, merge_scale


def adaptive_staleness_cap(latencies_s, pour_interval_s: float,
                           lo: int = 2, hi: int = 64) -> int:
    """Derive the staleness cap from OBSERVED arrival behavior
    (``async_staleness_cap: 0``): the slowest client's latency divided by
    the mean pour interval is how many versions its uploads lag — cap a
    bit above that so routine stragglers keep full decay resolution while
    a wedged client's eventual redemption still saturates. Fed by the
    selection store's arrival-rate posteriors (PR 5) on both the TPU
    engine and the cross-silo server."""
    lat = np.asarray(latencies_s, np.float64)
    lat = lat[np.isfinite(lat) & (lat > 0.0)]
    if lat.size == 0 or not np.isfinite(pour_interval_s) \
            or pour_interval_s <= 0.0:
        return int(hi)
    worst = float(np.max(lat))
    cap = int(np.ceil(worst / pour_interval_s)) + 1
    return int(np.clip(cap, lo, hi))
