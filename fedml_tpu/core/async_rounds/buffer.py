"""UpdateBuffer — staleness-tagged client updates awaiting a pour.

The server-side half of buffered-async rounds: producers (the TPU engine's
arrival simulation, the cross-silo upload handler) ``add`` updates as they
arrive; whenever ``ready()`` (>= K buffered) the owner ``pour``s — there is
no round barrier anywhere. Entries carry the model version the client was
DISPATCHED with, so staleness at pour time is ``current_version -
entry.version``: an honest per-update number, not a cohort-level guess.

The buffer is deliberately agnostic about what an ``update`` is (the TPU
engine stores device ``[D]`` vectors, the cross-silo server host NumPy
vectors, tests plain floats) — it owns ordering, capacity, staleness
arithmetic, and fixed-shape persistence, nothing else.

Persistence: ``state_dict`` pads the entries to ``capacity_k`` rows with a
validity mask so the checkpoint template shape never depends on how full
the buffer happened to be at the save — that is what lets the async server
state ride :class:`~fedml_tpu.core.checkpoint.RoundCheckpointer` (orbax
restores against a fixed template) and crash-resume replay identical pours.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np


@dataclass
class BufferedUpdate:
    """One arrived client update, staleness-tagged."""

    client_id: int
    update: Any            # opaque payload (device vec / np vec / model)
    weight: float          # sample weight (n_k)
    version: int           # model version the client trained FROM
    arrival_t: float       # arrival timestamp (simulated or wall clock)
    seq: int = 0           # arrival tiebreaker: total order even at equal t
    # trace context of the producing upload/dispatch span (core/obs):
    # the pour span LINKS every poured entry's context, staleness per
    # link. Observability only — not persisted (a crash-resumed pour
    # replays identical math, just without links to pre-crash spans).
    trace: Any = None

    def staleness(self, current_version: int) -> int:
        return max(int(current_version) - int(self.version), 0)


class UpdateBuffer:
    """FIFO-by-arrival buffer of at most ``2 * capacity_k`` updates (a
    pour drains ``capacity_k``; the slack absorbs a burst of arrivals
    between the trigger and the pour without dropping anyone — beyond
    that, the OLDEST entries pour first anyway so the bound never drops a
    fresh update). Thread-safe: the cross-silo server adds from transport
    threads while the pour runs on another."""

    def __init__(self, capacity_k: int):
        self.k = int(capacity_k)
        if self.k < 1:
            raise ValueError("async_buffer_k must be >= 1")
        # staleness CLAMPING deliberately lives in the weighting fn, not
        # here: the buffer tags versions, the decay interprets them
        self._entries: List[BufferedUpdate] = []
        self._seq = 0
        self._added = 0
        self._poured = 0
        self._lock = threading.Lock()

    # --- producers ----------------------------------------------------------
    def add(self, client_id: int, update: Any, weight: float, version: int,
            arrival_t: float, trace: Any = None) -> BufferedUpdate:
        with self._lock:
            e = BufferedUpdate(int(client_id), update, float(weight),
                               int(version), float(arrival_t), self._seq,
                               trace)
            self._seq += 1
            self._added += 1
            self._entries.append(e)
            # arrival order is the pour order; seq breaks exact-time ties
            # so a rerun with the same trace pours identically
            self._entries.sort(key=lambda x: (x.arrival_t, x.seq))
            return e

    # --- consumers ----------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def ready(self) -> bool:
        return len(self) >= self.k

    def pour(self, current_version: int,
             max_n: Optional[int] = None) -> List[BufferedUpdate]:
        """Drain the oldest ``min(len, max_n or k)`` entries in arrival
        order. Staleness is computed against ``current_version`` and
        CLAMPED to the cap by the weighting fn downstream — entries are
        never discarded for age (down-weighted, not dropped)."""
        n = self.k if max_n is None else int(max_n)
        with self._lock:
            take, self._entries = self._entries[:n], self._entries[n:]
            self._poured += len(take)
        return take

    # --- accounting (the soak test's ledger-balance assertion) --------------
    @property
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {"added": self._added, "poured": self._poured,
                    "buffered": len(self._entries)}

    # --- persistence --------------------------------------------------------
    def state_dict(self, encode: Callable[[Any], np.ndarray],
                   pad_rows: Optional[int] = None,
                   vec_dim: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Fixed-shape snapshot: ``encode`` maps each opaque update payload
        to a 1-D f32 vector (all the same length); rows are padded to
        ``pad_rows`` (default ``2 * k``, the buffer's hard bound) with a
        validity mask. Pass ``vec_dim`` so an EMPTY buffer still snapshots
        at the template's [rows, d] shape (orbax restores against a fixed
        template built from a fresh, empty instance)."""
        with self._lock:
            entries = list(self._entries)
            seq, added, poured = self._seq, self._added, self._poured
        rows = int(pad_rows) if pad_rows is not None else 2 * self.k
        if len(entries) > rows:
            raise ValueError(f"buffer holds {len(entries)} > pad_rows "
                             f"{rows} entries")
        vecs = [np.asarray(encode(e.update), np.float32) for e in entries]
        d = int(vec_dim) if vec_dim is not None else (
            vecs[0].shape[0] if vecs else 0)
        mat = np.zeros((rows, d), np.float32)
        for i, v in enumerate(vecs):
            mat[i] = v
        meta = np.zeros((rows, 5), np.float64)  # cid, weight, version, t, seq
        for i, e in enumerate(entries):
            meta[i] = (e.client_id, e.weight, e.version, e.arrival_t, e.seq)
        return {"mat": mat,
                "meta": meta,
                "mask": np.asarray([1.0] * len(entries)
                                   + [0.0] * (rows - len(entries)),
                                   np.float32),
                "counters": np.asarray([seq, added, poured], np.int64)}

    def load_state_dict(self, state: Dict[str, np.ndarray],
                        decode: Callable[[np.ndarray], Any]) -> None:
        mask = np.asarray(state["mask"], np.float32)
        meta = np.asarray(state["meta"], np.float64)
        mat = np.asarray(state["mat"], np.float32)
        ctr = np.asarray(state["counters"], np.int64)
        with self._lock:
            self._entries = []
            for i in range(mask.shape[0]):
                if mask[i] <= 0.0:
                    continue
                cid, w, ver, t, seq = meta[i]
                self._entries.append(BufferedUpdate(
                    int(cid), decode(mat[i]), float(w), int(ver), float(t),
                    int(seq)))
            self._entries.sort(key=lambda x: (x.arrival_t, x.seq))
            self._seq, self._added, self._poured = (int(ctr[0]), int(ctr[1]),
                                                    int(ctr[2]))
