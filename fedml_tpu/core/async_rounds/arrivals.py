"""Seeded client-arrival model — the clock the async benches run on.

Async federated rounds only beat the barrier when clients ARRIVE at
different times, so the simulators need a latency model. This is the one
shared definition (the SP ``async_fedavg`` toy and the TPU engine's
``async_buffered`` mode both draw from it): heterogeneous per-client base
durations, lognormal around 1.0 (the toy's historical distribution), drawn
from the PR 5 seeded sampling stream ``default_rng((random_seed, tag))`` —
a pure function of the seed, so two processes (or a crash-resumed run)
agree on every client's speed with zero coordination, and different seeds
actually produce different speed profiles (the old toy-local RandomState
respected the seed but lived outside the shared stream discipline).

Chaos maps onto arrivals the only way that makes sense for async:

* a STRAGGLER does its FULL local work, slowly — duration is divided by
  its work fraction (half-speed straggler = 2x duration). (The sync
  barrier path instead truncates local work via ``sched_work`` — there
  the round ends on the barrier regardless; here time IS the fault.)
* a DROPPED client never arrives — its update is lost and the client
  returns to the idle pool after its duration elapses (the reconnect /
  redemption event).
"""

from __future__ import annotations

import numpy as np

# domain-separation tag for the duration stream (arbitrary, distinct from
# the chaos plan's tags and the sampling streams' (seed, round) tuples)
_DURATION_TAG = 977


def client_durations(num_clients: int, random_seed: int = 0,
                     sigma: float = 0.6) -> np.ndarray:
    """[n] per-client base round durations (simulated seconds):
    ``1 + LogNormal(0, sigma)`` — heterogeneous, strictly positive,
    heavy-tailed enough that arrival order is genuinely scrambled."""
    gen = np.random.default_rng((int(random_seed), _DURATION_TAG))
    return 1.0 + gen.lognormal(0.0, float(sigma), size=int(num_clients))


def durations_from_args(num_clients: int, args) -> np.ndarray:
    # sigma=0 is a legitimate control config (homogeneous client speeds),
    # so absence — not falsiness — selects the default
    sigma = getattr(args, "async_duration_sigma", None)
    return client_durations(
        num_clients, random_seed=int(getattr(args, "random_seed", 0) or 0),
        sigma=float(0.6 if sigma is None else sigma))


def faulted_duration(base_s: float, work_scale: float) -> float:
    """Arrival-time semantics of a chaos work fraction: full work at
    ``work_scale`` speed. ``work_scale == 0`` (dropped) returns the base
    duration — that is when the client REDEEMS (rejoins the idle pool),
    not when an update arrives."""
    ws = float(work_scale)
    if ws <= 0.0:
        return float(base_s)
    return float(base_s) / min(ws, 1.0)
