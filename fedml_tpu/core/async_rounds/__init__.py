"""Buffered-async federated rounds (FedBuff + FedAsync staleness decay).

The pieces every async path composes:

* :mod:`.weighting` — staleness -> weight families (constant / polynomial
  / hinge), the pour's (relative mix, absolute merge scale) split, and the
  adaptive staleness cap driven by observed arrival rates.
* :mod:`.buffer` — the staleness-tagged :class:`UpdateBuffer` with
  fixed-shape checkpoint persistence.
* :mod:`.arrivals` — the seeded client-latency model the simulated async
  clock runs on (shared by the SP toy and the TPU engine).

Consumers: ``simulation/tpu/async_engine.py`` (``round_mode:
async_buffered``), ``cross_silo/server/async_server.py``,
``simulation/sp/async_fedavg.py``.
"""

from .arrivals import client_durations, durations_from_args, faulted_duration
from .buffer import BufferedUpdate, UpdateBuffer
from .weighting import (MAX_STALENESS_CAP, MIN_STALENESS_CAP,
                        STALENESS_WEIGHTINGS, adaptive_staleness_cap,
                        make_staleness_fn, merge_alpha_from_args,
                        pour_weights, staleness_cap_from_args,
                        staleness_fn_from_args, weighting_knobs_from_args)

ROUND_MODES = ("sync", "async_buffered")


def round_mode_from_args(args) -> str:
    mode = str(getattr(args, "round_mode", "sync") or "sync").lower()
    if mode not in ROUND_MODES:
        raise ValueError(f"round_mode {mode!r} unknown; choose from "
                         f"{ROUND_MODES}")
    return mode


def buffer_k_from_args(args, concurrency: int) -> int:
    """``async_buffer_k`` (0 = half the in-flight cohort, FedBuff's usual
    regime), clamped to the concurrency — a K no cohort can fill would
    deadlock the pour trigger."""
    k = int(getattr(args, "async_buffer_k", 0) or 0)
    if k <= 0:
        k = max(int(concurrency) // 2, 1)
    if k > int(concurrency):
        raise ValueError(
            f"async_buffer_k ({k}) exceeds the in-flight cohort "
            f"({concurrency}): the pour trigger could never fire")
    return k

__all__ = [
    "BufferedUpdate", "UpdateBuffer", "ROUND_MODES",
    "STALENESS_WEIGHTINGS", "MIN_STALENESS_CAP", "MAX_STALENESS_CAP",
    "adaptive_staleness_cap", "buffer_k_from_args", "client_durations",
    "durations_from_args", "faulted_duration", "make_staleness_fn",
    "merge_alpha_from_args", "pour_weights", "round_mode_from_args",
    "staleness_cap_from_args", "staleness_fn_from_args",
    "weighting_knobs_from_args",
]
