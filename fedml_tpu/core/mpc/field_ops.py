"""Finite-field arithmetic for secure aggregation, TPU-friendly.

Parity target: the field math under reference ``core/mpc/secagg.py`` (prime
field, quantization ``transform_tensor_to_finite`` :351, Shamir/BGW/LCC
coding). The reference computes in int64 numpy; TPUs have no fast int64, so
(SURVEY §7 hard parts) everything here is designed for **uint32 lanes with
p = 2^31 - 1** (Mersenne):

* add/sub fit uint32 with one conditional subtract;
* multiply uses 16-bit limb decomposition + the Mersenne fold 2^31 ≡ 1 (mod p),
  so all intermediates stay below 2^32 — jit-able on TPU;
* host-side helpers use numpy uint64 where convenience wins (share
  generation, Lagrange coefficients — tiny data).

The masking data path (quantize -> add masks -> sum -> dequantize) is pure
jnp/uint32 and can run inside the jitted round.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

P = np.uint32(2**31 - 1)  # Mersenne prime 2147483647
_P_I = int(P)


# ---------------------------------------------------------------------------
# jnp (TPU) path — uint32 lanes
# ---------------------------------------------------------------------------

def ff_add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a + b) mod p for a, b in [0, p). Sum < 2^32 so uint32 wraps are
    impossible; one conditional subtract reduces."""
    s = a.astype(jnp.uint32) + b.astype(jnp.uint32)
    return jnp.where(s >= _P_I, s - _P_I, s)


def ff_neg(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(a == 0, a, _P_I - a.astype(jnp.uint32))


def ff_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return ff_add(a, ff_neg(b))


def _fold31(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce a uint32 value < 2^32 mod p via the Mersenne identity
    x = (x >> 31) + (x & (2^31-1)) (mod p)."""
    y = (x >> 31) + (x & _P_I)
    return jnp.where(y >= _P_I, y - _P_I, y)


def ff_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a * b) mod p with all intermediates < 2^32.

    Split a = ah*2^16 + al (ah < 2^15, al < 2^16) and fold partial products:
        a*b = ah*b*2^16 + al*b (mod p)
    Each partial product is itself computed by splitting b the same way, and
    powers of two are folded with 2^31 ≡ 1 (mod p).
    """
    a = a.astype(jnp.uint32)
    b = b.astype(jnp.uint32)
    ah, al = a >> 16, a & 0xFFFF
    bh, bl = b >> 16, b & 0xFFFF
    # partial products, each < 2^31 (15+16 or 16+16 bits)
    hh = _fold31(ah * bh)          # * 2^32 ≡ * 2 (mod p)
    hl = _fold31(ah * bl)          # * 2^16
    lh = _fold31(al * bh)          # * 2^16
    ll = _fold31(al * bl)          # * 1
    # t16 = (hl + lh) * 2^16 (mod p), computed exactly:
    # t*2^16 = (t >> 15) * 2^31 + (t & 0x7FFF) * 2^16
    #        ≡ (t >> 15) + ((t & 0x7FFF) << 16)   (mod p)
    t = ff_add(hl, lh)
    t16 = ff_add(t >> 15, (t & 0x7FFF) << 16)
    # hh * 2^32 ≡ hh * 2  (mod p)
    h2 = ff_add(hh, hh)
    return ff_add(h2, ff_add(t16, ll))


def ff_random(rng: jax.Array, shape: Tuple[int, ...]) -> jnp.ndarray:
    """Uniform field elements in [0, p) — rejection-free: draw 32 bits and
    fold (bias 2^-31, negligible for masking)."""
    bits = jax.random.bits(rng, shape, dtype=jnp.uint32)
    return _fold31(bits)


# ---------------------------------------------------------------------------
# quantization: float tree <-> field vector
# ---------------------------------------------------------------------------

def quantize(x: jnp.ndarray, frac_bits: int = 16) -> jnp.ndarray:
    """Signed float -> field element (reference
    ``transform_tensor_to_finite`` semantics): q = round(x * 2^frac_bits),
    negatives represented as p - |q|."""
    scaled = jnp.round(x.astype(jnp.float32) * (2.0 ** frac_bits))
    # clip to +-2^29 so sums of many clients stay decodable
    lim = 2.0 ** 29
    scaled = jnp.clip(scaled, -lim, lim)
    pos = scaled >= 0
    mag = jnp.abs(scaled).astype(jnp.uint32)
    return jnp.where(pos, mag, (_P_I - mag).astype(jnp.uint32))


def dequantize(q: jnp.ndarray, frac_bits: int = 16) -> jnp.ndarray:
    """Field element -> signed float; values above p/2 are negative.

    The signed value is computed in int32 (exact — both branches are
    < 2^30) before the float conversion; converting the raw ~2^31 uint32 to
    float32 first would round away up to 7 low bits."""
    q = q.astype(jnp.uint32)
    neg = q > (_P_I // 2)
    mag = jnp.where(neg, (_P_I - q).astype(jnp.int32),
                    q.astype(jnp.int32))
    signed = jnp.where(neg, -mag, mag).astype(jnp.float32)
    return signed / (2.0 ** frac_bits)


# ---------------------------------------------------------------------------
# host (numpy uint64) path — coding math on small arrays
# ---------------------------------------------------------------------------

def np_mod(x) -> np.ndarray:
    return np.asarray(x, dtype=np.uint64) % np.uint64(_P_I)


def np_mul(a, b) -> np.ndarray:
    return (np.asarray(a, np.uint64) * np.asarray(b, np.uint64)) % np.uint64(_P_I)


def np_add(a, b) -> np.ndarray:
    return (np.asarray(a, np.uint64) + np.asarray(b, np.uint64)) % np.uint64(_P_I)


def np_sub(a, b) -> np.ndarray:
    return (np.asarray(a, np.uint64) + np.uint64(_P_I)
            - np.asarray(b, np.uint64) % np.uint64(_P_I)) % np.uint64(_P_I)


def np_pow(base: int, exp: int) -> int:
    return pow(int(base), int(exp), _P_I)


def np_inv(a: Union[int, np.ndarray]):
    """Modular inverse by Fermat's little theorem (p prime)."""
    if np.isscalar(a) or np.asarray(a).ndim == 0:
        return np_pow(int(a), _P_I - 2)
    flat = [np_pow(int(v), _P_I - 2) for v in np.asarray(a).ravel()]
    return np.asarray(flat, np.uint64).reshape(np.asarray(a).shape)


def lagrange_coeffs_at(xs: np.ndarray, x0: int = 0) -> np.ndarray:
    """Lagrange basis coefficients l_i(x0) over the field for interpolation
    points ``xs`` (used by Shamir reconstruction and LCC decoding)."""
    xs = np.asarray(xs, np.uint64)
    n = len(xs)
    out = np.zeros(n, np.uint64)
    for i in range(n):
        num, den = 1, 1
        for j in range(n):
            if j == i:
                continue
            num = (num * ((x0 - int(xs[j])) % _P_I)) % _P_I
            den = (den * ((int(xs[i]) - int(xs[j])) % _P_I)) % _P_I
        out[i] = (num * np_pow(den, _P_I - 2)) % _P_I
    return out
