"""Secure aggregation (SecAgg) primitives — Bonawitz et al. style.

Parity target: reference ``core/mpc/secagg.py`` (395 LoC: ``model_masking``
:83, ``BGW_encoding/decoding`` :164/:192, ``LCC_encoding/decoding``
:213/:297, ``transform_tensor_to_finite`` :351) re-designed for TPU
(SURVEY §7: requantized to p = 2^31 - 1 with uint32 lanes; the reference
uses int64 numpy).

Components:
* Shamir secret sharing over GF(p) (= the BGW encode/decode the reference
  uses for mask-seed shares);
* pairwise + self masks expanded from seeds with a counter-based PRG
  (deterministic, so a dropped client's masks can be re-expanded after its
  seed is reconstructed from shares);
* the jit-able masking data path: quantize -> add masks (uint32 mod p) ->
  sum -> unmask -> dequantize.

The wire protocol (advertise keys, share seeds, masked input, unmask) lives
in ``cross_silo/secagg``; this module is the math.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .field_ops import (P, dequantize, ff_add, ff_neg, ff_random, ff_sub,
                        lagrange_coeffs_at, np_add, np_mul, quantize)

_P_I = int(P)


# ---------------------------------------------------------------------------
# Shamir secret sharing over GF(p)  (reference BGW_encoding/decoding)
# ---------------------------------------------------------------------------

def shamir_share(secret: int, n_shares: int, threshold: int,
                 rng: np.random.RandomState) -> List[Tuple[int, int]]:
    """Split ``secret`` into ``n_shares`` points of a degree-(threshold-1)
    polynomial; any ``threshold`` shares reconstruct."""
    coeffs = [int(secret) % _P_I] + [int(rng.randint(0, _P_I))
                                     for _ in range(threshold - 1)]
    shares = []
    for xi in range(1, n_shares + 1):
        acc = 0
        for c in reversed(coeffs):  # Horner
            acc = (acc * xi + c) % _P_I
        shares.append((xi, acc))
    return shares


def shamir_reconstruct(shares: Sequence[Tuple[int, int]]) -> int:
    xs = np.asarray([s[0] for s in shares])
    ys = np.asarray([s[1] for s in shares], np.uint64)
    lag = lagrange_coeffs_at(xs, 0)
    return int(np.sum(np_mul(lag, ys) % np.uint64(_P_I)) % _P_I)


# ---------------------------------------------------------------------------
# PRG mask expansion (counter-based, deterministic per seed)
# ---------------------------------------------------------------------------

def expand_mask(seed: int, length: int) -> np.ndarray:
    """Expand a seed (any width up to 256 bits — field element or the
    128-bit seeds from ``channels``) into ``length`` field elements.
    SHA-256 counter mode — deterministic across hosts, no RNG-state
    coupling."""
    out = np.empty(length, np.uint32)
    n_blocks = -(-length // 8)  # 8 uint32 per 32-byte digest
    buf = np.empty(n_blocks * 8, np.uint32)
    sbytes = int(seed).to_bytes(32, "little")
    for b in range(n_blocks):
        d = hashlib.sha256(sbytes + b.to_bytes(4, "little")).digest()
        buf[b * 8:(b + 1) * 8] = np.frombuffer(d, np.uint32)
    out[:] = buf[:length] % np.uint32(_P_I)
    return out


def pairwise_seed(secret_i: int, public_j: int) -> int:
    """Symmetric pairwise seed derived from i's secret and j's public key.
    Stand-in for the ECDH agreement of full SecAgg (no crypto backend in
    this environment); the *protocol* shape is identical."""
    lo, hi = sorted((int(secret_i), int(public_j)))
    d = hashlib.sha256(f"{lo}:{hi}".encode()).digest()
    return int.from_bytes(d[:8], "little") % _P_I


# ---------------------------------------------------------------------------
# jit-able masking data path
# ---------------------------------------------------------------------------

def mask_vector(quantized: jnp.ndarray, self_mask: jnp.ndarray,
                pair_masks_add: jnp.ndarray,
                pair_masks_sub: jnp.ndarray) -> jnp.ndarray:
    """masked = q + b_i + sum_{j>i} s_ij - sum_{j<i} s_ji  (mod p)."""
    return ff_add(ff_add(quantized, self_mask),
                  ff_sub(pair_masks_add, pair_masks_sub))


def sum_mod_p(masked: jnp.ndarray) -> jnp.ndarray:
    """Sum a [K, D] uint32 matrix mod p without overflow: split into 16-bit
    limbs, sum in uint32 (safe for K < 2^16), recombine with the Mersenne
    identity 2^31 ≡ 1 -> 2^16*hi_sum folds into (hi_sum >> 15) + ((hi_sum &
    0x7fff) << 16)."""
    lo = jnp.sum(masked & 0xFFFF, axis=0, dtype=jnp.uint32)
    hi = jnp.sum(masked >> 16, axis=0, dtype=jnp.uint32)

    def fold(x):
        y = (x >> 31) + (x & _P_I)
        return jnp.where(y >= _P_I, y - _P_I, y)

    hi16 = ff_add(hi >> 15, (hi & 0x7FFF) << 16)
    return ff_add(fold(lo), hi16)


# ---------------------------------------------------------------------------
# whole-protocol simulation helpers (used by tests and the in-process
# cross-silo SecAgg runtime)
# ---------------------------------------------------------------------------

class SecAggClient:
    """One client's SecAgg state across the four protocol rounds."""

    def __init__(self, cid: int, n_clients: int, threshold: int, seed: int):
        self.cid = cid
        self.n = n_clients
        self.t = threshold
        rng = np.random.RandomState(seed)
        self.secret_key = int(rng.randint(0, _P_I))
        self.public_key = self.secret_key  # stand-in DH (see pairwise_seed)
        self.self_seed = int(rng.randint(0, _P_I))
        self._rng = rng
        self.peer_publics: Dict[int, int] = {}

    # round 1: advertise keys -> server broadcasts
    def receive_publics(self, publics: Dict[int, int]) -> None:
        self.peer_publics = dict(publics)

    # round 2: share self_seed and secret_key via Shamir
    def make_shares(self) -> Dict[int, Tuple[Tuple[int, int], Tuple[int, int]]]:
        seed_shares = shamir_share(self.self_seed, self.n, self.t, self._rng)
        key_shares = shamir_share(self.secret_key, self.n, self.t, self._rng)
        return {j: (seed_shares[j], key_shares[j]) for j in range(self.n)}

    # round 3: masked input
    def masked_update(self, vec: np.ndarray) -> np.ndarray:
        d = len(vec)
        q = np.asarray(quantize(jnp.asarray(vec)))
        total = expand_mask(self.self_seed, d).astype(np.uint64)
        for j, pub in self.peer_publics.items():
            if j == self.cid:
                continue
            s = expand_mask(pairwise_seed(self.secret_key, pub), d).astype(np.uint64)
            if self.cid < j:
                total = (total + s) % _P_I
            else:
                total = (total + _P_I - s) % _P_I
        return ((q.astype(np.uint64) + total) % _P_I).astype(np.uint32)


def secagg_unmask(
    masked_sum: np.ndarray,
    surviving: Sequence[int],
    dropped: Sequence[int],
    self_seed_shares: Dict[int, List[Tuple[int, int]]],
    secret_key_shares: Dict[int, List[Tuple[int, int]]],
    publics: Dict[int, int],
    length: int,
) -> np.ndarray:
    """Server-side unmasking: subtract surviving clients' self masks
    (reconstructed from their seed shares) and cancel dropped clients'
    pairwise masks (reconstructed from their key shares)."""
    total = masked_sum.astype(np.uint64)
    for i in surviving:
        seed = shamir_reconstruct(self_seed_shares[i])
        total = (total + _P_I - expand_mask(seed, length).astype(np.uint64)) % _P_I
    for i in dropped:
        sk = shamir_reconstruct(secret_key_shares[i])
        for j in surviving:
            s = expand_mask(pairwise_seed(sk, publics[j]), length).astype(np.uint64)
            if i < j:   # i added +s_ij into its (lost) contribution — but i
                # dropped, so the *surviving* j subtracted/added the
                # counterpart; cancel j's leftover term
                total = (total + s) % _P_I
            else:
                total = (total + _P_I - s) % _P_I
    return total.astype(np.uint32)
