"""LightSecAgg — one-shot aggregate-mask reconstruction via Lagrange coding.

Parity target: reference ``core/mpc/lightsecagg.py`` (205 LoC: mask encoding
``mask_encoding``, aggregate decoding ``aggregate_models_in_finite``) and the
LCC primitives of ``core/mpc/secagg.py:213-297``, requantized to
p = 2^31 - 1 (TPU-friendly field, see ``field_ops``).

Protocol shape (So et al.): each client encodes its random mask z_i into n
coded sub-masks via a Lagrange (MDS) code and distributes them; every
surviving client sends the *sum* of the coded sub-masks it holds; the server
interpolates the aggregate polynomial from any T+D surviving responses and
recovers sum_i z_i in one shot — no per-dropout reconstruction round like
SecAgg.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .field_ops import P, lagrange_coeffs_at, np_mul

_P_I = int(P)


def _eval_points(n: int, t: int, d: int):
    """Interpolation points: betas (data) then gammas (privacy padding),
    alphas (client share points) — all distinct, nonzero."""
    betas = np.arange(1, t + 1, dtype=np.uint64)
    gammas = np.arange(t + 1, t + d + 1, dtype=np.uint64)
    alphas = np.arange(t + d + 1, t + d + 1 + n, dtype=np.uint64)
    return betas, gammas, alphas


def _coding_matrix(src_pts: np.ndarray, dst_pts: np.ndarray) -> np.ndarray:
    """[len(dst), len(src)] Lagrange evaluation matrix over GF(p):
    row j = basis coefficients l_k(dst_j) on the src points."""
    rows = [lagrange_coeffs_at(src_pts, int(x)) for x in dst_pts]
    return np.stack(rows).astype(np.uint64)


def _mat_vec_mod(m: np.ndarray, v: np.ndarray) -> np.ndarray:
    """[R, S] x [S, L] mod p with uint64 intermediates (products < 2^62)."""
    out = np.zeros((m.shape[0], v.shape[1]), np.uint64)
    for k in range(m.shape[1]):
        out = (out + np_mul(m[:, k:k + 1], v[k:k + 1, :])) % _P_I
    return out


def mask_encoding(
    z: np.ndarray, n_clients: int, privacy_t: int, split_t: int,
    rng: np.random.RandomState,
) -> np.ndarray:
    """Encode a client's mask ``z`` (length d, field elements) into
    ``n_clients`` coded sub-masks of length d/split_t.

    z is split into ``split_t`` sub-vectors (polynomial values at the betas),
    padded with ``privacy_t`` random sub-vectors (values at the gammas — the
    privacy guarantee), and evaluated at each client's alpha.
    Returns [n_clients, d // split_t].
    """
    d = len(z)
    assert d % split_t == 0, "mask length must divide split_t"
    sub = z.reshape(split_t, d // split_t).astype(np.uint64)
    pad = rng.randint(0, _P_I, size=(privacy_t, d // split_t)).astype(np.uint64)
    data = np.concatenate([sub, pad], axis=0)
    betas, gammas, alphas = _eval_points(n_clients, split_t, privacy_t)
    src = np.concatenate([betas, gammas])
    enc = _coding_matrix(src, alphas)        # [n, split_t + privacy_t]
    return _mat_vec_mod(enc, data)           # [n, d // split_t]


def aggregate_encoded(shares: Sequence[np.ndarray]) -> np.ndarray:
    """Each surviving client sums the coded sub-masks it received (one per
    mask owner) — a single field addition."""
    acc = np.zeros_like(shares[0], dtype=np.uint64)
    for s in shares:
        acc = (acc + s.astype(np.uint64)) % _P_I
    return acc


def decode_aggregate_mask(
    responses: Sequence[np.ndarray], responders: Sequence[int],
    n_clients: int, privacy_t: int, split_t: int, d: int,
) -> np.ndarray:
    """Interpolate sum_i f_i at the betas from >= split_t + privacy_t
    surviving responses; returns the aggregate mask sum_i z_i (length d)."""
    need = split_t + privacy_t
    assert len(responses) >= need, "not enough responders to decode"
    betas, gammas, alphas = _eval_points(n_clients, split_t, privacy_t)
    pts = np.asarray([alphas[r] for r in responders[:need]], np.uint64)
    vals = np.stack([responses[i] for i in range(need)]).astype(np.uint64)
    dec = _coding_matrix(pts, betas)         # [split_t, need]
    sub = _mat_vec_mod(dec, vals)            # [split_t, d // split_t]
    return sub.reshape(d)


def lcc_encode(data: np.ndarray, n_out: int, privacy_t: int,
               rng: np.random.RandomState) -> np.ndarray:
    """General Lagrange-coded-computing encode (reference ``LCC_encoding``):
    [T, L] data sub-blocks -> [n_out, L] coded blocks."""
    t = data.shape[0]
    return mask_encoding(data.reshape(-1), n_out, privacy_t, t, rng)


def lcc_decode(coded: np.ndarray, points_idx: Sequence[int], t: int,
               n_clients: int, privacy_t: int) -> np.ndarray:
    """Inverse of :func:`lcc_encode` given any t + privacy_t coded blocks."""
    l = coded.shape[1]
    return decode_aggregate_mask(
        list(coded), list(points_idx), n_clients, privacy_t, t,
        t * l).reshape(t, l)
