"""Secure multi-party computation math (reference ``core/mpc/``): finite
field ops (TPU-friendly uint32 / Mersenne p = 2^31-1), Shamir/BGW secret
sharing, SecAgg masking, and LightSecAgg Lagrange-coded masks."""

from .field_ops import (P, dequantize, ff_add, ff_mul, ff_neg, ff_random,
                        ff_sub, quantize)
from .secagg import (SecAggClient, expand_mask, mask_vector, pairwise_seed,
                     secagg_unmask, shamir_reconstruct, shamir_share,
                     sum_mod_p)
from .lightsecagg import (aggregate_encoded, decode_aggregate_mask,
                          lcc_decode, lcc_encode, mask_encoding)

__all__ = ["P", "quantize", "dequantize", "ff_add", "ff_sub", "ff_neg",
           "ff_mul", "ff_random", "shamir_share", "shamir_reconstruct",
           "expand_mask", "pairwise_seed", "mask_vector", "sum_mod_p",
           "SecAggClient", "secagg_unmask", "mask_encoding",
           "aggregate_encoded", "decode_aggregate_mask", "lcc_encode",
           "lcc_decode"]
