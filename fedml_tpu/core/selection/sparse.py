"""Sparse/lazy client statistics — the million-client store backend.

The dense :class:`~fedml_tpu.core.selection.stats.ClientStatsStore`
allocates ``[N]`` NumPy state per signal and answers queries with
full-population reads — the right shape for 10–100 simulated clients or
silo ranks, five orders of magnitude wrong for a Beehive-scale
cross-device population (SURVEY §2.5). This backend keeps the SAME
observation/query API but materializes state only for *touched* clients:

* an id → row dict over **columnar** NumPy arrays that grow by
  amortized doubling (compaction keeps rows contiguous, so the
  vectorized query math is identical to the dense store's — same ops on
  the same dtypes);
* Beta/EMA posteriors exist only for observed ids; untouched ids answer
  with the exact dense-store defaults (work 1.0, loss +inf/NaN,
  reputation 1.0, the prior dropout mean, ``last_selected`` −1);
* pooled reductions (population dropout mean, the reputation cohort
  mean, Oort's RMS fill) run over observed rows in ascending-id order —
  the same canonical order the dense store now uses — so posteriors and
  therefore selections are **bit-identical** across backends given the
  same observations;
* an optional row ``capacity`` bounds memory on unbounded populations:
  a full table evicts the least-recently-touched row (deterministic
  given the observation order, so crash-resume still replays).

Checkpointing: ``state_dict`` emits the compacted columns plus the row
→ id map; ``load_state_dict`` accepts that layout OR a legacy **dense**
snapshot (``[N]`` arrays, no ``ids`` key), converting touched rows on
the fly — existing checkpoints stay restorable after a backend switch.
Orbax ``StandardRestore`` returns saved shapes even when the template's
row count differs (pinned by ``tests/test_population.py``), so the
growing columns ride :class:`~fedml_tpu.core.checkpoint.RoundCheckpointer`
unchanged.
"""

from __future__ import annotations

import logging
from typing import Dict, Iterable, Optional, Sequence

import numpy as np

from .stats import DROP_PRIOR_A, DROP_PRIOR_B, ClientStatsStore

logger = logging.getLogger(__name__)

_MIN_ROWS = 64


class SparseClientStatsStore:
    """Touched-client statistics over a population of ``n`` ids. Same
    observation/query API as :class:`ClientStatsStore`; cost scales with
    touched clients and query-batch size, never with ``n``."""

    def __init__(self, num_clients: int, loss_window: int = 8,
                 ema_alpha: float = 0.2,
                 drop_prior: tuple = (DROP_PRIOR_A, DROP_PRIOR_B),
                 capacity: int = 0):
        n = int(num_clients)
        if n <= 0:
            raise ValueError("SparseClientStatsStore needs a positive "
                             "population")
        self.n = n
        self.loss_window = max(int(loss_window), 1)
        self.ema_alpha = float(ema_alpha)
        self.drop_prior_a = float(drop_prior[0])
        self.drop_prior_b = float(drop_prior[1])
        # 0 = unbounded (rows track touched clients); > 0 caps rows with
        # least-recently-touched eviction
        self.capacity = max(int(capacity or 0), 0)
        self._index: Dict[int, int] = {}
        self._size = 0
        self._touch_clock = 0
        self._warned: set = set()
        # lazily-rebuilt sorted view for vectorized batch lookups
        # (np.searchsorted beats len(ids) dict gets by ~50x on the
        # chunked assembly scan); invalidated on any row insert/evict
        self._sorted_ids: Optional[np.ndarray] = None
        self._sorted_rows: Optional[np.ndarray] = None
        self._alloc(_MIN_ROWS if not self.capacity
                    else min(_MIN_ROWS, self.capacity))

    # --- row storage --------------------------------------------------------
    def _alloc(self, rows: int) -> None:
        w = self.loss_window
        self.ids = np.full(rows, -1, np.int64)
        self.last_touch = np.zeros(rows, np.int64)
        self.losses = np.zeros((rows, w), np.float32)
        self.loss_count = np.zeros(rows, np.int32)
        self.loss_ptr = np.zeros(rows, np.int32)
        self.ema_latency = np.zeros(rows, np.float32)
        self.has_latency = np.zeros(rows, np.float32)
        self.ema_interarrival = np.zeros(rows, np.float32)
        self.arr_obs = np.zeros(rows, np.float32)
        self.ema_work = np.ones(rows, np.float32)
        self.drop_obs = np.zeros(rows, np.float32)
        self.part_obs = np.zeros(rows, np.float32)
        self.incl_obs = np.zeros(rows, np.float32)
        self.excl_obs = np.zeros(rows, np.float32)
        self.times_selected = np.zeros(rows, np.int32)
        self.last_selected = np.full(rows, -1, np.int32)

    _COLUMNS = ("ids", "last_touch", "losses", "loss_count", "loss_ptr",
                "ema_latency", "has_latency", "ema_interarrival", "arr_obs",
                "ema_work", "drop_obs", "part_obs", "incl_obs", "excl_obs",
                "times_selected", "last_selected")

    def _grow(self) -> None:
        new_rows = max(len(self.ids) * 2, _MIN_ROWS)
        if self.capacity:
            new_rows = min(new_rows, self.capacity)
        for f in self._COLUMNS:
            cur = getattr(self, f)
            fresh = np.zeros((new_rows,) + cur.shape[1:], cur.dtype)
            if f == "ids":
                fresh[:] = -1
            elif f == "ema_work":
                fresh[:] = 1.0
            elif f == "last_selected":
                fresh[:] = -1
            fresh[:self._size] = cur[:self._size]
            setattr(self, f, fresh)

    def _reset_row(self, r: int, cid: int) -> None:
        self.ids[r] = cid
        self.last_touch[r] = 0
        self.losses[r] = 0.0
        self.loss_count[r] = 0
        self.loss_ptr[r] = 0
        self.ema_latency[r] = 0.0
        self.has_latency[r] = 0.0
        self.ema_interarrival[r] = 0.0
        self.arr_obs[r] = 0.0
        self.ema_work[r] = 1.0
        self.drop_obs[r] = 0.0
        self.part_obs[r] = 0.0
        self.incl_obs[r] = 0.0
        self.excl_obs[r] = 0.0
        self.times_selected[r] = 0
        self.last_selected[r] = -1

    def _row(self, client_id: int) -> int:
        """Row of ``client_id``, creating (and LRU-evicting at capacity)
        on first touch."""
        cid = int(client_id)
        r = self._index.get(cid)
        if r is None:
            if self.capacity and self._size >= self.capacity:
                # deterministic eviction: the least-recently-touched row
                # (ties broken by row order, which is insertion order)
                r = int(np.argmin(self.last_touch[:self._size]))
                del self._index[int(self.ids[r])]
                self._reset_row(r, cid)
            else:
                if self._size >= len(self.ids):
                    self._grow()
                r = self._size
                self._size += 1
                self.ids[r] = cid
            self._index[cid] = r
            self._sorted_ids = None  # membership changed
        self._touch_clock += 1
        self.last_touch[r] = self._touch_clock
        return r

    def _rows_for(self, ids: Sequence[int]) -> tuple:
        """(row index or -1 per id, found mask) — read-only vectorized
        lookup via the sorted view; no row creation, no eviction-clock
        advance."""
        ids = np.asarray(ids, np.int64)
        if self._size == 0:
            return np.full(len(ids), -1, np.int64), np.zeros(len(ids),
                                                             bool)
        if self._sorted_ids is None:
            present = self.ids[:self._size]
            order = np.argsort(present, kind="stable")
            self._sorted_ids = present[order]
            self._sorted_rows = order.astype(np.int64)
        pos = np.minimum(np.searchsorted(self._sorted_ids, ids),
                         len(self._sorted_ids) - 1)
        found = self._sorted_ids[pos] == ids
        rows = np.where(found, self._sorted_rows[pos], -1)
        return rows, found

    # --- observations (same contracts as the dense store) -------------------
    def record_selected(self, round_idx: int, ids: Sequence[int]) -> None:
        for cid in ids:
            r = self._row(cid)
            self.times_selected[r] += 1
            self.last_selected[r] = int(round_idx)

    def record_availability(self, client_id: int, participated: bool,
                            work: float = 1.0) -> None:
        r = self._row(client_id)
        if participated:
            self.part_obs[r] += 1.0
            a = self.ema_alpha
            self.ema_work[r] = (1.0 - a) * self.ema_work[r] + a * float(work)
        else:
            self.drop_obs[r] += 1.0

    def record_loss(self, client_id: int, loss: float) -> None:
        loss = float(loss)
        if not np.isfinite(loss):
            return
        r = self._row(client_id)
        p = int(self.loss_ptr[r])
        self.losses[r, p] = loss
        self.loss_ptr[r] = (p + 1) % self.loss_window
        self.loss_count[r] = self.loss_count[r] + 1

    def record_latency(self, client_id: int, latency_s: float) -> None:
        lat = float(latency_s)
        if not np.isfinite(lat) or lat < 0.0:
            return
        r = self._row(client_id)
        if self.has_latency[r] > 0:
            a = self.ema_alpha
            self.ema_latency[r] = (1.0 - a) * self.ema_latency[r] + a * lat
        else:
            self.ema_latency[r] = lat
            self.has_latency[r] = 1.0

    def record_arrival(self, client_id: int, interarrival_s: float) -> None:
        gap = float(interarrival_s)
        if not np.isfinite(gap) or gap <= 0.0:
            return
        r = self._row(client_id)
        if self.arr_obs[r] > 0:
            a = self.ema_alpha
            self.ema_interarrival[r] = ((1.0 - a) * self.ema_interarrival[r]
                                        + a * gap)
        else:
            self.ema_interarrival[r] = gap
        self.arr_obs[r] += 1.0

    def record_verdict(self, ids: Sequence[int],
                       verdict: Sequence[float]) -> None:
        ids = list(ids)
        v = np.clip(np.asarray(list(verdict), np.float32), 0.0, 1.0)
        if not ids or len(ids) != v.size:
            return
        for cid, vi in zip(ids, v):
            r = self._row(cid)
            self.incl_obs[r] += float(vi)
            self.excl_obs[r] += 1.0 - float(vi)

    # --- id-parameterized queries -------------------------------------------
    def last_loss_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        seen = found & (self.loss_count[r] > 0)
        idx = (self.loss_ptr[r] - 1) % self.loss_window
        last = self.losses[r, idx]
        return np.where(seen, last, np.inf).astype(np.float32)

    def rms_loss_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        k = np.where(found, np.minimum(self.loss_count[r],
                                       self.loss_window), 0)
        with np.errstate(invalid="ignore", divide="ignore"):
            ms = np.sum(self.losses[r] ** 2, axis=1) / np.maximum(k, 1)
        return np.where(k > 0, np.sqrt(ms), np.nan).astype(np.float32)

    def reputation_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        obs = np.where(found, self.incl_obs[r] + self.excl_obs[r], 0.0)
        raw = (1.0 + np.where(found, self.incl_obs[r], 0.0)) / (2.0 + obs)
        pop = self._reputation_pop_mean()
        if pop is None:
            return np.ones(len(raw), np.float32)
        rep = np.clip(raw / max(pop, 1e-9), 0.0, 1.0)
        return np.where(obs > 0, rep, 1.0).astype(np.float32)

    def _reputation_pop_mean(self) -> Optional[float]:
        s = self._size
        obs = self.incl_obs[:s] + self.excl_obs[:s]
        seen = obs > 0
        if not bool(np.any(seen)):
            return None
        # ascending-id order: the dense store's boolean-mask selection
        # walks ids ascending, so sorting here makes np.mean's pairwise
        # tree identical — the bit-parity contract
        order = np.argsort(self.ids[:s][seen], kind="stable")
        raw = ((1.0 + self.incl_obs[:s][seen]) / (2.0 + obs[seen]))[order]
        return float(np.mean(raw))

    def ema_work_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        return np.where(found, self.ema_work[r], 1.0).astype(np.float32)

    def latency_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        return np.where(found & (self.has_latency[r] > 0),
                        self.ema_latency[r], np.nan).astype(np.float32)

    def times_selected_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        return np.where(found, self.times_selected[r], 0).astype(np.int32)

    def last_selected_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        return np.where(found, self.last_selected[r], -1).astype(np.int32)

    def observed_rms_mean(self) -> float:
        s = self._size
        seen = self.loss_count[:s] > 0
        if not bool(np.any(seen)):
            return float("nan")
        ids = np.sort(self.ids[:s][seen])
        return float(np.mean(self.rms_loss_for(ids)))

    def observed_latency_median(self) -> float:
        s = self._size
        seen = self.has_latency[:s] > 0
        if not bool(np.any(seen)):
            return float("nan")
        return float(np.median(self.ema_latency[:s][seen]))

    def num_touched(self) -> int:
        return self._size

    def touched_ids(self) -> np.ndarray:
        """Ascending ids of ever-touched clients — O(size log size) on
        this backend (the row → id map IS the answer), never O(n)."""
        return np.sort(self.ids[:self._size].astype(np.int64))

    # --- pooled / whole-population queries ----------------------------------
    def dropout_posterior_mean(self,
                               ids: Optional[Iterable[int]] = None
                               ) -> np.ndarray:
        if ids is None:
            # the [n] materialization is the dense callers' surface; a
            # million-client caller passes ids
            self._warn_materialize("dropout_posterior_mean")
            ids = np.arange(self.n)
        rows, found = self._rows_for(list(ids))
        r = np.where(found, rows, 0)
        a = self.drop_prior_a + np.where(found, self.drop_obs[r], 0.0)
        b = self.drop_prior_b + np.where(found, self.part_obs[r], 0.0)
        return (a / (a + b)).astype(np.float32)

    def population_dropout_mean(self) -> float:
        s = self._size
        seen = (self.drop_obs[:s] > 0) | (self.part_obs[:s] > 0)
        order = np.argsort(self.ids[:s][seen], kind="stable")
        a = self.drop_prior_a + float(np.sum(self.drop_obs[:s][seen][order]))
        b = self.drop_prior_b + float(np.sum(self.part_obs[:s][seen][order]))
        return float(a / (a + b))

    def _warn_materialize(self, what: str) -> None:
        """Once per (store, query): whole-population reads exist for
        dense-API compatibility (the async engine's dispatch ranking)
        but defeat the sparse backend's point — say so, once, instead
        of spamming every dispatch."""
        if what not in self._warned:
            self._warned.add(what)
            logger.warning("%s materializes the full population (%d); "
                           "population-scale callers use the "
                           "id-parameterized queries", what, self.n)

    @property
    def reputation(self) -> np.ndarray:
        """[n] normalized inclusion posterior — dense-API compatibility
        read; materializes [n] (warned once)."""
        self._warn_materialize("reputation")
        return self.reputation_for(np.arange(self.n))

    def arrival_rate(self) -> np.ndarray:
        """[n] arrivals per unit time — the async engine's whole-
        population read; materializes [n] (warned once). Population-
        scale callers use :meth:`arrival_rate_for`."""
        self._warn_materialize("arrival_rate")
        return self.arrival_rate_for(np.arange(self.n))

    def last_loss(self) -> np.ndarray:
        """[n] most recent loss — dense-API compatibility read (the
        async dispatch ranking); materializes [n] (warned once)."""
        self._warn_materialize("last_loss")
        return self.last_loss_for(np.arange(self.n))

    def rms_loss(self) -> np.ndarray:
        """[n] RMS loss window — dense-API compatibility read;
        materializes [n] (warned once)."""
        self._warn_materialize("rms_loss")
        return self.rms_loss_for(np.arange(self.n))

    def predicted_staleness(self, pour_interval_s: float) -> np.ndarray:
        """[n] expected model-version lag (dense-store contract: NaN for
        never-observed clients); materializes [n]."""
        if not np.isfinite(pour_interval_s) or pour_interval_s <= 0.0:
            return np.full(self.n, np.nan, np.float32)
        rows, found = self._rows_for(np.arange(self.n))
        r = np.where(found, rows, 0)
        out = self.ema_interarrival[r] / np.float32(pour_interval_s)
        return np.where(found & (self.arr_obs[r] > 0), out,
                        np.nan).astype(np.float32)

    def arrival_rate_for(self, ids: Sequence[int]) -> np.ndarray:
        rows, found = self._rows_for(ids)
        r = np.where(found, rows, 0)
        with np.errstate(divide="ignore"):
            rate = np.where(self.ema_interarrival[r] > 0,
                            1.0 / self.ema_interarrival[r], 0.0)
        return np.where(found & (self.arr_obs[r] > 0), rate,
                        0.0).astype(np.float32)

    # --- persistence --------------------------------------------------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        """Compacted columns (rows [0, size)) + the row → id map. A
        fraction of the dense snapshot's bytes at population scale —
        and the shapes say how many clients were ever touched."""
        s = self._size
        out = {f: np.asarray(getattr(self, f)[:s]).copy()
               for f in self._COLUMNS}
        out["touch_clock"] = np.asarray(self._touch_clock, np.int64)
        return out

    def load_state_dict(self, state: Dict[str, np.ndarray]) -> None:
        state = dict(state)
        if "ids" not in state:
            self._load_dense(state)
            return
        ids = np.asarray(state["ids"], np.int64).reshape(-1)
        rows = len(ids)
        if rows and int(np.max(ids)) >= self.n:
            raise ValueError(
                f"sparse selection state touches client "
                f"{int(np.max(ids))}, outside this population of {self.n}")
        if self.capacity and rows > self.capacity:
            raise ValueError(
                f"sparse selection state has {rows} rows, over this "
                f"store's capacity {self.capacity}")
        alloc = _MIN_ROWS
        while alloc < rows:
            alloc *= 2
        self._alloc(alloc)
        for f in self._COLUMNS:
            if f not in state:
                raise ValueError(f"sparse selection state missing {f!r}")
            cur = getattr(self, f)
            val = np.asarray(state[f], cur.dtype)
            want = (rows,) + cur.shape[1:]
            if val.shape != want:
                raise ValueError(
                    f"sparse selection state field {f!r} has shape "
                    f"{val.shape}, expected {want} (loss-window mismatch "
                    "with the checkpoint?)")
            cur[:rows] = val
        self._size = rows
        self._index = {int(c): i for i, c in enumerate(ids)}
        self._sorted_ids = None
        self._touch_clock = int(state.get("touch_clock", rows))

    def _load_dense(self, state: Dict[str, np.ndarray]) -> None:
        """Restore from a legacy DENSE snapshot: materialize rows for the
        touched clients only."""
        dense = ClientStatsStore(self.n, loss_window=self.loss_window,
                                 ema_alpha=self.ema_alpha,
                                 drop_prior=(self.drop_prior_a,
                                             self.drop_prior_b))
        dense.load_state_dict(state)
        touched = np.flatnonzero(dense._touched_mask())
        alloc = _MIN_ROWS
        while alloc < len(touched):
            alloc *= 2
        if self.capacity and len(touched) > self.capacity:
            raise ValueError(
                f"dense selection snapshot touches {len(touched)} clients, "
                f"over this sparse store's capacity {self.capacity}")
        self._alloc(alloc)
        for i, cid in enumerate(touched):
            for f in ClientStatsStore._FIELDS:
                getattr(self, f)[i] = getattr(dense, f)[cid]
            self.ids[i] = int(cid)
            self.last_touch[i] = i + 1
        self._size = len(touched)
        self._index = {int(c): i for i, c in enumerate(touched)}
        self._sorted_ids = None
        self._touch_clock = len(touched)
        logger.info("sparse selection store restored from a dense "
                    "snapshot: %d touched of %d clients",
                    len(touched), self.n)

    def to_dense(self) -> ClientStatsStore:
        """Materialize a dense twin (tests' parity oracle; small n only)."""
        dense = ClientStatsStore(self.n, loss_window=self.loss_window,
                                 ema_alpha=self.ema_alpha,
                                 drop_prior=(self.drop_prior_a,
                                             self.drop_prior_b))
        for cid, r in self._index.items():
            for f in ClientStatsStore._FIELDS:
                getattr(dense, f)[cid] = getattr(self, f)[r]
        return dense
