"""Pluggable participant-selection strategies (``client_selection`` knob).

Selection stays HOST-side by design: a strategy turns the
:class:`~fedml_tpu.core.selection.stats.ClientStatsStore`'s observed
history into the next round's cohort, and the cohort rides the jitted
round programs purely as schedule DATA (indices / active mask / work
fractions) — the compiled programs never change shape, so the canonical
slot width and the compile-once invariant hold for every strategy.

Strategies:

* ``uniform`` — the reference's per-round draw, bit-identical to the
  pre-selection schedules at default knobs (it delegates to
  :func:`~fedml_tpu.simulation.sampling.client_sampling` on the same
  stream).
* ``power_of_choice`` (Cho et al., 2020) — sample ``d = d_factor * k``
  candidates uniformly, keep the ``k`` with the highest last observed
  loss. Unobserved clients rank as +inf loss, so exploration is built in.
* ``oort`` (Lai et al., OSDI 2021, simplified) — utility = statistical
  utility (RMS of the recent loss window + a temporal-uncertainty bonus
  for stale clients) × a system penalty for clients slower than the
  preferred latency; an ε fraction of each cohort explores never-selected
  clients.
* ``reputation`` — the byzantine-aware-dropout closer: sample on the
  UNIFORM stream (schedules stay comparable), then bench sampled clients
  whose defense-verdict reputation fell below the threshold. The engine
  turns benched clients into in-program dropout (work fraction 0,
  renormalized over survivors under ``chaos_tolerance``) instead of
  letting the defense zero their rows round after round — they stop
  burning training compute, and the denominator no longer carries them.

**Population scaling** (the million-client control plane): strategies
score a seeded *candidate pool* of ``m ≫ k`` ids instead of the full
population once ``n`` crosses ``selection_pool_threshold`` (or always,
with an explicit ``selection_candidate_pool``), and take the cohort via
``np.argpartition`` partial top-k — O(m + k log k) per round instead of
O(N log N), with store reads going through the id-parameterized query
surface so a sparse stats backend never materializes ``[N]`` state.
Below the threshold the legacy full-population path runs UNCHANGED
(bit-identical selections — the dense-parity pin).

Every stochastic draw is a pure function of ``(random_seed, strategy tag,
round_idx)`` via a fresh ``np.random.default_rng`` — rerunning a round
with the same observed history replays the same cohort, which is what
makes crash-resume selections assertable.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...simulation.sampling import (FAST_SAMPLE_MIN_N, client_sampling,
                                    sample_ids_streaming,
                                    sampling_stream_from_args)
from .stats import ClientStatsStore

logger = logging.getLogger(__name__)

# domain-separation tags for the per-strategy PRNG streams
_TAG_POC = 101
_TAG_OORT = 103
_TAG_POOL = 107

SELECTION_STRATEGIES = ("uniform", "power_of_choice", "oort", "reputation")

# population size past which candidate pools engage by default
# (selection_pool_threshold knob); matches the schedule-sampling fast
# path so "small N" means the same thing across the selection plane
DEFAULT_POOL_THRESHOLD = FAST_SAMPLE_MIN_N

Selection = Tuple[List[int], List[int]]  # (sampled ids, benched subset)


def pool_size(args, n: int, k: int) -> Optional[int]:
    """Candidate-pool size ``m`` for a population of ``n`` and cohort of
    ``k`` — or None for the legacy full-population path.

    ``selection_candidate_pool`` > 0 forces a pool of that size at any
    ``n`` (clamped to [k, n]); 0/unset means AUTO: full population below
    ``selection_pool_threshold`` (small-N selections stay bit-identical),
    ``m = ceil(selection_pool_factor * k)`` above it."""
    explicit = int(getattr(args, "selection_candidate_pool", 0) or 0)
    if explicit > 0:
        return int(min(max(explicit, k), n))
    threshold = int(getattr(args, "selection_pool_threshold",
                            DEFAULT_POOL_THRESHOLD)
                    or DEFAULT_POOL_THRESHOLD)
    if n < threshold:
        return None
    factor = float(getattr(args, "selection_pool_factor", 16.0) or 16.0)
    m = int(np.ceil(max(factor, 1.0) * max(k, 1)))
    return int(min(max(m, k), n))


def partial_top_k(scores: np.ndarray, k: int) -> np.ndarray:
    """Indices of the ``k`` highest scores, highest first — O(m) select
    + O(k log k) order via ``np.argpartition`` instead of a full sort.
    Ties break by LOWEST index (deterministic), matching a stable
    descending argsort."""
    k = min(int(k), len(scores))
    if k <= 0:
        return np.empty(0, np.int64)
    if k >= len(scores):
        return np.argsort(-scores, kind="stable")
    kth = scores[np.argpartition(-scores, k - 1)[k - 1]]
    # ties straddling the k boundary: argpartition picks an arbitrary
    # subset of the kth-value ties — take the lowest-index ones instead,
    # exactly what a stable descending argsort would keep
    above = np.flatnonzero(scores > kth)
    ties = np.sort(np.flatnonzero(scores == kth))
    top = np.concatenate([above, ties[:k - len(above)]])
    return top[np.lexsort((top, -scores[top]))]


def rep_bench_knobs(args) -> Tuple[float, float]:
    """(reputation threshold, min-keep fraction) — the ONE reading shared
    by the simulator's reputation strategy, the cross-silo silo
    selection, and the async engine's rotation benching; three
    independent ``getattr`` chains would let the default (or the
    None-falls-back-to-0 handling) drift per surface."""
    return (float(getattr(args, "selection_rep_threshold", 0.3) or 0.0),
            float(getattr(args, "selection_min_keep_frac", 0.5) or 0.5))


def cap_bench(cohort_n: int, flagged, badness, keep_frac: float,
              quorum: int = 1) -> List[int]:
    """The ONE bench-floor policy, shared by the simulator's reputation
    strategy and the cross-silo server's silo selection: never bench below
    ``max(quorum, ceil(keep_frac * cohort))`` survivors, and when the
    flagged set exceeds the cap keep only the WORST offenders (highest
    ``badness``). An adversary that poisons scores must not be able to
    empty a cohort, and a policy fix here fixes both callers."""
    min_keep = max(int(quorum), int(np.ceil(keep_frac * cohort_n)), 1)
    max_bench = max(cohort_n - min_keep, 0)
    flagged = list(flagged)
    if len(flagged) > max_bench:
        flagged = sorted(flagged, key=badness, reverse=True)[:max_bench]
    return flagged


class SelectionStrategy:
    """``select(round_idx, n) -> (sampled, excluded)``: ``sampled`` is the
    scheduled cohort in placement order; ``excluded`` ⊆ ``sampled`` are
    clients the strategy benches — the engine schedules them with work
    fraction 0 (renormalized in-program dropout), it does not unschedule
    them, so schedule shapes stay strategy-independent."""

    name = "?"

    def __init__(self, args, num_clients: int, store: ClientStatsStore):
        self.args = args
        self.n = int(num_clients)
        self.store = store
        self.seed = int(getattr(args, "random_seed", 0) or 0)
        self.stream = sampling_stream_from_args(args)

    def _uniform(self, round_idx: int, n: int) -> List[int]:
        return [int(c) for c in client_sampling(
            round_idx, self.n, n, random_seed=self.seed,
            stream=self.stream)]

    def _rng(self, tag: int, round_idx: int) -> np.random.Generator:
        return np.random.default_rng((self.seed, tag, int(round_idx)))

    def _pool(self, round_idx: int, k: int) -> Optional[np.ndarray]:
        """Seeded candidate pool of m ids, or None for the legacy
        full-population path. The pool rides its OWN tag (and generator)
        so enabling it never perturbs a strategy's other draws."""
        m = pool_size(self.args, self.n, k)
        if m is None or m >= self.n:
            return None
        return sample_ids_streaming(self._rng(_TAG_POOL, round_idx),
                                    self.n, m)

    def select(self, round_idx: int, n: int) -> Selection:
        raise NotImplementedError


class UniformSelection(SelectionStrategy):
    name = "uniform"

    def select(self, round_idx: int, n: int) -> Selection:
        return self._uniform(round_idx, n), []


class PowerOfChoiceSelection(SelectionStrategy):
    name = "power_of_choice"

    def select(self, round_idx: int, n: int) -> Selection:
        n = min(int(n), self.n)
        d_factor = float(getattr(self.args, "poc_d_factor", 2.0) or 2.0)
        d = int(min(self.n, max(n, int(np.ceil(n * max(d_factor, 1.0))))))
        rng = self._rng(_TAG_POC, round_idx)
        # d is already poc's candidate pool; the SAME knobs that govern
        # the other strategies' pools decide when the draw leaves the
        # legacy path (explicit selection_candidate_pool forces it,
        # selection_pool_threshold gates the auto switch) — only the
        # DRAW changes (O(d) streaming ids, no [N] permutation)
        if pool_size(self.args, self.n, n) is not None:
            cands = sample_ids_streaming(rng, self.n, d)
            score = self.store.last_loss_for(cands)
            return [int(c) for c in cands[partial_top_k(score, n)]], []
        cands = rng.choice(self.n, d, replace=False)
        # highest-loss first; the candidate draw is already a random
        # permutation, so equal scores tie-break randomly but stably
        score = self.store.last_loss_for(cands)
        order = np.argsort(-score, kind="stable")
        return [int(c) for c in cands[order[:n]]], []


class OortSelection(SelectionStrategy):
    name = "oort"

    def _utility_for(self, round_idx: int,
                     ids: np.ndarray) -> np.ndarray:
        """Oort utility for the given candidate ids — all store reads go
        through the id-parameterized surface, so cost is O(len(ids)) on
        both stats backends."""
        st = self.store
        stat = st.rms_loss_for(ids)
        seen = np.isfinite(stat)
        # never-observed clients get the observed mean utility (neutral):
        # the explore slots are their on-ramp, not a fake-high score
        fill = st.observed_rms_mean()
        if not np.isfinite(fill):
            fill = 1.0
        stat = np.where(seen, stat, fill)
        # temporal uncertainty (Oort eq. 2): clients not picked recently
        # regain priority instead of starving on a stale low loss
        age = np.maximum(int(round_idx) - st.last_selected_for(ids), 1)
        stat = stat + np.sqrt(0.1 * np.log(max(round_idx, 1) + 1.0) / age)
        # system utility: penalize clients slower than the preferred
        # latency (knob; 0 = the observed median), Oort's (T/t)^alpha
        alpha = float(getattr(self.args, "oort_alpha", 2.0) or 0.0)
        lat = st.latency_for(ids)
        pref = float(getattr(self.args, "oort_pref_latency_s", 0.0) or 0.0)
        if pref <= 0.0:
            pref = st.observed_latency_median()
            if not np.isfinite(pref):
                pref = 0.0
        if pref > 0.0 and alpha > 0.0:
            with np.errstate(invalid="ignore", divide="ignore"):
                pen = np.power(pref / np.maximum(lat, 1e-9), alpha)
            sys_u = np.where(np.isnan(lat) | (lat <= pref), 1.0,
                             np.minimum(pen, 1.0))
        else:
            sys_u = np.ones(len(ids), np.float32)
        # the simulator has no wall-clock per client, but it observes work
        # fractions: chronic stragglers (low EMA work) are the same signal
        return stat * sys_u * np.clip(st.ema_work_for(ids), 0.05, 1.0)

    def _utility(self, round_idx: int) -> np.ndarray:
        """[n] whole-population utility — the async engine's
        dispatch-ranking read (its rotation covers every client, so the
        materialization is the point there, not an accident)."""
        return self._utility_for(round_idx, np.arange(self.n))

    def select(self, round_idx: int, n: int) -> Selection:
        n = min(int(n), self.n)
        pool = self._pool(round_idx, n)
        cands = pool if pool is not None else np.arange(self.n)
        rng = self._rng(_TAG_OORT, round_idx)
        explore_frac = float(getattr(self.args, "oort_explore_frac", 0.1)
                             or 0.0)
        # positions (into cands) of never-selected candidates
        unexplored = np.flatnonzero(
            self.store.times_selected_for(cands) == 0)
        n_explore = min(int(np.ceil(n * max(explore_frac, 0.0))),
                        len(unexplored), n)
        explore = (rng.choice(unexplored, n_explore, replace=False)
                   if n_explore else np.empty(0, np.int64))
        util = self._utility_for(round_idx, cands)
        util[explore] = -np.inf  # already taken by the explore slots
        if pool is None:
            order = np.argsort(-util, kind="stable")
            exploit = order[:n - n_explore]
        else:
            exploit = partial_top_k(util, n - n_explore)
        picked = np.concatenate([exploit, explore])
        return [int(c) for c in cands[picked]], []


class ReputationSelection(SelectionStrategy):
    name = "reputation"

    def select(self, round_idx: int, n: int) -> Selection:
        sampled = self._uniform(round_idx, n)
        thresh, keep_frac = rep_bench_knobs(self.args)
        rep = self.store.reputation_for(sampled)
        by_id = {int(c): float(r) for c, r in zip(sampled, rep)}
        benched = cap_bench(
            len(sampled), [c for c in sampled if by_id[c] < thresh],
            badness=lambda c: -by_id[c], keep_frac=keep_frac)
        return sampled, benched


_STRATEGIES = {cls.name: cls for cls in
               (UniformSelection, PowerOfChoiceSelection, OortSelection,
                ReputationSelection)}


def create_strategy(args, num_clients: int,
                    store: ClientStatsStore) -> SelectionStrategy:
    name = str(getattr(args, "client_selection", "uniform")
               or "uniform").lower()
    cls = _STRATEGIES.get(name)
    if cls is None:
        raise ValueError(
            f"client_selection {name!r} unknown; choose from "
            f"{tuple(sorted(_STRATEGIES))}")
    return cls(args, num_clients, store)
