"""Pluggable participant-selection strategies (``client_selection`` knob).

Selection stays HOST-side by design: a strategy turns the
:class:`~fedml_tpu.core.selection.stats.ClientStatsStore`'s observed
history into the next round's cohort, and the cohort rides the jitted
round programs purely as schedule DATA (indices / active mask / work
fractions) — the compiled programs never change shape, so the canonical
slot width and the compile-once invariant hold for every strategy.

Strategies:

* ``uniform`` — the reference's per-round draw, bit-identical to the
  pre-selection schedules at default knobs (it delegates to
  :func:`~fedml_tpu.simulation.sampling.client_sampling` on the same
  stream).
* ``power_of_choice`` (Cho et al., 2020) — sample ``d = d_factor * k``
  candidates uniformly, keep the ``k`` with the highest last observed
  loss. Unobserved clients rank as +inf loss, so exploration is built in.
* ``oort`` (Lai et al., OSDI 2021, simplified) — utility = statistical
  utility (RMS of the recent loss window + a temporal-uncertainty bonus
  for stale clients) × a system penalty for clients slower than the
  preferred latency; an ε fraction of each cohort explores never-selected
  clients.
* ``reputation`` — the byzantine-aware-dropout closer: sample on the
  UNIFORM stream (schedules stay comparable), then bench sampled clients
  whose defense-verdict reputation fell below the threshold. The engine
  turns benched clients into in-program dropout (work fraction 0,
  renormalized over survivors under ``chaos_tolerance``) instead of
  letting the defense zero their rows round after round — they stop
  burning training compute, and the denominator no longer carries them.

Every stochastic draw is a pure function of ``(random_seed, strategy tag,
round_idx)`` via a fresh ``np.random.default_rng`` — rerunning a round
with the same observed history replays the same cohort, which is what
makes crash-resume selections assertable.
"""

from __future__ import annotations

import logging
from typing import List, Sequence, Tuple

import numpy as np

from ...simulation.sampling import client_sampling, sampling_stream_from_args
from .stats import ClientStatsStore

logger = logging.getLogger(__name__)

# domain-separation tags for the per-strategy PRNG streams
_TAG_POC = 101
_TAG_OORT = 103

SELECTION_STRATEGIES = ("uniform", "power_of_choice", "oort", "reputation")

Selection = Tuple[List[int], List[int]]  # (sampled ids, benched subset)


def rep_bench_knobs(args) -> Tuple[float, float]:
    """(reputation threshold, min-keep fraction) — the ONE reading shared
    by the simulator's reputation strategy, the cross-silo silo
    selection, and the async engine's rotation benching; three
    independent ``getattr`` chains would let the default (or the
    None-falls-back-to-0 handling) drift per surface."""
    return (float(getattr(args, "selection_rep_threshold", 0.3) or 0.0),
            float(getattr(args, "selection_min_keep_frac", 0.5) or 0.5))


def cap_bench(cohort_n: int, flagged, badness, keep_frac: float,
              quorum: int = 1) -> List[int]:
    """The ONE bench-floor policy, shared by the simulator's reputation
    strategy and the cross-silo server's silo selection: never bench below
    ``max(quorum, ceil(keep_frac * cohort))`` survivors, and when the
    flagged set exceeds the cap keep only the WORST offenders (highest
    ``badness``). An adversary that poisons scores must not be able to
    empty a cohort, and a policy fix here fixes both callers."""
    min_keep = max(int(quorum), int(np.ceil(keep_frac * cohort_n)), 1)
    max_bench = max(cohort_n - min_keep, 0)
    flagged = list(flagged)
    if len(flagged) > max_bench:
        flagged = sorted(flagged, key=badness, reverse=True)[:max_bench]
    return flagged


class SelectionStrategy:
    """``select(round_idx, n) -> (sampled, excluded)``: ``sampled`` is the
    scheduled cohort in placement order; ``excluded`` ⊆ ``sampled`` are
    clients the strategy benches — the engine schedules them with work
    fraction 0 (renormalized in-program dropout), it does not unschedule
    them, so schedule shapes stay strategy-independent."""

    name = "?"

    def __init__(self, args, num_clients: int, store: ClientStatsStore):
        self.args = args
        self.n = int(num_clients)
        self.store = store
        self.seed = int(getattr(args, "random_seed", 0) or 0)
        self.stream = sampling_stream_from_args(args)

    def _uniform(self, round_idx: int, n: int) -> List[int]:
        return [int(c) for c in client_sampling(
            round_idx, self.n, n, random_seed=self.seed,
            stream=self.stream)]

    def _rng(self, tag: int, round_idx: int) -> np.random.Generator:
        return np.random.default_rng((self.seed, tag, int(round_idx)))

    def select(self, round_idx: int, n: int) -> Selection:
        raise NotImplementedError


class UniformSelection(SelectionStrategy):
    name = "uniform"

    def select(self, round_idx: int, n: int) -> Selection:
        return self._uniform(round_idx, n), []


class PowerOfChoiceSelection(SelectionStrategy):
    name = "power_of_choice"

    def select(self, round_idx: int, n: int) -> Selection:
        n = min(int(n), self.n)
        d_factor = float(getattr(self.args, "poc_d_factor", 2.0) or 2.0)
        d = int(min(self.n, max(n, int(np.ceil(n * max(d_factor, 1.0))))))
        rng = self._rng(_TAG_POC, round_idx)
        cands = rng.choice(self.n, d, replace=False)
        # highest-loss first; the candidate draw is already a random
        # permutation, so equal scores tie-break randomly but stably
        score = self.store.last_loss()[cands]
        order = np.argsort(-score, kind="stable")
        return [int(c) for c in cands[order[:n]]], []


class OortSelection(SelectionStrategy):
    name = "oort"

    def _utility(self, round_idx: int) -> np.ndarray:
        st = self.store
        stat = st.rms_loss()
        seen = np.isfinite(stat)
        # never-observed clients get the observed mean utility (neutral):
        # the explore slots are their on-ramp, not a fake-high score
        fill = float(np.nanmean(stat)) if bool(np.any(seen)) else 1.0
        stat = np.where(seen, stat, fill)
        # temporal uncertainty (Oort eq. 2): clients not picked recently
        # regain priority instead of starving on a stale low loss
        age = np.maximum(int(round_idx) - st.last_selected, 1)
        stat = stat + np.sqrt(0.1 * np.log(max(round_idx, 1) + 1.0) / age)
        # system utility: penalize clients slower than the preferred
        # latency (knob; 0 = the observed median), Oort's (T/t)^alpha
        alpha = float(getattr(self.args, "oort_alpha", 2.0) or 0.0)
        lat = np.where(st.has_latency > 0, st.ema_latency, np.nan)
        pref = float(getattr(self.args, "oort_pref_latency_s", 0.0) or 0.0)
        if pref <= 0.0:
            pref = (float(np.nanmedian(lat))
                    if bool(np.any(st.has_latency > 0)) else 0.0)
        if pref > 0.0 and alpha > 0.0:
            with np.errstate(invalid="ignore", divide="ignore"):
                pen = np.power(pref / np.maximum(lat, 1e-9), alpha)
            sys_u = np.where(np.isnan(lat) | (lat <= pref), 1.0,
                             np.minimum(pen, 1.0))
        else:
            sys_u = np.ones(self.n, np.float32)
        # the simulator has no wall-clock per client, but it observes work
        # fractions: chronic stragglers (low EMA work) are the same signal
        return stat * sys_u * np.clip(st.ema_work, 0.05, 1.0)

    def select(self, round_idx: int, n: int) -> Selection:
        n = min(int(n), self.n)
        rng = self._rng(_TAG_OORT, round_idx)
        explore_frac = float(getattr(self.args, "oort_explore_frac", 0.1)
                             or 0.0)
        unexplored = np.flatnonzero(self.store.times_selected == 0)
        n_explore = min(int(np.ceil(n * max(explore_frac, 0.0))),
                        len(unexplored), n)
        explore = (rng.choice(unexplored, n_explore, replace=False)
                   if n_explore else np.empty(0, np.int64))
        util = self._utility(round_idx)
        util[explore] = -np.inf  # already taken by the explore slots
        order = np.argsort(-util, kind="stable")
        exploit = order[:n - n_explore]
        return [int(c) for c in np.concatenate([exploit, explore])], []


class ReputationSelection(SelectionStrategy):
    name = "reputation"

    def select(self, round_idx: int, n: int) -> Selection:
        sampled = self._uniform(round_idx, n)
        thresh, keep_frac = rep_bench_knobs(self.args)
        rep = self.store.reputation
        benched = cap_bench(
            len(sampled), [c for c in sampled if rep[c] < thresh],
            badness=lambda c: -rep[c], keep_frac=keep_frac)
        return sampled, benched


_STRATEGIES = {cls.name: cls for cls in
               (UniformSelection, PowerOfChoiceSelection, OortSelection,
                ReputationSelection)}


def create_strategy(args, num_clients: int,
                    store: ClientStatsStore) -> SelectionStrategy:
    name = str(getattr(args, "client_selection", "uniform")
               or "uniform").lower()
    cls = _STRATEGIES.get(name)
    if cls is None:
        raise ValueError(
            f"client_selection {name!r} unknown; choose from "
            f"{tuple(sorted(_STRATEGIES))}")
    return cls(args, num_clients, store)
