"""SelectionManager — the engine/server-facing seam of the subsystem.

Owns the :class:`ClientStatsStore` + the configured strategy and mediates
two directions of flow:

* **observations in**: host-side schedule facts (who was scheduled, who
  the chaos plan dropped, work fractions) are recorded immediately;
  DEVICE-side facts (per-slot training losses, defense verdicts) are
  queued as device arrays and materialized lazily at the next selection
  query — ``run_round`` itself never forces a device→host transfer, so
  the fused single-dispatch property (and the transfer-guard tests that
  pin it) survive selection.
* **selections out**: ``select(round_idx, n)`` flushes the queue and asks
  the strategy; ``round_target`` sizes the cohort from the pooled
  Beta-posterior dropout estimate when adaptive over-sampling is on.

With the default knobs (``client_selection: uniform``, adaptive
over-sampling off) the manager is PASSIVE: it records nothing, queues
nothing, adds no checkpoint state, and delegates straight to the legacy
sampling stream — schedules are bit-identical to a build without the
subsystem.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import mlops
from .sparse import SparseClientStatsStore
from .stats import ClientStatsStore
from .strategies import (DEFAULT_POOL_THRESHOLD, SELECTION_STRATEGIES,
                         create_strategy)

logger = logging.getLogger(__name__)

STORE_BACKENDS = ("auto", "dense", "sparse")


def make_stats_store(args, num_clients: int, **store_kw):
    """The ONE ``selection_store`` knob reading (``auto``/``dense``/
    ``sparse``), shared by the engine manager and the cross-device
    cohort plane. ``auto`` (default) keeps the dense backend — O(N)
    state, whole-population reads — below
    ``selection_sparse_threshold`` clients and flips to the sparse
    backend above it, where dense allocation alone would dwarf the
    round. ``selection_store_capacity`` (sparse only) caps rows with
    least-recently-touched eviction."""
    backend = str(getattr(args, "selection_store", "auto")
                  or "auto").lower()
    if backend not in STORE_BACKENDS:
        raise ValueError(f"selection_store {backend!r} unknown; choose "
                         f"from {STORE_BACKENDS}")
    n = int(num_clients)
    if backend == "auto":
        threshold = int(getattr(args, "selection_sparse_threshold",
                                DEFAULT_POOL_THRESHOLD)
                        or DEFAULT_POOL_THRESHOLD)
        backend = "sparse" if n >= threshold else "dense"
    if backend == "sparse":
        cap = int(getattr(args, "selection_store_capacity", 0) or 0)
        logger.info("selection stats: sparse backend over %d clients"
                    "%s", n, f" (capacity {cap})" if cap else "")
        return SparseClientStatsStore(n, capacity=cap, **store_kw)
    return ClientStatsStore(n, **store_kw)

# slot placement: client k of the sampled list lands on device
# cid // cpd at that device's next free slot — the SAME loop as
# build_schedule / the engine's _robust_rows, so (device, slot) -> client
# mapping is shared by schedules, update rows, and slot metrics
def slot_placement(sampled: Sequence[int], n_devices: int,
                   cpd: int) -> List[Tuple[int, int, int]]:
    counts = [0] * n_devices
    out = []
    for cid in sampled:
        d = int(cid) // cpd
        out.append((int(cid), d, counts[d]))
        counts[d] += 1
    return out


class SelectionManager:
    def __init__(self, args, num_clients: int):
        self.args = args
        self.num_clients = int(num_clients)
        self.strategy_name = str(getattr(args, "client_selection", "uniform")
                                 or "uniform").lower()
        if self.strategy_name not in SELECTION_STRATEGIES:
            raise ValueError(
                f"client_selection {self.strategy_name!r} unknown; choose "
                f"from {SELECTION_STRATEGIES}")
        self.adaptive = bool(getattr(args, "selection_adaptive_oversample",
                                     False))
        self.store = make_stats_store(
            args, self.num_clients,
            loss_window=int(getattr(args, "selection_loss_window", 8) or 8),
            ema_alpha=float(getattr(args, "selection_ema_alpha", 0.2)
                            or 0.2))
        self.strategy = create_strategy(args, self.num_clients, self.store)
        # passive at defaults: nothing observed, nothing checkpointed
        self.track = self.strategy_name != "uniform" or self.adaptive
        self._pending: List[Dict[str, Any]] = []
        self._excluded_by_round: Dict[int, set] = {}

    @property
    def stateful(self) -> bool:
        """True when selections depend on observed history — the store
        must then ride checkpoints so crash-resume replays identical
        cohorts."""
        return self.track

    def pin_adaptive(self, reason: str) -> None:
        """Disable adaptive cohort sizing (engine constraint — e.g. the
        fused robust program's [K] defense-kernel shape must stay
        constant for compile-once). Recomputes passivity: a uniform
        strategy that only tracked FOR adaptivity goes fully passive."""
        if not self.adaptive:
            return
        logger.warning("selection_adaptive_oversample disabled: %s",
                       reason)
        self.adaptive = False
        self.track = self.strategy_name != "uniform"

    # --- selections out -----------------------------------------------------
    def round_target(self, round_idx: int, base_n: int, cap_n: int) -> int:
        """Cohort size for this round. Adaptive over-sampling replaces the
        static ``chaos_over_sample`` factor with the pooled posterior
        dropout estimate: sample ``ceil(k / (1 - p))`` so the expected
        post-dropout cohort still hits ``k`` — capped at ``cap_n`` (the
        canonical-width cap: the compiled schedule shapes never move)."""
        if not self.adaptive:
            return int(base_n)
        self._flush()
        p = self.store.population_dropout_mean()
        n = int(np.ceil(base_n / max(1.0 - p, 0.5)))
        return int(min(max(n, base_n), cap_n))

    def select(self, round_idx: int, n: int) -> Tuple[List[int], List[int]]:
        if self.track:
            self._flush()
        return self.strategy.select(round_idx, int(n))

    # --- observations in ----------------------------------------------------
    def note_schedule(self, round_idx: int, sampled: Sequence[int],
                      excluded: Sequence[int], work_by_client: Dict[int,
                                                                    float],
                      target_n: int) -> None:
        """Host-side facts, recorded immediately (no device readback):
        selection, availability outcomes (chaos dropout / straggler work),
        and the mlops selection record."""
        if not self.track:
            return
        excl = set(int(c) for c in excluded)
        self._excluded_by_round[int(round_idx)] = excl
        for r in [r for r in self._excluded_by_round
                  if r < int(round_idx) - 64]:  # bound: verdicts consume
            del self._excluded_by_round[r]      # entries; prune strays
        self.store.record_selected(round_idx, sampled)
        for cid in sampled:
            if int(cid) in excl:
                continue  # we benched them: not reliability evidence
            w = float(work_by_client.get(int(cid), 1.0))
            self.store.record_availability(int(cid), participated=w > 0.0,
                                           work=w)
        mlops.log_selection(
            round_idx=int(round_idx), strategy=self.strategy_name,
            sampled=[int(c) for c in sampled],
            excluded=sorted(excl), target_n=int(target_n),
            dropout_posterior=round(self.store.population_dropout_mean(),
                                    5))

    def note_results(self, round_idx: int, sampled: Sequence[int],
                     placement: Sequence[Tuple[int, int, int]],
                     slot_metrics: Optional[Any] = None,
                     verdict: Optional[Any] = None) -> None:
        """Device-side facts (per-slot metrics pytree [n_dev, S] leaves,
        defense verdict [K]) queued WITHOUT materializing — flushed at the
        next selection query."""
        if not self.track:
            return
        self._pending.append({
            "round_idx": int(round_idx),
            "sampled": [int(c) for c in sampled],
            "placement": list(placement),
            "slot_metrics": slot_metrics,
            "verdict": verdict,
        })

    def note_latency(self, client_id: int, latency_s: float) -> None:
        if self.track:
            self.store.record_latency(client_id, latency_s)

    def note_arrival(self, client_id: int, interarrival_s: float) -> None:
        """Buffered-async arrival gap — the arrival-rate posterior's
        evidence stream (async engine / cross-silo pour loop)."""
        if self.track:
            self.store.record_arrival(client_id, interarrival_s)

    def flush(self) -> None:
        """Materialize queued device-side observations NOW — the async
        engine's dispatch ranking reads the store between pours, outside
        any selection query."""
        self._flush()

    def _flush(self) -> None:
        pending, self._pending = self._pending, []
        for rec in pending:
            sm = rec["slot_metrics"]
            if sm is not None:
                loss_sum = np.asarray(sm["loss_sum"])
                count = np.asarray(sm["count"])
                for cid, d, s in rec["placement"]:
                    c = float(count[d, s])
                    if c > 0:
                        self.store.record_loss(cid,
                                               float(loss_sum[d, s]) / c)
            v = rec["verdict"]
            if v is not None:
                # a BENCHED client's row was empty this round — the
                # defense's verdict about it is vacuous (a zero row looks
                # perfectly innocuous to krum) and must not launder its
                # reputation back up; record evidence for the clients
                # that actually trained only
                excl = self._excluded_by_round.pop(rec["round_idx"], set())
                ids = rec["sampled"]
                v = np.asarray(v)
                keep = [i for i, c in enumerate(ids) if c not in excl]
                if keep:
                    self.store.record_verdict([ids[i] for i in keep],
                                              v[keep])

    # --- persistence --------------------------------------------------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        self._flush()
        return self.store.state_dict()

    def load_state_dict(self, state: Dict[str, np.ndarray]) -> None:
        self._pending = []  # superseded by the restored history
        self.store.load_state_dict({k: np.asarray(v)
                                    for k, v in dict(state).items()})
