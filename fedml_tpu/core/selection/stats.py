"""Per-client observed statistics — the selection subsystem's memory.

Every signal here already flows through the framework and was previously
thrown away at the aggregation seam: per-round training losses (the round
programs' per-slot metrics), observed work fractions and dropouts (the
chaos ``FaultLedger`` seam), cross-silo upload latencies (the server FSM's
broadcast→receipt clock), and defense exclusion verdicts (the robust
pipeline's per-client weights). The store folds them into compact
per-client state:

* ``ema_latency`` / ``ema_work`` — exponential moving averages of observed
  round latency (cross-silo) and completed work fraction (simulator).
* a **Beta-posterior dropout estimate**: ``drop_obs`` / ``part_obs``
  counts over a weakly-informative Beta(1, 19) prior (≈5% prior dropout),
  so one flaky round does not brand a client and a reliable history is not
  erased by one miss. Posterior mean = (a0+drops)/(a0+b0+obs).
* ``losses`` — a last-K ring buffer of observed mean training losses per
  client (Power-of-Choice ranks on the latest, Oort on the RMS).
* ``reputation`` — a NORMALIZED inclusion posterior over defense
  verdicts: each client's Beta-posterior probability of being kept by the
  defense, divided by the cohort mean and clipped to [0, 1]. The
  normalization is load-bearing — selection-style defenses (krum picks m
  of K rows) exclude honest clients every round too, so the absolute
  exclusion rate is meaningless; what brands a byzantine client is being
  excluded consistently MORE than the cohort. Unobserved clients score
  1.0 (innocent until evidence).

All state is plain NumPy arrays, so ``state_dict``/``load_state_dict``
round-trip through :class:`~fedml_tpu.core.checkpoint.RoundCheckpointer`
(orbax ``StandardSave``) and crash-resume replays identical selections.

Two query surfaces coexist:

* the legacy **whole-population** arrays/properties (``reputation``,
  ``last_loss()``, ...) — O(N) reads kept for the dense cross-silo and
  small-simulation callers;
* **id-parameterized** queries (``last_loss_for(ids)``, ...) — the
  candidate-pool surface, O(len(ids)) on both backends. Strategies go
  through these exclusively so a
  :class:`~fedml_tpu.core.selection.sparse.SparseClientStatsStore` can
  stand in for the dense store without ever materializing the
  population.

Population-pooled reductions (``population_dropout_mean``, the
reputation cohort mean, ``observed_rms_mean``) are computed over the
OBSERVED rows in ascending-id order on both backends — same multiset,
same order, same pairwise-summation tree — which is what makes
dense-vs-sparse posterior parity *bit-identical*, not merely close.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

import numpy as np

# weakly-informative dropout prior: Beta(1, 19) -> 5% prior mean. Strong
# enough that a single observed dropout doesn't spike the posterior,
# weak enough that ~10 rounds of real behavior dominate it.
DROP_PRIOR_A = 1.0
DROP_PRIOR_B = 19.0


class ClientStatsStore:
    """Observed per-client statistics over a fixed population of ``n``
    clients (or silo ranks). Pure host-side NumPy — observations never
    touch the device, queries are vectorized reads."""

    def __init__(self, num_clients: int, loss_window: int = 8,
                 ema_alpha: float = 0.2,
                 drop_prior: tuple = (DROP_PRIOR_A, DROP_PRIOR_B)):
        n = int(num_clients)
        if n <= 0:
            raise ValueError("ClientStatsStore needs a positive population")
        self.n = n
        self.loss_window = max(int(loss_window), 1)
        self.ema_alpha = float(ema_alpha)
        # dropout-prior strength is a population property: cross-device
        # cohorts see many cheap observations (keep the default heavy
        # prior), cross-silo servers see one observation per slow round
        # (callers pass a lighter prior so benching reacts in rounds,
        # not epochs)
        self.drop_prior_a = float(drop_prior[0])
        self.drop_prior_b = float(drop_prior[1])
        self.losses = np.zeros((n, self.loss_window), np.float32)
        self.loss_count = np.zeros(n, np.int32)   # total losses ever seen
        self.loss_ptr = np.zeros(n, np.int32)     # ring write cursor
        self.ema_latency = np.zeros(n, np.float32)
        self.has_latency = np.zeros(n, np.float32)
        # arrival-rate posterior (buffered-async paths): inter-arrival EMA
        # + observation count per client. 1/EMA is the arrival rate; with
        # the pour interval it predicts a client's typical staleness —
        # what the adaptive staleness cap and async-aware selection read
        self.ema_interarrival = np.zeros(n, np.float32)
        self.arr_obs = np.zeros(n, np.float32)
        self.ema_work = np.ones(n, np.float32)
        self.drop_obs = np.zeros(n, np.float32)   # observed dropouts
        self.part_obs = np.zeros(n, np.float32)   # observed participations
        self.incl_obs = np.zeros(n, np.float32)   # defense kept (verdicts)
        self.excl_obs = np.zeros(n, np.float32)   # defense excluded
        self.times_selected = np.zeros(n, np.int32)
        self.last_selected = np.full(n, -1, np.int32)

    # --- observations -------------------------------------------------------
    def record_selected(self, round_idx: int, ids: Sequence[int]) -> None:
        ids = np.asarray(list(ids), np.int32)
        if ids.size == 0:
            return
        self.times_selected[ids] += 1
        self.last_selected[ids] = int(round_idx)

    def record_availability(self, client_id: int, participated: bool,
                            work: float = 1.0) -> None:
        """One (round, client) availability outcome: feeds the Beta
        posterior and (for participants) the work-fraction EMA. Callers
        must NOT report selector-forced exclusions here — a client the
        selector itself benched is not evidence about its reliability."""
        c = int(client_id)
        if participated:
            self.part_obs[c] += 1.0
            a = self.ema_alpha
            self.ema_work[c] = (1.0 - a) * self.ema_work[c] + a * float(work)
        else:
            self.drop_obs[c] += 1.0

    def record_loss(self, client_id: int, loss: float) -> None:
        c = int(client_id)
        loss = float(loss)
        if not np.isfinite(loss):
            return
        p = int(self.loss_ptr[c])
        self.losses[c, p] = loss
        self.loss_ptr[c] = (p + 1) % self.loss_window
        self.loss_count[c] = self.loss_count[c] + 1

    def record_latency(self, client_id: int, latency_s: float) -> None:
        c = int(client_id)
        lat = float(latency_s)
        if not np.isfinite(lat) or lat < 0.0:
            return
        if self.has_latency[c] > 0:
            a = self.ema_alpha
            self.ema_latency[c] = (1.0 - a) * self.ema_latency[c] + a * lat
        else:
            self.ema_latency[c] = lat
            self.has_latency[c] = 1.0

    def record_arrival(self, client_id: int,
                       interarrival_s: float) -> None:
        """One observed gap between this client's consecutive update
        arrivals (buffered-async paths). The EMA is the arrival-rate
        posterior's point estimate."""
        c = int(client_id)
        gap = float(interarrival_s)
        if not np.isfinite(gap) or gap <= 0.0:
            return
        if self.arr_obs[c] > 0:
            a = self.ema_alpha
            self.ema_interarrival[c] = ((1.0 - a) * self.ema_interarrival[c]
                                        + a * gap)
        else:
            self.ema_interarrival[c] = gap
        self.arr_obs[c] += 1.0

    def arrival_rate(self) -> np.ndarray:
        """[n] arrivals per unit time (1 / inter-arrival EMA); 0 for
        never-observed clients — a client with no arrivals has no rate,
        not an infinite one."""
        with np.errstate(divide="ignore"):
            rate = np.where(self.ema_interarrival > 0,
                            1.0 / self.ema_interarrival, 0.0)
        return np.where(self.arr_obs > 0, rate, 0.0).astype(np.float32)

    def arrival_rate_for(self, ids: Sequence[int]) -> np.ndarray:
        """[len(ids)] arrivals per unit time; 0 for never-observed ids —
        O(len(ids)): index first, divide after (the *_for contract)."""
        ids = np.asarray(ids, np.int64)
        ei = self.ema_interarrival[ids]
        with np.errstate(divide="ignore"):
            rate = np.where(ei > 0, 1.0 / ei, 0.0)
        return np.where(self.arr_obs[ids] > 0, rate, 0.0).astype(np.float32)

    def predicted_staleness(self, pour_interval_s: float) -> np.ndarray:
        """[n] expected model-version lag of each client's next upload:
        inter-arrival EMA over the pour interval. NaN for never-observed
        clients (callers substitute their own prior)."""
        if not np.isfinite(pour_interval_s) or pour_interval_s <= 0.0:
            return np.full(self.n, np.nan, np.float32)
        out = self.ema_interarrival / np.float32(pour_interval_s)
        return np.where(self.arr_obs > 0, out, np.nan).astype(np.float32)

    def record_verdict(self, ids: Sequence[int],
                       verdict: Sequence[float]) -> None:
        """One round's defense verdict ([K] effective inclusion in [0, 1],
        1 = fully kept): accumulate inclusion/exclusion evidence. A
        continuous verdict (foolsgold weights, residual confidences)
        contributes fractionally to both sides."""
        ids = np.asarray(list(ids), np.int32)
        v = np.clip(np.asarray(list(verdict), np.float32), 0.0, 1.0)
        if ids.size == 0 or ids.size != v.size:
            return
        np.add.at(self.incl_obs, ids, v)
        np.add.at(self.excl_obs, ids, 1.0 - v)

    @property
    def reputation(self) -> np.ndarray:
        """[n] normalized inclusion posterior in [0, 1]: the Beta(1, 1)
        posterior mean of P(kept by the defense), divided by the cohort
        mean over OBSERVED clients and clipped. Relative scoring is what
        makes this robust to harsh selection-style defenses (krum keeps m
        of K every round — absolute exclusion rates brand everyone);
        unobserved clients score 1.0."""
        obs = self.incl_obs + self.excl_obs
        raw = (1.0 + self.incl_obs) / (2.0 + obs)
        seen = obs > 0
        pop = self._reputation_pop_mean()
        if pop is None:
            return np.ones(self.n, np.float32)
        rep = np.clip(raw / max(pop, 1e-9), 0.0, 1.0)
        return np.where(seen, rep, 1.0).astype(np.float32)

    # --- id-parameterized queries (the candidate-pool surface) -------------
    # Every *_for query is O(len(ids)) on the sparse backend too; the
    # whole-population reads further down stay for dense callers.
    def last_loss_for(self, ids: Sequence[int]) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        seen = self.loss_count[ids] > 0
        idx = (self.loss_ptr[ids] - 1) % self.loss_window
        last = self.losses[ids, idx]
        return np.where(seen, last, np.inf).astype(np.float32)

    def rms_loss_for(self, ids: Sequence[int]) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        k = np.minimum(self.loss_count[ids], self.loss_window)
        with np.errstate(invalid="ignore", divide="ignore"):
            ms = np.sum(self.losses[ids] ** 2, axis=1) / np.maximum(k, 1)
        return np.where(k > 0, np.sqrt(ms), np.nan).astype(np.float32)

    def reputation_for(self, ids: Sequence[int]) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        obs = self.incl_obs[ids] + self.excl_obs[ids]
        raw = (1.0 + self.incl_obs[ids]) / (2.0 + obs)
        pop = self._reputation_pop_mean()
        if pop is None:
            return np.ones(len(ids), np.float32)
        rep = np.clip(raw / max(pop, 1e-9), 0.0, 1.0)
        return np.where(obs > 0, rep, 1.0).astype(np.float32)

    def _reputation_pop_mean(self) -> Optional[float]:
        """Cohort-mean inclusion posterior over OBSERVED clients in
        ascending-id order (the canonical reduction both backends share);
        None when nobody has a verdict yet."""
        obs = self.incl_obs + self.excl_obs
        seen = obs > 0
        if not bool(np.any(seen)):
            return None
        raw = (1.0 + self.incl_obs[seen]) / (2.0 + obs[seen])
        return float(np.mean(raw))

    def ema_work_for(self, ids: Sequence[int]) -> np.ndarray:
        return self.ema_work[np.asarray(ids, np.int64)]

    def latency_for(self, ids: Sequence[int]) -> np.ndarray:
        """[len(ids)] EMA latency; NaN for never-observed clients."""
        ids = np.asarray(ids, np.int64)
        return np.where(self.has_latency[ids] > 0, self.ema_latency[ids],
                        np.nan).astype(np.float32)

    def times_selected_for(self, ids: Sequence[int]) -> np.ndarray:
        return self.times_selected[np.asarray(ids, np.int64)]

    def last_selected_for(self, ids: Sequence[int]) -> np.ndarray:
        return self.last_selected[np.asarray(ids, np.int64)]

    def observed_rms_mean(self) -> float:
        """Mean RMS loss over clients WITH loss history (ascending-id
        order — the canonical reduction); NaN when nobody has one. Oort's
        neutral fill for unobserved candidates."""
        seen = self.loss_count > 0
        if not bool(np.any(seen)):
            return float("nan")
        ids = np.flatnonzero(seen)
        return float(np.mean(self.rms_loss_for(ids)))

    def observed_latency_median(self) -> float:
        """Median EMA latency over clients WITH a latency observation;
        NaN when nobody has one (Oort's default preferred latency)."""
        seen = self.has_latency > 0
        if not bool(np.any(seen)):
            return float("nan")
        return float(np.median(self.ema_latency[seen]))

    def num_touched(self) -> int:
        """How many clients carry ANY observed evidence — the dense
        backend's answer is a scan; the sparse backend's is its size."""
        return int(np.sum(self._touched_mask()))

    def touched_ids(self) -> np.ndarray:
        """Ascending ids of clients carrying ANY observed evidence — the
        fleet plane's restart diagnostics (which devices does a resumed
        posture actually remember?). Dense backend: a scan."""
        return np.flatnonzero(self._touched_mask()).astype(np.int64)

    def _touched_mask(self) -> np.ndarray:
        return ((self.loss_count > 0) | (self.part_obs > 0)
                | (self.drop_obs > 0) | (self.incl_obs + self.excl_obs > 0)
                | (self.has_latency > 0) | (self.times_selected > 0)
                | (self.arr_obs > 0) | (self.last_selected >= 0))

    # --- queries ------------------------------------------------------------
    def dropout_posterior_mean(self,
                               ids: Optional[Iterable[int]] = None
                               ) -> np.ndarray:
        """Per-client posterior mean dropout probability."""
        a = self.drop_prior_a + self.drop_obs
        b = self.drop_prior_b + self.part_obs
        post = a / (a + b)
        if ids is None:
            return post
        return post[np.asarray(list(ids), np.int32)]

    def population_dropout_mean(self) -> float:
        """POOLED posterior mean over the whole population — the adaptive
        over-sampling signal (per-client posteriors would be noise-
        dominated early; the pooled estimate converges in a few rounds).
        Summed over rows WITH availability evidence in ascending-id order
        (zero rows contribute nothing) so the sparse backend's pooled
        posterior is bit-identical, not merely close."""
        seen = (self.drop_obs > 0) | (self.part_obs > 0)
        a = self.drop_prior_a + float(np.sum(self.drop_obs[seen]))
        b = self.drop_prior_b + float(np.sum(self.part_obs[seen]))
        return float(a / (a + b))

    def last_loss(self) -> np.ndarray:
        """[n] most recently observed loss; +inf for never-observed
        clients (Power-of-Choice treats unknown as maximally interesting —
        exploration falls out for free)."""
        seen = self.loss_count > 0
        idx = (self.loss_ptr - 1) % self.loss_window
        last = self.losses[np.arange(self.n), idx]
        return np.where(seen, last, np.inf).astype(np.float32)

    def rms_loss(self) -> np.ndarray:
        """[n] root-mean-square of the recorded loss window (Oort's
        statistical-utility core); NaN for never-observed clients so the
        strategy can substitute its exploration value."""
        k = np.minimum(self.loss_count, self.loss_window)
        with np.errstate(invalid="ignore", divide="ignore"):
            ms = np.sum(self.losses ** 2, axis=1) / np.maximum(k, 1)
        return np.where(k > 0, np.sqrt(ms), np.nan).astype(np.float32)

    # --- persistence --------------------------------------------------------
    _FIELDS = ("losses", "loss_count", "loss_ptr", "ema_latency",
               "has_latency", "ema_work", "drop_obs", "part_obs",
               "incl_obs", "excl_obs", "times_selected", "last_selected",
               "ema_interarrival", "arr_obs")
    # fields added after checkpoints already existed in the wild: absent
    # from an old state dict means "resume cold", not "refuse to load"
    _OPTIONAL_FIELDS = ("ema_interarrival", "arr_obs")

    def state_dict(self) -> Dict[str, np.ndarray]:
        return {f: np.asarray(getattr(self, f)).copy() for f in self._FIELDS}

    def load_state_dict(self, state: Dict[str, np.ndarray]) -> None:
        for f in self._FIELDS:
            if f not in state:
                if f in self._OPTIONAL_FIELDS:
                    continue
                raise ValueError(f"selection state missing field {f!r}")
            cur = getattr(self, f)
            val = np.asarray(state[f], dtype=cur.dtype)
            if val.shape != cur.shape:
                raise ValueError(
                    f"selection state field {f!r} has shape {val.shape}, "
                    f"expected {cur.shape} (population or loss-window "
                    "mismatch with the checkpoint)")
            setattr(self, f, val.copy())
