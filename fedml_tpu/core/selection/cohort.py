"""Streaming cohort assembly — the cross-device round's front door.

Bonawitz et al. (MLSys'19, "Towards Federated Learning at Scale")
structure a cross-device round as *selection* over the devices that
happen to be reachable AND eligible (charging, idle, on unmetered
network), sized by a pace-steering target; Lai et al. (OSDI'21, Oort)
add utility-guided picking with a deadline-driven **pacer** that trades
cohort over-sampling against the round deadline from observed
completions. This module is those three pieces for this repo's
cross-device plane, shaped so no step ever materializes the population:

* :func:`required_eligibility` / :func:`eligible_mask` — predicate over
  the charging/idle/unmetered analogues each device reports on its
  registration handshake (``DeviceMessage``);
* :class:`StreamingCohortAssembler` — scans candidate ids in chunks
  (an iterator of id arrays — the online-device table, or
  :func:`population_chunks` for synthetic sweeps), filters eligibility,
  scores via the stats store's id-parameterized queries (Oort utility,
  or uniform), and folds each chunk into a running partial top-k — O(m
  scanned + target·log target) time, O(chunk + target) memory;
* :class:`DeadlinePacer` — adjusts the round deadline and the cohort
  over-sample factor from observed (completed, expected, wall) outcomes:
  under-delivering rounds stretch the deadline and over-sample harder,
  comfortably-early rounds tighten both. With ``pacer_adapt_cohort`` it
  also moves the cohort size k itself (Oort §5's pacer rule): when the
  aggregate statistical utility of consecutive windows saturates, grow
  k to harvest more parallelism per round; while utility is still
  climbing, decay back toward the configured k. A pure function of the
  observation history (no RNG), so trajectories are replayable.

Scoring adds a tiny seeded per-id jitter — a hash of ``(seed, round,
id)``, independent of chunking — so the cold-start case (every candidate
at the neutral fill utility) selects a uniformly-spread cohort instead
of the lowest ids.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..obs import metrics as obs_metrics
from .strategies import OortSelection, partial_top_k

# the charging / idle / unmetered-network analogues a device reports on
# its handshake; every key defaults to True when unreported (a silent
# device is assumed eligible, matching the reference's behavior of
# training every registered phone)
ELIGIBILITY_KEYS = ("charging", "idle", "unmetered")

_JITTER_MULT = np.uint64(0x9E3779B97F4A7C15)  # splitmix64 increment


def required_eligibility(args) -> Tuple[str, ...]:
    """Which handshake predicates this deployment enforces
    (``cohort_require_charging`` / ``_idle`` / ``_unmetered`` knobs; all
    off by default — eligibility then never filters)."""
    return tuple(k for k in ELIGIBILITY_KEYS
                 if bool(getattr(args, f"cohort_require_{k}", False)))


def eligible_mask(metas: Iterable[dict],
                  required: Tuple[str, ...]) -> np.ndarray:
    """[len(metas)] bool — device metadata dicts vs the required keys."""
    metas = list(metas)
    if not required:
        return np.ones(len(metas), bool)
    return np.asarray([all(bool(m.get(k, True)) for k in required)
                       for m in metas], bool)


def population_chunks(n: int, chunk: int = 8192,
                      start: int = 0) -> Iterator[np.ndarray]:
    """Id ranges [start, n) as arrays of ≤ chunk ids — the synthetic
    full-population candidate source; only one chunk exists at a time."""
    chunk = max(int(chunk), 1)
    for lo in range(int(start), int(n), chunk):
        yield np.arange(lo, min(lo + chunk, int(n)), dtype=np.int64)


def _seeded_jitter(ids: np.ndarray, seed: int,
                   round_idx: int) -> np.ndarray:
    """[len(ids)] uniform-ish floats in [0, 1) from a splitmix64-style
    hash of (seed, round, id) — deterministic AND independent of how the
    candidate stream is chunked, unlike drawing from a sequential
    generator."""
    x = (ids.astype(np.uint64)
         + np.uint64((seed * 1_000_003 + round_idx * 7919) & 0xFFFFFFFF))
    x = (x + np.uint64(1)) * _JITTER_MULT
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return (x >> np.uint64(11)).astype(np.float64) / float(1 << 53)


@dataclass
class AssemblyResult:
    cohort: List[int]            # best-first
    scanned: int = 0             # candidate ids seen
    eligible: int = 0            # candidates passing the predicates
    wall_ms: float = 0.0
    scores: Optional[np.ndarray] = None  # per-cohort-member, best-first


class StreamingCohortAssembler:
    """Chunked eligibility scan + utility scoring + running partial
    top-k over any candidate-id stream."""

    def __init__(self, args, store, num_clients: int):
        self.args = args
        self.store = store
        self.n = int(num_clients)
        self.seed = int(getattr(args, "random_seed", 0) or 0)
        self.chunk = max(int(getattr(args, "cohort_scan_chunk", 8192)
                             or 8192), 1)
        scoring = str(getattr(args, "cohort_scoring", "oort")
                      or "oort").lower()
        if scoring not in ("oort", "uniform"):
            raise ValueError(f"cohort_scoring {scoring!r} unknown; choose "
                             "from ('oort', 'uniform')")
        self.scoring = scoring
        # utility math is shared with the engine's oort strategy — one
        # implementation, two planes
        self._oort = OortSelection(args, self.n, store)
        self.jitter = float(getattr(args, "cohort_jitter", 1e-6) or 0.0)

    def _score(self, round_idx: int, ids: np.ndarray) -> np.ndarray:
        if self.scoring == "uniform":
            base = np.zeros(len(ids), np.float64)
        else:
            base = np.asarray(
                self._oort._utility_for(round_idx, ids), np.float64)
        if self.jitter > 0.0:
            base = base + self.jitter * _seeded_jitter(
                ids, self.seed, round_idx)
        return base

    def assemble(self, round_idx: int, target: int,
                 candidates: Iterable[np.ndarray],
                 eligible_fn: Optional[Callable[[np.ndarray], np.ndarray]]
                 = None,
                 deadline_s: Optional[float] = None,
                 over_sample: Optional[float] = None) -> AssemblyResult:
        """Stream candidate-id chunks into a cohort of ≤ ``target``.

        ``eligible_fn(ids) -> bool mask`` vectorizes the deployment's
        predicate over a chunk (the server wraps its online-device
        metadata; synthetic benches wrap a hash). Only ``chunk + target``
        ids are ever live at once."""
        t0 = time.perf_counter()
        target = max(int(target), 0)
        best_ids = np.empty(0, np.int64)
        best_scores = np.empty(0, np.float64)
        scanned = eligible = 0
        for ids in candidates:
            ids = np.asarray(ids, np.int64)
            scanned += len(ids)
            if eligible_fn is not None:
                mask = np.asarray(eligible_fn(ids), bool)
                ids = ids[mask]
            eligible += len(ids)
            if not len(ids) or not target:
                continue
            scores = self._score(round_idx, ids)
            # fold into the running top-k: concat is O(chunk + target),
            # partial_top_k is O(chunk + target + k log k)
            merged_ids = np.concatenate([best_ids, ids])
            merged_scores = np.concatenate([best_scores, scores])
            keep = partial_top_k(merged_scores, target)
            best_ids = merged_ids[keep]
            best_scores = merged_scores[keep]
        wall_ms = (time.perf_counter() - t0) * 1e3
        obs_metrics.record_cohort_assembly(
            wall_ms / 1e3, scanned, eligible, len(best_ids),
            deadline_s=deadline_s, over_sample=over_sample)
        return AssemblyResult(cohort=[int(c) for c in best_ids],
                              scanned=scanned, eligible=eligible,
                              wall_ms=wall_ms, scores=best_scores)


@dataclass
class DeadlinePacer:
    """Oort's deadline-driven pacer: the round deadline T and the cohort
    over-sample factor move together from observed round outcomes.

    A round that closes with fewer than ``target_frac`` of its expected
    reports by the deadline was paced too aggressively: stretch T and
    over-sample harder (more redundancy absorbs the stragglers). A round
    that delivers everything in well under T was paced too timidly:
    tighten both. Multiplicative steps, hard bounds, no RNG — the
    trajectory is a pure function of the observation sequence, which is
    what makes pacing assertable in tests."""

    deadline_s: float = 60.0
    over_sample: float = 1.3
    target_frac: float = 0.8
    step: float = 0.2
    min_deadline_s: float = 1.0
    max_deadline_s: float = 3600.0
    max_over_sample: float = 3.0
    rounds_observed: int = field(default=0)
    # --- utility-driven cohort sizing (pacer_adapt_cohort; off = the
    # configured k never moves — paced_cohort() is the identity) -------
    adapt_cohort: bool = False
    cohort_scale: float = 1.0
    min_cohort_scale: float = 1.0
    max_cohort_scale: float = 4.0
    util_window: int = 4
    util_saturation: float = 0.05
    _util_hist: List[float] = field(default_factory=list)

    @classmethod
    def from_args(cls, args) -> "DeadlinePacer":
        deadline = float(getattr(args, "pacer_deadline_s", 0) or 0)
        if deadline <= 0:
            deadline = float(getattr(args, "round_timeout_s", 0) or 0) \
                or 60.0
        return cls(
            deadline_s=deadline,
            over_sample=float(getattr(args, "pacer_over_sample", 1.3)
                              or 1.3),
            target_frac=float(getattr(args, "pacer_target_frac", 0.8)
                              or 0.8),
            step=float(getattr(args, "pacer_step", 0.2) or 0.2),
            min_deadline_s=float(getattr(args, "pacer_min_deadline_s", 1.0)
                                 or 1.0),
            max_deadline_s=float(getattr(args, "pacer_max_deadline_s",
                                         3600.0) or 3600.0),
            max_over_sample=float(getattr(args, "pacer_max_over_sample",
                                          3.0) or 3.0),
            adapt_cohort=bool(getattr(args, "pacer_adapt_cohort", False)),
            min_cohort_scale=float(getattr(args, "pacer_min_cohort_scale",
                                           1.0) or 1.0),
            max_cohort_scale=float(getattr(args, "pacer_max_cohort_scale",
                                           4.0) or 4.0),
            util_window=max(int(getattr(args, "pacer_util_window", 4)
                                or 4), 1),
            util_saturation=float(getattr(args, "pacer_util_saturation",
                                          0.05) or 0.05))

    def target_cohort(self, k: int, ceiling: Optional[int] = None) -> int:
        """Over-sampled dispatch size for a wanted cohort of ``k``."""
        t = int(np.ceil(max(int(k), 1) * self.over_sample))
        if ceiling is not None:
            t = min(t, int(ceiling))
        return max(t, 1)

    def paced_cohort(self, k: int) -> int:
        """The live cohort size for a configured k: identity unless
        ``adapt_cohort`` is on, else k scaled by the utility-driven
        ``cohort_scale`` (bounded; callers still ceiling by population)."""
        k = max(int(k), 1)
        if not self.adapt_cohort:
            return k
        return max(int(round(k * self.cohort_scale)), 1)

    def observe_utility(self, utility: float) -> None:
        """One round's aggregate statistical utility (the assembled
        cohort's summed scores). Every ``util_window`` observations the
        pacer compares the window mean against the previous window:
        saturation (no relative improvement past ``util_saturation``)
        grows the cohort scale — more devices per round keep progress
        moving once per-device utility plateaus (Oort's rule) — while a
        still-improving utility decays the scale back toward 1× (the
        configured k already harvests well). No-op when adaptation is
        off, so default-path trajectories carry no hidden state."""
        if not self.adapt_cohort:
            return
        self._util_hist.append(float(utility))
        w = self.util_window
        if len(self._util_hist) < 2 * w:
            return
        prev = float(np.mean(self._util_hist[-2 * w:-w]))
        cur = float(np.mean(self._util_hist[-w:]))
        rel = (cur - prev) / max(abs(prev), 1e-12)
        if rel <= self.util_saturation:
            self.cohort_scale = min(self.cohort_scale * (1.0 + self.step),
                                    self.max_cohort_scale)
        else:
            self.cohort_scale = max(self.cohort_scale * (1.0 - self.step / 2),
                                    self.min_cohort_scale)
        # the decided-on window becomes the next comparison's baseline
        self._util_hist = self._util_hist[-w:]

    def observe_round(self, completed: int, expected: int,
                      wall_s: float) -> None:
        """One closed round: ``completed`` of ``expected`` dispatched
        devices reported within ``wall_s``."""
        self.rounds_observed += 1
        expected = max(int(expected), 1)
        frac = min(max(int(completed), 0) / expected, 1.0)
        if frac < self.target_frac:
            # under-delivered: stretch the deadline AND over-sample more
            self.deadline_s = min(self.deadline_s * (1.0 + self.step),
                                  self.max_deadline_s)
            self.over_sample = min(self.over_sample * (1.0 + self.step),
                                   self.max_over_sample)
        elif frac >= 1.0 and wall_s <= 0.5 * self.deadline_s:
            # everyone reported in half the budget: pace up
            self.deadline_s = max(self.deadline_s * (1.0 - self.step / 2),
                                  self.min_deadline_s)
            self.over_sample = max(self.over_sample * (1.0 - self.step / 2),
                                   1.0)

    def state_dict(self) -> dict:
        # util_hist rides as a FIXED [2 * util_window] NaN-padded array:
        # template-based checkpoint restores (orbax-style) need stable
        # shapes between save and resume
        hist = np.full(2 * self.util_window, np.nan, np.float64)
        tail = self._util_hist[-len(hist):]
        if tail:
            hist[:len(tail)] = tail
        return {"deadline_s": np.float64(self.deadline_s),
                "over_sample": np.float64(self.over_sample),
                "rounds_observed": np.int64(self.rounds_observed),
                "cohort_scale": np.float64(self.cohort_scale),
                "util_hist": hist}

    def load_state_dict(self, state: dict) -> None:
        self.deadline_s = float(state["deadline_s"])
        self.over_sample = float(state["over_sample"])
        self.rounds_observed = int(state["rounds_observed"])
        # cohort-sizing fields postdate checkpoints in the wild: absent
        # means "resume with the configured scale", never a refusal
        if "cohort_scale" in state:
            self.cohort_scale = float(state["cohort_scale"])
        if "util_hist" in state:
            hist = np.asarray(state["util_hist"], np.float64).reshape(-1)
            self._util_hist = [float(v) for v in hist[np.isfinite(hist)]]
