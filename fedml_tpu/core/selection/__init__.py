"""Adaptive participant selection & client reputation.

Turns the signals the framework already produces — per-round losses,
observed work fractions and dropouts (chaos ledger), cross-silo upload
latencies, defense exclusion verdicts — into *who trains next round*:

* :class:`ClientStatsStore` — per-client EMA latency/work, Beta-posterior
  dropout estimate, last-K losses, defense-decayed reputation; NumPy
  state that rides :class:`~fedml_tpu.core.checkpoint.RoundCheckpointer`.
* strategies behind the ``client_selection`` knob: ``uniform`` (default,
  bit-identical schedules), ``power_of_choice``, ``oort``,
  ``reputation`` (low-reputation clients become renormalized in-program
  dropout — the byzantine-aware-dropout closer).
* :class:`SelectionManager` — the engine/server seam: lazy device-array
  observation queue, adaptive over-sampling from the dropout posterior.

Selection is host-side policy; cohorts ride the jitted round programs
purely as schedule DATA, so the canonical slot width and the compile-once
invariant hold for every strategy.
"""

from .manager import SelectionManager, slot_placement
from .stats import ClientStatsStore
from .strategies import (SELECTION_STRATEGIES, OortSelection,
                         PowerOfChoiceSelection, ReputationSelection,
                         SelectionStrategy, UniformSelection, cap_bench,
                         create_strategy)

__all__ = ["ClientStatsStore", "SelectionManager", "SelectionStrategy",
           "UniformSelection", "PowerOfChoiceSelection", "OortSelection",
           "ReputationSelection", "SELECTION_STRATEGIES",
           "cap_bench", "create_strategy", "slot_placement"]
