"""Adaptive participant selection & client reputation.

Turns the signals the framework already produces — per-round losses,
observed work fractions and dropouts (chaos ledger), cross-silo upload
latencies, defense exclusion verdicts — into *who trains next round*:

* :class:`ClientStatsStore` — per-client EMA latency/work, Beta-posterior
  dropout estimate, last-K losses, defense-decayed reputation; NumPy
  state that rides :class:`~fedml_tpu.core.checkpoint.RoundCheckpointer`.
* :class:`SparseClientStatsStore` — the million-client backend: the same
  observation/query API over *touched-client* columnar state, selected
  by the ``selection_store`` knob (``auto`` flips at
  ``selection_sparse_threshold``); posteriors bit-identical to dense.
* strategies behind the ``client_selection`` knob: ``uniform`` (default,
  bit-identical schedules), ``power_of_choice``, ``oort``,
  ``reputation`` (low-reputation clients become renormalized in-program
  dropout — the byzantine-aware-dropout closer). Above
  ``selection_pool_threshold`` clients they score a seeded candidate
  pool of ``m ≫ k`` ids with ``np.argpartition`` partial top-k —
  O(m + k log k), never O(N log N).
* :mod:`~fedml_tpu.core.selection.cohort` — the cross-device round's
  front door: handshake eligibility predicates, a streaming chunked
  top-k assembler, and Oort's deadline-driven :class:`DeadlinePacer`.
* :class:`SelectionManager` — the engine/server seam: lazy device-array
  observation queue, adaptive over-sampling from the dropout posterior.

Selection is host-side policy; cohorts ride the jitted round programs
purely as schedule DATA, so the canonical slot width and the compile-once
invariant hold for every strategy.
"""

from .cohort import (DeadlinePacer, StreamingCohortAssembler, eligible_mask,
                     population_chunks, required_eligibility)
from .manager import (STORE_BACKENDS, SelectionManager, make_stats_store,
                      slot_placement)
from .sparse import SparseClientStatsStore
from .stats import ClientStatsStore
from .strategies import (SELECTION_STRATEGIES, OortSelection,
                         PowerOfChoiceSelection, ReputationSelection,
                         SelectionStrategy, UniformSelection, cap_bench,
                         create_strategy, partial_top_k, pool_size)

__all__ = ["ClientStatsStore", "SparseClientStatsStore", "SelectionManager",
           "SelectionStrategy", "UniformSelection",
           "PowerOfChoiceSelection", "OortSelection", "ReputationSelection",
           "SELECTION_STRATEGIES", "STORE_BACKENDS",
           "cap_bench", "create_strategy", "slot_placement",
           "make_stats_store", "partial_top_k", "pool_size",
           "DeadlinePacer", "StreamingCohortAssembler", "eligible_mask",
           "population_chunks", "required_eligibility"]
