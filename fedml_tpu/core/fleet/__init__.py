"""Durable multi-tenant fleet plane (Bonawitz MLSys'19 §4 endgame).

* :class:`DeviceRegistry` — sqlite-backed persistent device registry:
  idempotent handshake upserts, participation history, atomic per-round
  claims (one task per device per round), and npz-serialized
  control-plane state snapshots.
* :class:`TaskPlane` / :class:`FleetTask` — N concurrent federated jobs
  (training, analytics, LLM-LoRA) over one registry, sharing one stats
  store, with per-task cohort assembly + pacing and registry-enforced
  fairness caps.
"""

from .plane import FleetTask, TaskPlane
from .registry import DeviceRegistry

__all__ = ["DeviceRegistry", "FleetTask", "TaskPlane"]
