"""Persistent device registry — the fleet's durable memory.

Parity target: Bonawitz et al. (MLSys'19, §4) keep a *device registry*
behind selection — the server knows every device that ever completed the
handshake, not just the ones currently connected — and run many
concurrent FL tasks against that one population. This module is the
sqlite half of that design (the pacing/claiming logic lives in
:mod:`.plane`), riding the ``ResourceDB`` idiom from
``fedml_tpu/api/scheduler.py``: one file per deployment, short-lived
connections, explicit ``BEGIN IMMEDIATE`` around every check-then-write
so concurrent task servers (separate *processes* sharing the file) stay
serialized without a daemon.

Four tables:

* ``devices`` — one row per device ever registered: handshake
  eligibility (charging/idle/unmetered analogues), first/last-heard
  timestamps, and a registration counter. :meth:`register` is an UPSERT:
  re-registering under the same id refreshes the eligibility and
  ``last_heard`` **in place** — never a duplicate row, never a reset of
  the participation history.
* ``participation`` — append-only (task, device, round, ts) records; the
  trailing-window fairness cap reads these.
* ``claims`` — the *live* round assignments; ``device_id`` is the
  primary key, so "a device serves at most one task per round" is a
  uniqueness constraint, not a convention.
* ``plane_state`` — npz-serialized control-plane snapshots (stats
  store, pacer posture, round cursor) keyed by name, so a restarted
  server resumes the learned fleet posture instead of re-learning it.

Every mutating method takes an optional ``now`` timestamp; tests and the
bench drive a logical clock through it, production callers leave the
default wall clock.
"""

from __future__ import annotations

import contextlib
import io
import logging
import os
import sqlite3
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# the handshake eligibility analogues a device row carries (mirrors
# core/selection/cohort.ELIGIBILITY_KEYS; duplicated as column names)
_ELIG_COLS = ("charging", "idle", "unmetered")

# sqlite IN(...) parameter batches stay well under SQLITE_MAX_VARIABLE_NUMBER
_IN_CHUNK = 512


def _now(now: Optional[float]) -> float:
    return time.time() if now is None else float(now)


class DeviceRegistry:
    """Sqlite-backed fleet registry: devices, participation history,
    live per-round claims, and checkpointed control-plane state."""

    def __init__(self, path: str):
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.path = path
        with self._conn() as c:
            c.execute("""CREATE TABLE IF NOT EXISTS devices (
                device_id INTEGER PRIMARY KEY,
                os TEXT DEFAULT '?',
                engine TEXT DEFAULT '?',
                charging INTEGER DEFAULT 1,
                idle INTEGER DEFAULT 1,
                unmetered INTEGER DEFAULT 1,
                first_seen REAL NOT NULL,
                last_heard REAL NOT NULL,
                registrations INTEGER DEFAULT 1)""")
            c.execute("""CREATE TABLE IF NOT EXISTS participation (
                task_id TEXT NOT NULL,
                device_id INTEGER NOT NULL,
                round INTEGER NOT NULL,
                ts REAL NOT NULL)""")
            c.execute("""CREATE INDEX IF NOT EXISTS idx_part_device
                ON participation(device_id, ts)""")
            c.execute("""CREATE INDEX IF NOT EXISTS idx_part_round
                ON participation(device_id, round)""")
            c.execute("""CREATE TABLE IF NOT EXISTS claims (
                device_id INTEGER PRIMARY KEY,
                task_id TEXT NOT NULL,
                round INTEGER NOT NULL,
                ts REAL NOT NULL)""")
            c.execute("""CREATE TABLE IF NOT EXISTS plane_state (
                key TEXT PRIMARY KEY,
                blob BLOB NOT NULL,
                ts REAL NOT NULL)""")

    @contextlib.contextmanager
    def _conn(self):
        conn = sqlite3.connect(self.path, timeout=10.0)
        conn.isolation_level = None  # autocommit; we use explicit BEGIN
        try:
            yield conn
        finally:
            conn.close()

    # --- device table -------------------------------------------------------
    def register(self, device_id: int, meta: Optional[dict] = None,
                 now: Optional[float] = None) -> None:
        """Idempotent handshake record: first registration inserts the
        row, every later one refreshes eligibility + ``last_heard`` in
        place (``first_seen``, participation history, and the claim
        table are untouched — a flapping device never looks new)."""
        meta = meta or {}
        ts = _now(now)
        vals = (int(device_id), str(meta.get("os", "?")),
                str(meta.get("engine", "?")),
                int(bool(meta.get("charging", True))),
                int(bool(meta.get("idle", True))),
                int(bool(meta.get("unmetered", True))), ts, ts)
        with self._conn() as c:
            c.execute(
                "INSERT INTO devices (device_id, os, engine, charging, "
                "idle, unmetered, first_seen, last_heard) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?) "
                "ON CONFLICT(device_id) DO UPDATE SET "
                "os=excluded.os, engine=excluded.engine, "
                "charging=excluded.charging, idle=excluded.idle, "
                "unmetered=excluded.unmetered, "
                "last_heard=excluded.last_heard, "
                "registrations=registrations+1", vals)

    def register_many(self, device_ids: Sequence[int],
                      metas: Optional[Sequence[dict]] = None,
                      now: Optional[float] = None) -> None:
        """Bulk :meth:`register` over one connection — fleet imports and
        the 100k-device bench; same UPSERT semantics per row."""
        ts = _now(now)
        metas = metas if metas is not None else [{}] * len(device_ids)
        rows = [(int(d), str(m.get("os", "?")), str(m.get("engine", "?")),
                 int(bool(m.get("charging", True))),
                 int(bool(m.get("idle", True))),
                 int(bool(m.get("unmetered", True))), ts, ts)
                for d, m in zip(device_ids, metas)]
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            try:
                c.executemany(
                    "INSERT INTO devices (device_id, os, engine, charging, "
                    "idle, unmetered, first_seen, last_heard) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(device_id) DO UPDATE SET "
                    "os=excluded.os, engine=excluded.engine, "
                    "charging=excluded.charging, idle=excluded.idle, "
                    "unmetered=excluded.unmetered, "
                    "last_heard=excluded.last_heard, "
                    "registrations=registrations+1", rows)
                c.execute("COMMIT")
            except sqlite3.Error:
                c.execute("ROLLBACK")
                raise

    def touch(self, device_ids: Sequence[int],
              now: Optional[float] = None) -> None:
        """Refresh ``last_heard`` (e.g. on a model upload)."""
        ts = _now(now)
        ids = [int(d) for d in device_ids]
        with self._conn() as c:
            c.executemany("UPDATE devices SET last_heard=? WHERE device_id=?",
                          [(ts, d) for d in ids])

    def device(self, device_id: int) -> Optional[dict]:
        with self._conn() as c:
            row = c.execute(
                "SELECT device_id, os, engine, charging, idle, unmetered, "
                "first_seen, last_heard, registrations FROM devices "
                "WHERE device_id=?", (int(device_id),)).fetchone()
        if row is None:
            return None
        return {"device_id": int(row[0]), "os": row[1], "engine": row[2],
                "charging": bool(row[3]), "idle": bool(row[4]),
                "unmetered": bool(row[5]), "first_seen": float(row[6]),
                "last_heard": float(row[7]), "registrations": int(row[8])}

    def device_count(self) -> int:
        with self._conn() as c:
            return int(c.execute("SELECT COUNT(*) FROM devices")
                       .fetchone()[0])

    def known_ids(self) -> np.ndarray:
        with self._conn() as c:
            rows = c.execute(
                "SELECT device_id FROM devices ORDER BY device_id"
            ).fetchall()
        return np.asarray([r[0] for r in rows], np.int64)

    def iter_id_chunks(self, chunk: int = 8192) -> Iterator[np.ndarray]:
        """Ascending device-id pages of ≤ ``chunk`` — the streaming
        cohort assembler's candidate source; the population is never
        materialized in one array."""
        chunk = max(int(chunk), 1)
        last = -1
        while True:
            with self._conn() as c:
                rows = c.execute(
                    "SELECT device_id FROM devices WHERE device_id > ? "
                    "ORDER BY device_id LIMIT ?", (last, chunk)).fetchall()
            if not rows:
                return
            ids = np.asarray([r[0] for r in rows], np.int64)
            last = int(ids[-1])
            yield ids

    def eligibility_for(self, ids: Sequence[int]) -> List[dict]:
        """Handshake metadata dicts for ``ids`` (unknown ids get the
        all-True default, matching the silent-device convention)."""
        ids = [int(d) for d in ids]
        found: Dict[int, dict] = {}
        with self._conn() as c:
            for lo in range(0, len(ids), _IN_CHUNK):
                batch = ids[lo:lo + _IN_CHUNK]
                q = ",".join("?" * len(batch))
                for row in c.execute(
                        f"SELECT device_id, charging, idle, unmetered "
                        f"FROM devices WHERE device_id IN ({q})", batch):
                    found[int(row[0])] = {"charging": bool(row[1]),
                                          "idle": bool(row[2]),
                                          "unmetered": bool(row[3])}
        default = {k: True for k in _ELIG_COLS}
        return [found.get(d, default) for d in ids]

    # --- fairness: participation history + live claims ----------------------
    def participation_counts(self, ids: Sequence[int], window_s: float,
                             now: Optional[float] = None) -> np.ndarray:
        """[len(ids)] rounds each device served (any task) inside the
        trailing ``window_s`` — the fairness cap's evidence."""
        ids = [int(d) for d in ids]
        since = _now(now) - float(window_s)
        counts: Dict[int, int] = {}
        with self._conn() as c:
            for lo in range(0, len(ids), _IN_CHUNK):
                batch = ids[lo:lo + _IN_CHUNK]
                q = ",".join("?" * len(batch))
                for did, n in c.execute(
                        f"SELECT device_id, COUNT(*) FROM participation "
                        f"WHERE ts >= ? AND device_id IN ({q}) "
                        f"GROUP BY device_id", [since] + batch):
                    counts[int(did)] = int(n)
        return np.asarray([counts.get(d, 0) for d in ids], np.int64)

    def active_claims(self) -> Dict[int, str]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT device_id, task_id FROM claims").fetchall()
        return {int(d): str(t) for d, t in rows}

    def claim(self, task_id: str, ids: Sequence[int], round_idx: int,
              cap: int = 0, window_s: float = 3600.0,
              now: Optional[float] = None) -> Tuple[List[int], int, int]:
        """Atomically claim ``ids`` for one round of ``task_id``.

        Returns ``(granted, denied_busy, denied_cap)`` — assembly order
        preserved. A device already claimed by ANOTHER task is busy
        (one task per round per device: the ``claims`` primary key);
        one at/over ``cap`` participations in the trailing ``window_s``
        is capped (0 = uncapped). The check-then-insert runs under
        ``BEGIN IMMEDIATE`` so concurrent task servers sharing the file
        cannot double-claim."""
        ids = [int(d) for d in ids]
        ts = _now(now)
        since = ts - float(window_s)
        granted: List[int] = []
        denied_busy = denied_cap = 0
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")  # serialize check+insert
            try:
                held = {int(d): str(t) for d, t in c.execute(
                    "SELECT device_id, task_id FROM claims")}
                if cap and ids:
                    counts: Dict[int, int] = {}
                    for lo in range(0, len(ids), _IN_CHUNK):
                        batch = ids[lo:lo + _IN_CHUNK]
                        q = ",".join("?" * len(batch))
                        for did, n in c.execute(
                                f"SELECT device_id, COUNT(*) "
                                f"FROM participation WHERE ts >= ? "
                                f"AND device_id IN ({q}) "
                                f"GROUP BY device_id", [since] + batch):
                            counts[int(did)] = int(n)
                else:
                    counts = {}
                for d in ids:
                    if d in held:
                        if held[d] != str(task_id):
                            denied_busy += 1
                        # re-claim by the SAME task (retry) is idempotent
                        else:
                            granted.append(d)
                        continue
                    if cap and counts.get(d, 0) >= int(cap):
                        denied_cap += 1
                        continue
                    c.execute("INSERT INTO claims VALUES (?, ?, ?, ?)",
                              (d, str(task_id), int(round_idx), ts))
                    held[d] = str(task_id)
                    granted.append(d)
                c.execute("COMMIT")
            except sqlite3.Error:
                c.execute("ROLLBACK")
                raise
        return granted, denied_busy, denied_cap

    def release(self, task_id: str, round_idx: int,
                participated: Sequence[int],
                now: Optional[float] = None) -> None:
        """Close ``task_id``'s round: drop its claims, append a
        participation record per device that actually served."""
        ts = _now(now)
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            try:
                c.executemany(
                    "INSERT INTO participation VALUES (?, ?, ?, ?)",
                    [(str(task_id), int(d), int(round_idx), ts)
                     for d in participated])
                c.execute("DELETE FROM claims WHERE task_id=?",
                          (str(task_id),))
                c.execute("COMMIT")
            except sqlite3.Error:
                c.execute("ROLLBACK")
                raise

    def prune_participation(self, keep_window_s: float,
                            now: Optional[float] = None) -> int:
        """Drop participation rows older than the fairness window (the
        cap never reads them again); returns rows removed."""
        cutoff = _now(now) - float(keep_window_s)
        with self._conn() as c:
            cur = c.execute("DELETE FROM participation WHERE ts < ?",
                            (cutoff,))
            return int(cur.rowcount)

    def audit(self, cap: int = 0,
              window_s: float = 3600.0) -> Dict[str, int]:
        """Fairness post-mortem over the FULL participation history:
        ``overlap`` counts (device, round) pairs served by more than one
        task; ``cap_violations`` counts devices whose sliding
        ``window_s`` participation ever exceeded ``cap`` (0 skips the
        check). The bench and the acceptance tests pin both at zero."""
        with self._conn() as c:
            overlap = int(c.execute(
                "SELECT COUNT(*) FROM (SELECT device_id, round "
                "FROM participation GROUP BY device_id, round "
                "HAVING COUNT(DISTINCT task_id) > 1)").fetchone()[0])
            cap_violations = 0
            if cap:
                rows = c.execute(
                    "SELECT device_id, ts FROM participation "
                    "ORDER BY device_id, ts").fetchall()
                i = 0
                while i < len(rows):
                    j = i
                    did = rows[i][0]
                    while j < len(rows) and rows[j][0] == did:
                        j += 1
                    ts = [r[1] for r in rows[i:j]]
                    lo = 0
                    worst = 0
                    for hi in range(len(ts)):
                        while ts[hi] - ts[lo] >= float(window_s):
                            lo += 1
                        worst = max(worst, hi - lo + 1)
                    if worst > int(cap):
                        cap_violations += 1
                    i = j
        return {"overlap": overlap, "cap_violations": cap_violations}

    # --- checkpointed control-plane state -----------------------------------
    def save_state(self, key: str, arrays: Dict[str, np.ndarray],
                   now: Optional[float] = None) -> None:
        """Persist one named control-plane snapshot (stats store columns,
        pacer posture, round cursor) as an npz blob — the shapes travel
        with the data, so the sparse store's compacted columns fit."""
        buf = io.BytesIO()
        np.savez_compressed(
            buf, **{k: np.asarray(v) for k, v in arrays.items()})
        with self._conn() as c:
            c.execute("INSERT OR REPLACE INTO plane_state VALUES (?, ?, ?)",
                      (str(key), buf.getvalue(), _now(now)))

    def load_state(self, key: str) -> Optional[Dict[str, np.ndarray]]:
        with self._conn() as c:
            row = c.execute("SELECT blob FROM plane_state WHERE key=?",
                            (str(key),)).fetchone()
        if row is None:
            return None
        with np.load(io.BytesIO(row[0]), allow_pickle=False) as z:
            return {k: z[k].copy() for k in z.files}

    def state_keys(self) -> List[str]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT key FROM plane_state ORDER BY key").fetchall()
        return [str(r[0]) for r in rows]
