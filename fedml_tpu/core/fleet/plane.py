"""Multi-tenant task plane: N concurrent FL tasks over one registry.

Bonawitz et al. (MLSys'19, §4) run many FL *tasks* — training jobs,
federated analytics, on-device personalization — against one shared
device population, with per-task eligibility and pace steering arbitrated
by the coordinator. :class:`TaskPlane` is that coordinator for this
repo's control plane:

* every task gets its own :class:`StreamingCohortAssembler` (own jitter
  stream — concurrent tasks spread over the population instead of all
  chasing the same top-utility devices) and its own
  :class:`DeadlinePacer` (per-task deadline / over-sample / cohort-scale
  posture);
* all tasks share ONE :class:`ClientStatsStore` — availability, latency,
  and reputation evidence observed by any task benefits every task (the
  PR 5 reputation store, fleet-wide);
* fairness is the registry's job: a device serves at most one task per
  round (the ``claims`` primary key) and at most
  ``fleet_max_rounds_per_window`` rounds in the trailing
  ``fleet_fairness_window_s`` (participation history), both enforced
  atomically in :meth:`DeviceRegistry.claim`.

The plane is deterministic under a logical clock: every method takes an
optional ``now``, and the assembler/pacer trajectories are pure
functions of the observation history — which is what makes
restart-and-resume replay *identical* cohorts, assertable in tests.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as obs_metrics
from ..selection import (DeadlinePacer, StreamingCohortAssembler,
                         make_stats_store, required_eligibility)
from ..selection.cohort import eligible_mask
from .registry import DeviceRegistry

logger = logging.getLogger(__name__)


class _TaskArgs:
    """Args proxy with a per-task ``random_seed`` — each task's assembler
    gets its own jitter stream (splitmix of the base seed and the task
    name) while every other knob passes through untouched."""

    def __init__(self, args, task_id: str):
        self._args = args
        base = int(getattr(args, "random_seed", 0) or 0)
        h = 0
        for ch in str(task_id):
            h = (h * 131 + ord(ch)) & 0x7FFFFFFF
        self.random_seed = (base * 1_000_003 + h) & 0x7FFFFFFF

    def __getattr__(self, name):
        return getattr(self._args, name)


class FleetTask:
    """One tenant: a named federated job with its own pacing posture."""

    def __init__(self, plane: "TaskPlane", task_id: str, cohort_k: int,
                 kind: str = "training",
                 require: Optional[Tuple[str, ...]] = None):
        self.task_id = str(task_id)
        self.kind = str(kind)
        self.cohort_k = int(cohort_k)
        self.require = (tuple(require) if require is not None
                        else required_eligibility(plane.args))
        targs = _TaskArgs(plane.args, self.task_id)
        self.assembler = StreamingCohortAssembler(targs, plane.stats,
                                                  plane.population)
        self.pacer = DeadlinePacer.from_args(plane.args)
        self.last_cohort: List[int] = []
        self.last_utility = 0.0
        self.rounds_run = 0

    def state_key(self) -> str:
        return f"pacer:{self.task_id}"


class TaskPlane:
    """N concurrent federated tasks over one :class:`DeviceRegistry`."""

    def __init__(self, args, registry: DeviceRegistry, population: int):
        self.args = args
        self.registry = registry
        self.population = int(population)
        # ONE stats store for the whole fleet — reputation/availability
        # evidence is shared across tenants (sparse backend at scale via
        # the selection_store knob, as everywhere else)
        self.stats = make_stats_store(args, self.population)
        self.cap = int(getattr(args, "fleet_max_rounds_per_window", 0)
                       or 0)
        self.window_s = float(getattr(args, "fleet_fairness_window_s",
                                      3600.0) or 3600.0)
        self.tasks: List[FleetTask] = []
        self.round_cursor = 0
        self.denied_busy = 0
        self.denied_cap = 0

    def add_task(self, task_id: str, cohort_k: int, kind: str = "training",
                 require: Optional[Tuple[str, ...]] = None) -> FleetTask:
        if any(t.task_id == str(task_id) for t in self.tasks):
            raise ValueError(f"fleet task {task_id!r} already exists")
        task = FleetTask(self, task_id, cohort_k, kind=kind,
                         require=require)
        self.tasks.append(task)
        return task

    def task(self, task_id: str) -> FleetTask:
        for t in self.tasks:
            if t.task_id == str(task_id):
                return t
        raise KeyError(task_id)

    # --- the per-round assignment -------------------------------------------
    def _eligible_fn(self, task: FleetTask, taken: set,
                     now: Optional[float]):
        """Chunk predicate: handshake eligibility ∧ not assigned to
        another task this round ∧ under the participation cap. The
        registry's atomic claim re-checks busy/cap — this pre-filter
        keeps the assembler from wasting its top-k on devices the claim
        would bounce."""
        held = self.registry.active_claims()

        def elig(ids: np.ndarray) -> np.ndarray:
            mask = np.asarray(
                [d not in taken
                 and held.get(d, task.task_id) == task.task_id
                 for d in ids.tolist()], bool)
            if task.require and mask.any():
                metas = self.registry.eligibility_for(ids[mask])
                sub = eligible_mask(metas, task.require)
                mask[np.flatnonzero(mask)] = sub
            if self.cap and mask.any():
                counts = self.registry.participation_counts(
                    ids[mask], self.window_s, now=now)
                keep = counts < self.cap
                mask[np.flatnonzero(mask)] = keep
            return mask

        return elig

    def assign_round(self, round_idx: Optional[int] = None,
                     now: Optional[float] = None) -> Dict[str, List[int]]:
        """One fleet round: each task assembles its cohort over the
        registry population (fairness pre-filtered), then claims it
        atomically. Returns ``{task_id: cohort}`` — disjoint by
        construction AND by the claims table."""
        if round_idx is None:
            round_idx = self.round_cursor
        round_idx = int(round_idx)
        taken: set = set()
        out: Dict[str, List[int]] = {}
        for task in self.tasks:
            k = task.pacer.paced_cohort(task.cohort_k)
            target = task.pacer.target_cohort(k)
            res = task.assembler.assemble(
                round_idx, target,
                self.registry.iter_id_chunks(task.assembler.chunk),
                eligible_fn=self._eligible_fn(task, taken, now),
                deadline_s=task.pacer.deadline_s,
                over_sample=task.pacer.over_sample)
            granted, busy, capped = self.registry.claim(
                task.task_id, res.cohort, round_idx, cap=self.cap,
                window_s=self.window_s, now=now)
            self.denied_busy += busy
            self.denied_cap += capped
            task.last_cohort = list(granted)
            # aggregate statistical utility of the picked cohort — the
            # pacer's saturation signal (Oort: grow k when this plateaus)
            if res.scores is not None and len(granted):
                pos = {int(c): i for i, c in enumerate(res.cohort)}
                task.last_utility = float(sum(
                    res.scores[pos[d]] for d in granted if d in pos))
            else:
                task.last_utility = 0.0
            self.stats.record_selected(round_idx, granted)
            out[task.task_id] = list(granted)
            taken.update(granted)
            obs_metrics.record_fleet_round(task.task_id, len(granted),
                                           busy, capped)
        self.round_cursor = round_idx + 1
        return out

    def observe_round(self, task_id: str, reported: Sequence[int],
                      round_idx: Optional[int] = None, wall_s: float = 0.0,
                      now: Optional[float] = None) -> None:
        """Close one task's round: availability evidence for its cohort,
        the pacer's deadline/over-sample step + utility-saturation step,
        and the registry release (claims dropped, participation
        recorded for the devices that actually served)."""
        task = self.task(task_id)
        if round_idx is None:
            round_idx = self.round_cursor - 1
        reported = [int(d) for d in reported]
        rep = set(reported)
        for d in task.last_cohort:
            self.stats.record_availability(d, participated=d in rep)
        k = task.pacer.paced_cohort(task.cohort_k)
        task.pacer.observe_round(
            completed=len(rep & set(task.last_cohort)),
            expected=min(k, max(len(task.last_cohort), 1)),
            wall_s=float(wall_s))
        task.pacer.observe_utility(task.last_utility)
        self.registry.release(task.task_id, int(round_idx), reported,
                              now=now)
        task.rounds_run += 1

    # --- persistence --------------------------------------------------------
    _STATS_KEY = "fleet:stats"
    _PLANE_KEY = "fleet:plane"

    def save(self, now: Optional[float] = None) -> None:
        """Checkpoint the control plane into the registry: the shared
        stats store, every task's pacer posture, and the round cursor.
        A restarted plane resumes the learned posture — replaying
        identical cohorts, not re-learning the fleet."""
        self.registry.save_state(self._STATS_KEY, self.stats.state_dict(),
                                 now=now)
        for task in self.tasks:
            self.registry.save_state(f"fleet:{task.state_key()}",
                                     task.pacer.state_dict(), now=now)
        self.registry.save_state(
            self._PLANE_KEY,
            {"round_cursor": np.int64(self.round_cursor)}, now=now)

    def load(self) -> bool:
        """Restore a :meth:`save` snapshot; False = nothing persisted
        (fresh registry — start cold). Tasks must be added first, with
        the same ids as at save time."""
        st = self.registry.load_state(self._STATS_KEY)
        if st is None:
            return False
        self.stats.load_state_dict(st)
        for task in self.tasks:
            pst = self.registry.load_state(f"fleet:{task.state_key()}")
            if pst is not None:
                task.pacer.load_state_dict(pst)
        plane = self.registry.load_state(self._PLANE_KEY)
        if plane is not None:
            self.round_cursor = int(plane["round_cursor"])
        return True
