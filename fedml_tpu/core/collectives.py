"""In-pod collective primitives over named mesh axes.

These are the TPU-native equivalents of the reference's NCCL helpers
(``nccl/base_framework/common.py:180-228``: ``broadcast_model_state``,
``reduce`` of pre-scaled state-dicts) and of ``FedMLAggOperator.agg``
(``ml/aggregator/agg_operator.py:8-30``). They are pure functions intended to
run *inside* ``shard_map`` — the whole FL round compiles to one XLA program
and the collectives ride ICI.

Everything operates on pytrees of arrays (the JAX analogue of a state-dict).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..constants import AXIS_CLIENT

PyTree = Any


def psum_tree(tree: PyTree, axis_name: str = AXIS_CLIENT) -> PyTree:
    """SUM-reduce a pytree across a named axis (``dist.reduce(SUM)`` of
    ``common.py:196`` — but symmetric: every participant gets the result,
    which is what the next round's broadcast needs anyway)."""
    return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis_name), tree)


def pmean_tree(tree: PyTree, axis_name: str = AXIS_CLIENT) -> PyTree:
    return jax.tree_util.tree_map(lambda x: jax.lax.pmean(x, axis_name), tree)


def weighted_psum_tree(
    tree: PyTree,
    weight: jnp.ndarray,
    axis_name: str = AXIS_CLIENT,
    total_weight: Optional[jnp.ndarray] = None,
) -> PyTree:
    """The FedAvg kernel: pre-scale by ``weight`` then SUM-reduce, dividing by
    the global weight sum.

    Exactness note (SURVEY §7 "hard parts"): the reference computes client
    weights ``n_k/Σn`` with the *post-sampling global* denominator
    (``sp/fedavg/fedavg_api.py:144-159``); we reproduce that by psum-ing the
    local weights to form Σn unless a precomputed ``total_weight`` is given.
    """
    if total_weight is None:
        total_weight = jax.lax.psum(weight, axis_name)
    scaled = jax.tree_util.tree_map(
        lambda x: x * weight.astype(x.dtype), tree)
    summed = psum_tree(scaled, axis_name)
    return jax.tree_util.tree_map(
        lambda x: x / jnp.maximum(total_weight, 1e-12).astype(x.dtype), summed)


def all_gather_tree(tree: PyTree, axis_name: str = AXIS_CLIENT,
                    tiled: bool = False) -> PyTree:
    """Gather per-shard values into a leading axis on every shard. Used by
    robust-aggregation defenses (krum/median need all client updates, not a
    sum — reference ``core/security/defense``)."""
    return jax.tree_util.tree_map(
        lambda x: jax.lax.all_gather(x, axis_name, tiled=tiled), tree)


def ppermute_tree(tree: PyTree, perm, axis_name: str = AXIS_CLIENT) -> PyTree:
    """Neighbor exchange for decentralized/gossip FL (reference
    ``simulation/mpi/decentralized_framework``) and ring attention."""
    return jax.tree_util.tree_map(
        lambda x: jax.lax.ppermute(x, axis_name, perm), tree)


def stack_trees(trees) -> PyTree:
    """List of same-structure pytrees -> one pytree with a leading stacked
    axis (the host-side input shape of :func:`tree_weighted_average`)."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *trees)


def tree_weighted_average(stacked: PyTree, weights: jnp.ndarray) -> PyTree:
    """Host/golden-loop aggregation: leaves have a leading client axis;
    returns the weighted average (``FedMLAggOperator.agg``,
    ``agg_operator.py:8-30``, engine-neutral)."""
    norm = weights / jnp.maximum(jnp.sum(weights), 1e-12)

    def avg(leaf):
        w = norm.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype)
        return jnp.sum(leaf * w, axis=0)

    return jax.tree_util.tree_map(avg, stacked)


def tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_sub(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def tree_scale(tree: PyTree, s) -> PyTree:
    return jax.tree_util.tree_map(lambda x: x * jnp.asarray(s, x.dtype), tree)


def tree_zeros_like(tree: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_dot(a: PyTree, b: PyTree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_map(
        lambda x, y: jnp.sum(x.astype(jnp.float32) * y.astype(jnp.float32)), a, b)
    return jax.tree_util.tree_reduce(jnp.add, leaves, jnp.float32(0.0))


def global_norm(tree: PyTree) -> jnp.ndarray:
    return jnp.sqrt(tree_dot(tree, tree))


def tree_flatten_to_vector(tree: PyTree) -> jnp.ndarray:
    """Flatten a pytree to one vector (reference ``utils/model_utils.py``
    flatten; used by defenses & secagg masking)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.concatenate([jnp.ravel(l) for l in leaves]) if leaves else jnp.zeros((0,))


def vector_to_tree_like(vec: jnp.ndarray, tree: PyTree) -> PyTree:
    """Inverse of :func:`tree_flatten_to_vector`."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out, off = [], 0
    for leaf in leaves:
        n = leaf.size
        out.append(jnp.reshape(vec[off:off + n], leaf.shape).astype(leaf.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)
