"""Pallas TPU kernels for the simulation compute plane.

The LLM stack keeps its kernels next to its models (``llm/attention.py``);
this package holds the kernels the FL simulator's CV models dispatch to —
starting with the fused conv->GroupNorm->residual->ReLU block that kills
the flagship's memory-bound elementwise stream (ISSUE 16).
"""

from .conv_block import fused_block, reference_block  # noqa: F401
