"""Fused conv -> GroupNorm -> residual-add -> ReLU block (Pallas TPU).

The flagship roofline (BASELINE §"Compute-plane roofline", ISSUE 14/16)
shows the ResNet-56 16-channel stage 100% memory-bound: every GroupNorm
and residual elementwise op round-trips the full activation through HBM
at AI ~ 0.55-0.60. This kernel keeps the whole ``BasicBlock`` chain —

    conv3x3(s) -> GN -> relu -> conv3x3 -> GN -> (+residual|proj) -> relu

— inside ONE VMEM-resident grid program per batch block, so the
intermediate activations never leave VMEM. Design notes:

* Convolutions are 9 shifted matmuls on the spatially pre-padded input
  (``acc += x_pad[:, dy:dy+H, dx:dx+W, :] @ w[dy, dx]``) — MXU dots with
  ``preferred_element_type=f32``, no conv primitive inside the kernel.
* Stride-2 blocks compute the stride-1 output and subsample: a SAME-padded
  3x3 stride-2 conv equals the stride-1 SAME conv sampled at odd positions
  for even extents (pad_lo 0 vs 1 cancels) and even positions for odd
  extents; the 1x1 projection samples even positions for both parities.
  Only 2 of ResNet-56's 27 blocks are strided, so the extra full-res conv
  work is noise next to the saved elementwise HBM traffic.
* GroupNorm statistics are computed in f32 with the same one-pass
  ``max(0, E[x^2] - E[x]^2)`` formula as flax, per sample per group.
* ``interpret=True`` off-TPU (the repo-wide ``_interp`` idiom from
  ``llm/attention.py``) keeps tier-1 parity tests runnable on CPU.
* The backward pass is a ``custom_vjp`` that RECOMPUTES the block via
  ``jax.vjp`` of :func:`reference_block` — residual-recompute semantics:
  no intermediate activations are saved, and gradients are exactly the
  reference path's gradients.

Channel widths here are narrow (16-64 lanes of the 128-lane VPU);
``model/cv/resnet.py`` only routes blocks with <= 64 filters to this
kernel — wide ImageNet stages already saturate the MXU through XLA.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # CPU wheels may lack the TPU extension; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

#: flax GroupNorm default epsilon — the unfused path's value
GN_EPS = 1e-6

#: largest channel width routed to the fused kernel (narrow stages only)
MAX_FUSED_CHANNELS = 64

#: batch rows per grid program; at the flagship 32x32x16 geometry this
#: keeps the f32 working set (padded input + two activations) ~1.5 MiB,
#: comfortably inside the ~16 MiB/core VMEM budget
DEFAULT_BLOCK_N = 8

Params = Dict[str, Any]


def _interp() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params():
    if _interp() or pltpu is None:
        return None
    return pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)


# ---------------------------------------------------------------------------
# XLA reference path — the numerical golden, and the backward recompute.


def _conv_same(x, w, strides: int):
    dt = jnp.promote_types(x.dtype, w.dtype)
    return jax.lax.conv_general_dilated(
        x.astype(dt), w.astype(dt), window_strides=(strides, strides),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _group_norm(x, scale, bias, groups: int, eps: float):
    """flax GroupNorm semantics: f32 one-pass stats per (sample, group),
    normalized output scaled/shifted and cast back to the input dtype."""
    n, h, w, c = x.shape
    xg = x.reshape(n, h, w, groups, c // groups).astype(jnp.float32)
    mean = jnp.mean(xg, axis=(1, 2, 4), keepdims=True)
    mean2 = jnp.mean(jax.lax.square(xg), axis=(1, 2, 4), keepdims=True)
    var = jnp.maximum(mean2 - jax.lax.square(mean), 0.0)
    y = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(n, h, w, c)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    out_dt = jnp.promote_types(x.dtype, scale.dtype)
    return y.astype(out_dt)


def reference_block(x, params: Params, *, strides: int = 1, groups: int = 8,
                    eps: float = GN_EPS):
    """Pure-XLA BasicBlock math on an explicit param dict — mirrors
    ``model/cv/resnet.py:BasicBlock`` (and is parity-tested against it).

    ``params``: ``w1``/``w2`` [3,3,cin,c]/[3,3,c,c] conv kernels,
    ``g1_*``/``g2_*`` GroupNorm scale/bias [c]; a strided or
    channel-changing block adds the 1x1 projection ``wp`` + ``gp_*``.
    """
    y = _conv_same(x, params["w1"], strides)
    y = _group_norm(y, params["g1_scale"], params["g1_bias"], groups, eps)
    y = jax.nn.relu(y)
    y = _conv_same(y, params["w2"], 1)
    y = _group_norm(y, params["g2_scale"], params["g2_bias"], groups, eps)
    if "wp" in params:
        r = _conv_same(x, params["wp"], strides)
        r = _group_norm(r, params["gp_scale"], params["gp_bias"], groups,
                        eps)
    else:
        r = x
    return jax.nn.relu(r + y)


# ---------------------------------------------------------------------------
# Pallas kernel.


def _subsample2(y, off_h: int, off_w: int):
    """Static stride-2 subsample along H and W starting at the given
    offsets, via pad+reshape (Mosaic-friendly: no strided slicing)."""
    for axis, off in ((1, off_h), (2, off_w)):
        shape = list(y.shape)
        if shape[axis] % 2:
            pads = [(0, 0)] * y.ndim
            pads[axis] = (0, 1)
            y = jnp.pad(y, pads)
            shape[axis] += 1
        new_shape = shape[:axis] + [shape[axis] // 2, 2] + shape[axis + 1:]
        idx = [slice(None)] * (y.ndim + 1)
        idx[axis + 1] = off
        y = y.reshape(new_shape)[tuple(idx)]
    return y


def _block_kernel(*refs, strides: int, groups: int, eps: float, h: int,
                  w: int, has_proj: bool):
    if has_proj:
        (xp_ref, w1_ref, g1s_ref, g1b_ref, w2_ref, g2s_ref, g2b_ref,
         wp_ref, gps_ref, gpb_ref, o_ref) = refs
    else:
        (xp_ref, w1_ref, g1s_ref, g1b_ref, w2_ref, g2s_ref, g2b_ref,
         o_ref) = refs
    f32 = jnp.float32
    xp = xp_ref[...].astype(f32)                  # [bn, h+2, w+2, cin]
    bn = xp.shape[0]
    ho = -(-h // strides)
    wo = -(-w // strides)
    # stride-2 = stride-1 sampled at parity-dependent offsets (see module
    # docstring): odd positions for even extents, even for odd extents
    off_h, off_w = (h % 2 == 0), (w % 2 == 0)

    def conv3(xpad, w_ref, hh, ww):
        cin = xpad.shape[-1]
        cout = w_ref.shape[-1]
        wk = w_ref[...].astype(f32)
        acc = jnp.zeros((bn * hh * ww, cout), f32)
        for dy in range(3):
            for dx in range(3):
                xs = xpad[:, dy:dy + hh, dx:dx + ww, :]
                acc = acc + jnp.dot(xs.reshape(bn * hh * ww, cin),
                                    wk[dy, dx],
                                    preferred_element_type=f32)
        return acc.reshape(bn, hh, ww, cout)

    def gn(y, s_ref, b_ref):
        _, hh, ww, c = y.shape
        yg = y.reshape(bn, hh * ww, groups, c // groups)
        mean = jnp.mean(yg, axis=(1, 3), keepdims=True)
        mean2 = jnp.mean(yg * yg, axis=(1, 3), keepdims=True)
        var = jnp.maximum(mean2 - mean * mean, 0.0)
        yn = ((yg - mean) * jax.lax.rsqrt(var + eps)).reshape(bn, hh, ww, c)
        return yn * s_ref[...].astype(f32) + b_ref[...].astype(f32)

    y = conv3(xp, w1_ref, h, w)
    if strides == 2:
        y = _subsample2(y, int(off_h), int(off_w))
    y = jnp.maximum(gn(y, g1s_ref, g1b_ref), 0.0)
    yp = jnp.pad(y, ((0, 0), (1, 1), (1, 1), (0, 0)))
    y2 = gn(conv3(yp, w2_ref, ho, wo), g2s_ref, g2b_ref)

    x_core = xp[:, 1:1 + h, 1:1 + w, :]
    if has_proj:
        if strides == 2:  # 1x1 stride-2 samples EVEN positions always
            x_core = _subsample2(x_core, 0, 0)
        cin = x_core.shape[-1]
        cout = wp_ref.shape[-1]
        r = jnp.dot(x_core.reshape(bn * ho * wo, cin),
                    wp_ref[...].astype(f32)[0, 0],
                    preferred_element_type=f32).reshape(bn, ho, wo, cout)
        r = gn(r, gps_ref, gpb_ref)
    else:
        r = x_core
    o_ref[...] = jnp.maximum(r + y2, 0.0).astype(o_ref.dtype)


def _pallas_block(x, params: Params, strides: int, groups: int, eps: float,
                  block_n: int = DEFAULT_BLOCK_N):
    n, h, w, cin = x.shape
    cout = params["w1"].shape[-1]
    ho = -(-h // strides)
    wo = -(-w // strides)
    bn = max(1, min(int(block_n), n))
    n_pad = -(-n // bn) * bn
    # host-side spatial pre-pad (SAME halo) + batch pad to the grid
    xp = jnp.pad(x, ((0, n_pad - n), (1, 1), (1, 1), (0, 0)))
    has_proj = "wp" in params

    def row2(a):  # [c] GN params as [1, c]: TPU refs want >= 2D
        return a.reshape(1, -1)

    const = lambda blk: pl.BlockSpec(blk, lambda i: (0,) * len(blk))
    inputs = [xp, params["w1"], row2(params["g1_scale"]),
              row2(params["g1_bias"]), params["w2"],
              row2(params["g2_scale"]), row2(params["g2_bias"])]
    in_specs = [pl.BlockSpec((bn, h + 2, w + 2, cin),
                             lambda i: (i, 0, 0, 0)),
                const((3, 3, cin, cout)), const((1, cout)),
                const((1, cout)), const((3, 3, cout, cout)),
                const((1, cout)), const((1, cout))]
    if has_proj:
        inputs += [params["wp"], row2(params["gp_scale"]),
                   row2(params["gp_bias"])]
        in_specs += [const((1, 1, cin, cout)), const((1, cout)),
                     const((1, cout))]
    kernel = functools.partial(
        _block_kernel, strides=strides, groups=groups, eps=eps, h=h, w=w,
        has_proj=has_proj)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // bn,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bn, ho, wo, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, ho, wo, cout), x.dtype),
        interpret=_interp(),
        compiler_params=_compiler_params(),
    )(*inputs)
    return out[:n] if n_pad != n else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused(x, params, strides, groups, eps):
    return _pallas_block(x, params, strides, groups, eps)


def _fused_fwd(x, params, strides, groups, eps):
    return _pallas_block(x, params, strides, groups, eps), (x, params)


def _fused_bwd(strides, groups, eps, res, g):
    # residual recompute: re-run the XLA reference forward under jax.vjp —
    # nothing from the kernel's VMEM-resident intermediates is saved, and
    # the gradient is exactly the reference path's gradient
    x, params = res
    _, vjp = jax.vjp(
        lambda xx, pp: reference_block(xx, pp, strides=strides,
                                       groups=groups, eps=eps), x, params)
    return vjp(g)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_block(x, params: Params, *, strides: int = 1, groups: int = 8,
                eps: float = GN_EPS):
    """The fused BasicBlock: Pallas forward (interpret mode off-TPU),
    reference-recompute backward. Same signature/params as
    :func:`reference_block`; parity within f32 round-off."""
    return _fused(x, params, int(strides), int(groups), float(eps))
