"""Critical-path profiling at the engine dispatch seam.

The MFU-gap investigation's missing tool: the flagship ``fedavg_resnet56``
has sat at 6.9% MFU for four bench rounds while ResNet-18 hits 40% on the
same engine — i.e. the gap is host/input-side, and a single opaque
``wall_s`` per dispatch cannot localize it. This module splits a
dispatch's wall time into

* ``host_s`` — the host-side dispatch call (arg staging, trace/lowering,
  enqueue; jax returns before the device finishes), and
* ``device_wait_s`` — the tail the host then waits for the device
  (``block_until_ready``), i.e. device compute not overlapped by host
  work,

wraps the dispatch in a ``jax.profiler`` annotation (so a TensorBoard
trace captured around a run carries the same names), and converts the
engine's existing FLOPs model (``round_cost_flops`` — unchanged, so the
BENCH trajectory stays comparable) into a per-round MFU gauge + ``kind:
profile`` JSONL record.

Device profiling is OPT-IN (``obs_profile_device: true``): blocking on
every dispatch defeats the async-dispatch overlap the engines are built
around (most of all the async pour's train/aggregate overlap), so the
default path measures nothing it didn't before.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Any, Optional

from . import metrics as obs_metrics

logger = logging.getLogger(__name__)

# bf16 peak TFLOP/s per chip, by device-kind substring (public specs).
# Single source of truth — bench.py imports this table, so the bench's
# MFU and the profiling plane's gauge can never disagree on peaks.
PEAK_TFLOPS_BF16 = (
    ("v6", 918.0), ("v5p", 459.0), ("v5e", 197.0), ("v5", 197.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0), ("cpu", 0.5),
)

_cfg = {"device": False}


def set_device_profiling(on: bool) -> None:
    _cfg["device"] = bool(on)


def device_profiling_enabled() -> bool:
    return _cfg["device"]


def peak_tflops(device) -> Optional[float]:
    """Per-chip bf16 peak for a jax device, or None for unknown kinds
    (report MFU as null, never a guess)."""
    kind = str(getattr(device, "device_kind", "cpu")).lower()
    for key, peak in PEAK_TFLOPS_BF16:
        if key in kind:
            return peak
    return None


def mfu_value(flops: float, wall_s: float, n_devices: int,
              peak_tflops_per_chip: Optional[float] = None,
              device: Any = None) -> Optional[float]:
    """MFU = achieved FLOP/s ÷ (peak per chip × chips). ``flops`` is the
    total useful work executed in ``wall_s`` across all devices — the
    engine's FLOPs model already excludes padded batches and chaos-dropped
    steps, so this stays honest under injection."""
    if not flops or not wall_s or wall_s <= 0:
        return None
    if peak_tflops_per_chip is None:
        if device is None:
            import jax
            device = jax.devices()[0]
        peak_tflops_per_chip = peak_tflops(device)
    if not peak_tflops_per_chip:
        return None
    achieved_tflops = (flops / wall_s) / 1e12
    return achieved_tflops / (peak_tflops_per_chip * max(int(n_devices), 1))


def trace_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when available (names dispatch
    regions in a TensorBoard/XPlane trace), else a null context."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # older jax or no profiler backend
        return contextlib.nullcontext()


def sample_hbm_peak_gb() -> Optional[float]:
    """Per-device peak HBM (GiB) from memory_stats, or None off-TPU; the
    counter is process-monotonic, so deltas attribute intervals."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if not peak:
            return None
        gb = peak / 2 ** 30
        obs_metrics.record_hbm_peak(gb)
        return round(gb, 4)
    except Exception:
        return None


def record_dispatch_profile(name: str, rounds: int, host_s: float,
                            device_wait_s: Optional[float],
                            flops_per_round: Optional[float],
                            n_devices: int,
                            compiles: int = 0) -> Optional[float]:
    """Emit one ``profile`` record (+ MFU/TFLOPs gauges when the FLOPs
    model is available). Returns the per-round MFU or None.

    ``total_s = host_s + device_wait_s`` is the honest wall cost of the
    dispatch when the host blocked (device profiling on); with only
    ``host_s`` known the MFU is not computed — an enqueue time is not a
    round time."""
    total_s = host_s + (device_wait_s or 0.0)
    mfu = None
    tflops = None
    if (flops_per_round and rounds and device_wait_s is not None
            and total_s > 0):
        flops = float(flops_per_round) * int(rounds)
        tflops = (flops / total_s) / 1e12
        mfu = mfu_value(flops, total_s, n_devices)
        if mfu is not None:
            obs_metrics.record_round_mfu(mfu, tflops=tflops)
    rec = {"dispatch": str(name), "rounds": int(rounds),
           "host_s": round(float(host_s), 6),
           "total_s": round(total_s, 6)}
    if device_wait_s is not None:
        rec["device_wait_s"] = round(float(device_wait_s), 6)
    if compiles:
        rec["compiles"] = int(compiles)
    if tflops is not None:
        rec["tflops"] = round(tflops, 4)
    if mfu is not None:
        rec["mfu"] = round(mfu, 5)
    from .. import mlops
    mlops._emit("profile", rec)
    return mfu
