"""Typed metrics registry — counters, gauges, fixed-bucket histograms.

Absorbs the scattered one-shot ``mlops.log_*`` numbers into ONE queryable
surface: wire bytes by message type (fed at the ``Message.encode`` seam),
pour staleness and buffer occupancy histograms, arrival-rate gauges,
selection decisions, XLA compile count, dispatch wall time, checkpoint
flush time, HBM peak, per-round MFU. Two readouts:

* :func:`exposition` — Prometheus text format (the de-facto wire format
  for pull-based scrapers; also what a human pastes into an issue);
* periodic ``kind: metrics_snapshot`` JSONL records through the mlops
  sink (:func:`maybe_flush` fires on round boundaries), so a run log is
  self-contained for ``scripts/trace_report.py`` and post-mortems.

Instruments are get-or-create by name (re-registration with a different
type raises — a name means one thing). Histogram buckets are FIXED at
registration: snapshots from different processes/rounds merge by simple
addition, and the hot-path observe is a bisect, not an allocation.

Default-on (``obs_metrics: true``): the hot hooks are a dict lookup and a
float add. The registry itself always works — only the convenience
``record_*`` hooks consult the knob, so instrumented code never branches.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

_cfg = {"enabled": True, "flush_every": 10}


def set_enabled(on: bool) -> None:
    _cfg["enabled"] = bool(on)


def is_enabled() -> bool:
    return _cfg["enabled"]


def set_flush_every(rounds: int) -> None:
    """Snapshot-to-JSONL cadence for :func:`maybe_flush` (0 = never).
    Also resets the per-round dedup — ``configure`` runs on every
    ``mlops.init``, so a NEW run's round 0 flushes even when the
    previous run in this process also flushed at round 0."""
    _cfg["flush_every"] = max(int(rounds), 0)
    _flush_state["last"] = None


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Tuple[str, ...]):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}")
        return tuple(str(labels[k]) for k in self.label_names)

    def _label_str(self, key: Tuple[str, ...]) -> str:
        if not self.label_names:
            return ""
        pairs = ",".join(f'{n}="{v}"'
                         for n, v in zip(self.label_names, key))
        return "{" + pairs + "}"


# process-wide mutation epoch: every instrument write bumps it, so the
# wall-clock flusher can skip snapshots when nothing changed (an idle
# process stays silent instead of re-emitting identical instruments;
# flushed starts EQUAL to epoch so a process that never records
# anything never emits an empty snapshot)
_activity = {"epoch": 0, "flushed": 0}


class Counter(_Instrument):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels: Any) -> None:
        if value < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._data[key] = self._data.get(key, 0.0) + float(value)
        _activity["epoch"] += 1

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._data.get(self._key(labels), 0.0))

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"labels": dict(zip(self.label_names, k)), "value": v}
                    for k, v in sorted(self._data.items())]

    def expose(self) -> List[str]:
        # same lock as snapshot: a transport thread inserting a new
        # label key mid-exposition would otherwise crash the iteration
        with self._lock:
            items = sorted(self._data.items())
        return [f"{self.name}{self._label_str(k)} {v}" for k, v in items]


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._data[key] = float(value)
        _activity["epoch"] += 1

    def inc(self, value: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._data[key] = self._data.get(key, 0.0) + float(value)
        _activity["epoch"] += 1

    def value(self, **labels: Any) -> Optional[float]:
        with self._lock:
            v = self._data.get(self._key(labels))
            return None if v is None else float(v)

    snapshot = Counter.snapshot
    expose = Counter.expose


class Histogram(_Instrument):
    """Fixed upper-bound buckets (+Inf implied). Per label set:
    cumulative bucket counts, sum, count — the Prometheus layout."""

    kind = "histogram"

    def __init__(self, name: str, help: str, label_names: Tuple[str, ...],
                 buckets: Sequence[float]):
        super().__init__(name, help, label_names)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"{name}: histogram needs >= 1 bucket bound")
        self.buckets = bs

    def observe(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                ent = self._data[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            ent["counts"][i] += 1
            ent["sum"] += value
            ent["count"] += 1
        _activity["epoch"] += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for k, ent in sorted(self._data.items()):
                out.append({"labels": dict(zip(self.label_names, k)),
                            "buckets": list(self.buckets),
                            "counts": list(ent["counts"]),
                            "sum": ent["sum"], "count": ent["count"]})
            return out

    def expose(self) -> List[str]:
        lines = []
        with self._lock:  # see Counter.expose
            items = [(k, {"counts": list(e["counts"]), "sum": e["sum"],
                          "count": e["count"]})
                     for k, e in sorted(self._data.items())]
        for k, ent in items:
            cum = 0
            for b, c in zip(self.buckets, ent["counts"]):
                cum += c
                le = self._le_labels(k, b)
                lines.append(f"{self.name}_bucket{le} {cum}")
            le = self._le_labels(k, "+Inf")
            lines.append(f"{self.name}_bucket{le} {ent['count']}")
            ls = self._label_str(k)
            lines.append(f"{self.name}_sum{ls} {ent['sum']}")
            lines.append(f"{self.name}_count{ls} {ent['count']}")
        return lines

    def _le_labels(self, key: Tuple[str, ...], bound) -> str:
        pairs = [f'{n}="{v}"' for n, v in zip(self.label_names, key)]
        pairs.append(f'le="{bound}"')
        return "{" + ",".join(pairs) + "}"


class MetricsRegistry:
    """Get-or-create instrument registry; the process-wide instance is
    :data:`REGISTRY` (one process = one rank, like ``WIRE_STATS``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get(self, cls, name: str, help: str, labels: Tuple[str, ...],
             **kw) -> _Instrument:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help,
                                                     tuple(labels), **kw)
                return inst
        if not isinstance(inst, cls):
            raise ValueError(f"{name} already registered as {inst.kind}")
        if tuple(labels) != inst.label_names:
            raise ValueError(
                f"{name} already registered with labels "
                f"{inst.label_names}, not {tuple(labels)}")
        want_buckets = kw.get("buckets")
        if (want_buckets is not None
                and tuple(sorted(float(b) for b in want_buckets))
                != getattr(inst, "buckets", ())):
            raise ValueError(
                f"{name} already registered with buckets "
                f"{inst.buckets}, not {tuple(want_buckets)}")
        return inst

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get(Counter, name, help, tuple(labels))

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get(Gauge, name, help, tuple(labels))

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  labels: Sequence[str] = ()) -> Histogram:
        """``buckets=None`` means "whatever is registered" on a re-get
        (the default bounds apply only on first creation); passing
        explicit buckets that differ from the registered ones raises —
        the observations would land in bounds the caller never asked
        for, silently."""
        if buckets is None and name not in self._instruments:
            buckets = (0.01, 0.1, 1.0, 10.0)
        if buckets is None:
            return self._get(Histogram, name, help, tuple(labels))
        return self._get(Histogram, name, help, tuple(labels),
                         buckets=buckets)

    # --- readouts -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            insts = list(self._instruments.values())
        return {i.name: {"type": i.kind, "help": i.help,
                         "values": i.snapshot()} for i in insts}

    def exposition(self) -> str:
        """Prometheus text exposition of every instrument."""
        with self._lock:
            insts = sorted(self._instruments.values(), key=lambda i: i.name)
        lines: List[str] = []
        for i in insts:
            if i.help:
                lines.append(f"# HELP {i.name} {i.help}")
            lines.append(f"# TYPE {i.name} {i.kind}")
            lines.extend(i.expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def flush(self, step: Optional[int] = None) -> None:
        """Emit one ``metrics_snapshot`` JSONL record through mlops."""
        from .. import mlops
        _activity["flushed"] = _activity["epoch"]
        mlops._emit("metrics_snapshot", {"metrics": self.snapshot(),
                                         "step": step})

    def reset(self) -> None:
        """Drop every instrument (tests only — production counters are
        process-lifetime by design)."""
        with self._lock:
            self._instruments.clear()


REGISTRY = MetricsRegistry()

# shared bucket ladders (fixed at registration; see module docstring)
STALENESS_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)
OCCUPANCY_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
WALL_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0)
LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 300.0)


# --- canonical hooks --------------------------------------------------------
# One helper per seam, so the instrumented code is a single line and the
# metric names/labels cannot drift between callers. Each consults the
# enable knob; the registry itself is always live for direct users.

def record_wire(msg_type: Any, nbytes: int) -> None:
    """``Message.encode`` seam: per-message-type bytes on the wire."""
    if not _cfg["enabled"]:
        return
    t = str(msg_type)
    REGISTRY.counter("fed_wire_bytes_total",
                     "bytes serialized at Message.encode, by message type",
                     labels=("msg_type",)).inc(int(nbytes), msg_type=t)
    REGISTRY.counter("fed_wire_messages_total",
                     "messages serialized at Message.encode",
                     labels=("msg_type",)).inc(1, msg_type=t)


def record_wire_stage(msg_type: Any, stage: str, nbytes: int) -> None:
    """``core/wire`` pipeline seam: bytes attributed to one pipeline
    stage (raw / sparsified / masked) by message type — the per-stage
    ledger behind the framed totals of :func:`record_wire`."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("fed_wire_stage_bytes_total",
                     "bytes by wire-pipeline stage and message type",
                     labels=("msg_type", "stage")).inc(
                         int(nbytes), msg_type=str(msg_type),
                         stage=str(stage))


def record_dispatch(name: str, wall_s: float, rounds: int,
                    compiles: int) -> None:
    """Engine ``_traced`` seam: dispatch wall time + compile counter."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("fed_dispatch_wall_seconds",
                       "host wall time of one device dispatch",
                       buckets=WALL_BUCKETS,
                       labels=("dispatch",)).observe(float(wall_s),
                                                     dispatch=str(name))
    REGISTRY.counter("fed_dispatch_rounds_total",
                     "FL rounds carried by dispatches",
                     labels=("dispatch",)).inc(int(rounds),
                                               dispatch=str(name))
    if compiles:
        REGISTRY.counter("fed_xla_compiles_total",
                         "XLA backend compiles observed at dispatch "
                         "seams").inc(int(compiles))


def record_pour(staleness: Sequence[float], buffered: int,
                poured: int) -> None:
    """Async pour seam: staleness + buffer occupancy histograms."""
    if not _cfg["enabled"]:
        return
    h = REGISTRY.histogram("fed_pour_staleness",
                           "per-update staleness (versions) at pour time",
                           buckets=STALENESS_BUCKETS)
    for s in staleness:
        h.observe(float(s))
    REGISTRY.histogram("fed_buffer_occupancy",
                       "buffered update count after each pour",
                       buckets=OCCUPANCY_BUCKETS).observe(int(buffered))
    REGISTRY.counter("fed_pours_total", "pours executed").inc(1)
    REGISTRY.counter("fed_updates_poured_total",
                     "client updates aggregated by pours").inc(int(poured))


def record_arrival(latency_s: float, rate_mean: Optional[float] = None
                   ) -> None:
    """Async arrival seam: per-update latency histogram + the population
    arrival-rate gauge the adaptive staleness cap reads."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("fed_arrival_latency_seconds",
                       "dispatch-to-arrival latency of client updates",
                       buckets=LATENCY_BUCKETS).observe(float(latency_s))
    if rate_mean is not None and rate_mean > 0:
        REGISTRY.gauge("fed_arrival_rate_mean",
                       "population-mean client arrival rate "
                       "(updates/sec)").set(float(rate_mean))


def record_selection(strategy: str, sampled: int, excluded: int) -> None:
    """Selection seam: scheduled vs benched decisions per strategy."""
    if not _cfg["enabled"]:
        return
    c = REGISTRY.counter("fed_selection_decisions_total",
                         "participant-selection decisions",
                         labels=("strategy", "outcome"))
    c.inc(int(sampled), strategy=str(strategy), outcome="sampled")
    if excluded:
        c.inc(int(excluded), strategy=str(strategy), outcome="excluded")


def record_cohort_assembly(wall_s: float, scanned: int, eligible: int,
                           cohort: int, deadline_s: Optional[float] = None,
                           over_sample: Optional[float] = None) -> None:
    """Cross-device cohort-assembly seam (streaming eligibility scan +
    partial top-k + pacer): per-assembly wall histogram, scan/eligible
    counters, cohort-size gauge, and the pacer's live deadline /
    over-sample knobs. Round-less cross-device servers surface these via
    the wall-clock flusher (``obs_metrics_flush_s``)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("fed_cohort_assembly_seconds",
                       "streaming cohort-assembly wall time",
                       buckets=WALL_BUCKETS).observe(float(wall_s))
    c = REGISTRY.counter("fed_cohort_candidates_total",
                         "candidate ids seen by cohort assembly",
                         labels=("outcome",))
    c.inc(int(scanned), outcome="scanned")
    c.inc(int(eligible), outcome="eligible")
    REGISTRY.gauge("fed_cohort_size",
                   "devices in the most recent cohort").set(int(cohort))
    if deadline_s is not None:
        REGISTRY.gauge("fed_cohort_pacer_deadline_seconds",
                       "pacer round deadline").set(float(deadline_s))
    if over_sample is not None:
        REGISTRY.gauge("fed_cohort_pacer_over_sample",
                       "pacer cohort over-sample factor").set(
                           float(over_sample))


def record_fleet_round(task_id: str, cohort: int, denied_busy: int,
                       denied_cap: int) -> None:
    """Multi-tenant fleet-plane seam (core/fleet): per-task selected
    devices plus the fairness arbiter's denial counts — ``busy`` is the
    one-task-per-round rule firing, ``cap`` the trailing-window
    participation cap. A healthy single-tenant fleet shows zero of
    both; a saturated multi-tenant one shows busy denials growing."""
    if not _cfg["enabled"]:
        return
    c = REGISTRY.counter("fed_fleet_devices_total",
                         "fleet-plane per-task device decisions",
                         labels=("task", "outcome"))
    c.inc(int(cohort), task=str(task_id), outcome="selected")
    if denied_busy:
        c.inc(int(denied_busy), task=str(task_id), outcome="denied_busy")
    if denied_cap:
        c.inc(int(denied_cap), task=str(task_id), outcome="denied_cap")
    REGISTRY.gauge("fed_fleet_cohort_size",
                   "devices granted to the most recent fleet round",
                   labels=("task",)).set(int(cohort), task=str(task_id))


def record_checkpoint_flush(wall_s: float) -> None:
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("fed_checkpoint_flush_seconds",
                       "blocking checkpoint flush wall time",
                       buckets=WALL_BUCKETS).observe(float(wall_s))


def record_hbm_peak(gb: float) -> None:
    if not _cfg["enabled"]:
        return
    REGISTRY.gauge("fed_hbm_peak_gb",
                   "per-device peak HBM (GiB, process-monotonic "
                   "counter)").set(float(gb))


def record_round_mfu(mfu: float, tflops: Optional[float] = None) -> None:
    """Profiling plane: per-round model FLOPs utilization (same FLOPs
    model as the bench — ``engine.round_cost_flops``)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.gauge("fed_round_mfu",
                   "per-round model FLOPs utilization").set(float(mfu))
    if tflops is not None:
        REGISTRY.gauge("fed_round_tflops",
                       "achieved TFLOP/s over the round").set(float(tflops))


def record_roofline(program: str, predicted_mfu: Optional[float],
                    memory_bound_share: Optional[float],
                    collective_wire_bytes: Optional[float]) -> None:
    """Compute-plane roofline capture (core/obs/roofline): predicted
    program MFU, time share classified memory-bound, and the per-device
    collective wire bytes one execution moves."""
    if not _cfg["enabled"]:
        return
    if predicted_mfu is not None:
        REGISTRY.gauge("roofline_predicted_mfu",
                       "roofline-predicted program MFU",
                       labels=("program",)).set(float(predicted_mfu),
                                                program=str(program))
    if memory_bound_share is not None:
        REGISTRY.gauge("roofline_memory_bound_share",
                       "share of predicted device time in memory-bound "
                       "ops", labels=("program",)).set(
                           float(memory_bound_share),
                           program=str(program))
    if collective_wire_bytes is not None:
        REGISTRY.gauge("roofline_collective_wire_bytes",
                       "predicted per-device collective wire bytes per "
                       "program execution",
                       labels=("program",)).set(
                           float(collective_wire_bytes),
                           program=str(program))
    REGISTRY.counter("roofline_captures_total",
                     "compiled programs analyzed by the roofline "
                     "plane").inc(1)


def record_recompile(program: str) -> None:
    """Recompile forensics: a program compiled PAST its pinned
    expectation (the steady-state invariant is zero)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("roofline_recompiles_total",
                     "dispatches that recompiled past the pinned "
                     "one-compile expectation",
                     labels=("program",)).inc(1, program=str(program))


def record_llm_serving_step(tokens_out: int, occupancy: int,
                            queue_depth: int, tokens_per_s: float) -> None:
    """Continuous-batching decode seam (serving/batch): per-step slot
    occupancy + queue depth histograms and the decode-throughput gauge."""
    if not _cfg["enabled"]:
        return
    REGISTRY.gauge("llm_tokens_per_s",
                   "decode throughput over the engine's rolling window "
                   "(generated tokens/sec)").set(float(tokens_per_s))
    REGISTRY.histogram("llm_slot_occupancy",
                       "in-flight requests per decode step",
                       buckets=OCCUPANCY_BUCKETS).observe(int(occupancy))
    REGISTRY.histogram("llm_queue_depth",
                       "requests waiting for a slot at each decode step",
                       buckets=OCCUPANCY_BUCKETS).observe(int(queue_depth))
    REGISTRY.counter("llm_tokens_generated_total",
                     "tokens emitted by the batched decode "
                     "step").inc(int(tokens_out))


def record_llm_admit(n: int = 1) -> None:
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_requests_admitted_total",
                     "requests admitted into decode slots").inc(int(n))


def record_llm_evict(reason: str) -> None:
    """Eviction seam: deadline evictions vs queued-request expiry."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_requests_evicted_total",
                     "requests evicted before natural finish",
                     labels=("reason",)).inc(1, reason=str(reason))


def record_gateway_latency(latency_s: float) -> None:
    """Serving gateway seam: per-request end-to-end latency histogram
    (the exact p50/p99 the autoscaler reads comes from the gateway's
    :class:`LatencyWindow`; this is the exposition/post-mortem view)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("serving_gateway_latency_seconds",
                       "gateway request latency",
                       buckets=LATENCY_BUCKETS).observe(float(latency_s))


# serving-plane SLO buckets: TTFT is gated by queue wait + prefill (tens
# of ms to seconds); ITL is one decode step (sub-ms to tens of ms)
TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0)
ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)
TOKRATE_BUCKETS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0)


def record_llm_ttft(seconds: float) -> None:
    """Time-to-first-token: request submit → first generated token (the
    Orca-style admission SLO — queue wait + chunked prefill)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("llm_ttft_seconds",
                       "request submit to first generated token",
                       buckets=TTFT_BUCKETS).observe(float(seconds))


def record_llm_itl(step_wall_s: float) -> None:
    """Inter-token latency: one observation per decode STEP (every active
    slot experienced this gap — per-step, not per-token, so the hot loop
    costs one bisect regardless of occupancy)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("llm_inter_token_seconds",
                       "decode-step wall time = inter-token latency of "
                       "every in-flight request",
                       buckets=ITL_BUCKETS).observe(float(step_wall_s))


def record_llm_request(tokens_per_s: float, queue_wait_s: float) -> None:
    """Per-request close-out: individual decode throughput + queue wait
    (the aggregate tokens/s gauge hides per-request starvation)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("llm_request_tokens_per_s",
                       "per-request decode throughput at finish",
                       buckets=TOKRATE_BUCKETS).observe(
                           float(tokens_per_s))
    REGISTRY.histogram("llm_queue_wait_seconds",
                       "request submit to decode-slot admission",
                       buckets=TTFT_BUCKETS).observe(float(queue_wait_s))


def record_llm_kv_pool(used_blocks: int, free_blocks: int,
                       headroom_requests: int, fragmentation: float,
                       aliased_blocks: Optional[int] = None,
                       cached_blocks: Optional[int] = None) -> None:
    """Paged-KV pool state: occupancy, free list, how many WORST-CASE
    requests the admission reserve could still take, internal
    fragmentation (reserved-but-unwritten fraction of allocated
    blocks), and — with the shared-prefix cache on — how many blocks
    are currently shared (refcount >= 2) or held warm by the index."""
    if not _cfg["enabled"]:
        return
    REGISTRY.gauge("llm_kv_blocks_used",
                   "KV pool blocks allocated to slots").set(
                       int(used_blocks))
    REGISTRY.gauge("llm_kv_blocks_free",
                   "KV pool blocks on the free list").set(int(free_blocks))
    REGISTRY.gauge("llm_kv_admission_headroom_requests",
                   "worst-case (max_seq_len) requests the free list can "
                   "still admit").set(int(headroom_requests))
    REGISTRY.gauge("llm_kv_fragmentation",
                   "reserved-but-unwritten fraction of allocated KV "
                   "blocks").set(float(fragmentation))
    if aliased_blocks is not None:
        REGISTRY.gauge("llm_kv_aliased_blocks",
                       "physical KV blocks shared by more than one "
                       "reference (prefix aliasing)").set(
                           int(aliased_blocks))
    if cached_blocks is not None:
        REGISTRY.gauge("llm_kv_cached_blocks",
                       "KV blocks pinned warm by the prefix index").set(
                           int(cached_blocks))


def record_llm_prefix_cache(cached_tokens: int, novel_tokens: int) -> None:
    """Prefix-cache admission outcome: tokens reused from resident
    blocks vs tokens actually prefilled. The hit-rate the bench gates is
    ``cached_total / (cached_total + prefilled_total)``."""
    if not _cfg["enabled"]:
        return
    c = REGISTRY.counter("llm_prefix_lookups_total",
                         "prefix-cache lookups at admission",
                         labels=("outcome",))
    c.inc(1, outcome="hit" if cached_tokens > 0 else "miss")
    REGISTRY.counter("llm_prefix_cached_tokens_total",
                     "prompt tokens served from cached KV blocks "
                     "(never prefilled)").inc(int(cached_tokens))
    REGISTRY.counter("llm_prefix_prefilled_tokens_total",
                     "prompt tokens actually prefilled").inc(
                         int(novel_tokens))


def record_llm_suffix_cache(reused_tokens: int) -> None:
    """Suffix-cache admission outcome: generated (decode-origin) tokens
    a follow-up/requeued request aliased instead of re-prefilling."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_suffix_hits_total",
                     "admissions that aliased generated-token "
                     "(decode-origin) cached blocks").inc(1)
    REGISTRY.counter("llm_suffix_reused_tokens_total",
                     "generated tokens served from cached KV blocks "
                     "(never re-prefilled)").inc(int(reused_tokens))


def record_llm_suffix_insert(blocks: int) -> None:
    """Decode blocks indexed into the prefix cache at slot release."""
    if not _cfg["enabled"] or not blocks:
        return
    REGISTRY.counter("llm_suffix_inserted_blocks_total",
                     "generated-token KV blocks indexed at release").inc(
                         int(blocks))


def record_llm_prefix_evictions(n: int) -> None:
    """Cached prefix blocks evicted under KV pool pressure."""
    if not _cfg["enabled"] or not n:
        return
    REGISTRY.counter("llm_prefix_evictions_total",
                     "prefix-cache entries evicted for admission "
                     "headroom").inc(int(n))


def record_llm_prefill_wave(wave_size: int) -> None:
    """One piggybacked-prefill admission wave of ``wave_size`` requests
    (1 = a serial admission)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.histogram("llm_prefill_wave_requests",
                       "admissions batched into one prefill wave",
                       buckets=OCCUPANCY_BUCKETS).observe(int(wave_size))


def record_llm_stream_request() -> None:
    """One request served as an SSE token stream."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_stream_requests_total",
                     "requests served as SSE token streams").inc(1)


def record_llm_adapter_swap(name: str) -> None:
    """Adapter hot-swap: a watched export went live as a bank row write
    (zero restart, zero recompile)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_adapter_swaps_total",
                     "adapter-bank hot-swaps from the watched export "
                     "dir", labels=("adapter",)).inc(1, adapter=str(name))


def record_llm_adapter(name: str) -> None:
    """Adapter-bank mix: which personalization each request selected."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_adapter_requests_total",
                     "requests by selected adapter",
                     labels=("adapter",)).inc(1, adapter=str(name))


def record_llm_reject(reason: str) -> None:
    """Submit-time rejections (never admitted), by reason — distinct from
    evictions, which had a slot and lost it."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_requests_rejected_total",
                     "requests rejected at submit",
                     labels=("reason",)).inc(1, reason=str(reason))


def record_llm_reset(reason: str) -> None:
    """One watchdog-driven engine reset (crash-only recovery): the slot
    matrix + KV pool were rebuilt and the in-flight snapshots requeued."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_engine_resets_total",
                     "controlled engine resets (watchdog-driven "
                     "recovery)", labels=("reason",)).inc(
                         1, reason=str(reason))


def record_llm_requeue(reason: str, n: int = 1) -> None:
    """Requests snapshotted and requeued for recompute-from-prompt —
    by an engine reset or a preempt-under-pressure decision."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("llm_requests_requeued_total",
                     "in-flight requests requeued for recompute",
                     labels=("reason",)).inc(int(n), reason=str(reason))


def record_gateway_failover(reason: str) -> None:
    """Gateway routed a request away from a replica (dead connect,
    503-shedding replica, failed health probe)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("serving_gateway_failovers_total",
                     "requests re-routed off a failed/unhealthy replica",
                     labels=("reason",)).inc(1, reason=str(reason))


def record_gateway_route(outcome: str) -> None:
    """Cache-aware routing decision: ``warm_hit`` (digest stuck to its
    warm replica), ``warm_spill`` (warm replica saturated — spilled to
    round-robin without rehoming), ``cold`` (first sight of the digest,
    round-robin pick recorded as the digest's home)."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("serving_gateway_routes_total",
                     "cache-aware routing decisions by outcome",
                     labels=("outcome",)).inc(1, outcome=str(outcome))


def record_gateway_heal(port: int) -> None:
    """A quarantined replica passed its recovery probe and rejoined the
    rotation."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("serving_gateway_heals_total",
                     "quarantined replicas healed back into "
                     "rotation").inc(1)


def record_fleet_scale(direction: str, replicas: int) -> None:
    """One SLO-driven autoscaler move (``up`` / ``down``) landing on
    ``replicas`` replicas."""
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("serving_fleet_scale_events_total",
                     "autoscaler replica-count changes",
                     labels=("direction",)).inc(1, direction=str(direction))
    REGISTRY.gauge("serving_fleet_replicas",
                   "current serving replica count").set(int(replicas))


def record_watchdog_trip(component: str, reason: str) -> None:
    if not _cfg["enabled"]:
        return
    REGISTRY.counter("obs_watchdog_trips_total",
                     "black-box watchdog trips",
                     labels=("component", "reason")).inc(
                         1, component=str(component), reason=str(reason))


class LatencyWindow:
    """Trailing-window latency store with EXACT nearest-rank percentiles —
    the one implementation of windowed tail stats (the serving gateway's
    p50/p99 and any autoscaler signal read this; the cumulative registry
    histograms remain the exposition/post-mortem view, fed separately by
    the ``record_*`` hooks)."""

    def __init__(self, window_s: float = 5.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._events: Deque[Tuple[float, float]] = collections.deque()

    def observe(self, latency_s: float, ts: Optional[float] = None) -> None:
        now = time.time() if ts is None else float(ts)
        with self._lock:
            self._events.append((now, float(latency_s)))
            self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    @staticmethod
    def _rank(lats: List[float], q: float) -> float:
        n = len(lats)
        return lats[min(n - 1, int(q * (n - 1) + 0.5))]

    def stats(self) -> Tuple[float, float, float, float, int]:
        """``(qps, mean, p50, p99, count)`` over the trailing window."""
        now = time.time()
        with self._lock:
            self._trim(now)
            lats = sorted(l for _, l in self._events)
        n = len(lats)
        if not n:
            return 0.0, 0.0, 0.0, 0.0, 0
        return (n / self.window_s, sum(lats) / n,
                self._rank(lats, 0.50), self._rank(lats, 0.99), n)


_flush_state = {"last": None}

# wall-clock flusher state: at most one live daemon thread per process —
# ownership is `_wall_flush["thread"] is current_thread()`, so a
# re-configure (new interval, or 0 = off) retires the old loop instead
# of stacking threads
_wall_flush = {"interval_s": 0.0, "thread": None, "last_ts": 0.0}


def set_flush_interval(seconds: float) -> None:
    """Wall-clock snapshot cadence (``obs_metrics_flush_s``; 0 = off).

    The round-boundary flusher (:func:`maybe_flush`) only fires on
    ``log_round_info`` — serving, cross-device handshakes, and agent
    paths never cross a round boundary, so without this their metrics
    exist only in the final :func:`flush_final` snapshot (or not at all
    on a crash). The wall-clock loop emits a ``metrics_snapshot`` every
    ``seconds`` — but only when an instrument actually changed since the
    last flush (the activity epoch), so an idle process stays silent."""
    interval = max(float(seconds or 0.0), 0.0)
    _wall_flush["interval_s"] = interval
    if interval <= 0:
        _wall_flush["thread"] = None  # orphan the loop; it exits itself
        return
    th = _wall_flush["thread"]
    if th is not None and th.is_alive():
        return  # live loop re-reads interval_s every tick

    def loop() -> None:
        me = threading.current_thread()
        while _wall_flush["thread"] is me:
            ivl = _wall_flush["interval_s"]
            if ivl <= 0:
                return
            time.sleep(min(ivl, 1.0))
            # re-check AFTER the sleep: a disable (or takeover) during
            # the nap must not let one more flush slip through
            if (_wall_flush["thread"] is not me
                    or _wall_flush["interval_s"] <= 0):
                return
            now = time.time()
            if now - _wall_flush["last_ts"] < _wall_flush["interval_s"]:
                continue
            if not _cfg["enabled"]:
                continue
            if _activity["epoch"] == _activity["flushed"]:
                continue  # nothing changed since the last snapshot
            _wall_flush["last_ts"] = now
            try:
                REGISTRY.flush()
            except Exception:  # pragma: no cover — sink died mid-run
                pass

    t = threading.Thread(target=loop, daemon=True,
                         name="obs-metrics-wall-flush")
    _wall_flush["thread"] = t
    t.start()


def maybe_flush(round_idx: int) -> None:
    """Round-boundary hook (``mlops.log_round_info``): snapshot to JSONL
    every ``obs_metrics_flush_rounds`` rounds. Deduped per round — fused
    blocks replay round boundaries in bursts."""
    every = _cfg["flush_every"]
    if not _cfg["enabled"] or every <= 0:
        return
    if round_idx % every == 0 and _flush_state["last"] != round_idx:
        _flush_state["last"] = round_idx
        REGISTRY.flush(step=round_idx)


def flush_final(step: Optional[int] = None) -> None:
    """Unconditional end-of-run snapshot (engines' ``run()`` end, the
    server's ``finish_session``): without it, everything accumulated
    since the last cadence boundary — the final rounds' wire bytes,
    staleness histograms, MFU — would die with the process and the run
    log would NOT be self-contained."""
    if not _cfg["enabled"]:
        return
    REGISTRY.flush(step=step)
