"""Canonical schema for every JSONL record kind the mlops sink emits.

One table, one validator: every record crossing ``mlops._emit`` has a
``kind`` listed here, carries the common envelope (``kind``/``ts``/
``run_id``), and types its fields as declared. The tier-1 replay test
runs a small engine session and validates EVERY line of the run log
against this table — so a new record kind (or a silently-retyped field)
fails CI instead of quietly producing logs ``trace_report``/dashboards
cannot parse.

The validator is deliberately tolerant of EXTRA fields (records grow;
readers must ignore what they don't know) and strict about declared ones
(required present, types as stated). ``None`` is allowed exactly where
the spec says so.
"""

from __future__ import annotations

import numbers
import re
from typing import Any, Dict, List, Tuple

_HEX32 = re.compile(r"^[0-9a-f]{32}$")
_HEX16 = re.compile(r"^[0-9a-f]{16}$")

# sentinels for the spec table
NUM = "num"          # int or float (bools rejected)
INT = "int"
STR = "str"
BOOL = "bool"
DICT = "dict"
LIST = "list"
ANY = "any"
HEX32 = "hex32"      # 32-char lowercase hex (trace ids)
HEX16 = "hex16"      # 16-char lowercase hex (span ids)

# field spec: (type sentinel, required, nullable)
FieldSpec = Tuple[str, bool, bool]


def _f(ty: str, required: bool = False, nullable: bool = False) -> FieldSpec:
    return (ty, required, nullable)


# the common envelope _emit stamps on every record
ENVELOPE: Dict[str, FieldSpec] = {
    "kind": _f(STR, required=True),
    "ts": _f(NUM, required=True),
    "run_id": _f(STR, required=True),
}

RECORD_SCHEMAS: Dict[str, Dict[str, FieldSpec]] = {
    # mlops.log / log_metric
    "metric": {"metrics": _f(DICT, required=True),
               "step": _f(INT, nullable=True)},
    # mlops.log_round_info
    "round": {"round_idx": _f(INT, required=True),
              "total_rounds": _f(INT, required=True)},
    # mlops.log_comm_round (WireStats ledger diff per FL round)
    "comm": {"round_idx": _f(INT, required=True),
             "wire_bytes": _f(INT, required=True),
             "compression": _f(STR, nullable=True),
             "by_type": _f(DICT, nullable=True)},
    # mlops.log_chaos (fault ledger mirror; arrivals = per-pour records)
    "chaos": {"round_idx": _f(INT),
              "injected": _f(DICT),
              "observed": _f(DICT),
              "link": _f(DICT),
              "arrivals": _f(LIST),
              "serving": _f(DICT)},
    # mlops.log_selection
    "selection": {"round_idx": _f(INT, required=True),
                  "strategy": _f(STR, required=True),
                  "sampled": _f(LIST),
                  "excluded": _f(LIST),
                  "target_n": _f(INT),
                  "dropout_posterior": _f(NUM)},
    # mlops.log_dispatch (engine _traced seam)
    "dispatch": {"dispatch": _f(STR, required=True),
                 "wall_s": _f(NUM, required=True),
                 "rounds": _f(INT, required=True),
                 "compiles": _f(INT, required=True)},
    # mlops.log_training_status / log_aggregation_status
    "status": {"role": _f(STR, required=True),
               "status": _f(STR, required=True)},
    # mlops.log_model_info
    "model": {"round_idx": _f(INT, required=True),
              "path": _f(STR, required=True)},
    # legacy event pair records (kept as the mlops.event shim's output
    # next to the tracer's span records)
    "event_start": {"event": _f(STR, required=True),
                    "value": _f(ANY, nullable=True)},
    "event_end": {"event": _f(STR, required=True),
                  "value": _f(ANY, nullable=True),
                  "duration_s": _f(NUM, nullable=True)},
    # mlops.start_sys_perf sampler
    "sys_perf": {"cpu_pct": _f(NUM),
                 "mem_pct": _f(NUM),
                 "mem_used_gb": _f(NUM),
                 "device_mem_gb": _f(NUM),
                 "degraded": _f(BOOL)},
    # core/obs/trace.py span emission
    "span": {"name": _f(STR, required=True),
             "trace_id": _f(HEX32, required=True),
             "span_id": _f(HEX16, required=True),
             "parent_id": _f(HEX16, required=True, nullable=True),
             "start_ts": _f(NUM, required=True),
             "end_ts": _f(NUM, required=True),
             "duration_s": _f(NUM, required=True),
             "pid": _f(INT, required=True),
             "attrs": _f(DICT),
             "events": _f(LIST),
             "links": _f(LIST)},
    # core/obs/metrics.py registry flush
    "metrics_snapshot": {"metrics": _f(DICT, required=True),
                         "step": _f(INT, nullable=True)},
    # core/obs/profiler.py dispatch profile
    "profile": {"dispatch": _f(STR, required=True),
                "rounds": _f(INT, required=True),
                "host_s": _f(NUM, required=True),
                "total_s": _f(NUM, required=True),
                "device_wait_s": _f(NUM),
                "compiles": _f(INT),
                "tflops": _f(NUM),
                "mfu": _f(NUM)},
    # mlops.log_health — component health transitions: watchdog trips
    # (status: stalled | nan_logits), serving /healthz state changes
    "health": {"component": _f(STR, required=True),
               "status": _f(STR, required=True),
               "detail": _f(DICT, nullable=True)},
    # core/obs/flight.py ring-buffer dump: one line per recorded event,
    # oldest first — the black-box artifact validates like a run log
    "flight": {"component": _f(STR, required=True),
               "seq": _f(INT, required=True),
               "event": _f(STR, required=True),
               "data": _f(DICT)},
    # core/obs/roofline.py per-program compute-plane capture: one record
    # per (program, abstract-shape signature), opt-in (obs_roofline).
    # ``ops`` rows carry name/op/out/operands/flops/bytes/intensity/
    # bound/time_s/share; ``collectives`` rows op/group/count/wire_bytes
    "roofline": {"program": _f(STR, required=True),
                 "device_kind": _f(STR, required=True),
                 "n_devices": _f(INT, required=True),
                 "static_only": _f(BOOL, required=True),
                 "peak_tflops": _f(NUM, nullable=True),
                 "hbm_gbps": _f(NUM, nullable=True),
                 "balance_flops_per_byte": _f(NUM, nullable=True),
                 "total_flops": _f(NUM, required=True),
                 "total_bytes": _f(NUM, required=True),
                 "predicted_s": _f(NUM, required=True),
                 "predicted_mfu": _f(NUM, required=True, nullable=True),
                 "attributed_share": _f(NUM, required=True),
                 "memory_bound_share": _f(NUM, required=True),
                 "compute_bound_share": _f(NUM),
                 "collective_wire_bytes": _f(NUM, required=True),
                 "xla_flops": _f(NUM, nullable=True),
                 "xla_bytes": _f(NUM, nullable=True),
                 "arg_bytes": _f(NUM),
                 "output_bytes": _f(NUM),
                 "temp_bytes": _f(NUM),
                 "ops": _f(LIST, required=True),
                 "collectives": _f(LIST, required=True)},
    # core/obs/roofline.py recompile forensics: the compile counter
    # incremented past the pinned one-compile-per-program expectation;
    # ``changed`` names the abstract arg shapes that moved (empty =
    # cache miss with identical shapes — new callable / jit options)
    "recompile": {"program": _f(STR, required=True),
                  "compiles": _f(INT, required=True),
                  "total_compiles": _f(INT, required=True),
                  "expected": _f(INT),
                  "changed": _f(LIST, required=True),
                  "note": _f(STR, nullable=True)},
}

# Span names the serving request lifecycle emits (engine + HTTP surface).
# scripts/serving_report.py keys its waterfall on these; the e2e trace
# test pins that every emitted serving span uses a name from this set,
# so the report and the instrumentation cannot drift apart.
SERVING_SPAN_NAMES = frozenset({
    "serving.http",          # replica/gateway HTTP receive -> reply
    "serving.request",       # submit -> finish (the per-request root)
    "serving.queue",         # submit -> admission (queue wait)
    "serving.prefill",       # chunked prefill inside admit
    "serving.decode",        # first token -> finish/evict
    "serving.decode_steps",  # shared engine-side step block (fan-in links)
})


def _type_ok(ty: str, v: Any) -> bool:
    if ty == ANY:
        return True
    if ty == NUM:
        return isinstance(v, numbers.Real) and not isinstance(v, bool)
    if ty == INT:
        return isinstance(v, numbers.Integral) and not isinstance(v, bool)
    if ty == STR:
        return isinstance(v, str)
    if ty == BOOL:
        return isinstance(v, bool)
    if ty == DICT:
        return isinstance(v, dict)
    if ty == LIST:
        return isinstance(v, (list, tuple))
    if ty == HEX32:
        return isinstance(v, str) and _HEX32.match(v) is not None
    if ty == HEX16:
        return isinstance(v, str) and _HEX16.match(v) is not None
    raise ValueError(f"unknown type sentinel {ty!r}")


def validate_record(rec: Any) -> List[str]:
    """Validate one decoded JSONL record; returns a list of problems
    (empty = valid). Never raises on malformed input — validation runs
    over logs from crashed runs too."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    errs: List[str] = []
    kind = rec.get("kind")
    for name, (ty, required, nullable) in ENVELOPE.items():
        if name not in rec:
            errs.append(f"missing envelope field {name!r}")
        elif rec[name] is None:
            if not nullable:
                errs.append(f"envelope field {name!r} is null")
        elif not _type_ok(ty, rec[name]):
            errs.append(f"envelope field {name!r} has type "
                        f"{type(rec[name]).__name__}, want {ty}")
    if not isinstance(kind, str):
        return errs or ["record has no usable 'kind'"]
    spec = RECORD_SCHEMAS.get(kind)
    if spec is None:
        errs.append(f"unknown record kind {kind!r}")
        return errs
    for name, (ty, required, nullable) in spec.items():
        if name not in rec:
            if required:
                errs.append(f"{kind}: missing required field {name!r}")
            continue
        v = rec[name]
        if v is None:
            if not nullable:
                errs.append(f"{kind}: field {name!r} is null")
            continue
        if not _type_ok(ty, v):
            errs.append(f"{kind}: field {name!r} has type "
                        f"{type(v).__name__}, want {ty}")
    return errs


def validate_lines(lines) -> List[Tuple[int, str]]:
    """Validate an iterable of raw JSONL lines; returns [(lineno, error)]
    over every problem found (blank lines skipped)."""
    import json
    problems: List[Tuple[int, str]] = []
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            problems.append((i, f"not JSON: {e}"))
            continue
        for err in validate_record(rec):
            problems.append((i, err))
    return problems
