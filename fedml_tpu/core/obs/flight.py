"""Black-box flight recorder + stall watchdog.

An aircraft flight recorder answers the only question that matters after
a crash: *what were the last moments like?* The serving engine has the
same post-mortem problem — a wedged decode queue, a NaN'd logits step, a
SIGTERM from the platform — and the run JSONL only carries what was
*flushed* before the process died. This module keeps the answer resident:

* :class:`FlightRecorder` — a bounded ring buffer of the last N
  request-lifecycle and engine-step records (a ``deque`` of dicts; an
  append is O(1) and never blocks the decode loop), dumped as
  schema-valid ``kind: flight`` JSONL on demand, on unhandled engine
  crash, or on SIGTERM (:func:`install_signal_dump`).
* :class:`Watchdog` — a daemon thread that trips when the component it
  watches reports no progress for T seconds while it has live work
  (``occupancy > 0``), or when the component flags a poisoned step
  (NaN/inf decode logits). A trip records the
  ``obs_watchdog_trips_total`` counter, emits a ``kind: health`` record
  through the mlops sink, and dumps the ring — so a wedged engine is
  diagnosable from the artifact alone.

Every dumped line validates against :mod:`.schema` (``kind: flight``),
so the same replay tooling that checks run logs checks black boxes.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import metrics as obs_metrics

logger = logging.getLogger(__name__)


class FlightRecorder:
    """Bounded ring of the last ``capacity`` event records.

    ``note(event, **data)`` is the hot-path API: one dict build and one
    deque append under a lock (the deque's maxlen does the eviction).
    ``dump(path)`` writes the ring oldest-first as JSONL where every
    line is a full schema-valid record (envelope included) — the file
    stands alone, no run log needed to parse it.
    """

    def __init__(self, component: str, capacity: int = 256):
        self.component = str(component)
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self._dumped_paths: List[str] = []
        self._dump_counts: Dict[str, int] = {}

    def note(self, event: str, **data: Any) -> None:
        """Record one lifecycle/step event. Values must be JSON-encodable
        (the dump serializes verbatim); keep them scalars."""
        with self._lock:
            self._ring.append({"seq": self._seq, "ts": time.time(),
                               "event": str(event),
                               **({"data": data} if data else {})})
            self._seq += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def records(self) -> List[Dict[str, Any]]:
        """The ring as full schema-valid ``kind: flight`` records."""
        from .. import mlops
        run_id = str(mlops._state.get("run_id", "0"))
        out = []
        for ev in self.snapshot():
            rec = {"kind": "flight", "ts": ev["ts"], "run_id": run_id,
                   "component": self.component, "seq": ev["seq"],
                   "event": ev["event"]}
            if "data" in ev:
                rec["data"] = ev["data"]
            out.append(rec)
        return out

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> Optional[str]:
        """Write the ring to ``path`` (default: ``flight_<component>_
        <pid>.jsonl`` next to the run logs). Returns the path written,
        or None when the ring is empty. Never raises — the dump runs
        from crash handlers.

        Repeat dumps to the same nominal path get a monotonic ``.N``
        suffix: a second watchdog trip (or a reset after a trip) in one
        process must never overwrite the first episode's post-mortem."""
        try:
            recs = self.records()
            if not recs:
                return None
            if path is None:
                base = os.path.expanduser("~/.cache/fedml_tpu/logs")
                path = os.path.join(
                    base, f"flight_{self.component}_{os.getpid()}.jsonl")
            root, ext = os.path.splitext(path)
            # reserve the slot atomically: worker loop (reset dump) and
            # watchdog thread (trip dump) can dump the SAME recorder
            # concurrently — racing the count/probe would hand both the
            # same target (and the same tmp name) and lose one episode
            with self._lock:
                n = self._dump_counts.get(path, 0)
                actual = path if n == 0 else f"{root}.{n}{ext}"
                # a recorder rebuilt mid-process restarts its count at
                # 0 — probe the disk so it still never clobbers
                while os.path.exists(actual):
                    n += 1
                    actual = f"{root}.{n}{ext}"
                self._dump_counts[path] = n + 1
            path = actual
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.{os.getpid()}.{n}.tmp"
            with open(tmp, "w") as f:
                for rec in recs:
                    f.write(json.dumps(rec) + "\n")
            os.replace(tmp, path)
            self._dumped_paths.append(path)
            logger.warning("flight recorder: dumped %d records to %s "
                           "(reason=%s)", len(recs), path, reason)
            return path
        except Exception:  # pragma: no cover — crash path must not raise
            logger.exception("flight recorder dump failed")
            return None


_signal_state: Dict[str, Any] = {"installed": False, "recorders": []}


def install_signal_dump(recorder: FlightRecorder,
                        path: Optional[str] = None) -> bool:
    """Dump ``recorder`` on SIGTERM (the platform's shutdown signal),
    then re-raise the default action so the process still dies. Only the
    main thread may install signal handlers — callers on worker threads
    get False and should rely on the crash/watchdog dumps instead.
    Multiple recorders chain onto one handler."""
    entry = (recorder, path)
    if _signal_state["installed"]:
        # a False return must mean NOT registered — only queue the
        # recorder once a handler exists (or below, once one installs)
        if entry not in _signal_state["recorders"]:
            _signal_state["recorders"].append(entry)
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):  # pragma: no cover — signal path
            for rec, p in _signal_state["recorders"]:
                rec.dump(p, reason="sigterm")
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
        _signal_state["installed"] = True
        _signal_state["recorders"].append(entry)
        return True
    except (ValueError, OSError):  # not main thread / restricted env
        return False


class Watchdog:
    """Stall + poisoned-step detector for one component.

    ``probe`` is called every ``interval``: it returns a dict with
    ``occupancy`` (live work count), ``last_progress_ts`` (wall time of
    the last forward step), and optionally ``poisoned`` (truthy = NaN or
    inf observed in the compute path). The watchdog trips when

    * ``occupancy > 0`` and ``now - last_progress_ts > stall_s`` — work
      exists but nothing has moved (a wedged queue), or
    * ``poisoned`` is truthy — the step still "progresses" but emits
      garbage.

    A trip fires once per episode (re-arming when progress resumes):
    bumps ``obs_watchdog_trips_total``, emits a ``kind: health`` record,
    dumps the flight recorder, and calls ``on_trip`` if given.
    """

    def __init__(self, component: str, probe: Callable[[], Dict[str, Any]],
                 recorder: Optional[FlightRecorder] = None,
                 stall_s: float = 30.0, dump_path: Optional[str] = None,
                 on_trip: Optional[Callable[[str], None]] = None):
        self.component = str(component)
        self.probe = probe
        self.recorder = recorder
        self.stall_s = float(stall_s)
        self.dump_path = dump_path
        self.on_trip = on_trip
        self.trips = 0
        self.last_trip_reason: Optional[str] = None
        self._tripped = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        if self.stall_s <= 0 or self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"watchdog-{self.component}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # one sweep, separated from the loop so tests (and manual health
    # checks) can drive the exact trip logic without waiting on a thread
    def check(self, now: Optional[float] = None) -> Optional[str]:
        """Evaluate the trip conditions once; returns the trip reason if
        this call tripped, else None."""
        try:
            state = self.probe() or {}
        except Exception:  # the probe must never kill the watchdog
            logger.exception("watchdog probe failed")
            return None
        now = time.time() if now is None else float(now)
        reason = None
        if state.get("poisoned"):
            reason = "nan_logits"
        else:
            occ = int(state.get("occupancy", 0) or 0)
            last = float(state.get("last_progress_ts", now) or now)
            if occ > 0 and now - last > self.stall_s:
                reason = "stalled"
            elif occ == 0 or now - last <= self.stall_s:
                self._tripped = False  # progress resumed: re-arm
        if reason is None or self._tripped:
            return None
        self._tripped = True
        self._trip(reason, state)
        return reason

    def _trip(self, reason: str, state: Dict[str, Any]) -> None:
        self.last_trip_reason = reason
        logger.error("watchdog[%s] TRIP: %s (state=%s)", self.component,
                     reason, state)
        obs_metrics.record_watchdog_trip(self.component, reason)
        from .. import mlops
        mlops.log_health(self.component, reason, detail={
            k: v for k, v in state.items()
            if isinstance(v, (int, float, str, bool))})
        if self.recorder is not None:
            self.recorder.note("watchdog_trip", reason=reason)
            self.recorder.dump(self.dump_path, reason=reason)
        # the counter moves LAST: a watcher polling `trips` may rely on
        # the dump/health artifacts already existing when it advances
        self.trips += 1
        if self.on_trip is not None:
            try:
                self.on_trip(reason)
            except Exception:
                logger.exception("watchdog on_trip callback failed")

    def _loop(self) -> None:
        interval = max(min(self.stall_s / 4.0, 5.0), 0.05)
        while not self._stop.wait(interval):
            self.check()
