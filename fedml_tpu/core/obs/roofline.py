"""Compute-plane observability: per-op roofline attribution, collective
traffic accounting, and recompile forensics.

PR 10's critical path proved the flagship round is 99.9% device-wait —
and that is where the host-side instruments stop. This module looks
INSIDE the compiled program: after a jitted engine/serving program
compiles, it walks the optimized HLO (plus ``compiled.cost_analysis()``
/ ``memory_analysis()`` as cross-checks) and emits, per op:

* operand/output shapes and analytical FLOPs + bytes accessed,
* arithmetic intensity and a compute- vs memory-bound classification
  against a per-device-kind machine-balance table (:data:`HBM_GBPS`
  extends :data:`profiler.PEAK_TFLOPS_BF16` with memory bandwidth),
* a roofline-predicted execution time (``max(flops/peak, bytes/bw)``)
  and its share of the program's predicted device time, plus a
  predicted whole-program MFU,

as a schema-validated ``kind: roofline`` JSONL record and registry
gauges. Fusions are the attribution unit (their internals never touch
memory — boundary bytes, summed inner FLOPs); ``while`` bodies are
multiplied by XLA's ``known_trip_count`` (falling back to the loop
condition's comparison constant), so a scanned conv stream attributes
its true repeated cost. Collectives (all-reduce / all-gather /
reduce-scatter / all-to-all / collective-permute) get a wire-byte
estimate per execution from the standard ring-algorithm factors and the
parsed replica groups — the accounting the multi-chip weak-scaling
bench reads.

On a CPU mesh there is no HBM: the machine-balance entry is a nominal
host value and every prediction is STATIC-ONLY — shapes, FLOPs, bytes,
intensities and collective bytes are exact, the time/MFU columns are a
model, not a measurement. The record says so (``static_only: true``)
and the capture logs it loudly once.

Capture is OPT-IN (``obs_roofline: true``): it AOT-lowers and compiles
the dispatched program once per (name, abstract-shape signature), which
is an extra backend compile the compile-once tests would otherwise
trip on. Recompile FORENSICS, by contrast, is always on and free: every
dispatch records its abstract arg signature (shapes/dtypes, never
values), and when the compile counter increments past the pinned
expectation — one compile per program — the changed leaves are emitted
as a ``kind: recompile`` record, so a compile-once regression names the
shape that moved instead of failing a bare counter assertion.

``scripts/roofline_report.py`` renders the records: top-N ops by
predicted time, per-operand-shape aggregation of the conv stream,
bound-class split, collective-bytes table, ``--compare`` across runs or
device counts, and a ``--min-attr`` coverage gate.
"""

from __future__ import annotations

import collections
import logging
import math
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import metrics as obs_metrics
from . import profiler as obs_profiler

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# machine balance: HBM GB/s per device kind, keyed like PEAK_TFLOPS_BF16
# (public specs). Together the two tables give the machine balance
# (flops/byte) every op's arithmetic intensity classifies against. The
# "cpu" entry is a NOMINAL host-memory figure so a laptop/CI run still
# produces a ranked table — flagged static-only, never trusted as a
# measurement.
HBM_GBPS = (
    ("v6", 1640.0), ("v5p", 2765.0), ("v5e", 819.0), ("v5", 819.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0), ("cpu", 25.0),
)

_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1,
          "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
          "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
          "c64": 8, "c128": 16, "token": 0, "s4": 1, "u4": 1}

_cfg = {"default_enabled": False, "max_ops": 64}


def set_default_enabled(on: bool) -> None:
    """Process default for the ``obs_roofline`` knob (``configure``);
    engines read their own args first and fall back to this."""
    _cfg["default_enabled"] = bool(on)


def default_enabled() -> bool:
    return _cfg["default_enabled"]


def hbm_gbps(device) -> Optional[float]:
    kind = str(getattr(device, "device_kind", "cpu")).lower()
    for key, bw in HBM_GBPS:
        if key in kind:
            return bw
    return None


@dataclass
class MachineBalance:
    device_kind: str
    peak_tflops: Optional[float]
    hbm_gbps: Optional[float]
    static_only: bool

    @property
    def flops_per_byte(self) -> Optional[float]:
        if not self.peak_tflops or not self.hbm_gbps:
            return None
        return (self.peak_tflops * 1e12) / (self.hbm_gbps * 1e9)


_static_warned = [False]


def machine_balance(device=None) -> MachineBalance:
    """Peak FLOP/s + HBM bandwidth for a jax device. A CPU (or unknown)
    kind degrades LOUDLY to static-only predictions — the table's host
    entry keeps the ranking meaningful, but time/MFU columns are a
    model, and the record carries ``static_only: true``."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = str(getattr(device, "device_kind", "cpu")).lower()
    peak = obs_profiler.peak_tflops(device)
    bw = hbm_gbps(device)
    static = ("cpu" in kind) or peak is None or bw is None
    if static and not _static_warned[0]:
        _static_warned[0] = True
        logger.warning(
            "roofline: device kind %r has no measured machine balance — "
            "predictions are STATIC-ONLY (shapes/FLOPs/bytes exact, "
            "time/MFU a model); re-capture on TPU for real numbers", kind)
    return MachineBalance(kind, peak, bw, static)


# ---------------------------------------------------------------------------
# optimized-HLO text parser. The compiled module is the per-device SPMD
# program; computations arrive as named blocks, entry last. We keep it
# deliberately tolerant: an unparseable line is skipped and surfaces in
# the record's attribution share instead of crashing a capture.

_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.+\s*\{")
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"(\(.*?\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"([a-z][a-z0-9\-]*)\(")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_TRIP_RE = re.compile(r'known_trip_count[^0-9]*"?n"?[^0-9]*(\d+)')
_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9,]+)\}")
_METADATA_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')
_CALL_RE = re.compile(r"(?:calls|to_apply|body)=%?([\w.\-]+)")
_COND_RE = re.compile(r"condition=%?([\w.\-]+)")


def _parse_shapes(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    out = []
    for dt, dims in _SHAPE_RE.findall(text):
        out.append((dt, tuple(int(d) for d in dims.split(",") if d)))
    return out


def _shape_bytes(shapes: Sequence[Tuple[str, Tuple[int, ...]]]) -> float:
    total = 0.0
    for dt, dims in shapes:
        total += _BYTES.get(dt, 4) * float(np_prod(dims))
    return total


def np_prod(dims: Sequence[int]) -> int:
    p = 1
    for d in dims:
        p *= int(d)
    return p


@dataclass
class HloOp:
    name: str
    opcode: str
    out_shapes: List[Tuple[str, Tuple[int, ...]]]
    operand_shapes: List[Tuple[str, Tuple[int, ...]]]
    attrs: str
    operand_text: str = ""
    op_name: str = ""
    calls: List[str] = field(default_factory=list)
    cond: Optional[str] = None
    trip_count: Optional[int] = None


def _split_operands(line: str, start: int) -> Tuple[str, str]:
    """Split ``opcode(OPERANDS), ATTRS`` at the top-level closing paren.
    Returns (operand_text, attrs_text)."""
    depth = 0
    for i in range(start, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return line[start + 1:i], line[i + 1:]
    return line[start + 1:], ""


def parse_hlo(text: str) -> Tuple[Dict[str, List[HloOp]], Optional[str]]:
    """Parse optimized HLO text into ``{computation: [HloOp]}`` plus the
    entry computation's name. Tolerant: unmatched lines are skipped."""
    comps: Dict[str, List[HloOp]] = {}
    entry: Optional[str] = None
    cur: Optional[List[HloOp]] = None
    for line in text.splitlines():
        m = _COMP_RE.match(line)
        if m:
            name = m.group(2)
            cur = comps.setdefault(name, [])
            if m.group(1):
                entry = name
            continue
        if line.startswith("}"):
            cur = None
            continue
        if cur is None:
            continue
        om = _OP_RE.match(line)
        if om is None:
            continue
        opname, shape_text, opcode = om.group(1), om.group(2), om.group(3)
        operands, attrs = _split_operands(line, om.end() - 1)
        op = HloOp(
            name=opname, opcode=opcode,
            out_shapes=_parse_shapes(shape_text),
            operand_shapes=_parse_shapes(operands),
            attrs=attrs, operand_text=operands)
        mm = _METADATA_RE.search(attrs)
        if mm:
            op.op_name = mm.group(1)
        if opcode in ("fusion", "call", "while", "reduce", "sort", "map",
                      "scatter", "reduce-window", "conditional",
                      "select-and-scatter", "all-reduce", "reduce-scatter"):
            op.calls = _CALL_RE.findall(attrs)
            cm = _COND_RE.search(attrs)
            if cm:
                op.cond = cm.group(1)
        if opcode == "while":
            tm = _TRIP_RE.search(attrs)
            if tm:
                op.trip_count = int(tm.group(1))
        cur.append(op)
    return comps, entry


def _cond_trip_count(comps: Dict[str, List[HloOp]],
                     cond: Optional[str]) -> Optional[int]:
    """Fallback trip count when ``known_trip_count`` is absent: the
    canonical counted-loop condition is a single scalar
    ``compare(counter, constant N), direction=LT`` — read N. Only
    trusted when the condition has exactly one integer constant."""
    if not cond or cond not in comps:
        return None
    has_lt = any(op.opcode == "compare" and "direction=LT" in op.attrs
                 for op in comps[cond])
    if not has_lt:
        return None
    consts = []
    for op in comps[cond]:
        if op.opcode == "constant" and op.out_shapes \
                and op.out_shapes[0][0].startswith(("s", "u")):
            m = re.fullmatch(r"\s*(\d+)\s*", op.operand_text)
            if m:
                consts.append(int(m.group(1)))
    return consts[0] if len(consts) == 1 else None


# --- analytical per-op cost model ------------------------------------------

# elementwise opcodes: 1 flop per output element (transcendentals are a
# handful of hardware ops but roofline-wise they stay bandwidth-bound at
# these intensities; precision here buys nothing)
_ELEMENTWISE = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "abs",
    "negate", "compare", "select", "and", "or", "xor", "not", "exp",
    "expm1", "log", "log1p", "tanh", "sqrt", "rsqrt", "cbrt", "power",
    "floor", "ceil", "round-nearest-afz", "round-nearest-even", "sign",
    "cosine", "sine", "tan", "atan2", "is-finite", "clamp", "remainder",
    "shift-left", "shift-right-logical", "shift-right-arithmetic",
    "popcnt", "clz", "erf", "logistic", "stochastic-convert",
))

# pure data movement: 0 flops, bytes from shapes
_MOVEMENT = frozenset((
    "copy", "transpose", "reshape", "broadcast", "slice", "dynamic-slice",
    "dynamic-update-slice", "concatenate", "pad", "gather", "scatter",
    "reverse", "convert", "bitcast-convert", "iota", "rng-bit-generator",
    "rng", "copy-start", "copy-done",
))

# free at runtime (no materialized traffic of their own). The async
# collectives' "-done" halves are free too: their cost was charged to
# the "-start" op — charging both would double-count every TPU
# collective and deflate attributed_share on the platform that matters.
_FREE = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "opt-barrier",
    "all-reduce-done", "all-gather-done", "reduce-scatter-done",
    "all-to-all-done", "collective-permute-done", "async-done",
))

COLLECTIVE_OPCODES = frozenset((
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "all-reduce-start",
    "all-gather-start", "reduce-scatter-start", "all-to-all-start",
    "collective-permute-start",
))


def _out_elems(op: HloOp) -> float:
    return float(sum(np_prod(d) for _, d in op.out_shapes)) or 0.0


def _dot_flops(op: HloOp) -> Optional[float]:
    if len(op.operand_shapes) < 1:
        return None
    m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", op.attrs)
    if not m:
        return None
    lhs = op.operand_shapes[0][1]
    contracting = [int(i) for i in m.group(1).split(",") if i]
    k = np_prod([lhs[i] for i in contracting if i < len(lhs)])
    return 2.0 * _out_elems(op) * float(k)


def _conv_flops(op: HloOp) -> Optional[float]:
    m = re.search(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)", op.attrs)
    if not m or len(op.operand_shapes) < 2:
        return None
    kern_labels = m.group(2)
    kern = op.operand_shapes[1][1]
    if len(kern_labels) != len(kern):
        return None
    spatial = 1
    in_feat = 1
    for lab, dim in zip(kern_labels, kern):
        if lab == "i":
            in_feat = dim
        elif lab != "o":
            spatial *= dim
    return 2.0 * _out_elems(op) * float(spatial) * float(in_feat)


def _comp_flops(comps: Dict[str, List[HloOp]], name: str,
                memo: Dict[str, float]) -> float:
    """Total analytical FLOPs of one computation, descending through
    fusions/calls (while bodies inside a fusion are impossible; while at
    computation level is handled by the attribution walk)."""
    if name in memo:
        return memo[name]
    memo[name] = 0.0  # cycle guard
    total = 0.0
    for op in comps.get(name, ()):
        fl, _known = _op_flops(op, comps, memo)
        total += fl or 0.0
    memo[name] = total
    return total


def _op_flops(op: HloOp, comps: Dict[str, List[HloOp]],
              memo: Dict[str, float]) -> Tuple[Optional[float], bool]:
    """(flops, known) for ONE op. ``known=False`` marks an opcode the
    model has no formula for (custom-call): bytes-only attribution."""
    oc = op.opcode
    if oc in _FREE or oc in _MOVEMENT:
        return 0.0, True
    if oc in _ELEMENTWISE:
        return _out_elems(op), True
    if oc == "dot":
        fl = _dot_flops(op)
        return (fl, True) if fl is not None else (0.0, False)
    if oc == "convolution":
        fl = _conv_flops(op)
        return (fl, True) if fl is not None else (0.0, False)
    if oc in ("fusion", "call", "map"):
        return sum(_comp_flops(comps, c, memo) for c in op.calls), True
    if oc in ("reduce", "reduce-window", "select-and-scatter"):
        return float(sum(np_prod(d) for _, d in op.operand_shapes)), True
    if oc == "sort":
        n = _out_elems(op)
        return n * max(math.log2(max(n, 2.0)), 1.0), True
    if oc in COLLECTIVE_OPCODES:
        # the reduction adds; wire time is modeled separately
        return _out_elems(op), True
    if oc == "custom-call":
        return 0.0, False
    # unknown opcode: elementwise-ish guess, flagged
    return _out_elems(op), False


# ops that read only a window of their (possibly huge) first operand —
# charging the full operand would let a per-slot dynamic-slice of the
# whole client-data array dwarf the conv stream it feeds
_WINDOW_READS = frozenset(("slice", "dynamic-slice", "gather"))
# ops that write only the update region of an aliased buffer
_WINDOW_WRITES = frozenset(("dynamic-update-slice", "scatter"))


def _op_bytes(op: HloOp) -> float:
    """Boundary memory traffic: operands read + outputs written. For a
    fusion this is exactly the roofline-correct figure — fused
    intermediates never touch memory. Window ops (slice / gather /
    dynamic-update-slice) are charged the window, not the buffer."""
    if op.opcode in _WINDOW_READS:
        return 2.0 * _shape_bytes(op.out_shapes)
    if op.opcode in _WINDOW_WRITES and len(op.operand_shapes) >= 2:
        return 2.0 * _shape_bytes(op.operand_shapes[1:2])
    return _shape_bytes(op.operand_shapes) + _shape_bytes(op.out_shapes)


def _fusion_bytes(comps: Dict[str, List[HloOp]], op: HloOp) -> float:
    """A fusion's traffic is its boundary — EXCEPT parameters consumed
    only through window reads (a fused ``dynamic-slice`` of the stacked
    client data reads one slice per iteration, not the stack). Charge
    those parameters their windows."""
    body = comps.get(op.calls[0]) if op.calls else None
    if not body:
        return _op_bytes(op)
    total = _shape_bytes(op.out_shapes)
    windowed: Dict[str, float] = {}
    for inner in body:
        if inner.opcode != "parameter":
            continue
        consumers = [o for o in body
                     if re.search(r"%" + re.escape(inner.name) + r"\b",
                                  o.operand_text)]
        if consumers and all(o.opcode in _WINDOW_READS
                             for o in consumers):
            windowed[inner.name] = sum(
                _shape_bytes(o.out_shapes) for o in consumers)
    # parameters line up with the fusion's operands by index; the ones
    # we re-priced subtract their full size and add their window
    params = [o for o in body if o.opcode == "parameter"]
    for p in params:
        size = _shape_bytes(p.out_shapes)
        total += windowed.get(p.name, size)
    return total


def _group_size(op: HloOp, n_devices: int) -> int:
    m = _GROUPS_RE.search(op.attrs)
    if m:
        return max(len([x for x in m.group(1).split(",") if x]), 1)
    return max(int(n_devices), 1)


def _collective_wire_bytes(op: HloOp, n_devices: int) -> Tuple[int, float]:
    """(group_size, per-device wire bytes) for one execution, from the
    standard ring-algorithm factors. Payload = operand bytes (result
    bytes for all-gather, whose output is the concatenation)."""
    g = _group_size(op, n_devices)
    oc = op.opcode.replace("-start", "")
    if oc == "all-gather":
        # the concatenated result; the async "-start" form's output is a
        # (operand, result) tuple, so take the LARGEST output shape, not
        # the sum, or wire bytes inflate by payload/g
        payload = max((_shape_bytes([s]) for s in op.out_shapes),
                      default=0.0)
    else:
        payload = _shape_bytes(op.operand_shapes)
    if g <= 1:
        return g, 0.0
    frac = (g - 1) / g
    if oc == "all-reduce":
        return g, 2.0 * frac * payload
    if oc in ("all-gather", "reduce-scatter", "all-to-all"):
        return g, frac * payload
    if oc in ("collective-permute", "collective-broadcast"):
        return g, payload
    return g, frac * payload


# ---------------------------------------------------------------------------
# attribution walk


@dataclass
class OpRow:
    name: str
    opcode: str
    op_name: str
    out: str
    operands: List[str]
    flops: float
    bytes: float
    mult: int
    known: bool
    loop_estimated: bool
    group: int = 0           # collective group size (0 = not one)
    wire_bytes: float = 0.0  # collective per-device wire bytes

    def shape_key(self) -> str:
        return f"{self.opcode}({','.join(self.operands)})->{self.out}"


def _fmt_shape(s: Tuple[str, Tuple[int, ...]]) -> str:
    dt, dims = s
    return f"{dt}[{','.join(str(d) for d in dims)}]"


def attribute(comps: Dict[str, List[HloOp]], entry: str,
              n_devices: int = 1) -> List[OpRow]:
    """Flatten the entry computation into costed leaf rows: fusions are
    one row each (boundary bytes, summed inner FLOPs), while bodies are
    multiplied by their trip count, free ops dropped."""
    memo: Dict[str, float] = {}
    rows: List[OpRow] = []

    def walk(comp: str, mult: int, loop_est: bool) -> None:
        for op in comps.get(comp, ()):
            oc = op.opcode
            if oc in _FREE:
                continue
            if oc == "while":
                trip = op.trip_count
                est = False
                if trip is None:
                    trip = _cond_trip_count(comps, op.cond)
                if trip is None:
                    trip, est = 1, True
                for body in op.calls:
                    walk(body, mult * max(trip, 1), loop_est or est)
                continue
            if oc == "conditional":
                # branch cost is data-dependent; attribute the branches
                # once (upper-bound-ish, rare in our programs)
                for body in op.calls:
                    walk(body, mult, True)
                continue
            if oc == "call":
                for body in op.calls:
                    walk(body, mult, loop_est)
                continue
            flops, known = _op_flops(op, comps, memo)
            nbytes = (_fusion_bytes(comps, op) if oc == "fusion"
                      else _op_bytes(op))
            if not flops and not nbytes:
                continue
            row = OpRow(
                name=op.name, opcode=oc, op_name=op.op_name,
                out=",".join(_fmt_shape(s) for s in op.out_shapes[:2]),
                operands=[_fmt_shape(s) for s in op.operand_shapes[:4]],
                flops=float(flops or 0.0), bytes=float(nbytes),
                mult=int(mult), known=bool(known),
                loop_estimated=bool(loop_est))
            if oc in COLLECTIVE_OPCODES:
                row.group, row.wire_bytes = _collective_wire_bytes(
                    op, n_devices)
            rows.append(row)

    walk(entry, 1, False)
    return rows


# ---------------------------------------------------------------------------
# analysis → record


def _xla_totals(compiled) -> Tuple[Optional[float], Optional[float]]:
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return None, None
        fl = ca.get("flops")
        by = ca.get("bytes accessed")
        return (float(fl) if fl is not None else None,
                float(by) if by is not None else None)
    except Exception:
        return None, None


def analyze_compiled(program: str, compiled, *, device=None,
                     n_devices: int = 1,
                     max_ops: Optional[int] = None) -> Dict[str, Any]:
    """Walk one compiled program into the ``kind: roofline`` record
    payload. Never raises on a parse gap — unattributed cost shows up in
    ``attributed_share`` instead."""
    bal = machine_balance(device)
    text = compiled.as_text()
    comps, entry = parse_hlo(text)
    rows = attribute(comps, entry, n_devices) if entry else []

    peak_fs = (bal.peak_tflops or 0.0) * 1e12
    bw_bs = (bal.hbm_gbps or 0.0) * 1e9

    def row_time(r: OpRow) -> float:
        t_c = (r.flops * r.mult / peak_fs) if peak_fs else 0.0
        t_m = (r.bytes * r.mult / bw_bs) if bw_bs else 0.0
        return max(t_c, t_m)

    total_flops = sum(r.flops * r.mult for r in rows)
    total_bytes = sum(r.bytes * r.mult for r in rows)
    times = [row_time(r) for r in rows]
    predicted_s = sum(times)
    mem_t = comp_t = unknown_t = 0.0
    balance = bal.flops_per_byte
    op_rows: List[Dict[str, Any]] = []
    for r, t in zip(rows, times):
        intensity = (r.flops / r.bytes) if r.bytes else None
        if not r.known:
            cls = "unknown"
            unknown_t += t
        elif balance is None or intensity is None:
            cls = "memory"
            mem_t += t
        elif intensity >= balance:
            cls = "compute"
            comp_t += t
        else:
            cls = "memory"
            mem_t += t
        op_rows.append({
            "name": r.name, "op": r.opcode, "op_name": r.op_name,
            "out": r.out, "operands": r.operands,
            "flops": r.flops * r.mult, "bytes": r.bytes * r.mult,
            "mult": r.mult,
            "intensity": (round(intensity, 4) if intensity is not None
                          else None),
            "bound": cls,
            "time_s": t,
            "share": (t / predicted_s) if predicted_s else 0.0,
            "estimated": bool(r.loop_estimated or not r.known),
        })
    op_rows.sort(key=lambda d: d["time_s"], reverse=True)
    cap = _cfg["max_ops"] if max_ops is None else int(max_ops)
    if cap and len(op_rows) > cap:
        rest = op_rows[cap:]
        op_rows = op_rows[:cap]
        op_rows.append({
            "name": "(other)", "op": "(other)", "op_name": "",
            "out": "", "operands": [],
            "flops": sum(d["flops"] for d in rest),
            "bytes": sum(d["bytes"] for d in rest), "mult": 1,
            "intensity": None, "bound": "mixed",
            "time_s": sum(d["time_s"] for d in rest),
            "share": sum(d["share"] for d in rest),
            "estimated": False,
        })

    colls: Dict[Tuple[str, str, int], Dict[str, Any]] = {}
    for r in rows:
        if not r.group:
            continue
        key = (r.opcode, ",".join(r.operands), r.group)
        ent = colls.setdefault(key, {
            "op": r.opcode.replace("-start", ""),
            "operands": r.operands, "group": r.group,
            "count": 0, "payload_bytes": 0.0, "wire_bytes": 0.0})
        ent["count"] += r.mult
        ent["payload_bytes"] += _collective_payload(r)
        ent["wire_bytes"] += r.wire_bytes * r.mult
    coll_rows = sorted(colls.values(), key=lambda d: d["wire_bytes"],
                       reverse=True)
    coll_total = sum(d["wire_bytes"] for d in coll_rows)

    xla_flops, xla_bytes = _xla_totals(compiled)
    mem_stats = _memory_stats(compiled)
    # computed even static-only: a useful ranking number, and the record
    # carries the static_only flag that labels it as a model
    predicted_mfu = None
    if peak_fs and predicted_s:
        predicted_mfu = total_flops / predicted_s / peak_fs
    attributed = 1.0 - (unknown_t / predicted_s if predicted_s else 0.0)
    rec: Dict[str, Any] = {
        "program": str(program),
        "device_kind": bal.device_kind,
        "n_devices": int(n_devices),
        "static_only": bool(bal.static_only),
        "peak_tflops": bal.peak_tflops,
        "hbm_gbps": bal.hbm_gbps,
        "balance_flops_per_byte": (round(balance, 2)
                                   if balance is not None else None),
        "total_flops": float(total_flops),
        "total_bytes": float(total_bytes),
        "predicted_s": float(predicted_s),
        "predicted_mfu": (round(predicted_mfu, 5)
                          if predicted_mfu is not None else None),
        "attributed_share": round(attributed, 5),
        "memory_bound_share": round(mem_t / predicted_s, 5)
        if predicted_s else 0.0,
        "compute_bound_share": round(comp_t / predicted_s, 5)
        if predicted_s else 0.0,
        "collective_wire_bytes": float(coll_total),
        "xla_flops": xla_flops,
        "xla_bytes": xla_bytes,
        "ops": op_rows,
        "collectives": coll_rows,
    }
    if mem_stats:
        rec.update(mem_stats)
    return rec


def _collective_payload(r: OpRow) -> float:
    # payload per execution × loop multiplier. The row's bytes field is
    # operands + outputs; payload ≈ half of that for the symmetric
    # collectives we model.
    return r.mult * r.bytes / 2.0


def _memory_stats(compiled) -> Dict[str, Any]:
    try:
        ms = compiled.memory_analysis()
        return {
            "arg_bytes": float(getattr(ms, "argument_size_in_bytes", 0)),
            "output_bytes": float(getattr(ms, "output_size_in_bytes", 0)),
            "temp_bytes": float(getattr(ms, "temp_size_in_bytes", 0)),
        }
    except Exception:
        return {}


# ---------------------------------------------------------------------------
# per-engine dispatch tracker: opt-in roofline capture + always-on
# recompile forensics at the `_traced` / serving-dispatch seam.

# most recent recompile-forensics records, process-wide: the
# xla_compile_counter fixture prints these when a compile-once
# assertion fails, so the failure names the shape that moved
_recent_recompiles: collections.deque = collections.deque(maxlen=16)

# last roofline record per program name, process-wide (bench legs read
# collective totals from here without re-parsing the run log)
_reports: Dict[str, Dict[str, Any]] = {}


def recent_recompiles() -> List[Dict[str, Any]]:
    return list(_recent_recompiles)


def report(program: str) -> Optional[Dict[str, Any]]:
    return _reports.get(program)


def reports() -> Dict[str, Dict[str, Any]]:
    return dict(_reports)


def _leaf_desc(leaf: Any) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return f"py:{type(leaf).__name__}"
    return f"{dtype}[{','.join(str(d) for d in shape)}]"


# leaf-path strings memoized per treedef: the serving decode step calls
# dispatch_signature once per generated token, and keystr's per-leaf
# string building is the expensive half — structure repeats, so pay it
# once per distinct treedef
_path_cache: Dict[Any, List[str]] = {}


def dispatch_signature(args: Any) -> Tuple[Tuple[str, str], ...]:
    """Abstract signature of a dispatch's args: (tree path, shape/dtype)
    per leaf — values never recorded. Cheap enough for every dispatch
    (it is what makes recompile forensics free at default knobs)."""
    import jax
    try:
        leaves, td = jax.tree_util.tree_flatten(args)
        paths = _path_cache.get(td)
        if paths is None:
            if len(_path_cache) > 128:   # bounded: treedefs per process
                _path_cache.clear()
            flat = jax.tree_util.tree_flatten_with_path(args)[0]
            paths = [jax.tree_util.keystr(p) for p, _ in flat]
            _path_cache[td] = paths
        return tuple(zip(paths, (_leaf_desc(l) for l in leaves)))
    except Exception:
        leaves = jax.tree_util.tree_leaves(args)
        return tuple((f"[{i}]", _leaf_desc(l))
                     for i, l in enumerate(leaves))


class DispatchTracker:
    """Per-engine-instance compute-plane seam. ``signature`` +
    ``observe`` give recompile forensics on every dispatch;
    ``maybe_capture`` does the opt-in AOT roofline capture (once per
    (program, signature) — call it BEFORE the dispatch so donated
    buffers are still alive, and BEFORE snapshotting the compile
    counter so its AOT compile is not charged to the dispatch)."""

    def __init__(self, enabled: Optional[bool] = None,
                 n_devices: int = 1, device: Any = None):
        self.enabled = (bool(enabled) if enabled is not None
                        else _cfg["default_enabled"])
        self.n_devices = int(n_devices)
        self.device = device
        self._sigs: Dict[str, Tuple[Tuple[str, str], ...]] = {}
        self._compiles: Dict[str, int] = {}
        # SET of captured signatures per program: a shape-alternating
        # program (the exact pathology this plane diagnoses) must pay
        # one AOT compile per distinct signature, not one per dispatch
        self._captured: Dict[str, set] = {}

    # --- roofline capture (opt-in) -------------------------------------
    def maybe_capture(self, program: str, fn: Any, args: Sequence[Any],
                      sig: Optional[Tuple] = None) -> Optional[Dict[str, Any]]:
        if not self.enabled:
            return None
        if sig is None:
            sig = dispatch_signature(tuple(args))
        seen = self._captured.setdefault(program, set())
        if sig in seen:
            return None
        seen.add(sig)
        try:
            compiled = fn.lower(*args).compile()
            rec = analyze_compiled(program, compiled, device=self.device,
                                   n_devices=self.n_devices)
        except Exception as e:  # capture must never sink a run
            logger.warning("roofline capture of %r failed (%s: %s)",
                           program, type(e).__name__, e)
            return None
        _reports[program] = rec
        from .. import mlops
        mlops._emit("roofline", rec)
        obs_metrics.record_roofline(
            program, rec.get("predicted_mfu"),
            rec.get("memory_bound_share"),
            rec.get("collective_wire_bytes"))
        logger.info(
            "roofline[%s]: %d ops, predicted %s, mfu %s, memory-bound "
            "share %.2f, collective wire bytes %.0f%s",
            program, len(rec["ops"]),
            f"{rec['predicted_s'] * 1e3:.3f} ms",
            rec["predicted_mfu"], rec["memory_bound_share"],
            rec["collective_wire_bytes"],
            " (STATIC-ONLY: cpu balance)" if rec["static_only"] else "")
        return rec

    # --- recompile forensics (always on) -------------------------------
    def observe(self, program: str, sig: Tuple[Tuple[str, str], ...],
                compiles: int) -> Optional[Dict[str, Any]]:
        """Record a dispatch's signature; when the compile counter
        incremented PAST the pinned expectation (one compile per
        program), emit the ``kind: recompile`` forensics record naming
        the changed abstract shapes."""
        prev = self._sigs.get(program)
        self._sigs[program] = sig
        if compiles <= 0:
            return None
        total = self._compiles.get(program, 0) + int(compiles)
        self._compiles[program] = total
        if prev is None:
            return None   # the expected first compile
        changed: List[Dict[str, Any]] = []
        old = dict(prev)
        new = dict(sig)
        for path in new:
            if path not in old:
                changed.append({"arg": path, "was": None,
                                "now": new[path]})
            elif old[path] != new[path]:
                changed.append({"arg": path, "was": old[path],
                                "now": new[path]})
        for path in old:
            if path not in new:
                changed.append({"arg": path, "was": old[path],
                                "now": None})
        note = None
        if not changed:
            note = ("no abstract-shape change — cache miss from a new "
                    "callable, jit options, or sharding change")
        rec = {"program": str(program), "compiles": int(compiles),
               "total_compiles": int(total), "expected": 1,
               "changed": changed, "note": note}
        from .. import mlops
        mlops._emit("recompile", rec)
        obs_metrics.record_recompile(program)
        _recent_recompiles.append(rec)
        logger.warning(
            "recompile forensics[%s]: %d compile(s) past the pinned "
            "expectation; changed: %s", program,
            compiles, changed or note)
        return rec
