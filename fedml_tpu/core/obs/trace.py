"""Distributed tracing — real spans with trace/span IDs, Dapper-style.

The plane the old ``mlops.event`` never was: every span carries a
process-unique ``span_id`` inside a run-spanning ``trace_id``, nests under
a parent (thread-local context stack), records point-in-time EVENTS
(backoff retries, chaos link faults), and LINKS to spans in *other*
traces (an async pour links the K upload spans it consumed, staleness
attached per link — the links-not-parents shape is exactly OpenTelemetry's
answer to fan-in). Context crosses the wire as a W3C ``traceparent``
header (``00-<trace_id>-<span_id>-01``) on :class:`Message`, so one
federated round — server broadcast → per-silo train → upload → aggregate —
reconstructs as a single trace tree across processes regardless of
transport (the header is an ordinary message param; TCP, gRPC, and the
pub/sub broker all carry it for free).

Spans are emitted as ``kind: span`` JSONL records through the mlops sink
on :meth:`Span.end`; ``scripts/trace_report.py`` rebuilds the trees and
prints the per-round critical path. Tracing is default-ON (it is cheap:
a span is a dict and one JSONL line; there is no per-op instrumentation)
and disabled with ``obs_tracing: false`` — every entry point then returns
the shared no-op span, so instrumented code never branches.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

# the Message param carrying the W3C context header
TRACEPARENT_KEY = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")

_cfg = {"enabled": True}


def set_enabled(on: bool) -> None:
    _cfg["enabled"] = bool(on)


def is_enabled() -> bool:
    return _cfg["enabled"]


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class SpanContext:
    """The propagatable identity of a span: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __repr__(self) -> str:
        return f"SpanContext({self.traceparent()})"


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """W3C ``traceparent`` -> :class:`SpanContext`, or None on anything
    malformed (a garbled header degrades to an unparented span, never an
    error — observability must not take down the data path)."""
    if not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    return SpanContext(m.group(1), m.group(2))


# thread-local active-span stack (the implicit parent for new spans)
_tls = threading.local()


def _stack() -> List["Span"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional["Span"]:
    st = _stack()
    return st[-1] if st else None


def add_event(name: str, **attrs: Any) -> None:
    """Attach a point-in-time event to the current span, if any — the
    seam deep layers (backoff retries, chaos faults) use without needing
    a span handle threaded through."""
    sp = current_span()
    if sp is not None:
        sp.add_event(name, **attrs)


class Span:
    """One timed operation. Usable as a context manager (activates on the
    thread-local stack: children started on this thread nest under it) or
    as a bare handle (``start_span`` + ``end()`` — the pair-API shape the
    ``mlops.event`` shim rides)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ts",
                 "end_ts", "attrs", "events", "links", "_lock", "_active")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = str(name)
        self.trace_id = trace_id
        self.span_id = _rand_hex(8)
        self.parent_id = parent_id
        self.start_ts = time.time()
        self.end_ts: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.events: List[Dict[str, Any]] = []
        self.links: List[Dict[str, Any]] = []
        # events/links can arrive from other threads (upload handlers
        # annotate the server's wait span); end() is guarded idempotent
        self._lock = threading.Lock()
        self._active = False

    # --- identity -----------------------------------------------------------
    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def traceparent(self) -> str:
        return self.context.traceparent()

    # --- enrichment ---------------------------------------------------------
    def set_attr(self, key: str, value: Any) -> "Span":
        with self._lock:
            self.attrs[str(key)] = value
        return self

    def add_event(self, name: str, **attrs: Any) -> "Span":
        with self._lock:
            self.events.append({"name": str(name), "ts": time.time(),
                                **({"attrs": attrs} if attrs else {})})
        return self

    def add_link(self, ctx: Any, **attrs: Any) -> "Span":
        """Link another span (a :class:`SpanContext`, a :class:`Span`, or
        a raw traceparent string) — possibly from a different trace; the
        fan-in edge a parent/child tree cannot express."""
        if isinstance(ctx, Span):
            ctx = ctx.context
        elif isinstance(ctx, str):
            ctx = parse_traceparent(ctx)
        if ctx is None:
            return self
        with self._lock:
            self.links.append({"trace_id": ctx.trace_id,
                               "span_id": ctx.span_id,
                               **({"attrs": attrs} if attrs else {})})
        return self

    # --- lifecycle ----------------------------------------------------------
    def end(self) -> Optional[float]:
        """Close the span and emit its record. Idempotent; returns the
        duration in seconds (None if already ended elsewhere)."""
        with self._lock:
            if self.end_ts is not None:
                return None
            self.end_ts = time.time()
            rec = {"name": self.name, "trace_id": self.trace_id,
                   "span_id": self.span_id, "parent_id": self.parent_id,
                   "start_ts": self.start_ts, "end_ts": self.end_ts,
                   "duration_s": self.end_ts - self.start_ts,
                   "pid": os.getpid()}
            if self.attrs:
                rec["attrs"] = dict(self.attrs)
            if self.events:
                rec["events"] = list(self.events)
            if self.links:
                rec["links"] = list(self.links)
        _emit_span(rec)
        return rec["duration_s"]

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.end_ts is None else self.end_ts - self.start_ts

    def __enter__(self) -> "Span":
        self._active = True
        _stack().append(self)
        return self

    def __exit__(self, *exc) -> bool:
        st = _stack()
        if self._active and self in st:
            # remove THIS span even if a child leaked (mis-nesting must
            # not shift which span later code annotates)
            st.remove(self)
        self._active = False
        if exc and exc[0] is not None:
            self.set_attr("error", getattr(exc[0], "__name__", str(exc[0])))
        self.end()
        return False


class _NoopSpan:
    """Shared inert span: every mutator no-ops, context is None — the
    instrumented call sites never branch on the tracing knob."""

    context = None
    duration_s = None
    name = trace_id = span_id = parent_id = None

    def traceparent(self):
        return None

    def set_attr(self, key, value):
        return self

    def add_event(self, name, **attrs):
        return self

    def add_link(self, ctx, **attrs):
        return self

    def end(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Span factory. One module-level instance (:data:`tracer`) — a
    process is one rank, exactly like ``WIRE_STATS``."""

    def start_span(self, name: str, parent: Any = None, root: bool = False,
                   attrs: Optional[Dict[str, Any]] = None):
        """Create a span (not yet on the context stack — use it as a
        context manager to activate it, or keep it as a bare handle).

        ``parent`` may be a Span, a SpanContext, a traceparent string, or
        None (inherit the thread's current span). ``root=True`` forces a
        fresh trace even when a span is active — round/pour boundaries."""
        if not _cfg["enabled"]:
            return NOOP_SPAN
        if isinstance(parent, str):
            parent = parse_traceparent(parent)
        if isinstance(parent, Span):
            parent = parent.context
        if parent is not None and getattr(parent, "trace_id", None) is None:
            # a _NoopSpan handle (stored while tracing was off) or a
            # degenerate context: treat as no parent rather than minting
            # a span with trace_id=None that violates the schema
            parent = None
        if parent is None and not root:
            cur = current_span()
            if cur is not None:
                parent = cur.context
        if root:
            parent = None
        if parent is not None:
            return Span(name, parent.trace_id, parent.span_id, attrs)
        return Span(name, _rand_hex(16), None, attrs)

    # context-manager spelling reads better at call sites
    span = start_span


tracer = Tracer()


def span(name: str, parent: Any = None, root: bool = False,
         attrs: Optional[Dict[str, Any]] = None):
    """Module-level shortcut: ``with obs_trace.span("broadcast"): ...``"""
    return tracer.start_span(name, parent=parent, root=root, attrs=attrs)


# --- Message propagation ----------------------------------------------------

def inject(msg, span_or_ctx: Any = None) -> None:
    """Stamp the current (or given) span's traceparent onto an outgoing
    :class:`Message` — the ONE seam every transport inherits, because the
    header is an ordinary message param."""
    if not _cfg["enabled"]:
        return
    sp = span_or_ctx if span_or_ctx is not None else current_span()
    if isinstance(sp, Span):
        sp = sp.context
    if isinstance(sp, SpanContext):
        msg.add_params(TRACEPARENT_KEY, sp.traceparent())


def extract(msg) -> Optional[SpanContext]:
    """Read the remote trace context off a received :class:`Message`."""
    return parse_traceparent(msg.get(TRACEPARENT_KEY))


# --- emission ---------------------------------------------------------------

def _emit_span(rec: Dict[str, Any]) -> None:
    # lazy import: mlops imports obs for configure(); the emission seam
    # is the reverse edge, resolved at call time
    from .. import mlops
    mlops._emit("span", rec)
