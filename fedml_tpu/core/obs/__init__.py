"""Unified observability layer — three planes over one JSONL sink.

1. **Tracing** (:mod:`.trace`): real spans with trace/span IDs and W3C
   ``traceparent`` propagation on :class:`Message`, so one federated
   round reconstructs as a single trace tree across processes; async
   pours LINK the upload spans they consume, staleness per link.
2. **Metrics** (:mod:`.metrics`): a typed counter/gauge/histogram
   registry absorbing the scattered one-shot records — wire bytes by
   message type, pour staleness and buffer occupancy, arrival rates,
   selection decisions, compile count, dispatch wall time, checkpoint
   flush time, HBM peak, per-round MFU — with Prometheus text exposition
   and a periodic JSONL snapshot.
3. **Profiling** (:mod:`.profiler`): per-dispatch host/device wall-time
   attribution at the engine seam + the FLOPs model as a first-class
   per-round MFU gauge (opt-in: blocking defeats dispatch overlap).

4. **Compute plane** (:mod:`.roofline`): per-op roofline attribution of
   compiled programs (opt-in ``obs_roofline`` — one AOT compile per
   program), collective-traffic accounting, and always-on recompile
   forensics that name the changed abstract shapes when a dispatch
   compiles past its pinned expectation.

``scripts/trace_report.py`` reads a run's JSONL and prints the per-round
critical path; ``scripts/roofline_report.py`` renders the compute
plane's records. :mod:`.schema` is the one table every record kind
validates against.

Knobs (``arguments.py``): tracing + metrics default ON (cheap — spans
are dicts, metric hooks are dict lookups); ``obs_profile_device``
defaults OFF. ``configure(args)`` is called by ``mlops.init``; without
it the defaults apply, so library use without init still traces.
"""

from __future__ import annotations

from . import flight, metrics, profiler, roofline, schema, trace  # noqa: F401
from .flight import FlightRecorder, Watchdog                    # noqa: F401
from .metrics import REGISTRY                                   # noqa: F401
from .trace import (NOOP_SPAN, SpanContext, add_event, current_span,  # noqa: F401
                    extract, inject, parse_traceparent, span, tracer)


def configure(args=None) -> None:
    """Wire the obs knobs from the flat config (idempotent; called by
    ``mlops.init``). ``args=None`` restores the documented defaults."""
    trace.set_enabled(bool(getattr(args, "obs_tracing", True)))
    metrics.set_enabled(bool(getattr(args, "obs_metrics", True)))
    metrics.set_flush_every(
        int(getattr(args, "obs_metrics_flush_rounds", 10) or 0))
    # wall-clock snapshot cadence for workloads with no round boundary
    # (serving, cross-device handshakes, agents): the round flusher
    # never fires there, so a crash would lose everything since init
    metrics.set_flush_interval(
        float(getattr(args, "obs_metrics_flush_s", 60.0) or 0.0))
    profiler.set_device_profiling(
        bool(getattr(args, "obs_profile_device", False)))
    # compute-plane roofline capture (opt-in: costs one AOT backend
    # compile per program); engines read their own args knob first —
    # this default covers seams without an args object (serving)
    roofline.set_default_enabled(
        bool(getattr(args, "obs_roofline", False)))
