"""FHE-style encrypted aggregation (reference ``core/fhe/fhe_agg.py:10``:
TenSEAL-CKKS ``fhe_enc``/``fhe_dec``/``fhe_fedavg``). Backed here by pure-
Python Paillier (:mod:`.paillier`) — exact additive homomorphism, no
native crypto dependency. The server only ever handles ciphertexts; key
generation/holding is client-side (in deployment: threshold keygen — the
shared-key stand-in is for protocol-shape parity, like SA/LSA note)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from .paillier import (PrivateKey, PublicKey, add_ciphertexts, keygen,
                       pack_vector, unpack_vector)

__all__ = ["FedMLFHE", "fhe_fedavg", "keygen", "PublicKey", "PrivateKey"]


def fhe_fedavg(vectors: Sequence[np.ndarray], weights: Sequence[float],
               pub: PublicKey, priv: PrivateKey,
               frac_bits: int = 16) -> np.ndarray:
    """Weighted FedAvg where the server-side reduction happens on
    ciphertexts: each client encrypts (w_k/W) * v_k; the 'server' multiplies
    ciphertexts (= adds plaintexts); decrypt yields the weighted average."""
    total = float(sum(weights)) or 1.0
    cts = [pack_vector(np.asarray(v) * (w / total), pub,
                       frac_bits=frac_bits)
           for v, w in zip(vectors, weights)]
    agg = add_ciphertexts(cts, pub)
    return unpack_vector(agg, priv, len(vectors[0]), n_added=len(vectors),
                         frac_bits=frac_bits)


class FedMLFHE:
    """L4 singleton consulted by the algframe hooks (reference
    ``FedMLFHE`` in ``fhe_agg.py``): enabled by ``args.enable_fhe``."""

    def __init__(self, args: Optional[Any] = None, key_bits: int = 2048):
        self.enabled = bool(getattr(args, "enable_fhe", False))
        self._pub: Optional[PublicKey] = None
        self._priv: Optional[PrivateKey] = None
        self.key_bits = int(getattr(args, "fhe_key_bits", key_bits)
                            or key_bits)
        if self.enabled and self.key_bits < 2048:
            import logging
            logging.getLogger(__name__).warning(
                "FHE key_bits=%d is below the ~2048-bit Paillier minimum — "
                "the modulus is practically factorable. NOT for production "
                "(tests may override for speed).", self.key_bits)

    def is_fhe_enabled(self) -> bool:
        return self.enabled

    def _ensure_keys(self):
        if self._pub is None:
            self._pub, self._priv = keygen(self.key_bits)

    def fhe_enc(self, vec: np.ndarray) -> List[int]:
        self._ensure_keys()
        return pack_vector(np.asarray(vec, np.float64), self._pub)

    def fhe_dec(self, cts: List[int], length: int,
                n_added: int = 1) -> np.ndarray:
        self._ensure_keys()
        return unpack_vector(cts, self._priv, length, n_added=n_added)

    def fhe_agg(self, cts_list: Sequence[List[int]]) -> List[int]:
        self._ensure_keys()
        return add_ciphertexts(cts_list, self._pub)
