"""Paillier additively-homomorphic encryption, pure Python.

The reference's FHE aggregation (``core/fhe/fhe_agg.py:10``) uses TenSEAL
CKKS (approximate HE over floats). TenSEAL is unavailable here, and CKKS
from scratch is out of scope — Paillier gives the property the FL
aggregation actually needs (ciphertext addition = plaintext addition,
exactly) with nothing but big-int arithmetic, so the aggregate of encrypted
client updates is bit-exact rather than approximate.

Packing: model updates are fixed-point-quantized and packed many slots per
ciphertext (``slot_bits`` per value, sized to hold the sum over clients),
so a 100k-parameter update needs ~100k/slots ciphertext ops, not 100k
exponentiations per value.
"""

from __future__ import annotations

import dataclasses
import secrets
from typing import List, Sequence, Tuple

import numpy as np

_SMALL_PRIMES = [3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53,
                 59, 61, 67, 71, 73, 79, 83, 89, 97]


def _is_probable_prime(n: int, rounds: int = 20) -> bool:
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = pow(x, 2, n)
            if x == n - 1:
                break
        else:
            return False
    return True


def _gen_prime(bits: int) -> int:
    while True:
        cand = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _is_probable_prime(cand):
            return cand


@dataclasses.dataclass
class PublicKey:
    n: int

    @property
    def n_sq(self) -> int:
        return self.n * self.n

    def encrypt_int(self, m: int) -> int:
        """E(m) = (1 + n)^m * r^n mod n^2 (g = n+1 variant)."""
        if not 0 <= m < self.n:
            raise ValueError("plaintext out of range")
        n, n_sq = self.n, self.n_sq
        while True:
            r = secrets.randbelow(n - 1) + 1
            if r % n != 0:
                break
        return (pow(n + 1, m, n_sq) * pow(r, n, n_sq)) % n_sq

    def add(self, c1: int, c2: int) -> int:
        """Homomorphic addition: E(a) * E(b) = E(a + b)."""
        return (c1 * c2) % self.n_sq


@dataclasses.dataclass
class PrivateKey:
    public: PublicKey
    lam: int     # lcm(p-1, q-1)
    mu: int      # (L(g^lam mod n^2))^-1 mod n

    def decrypt_int(self, c: int) -> int:
        n, n_sq = self.public.n, self.public.n_sq
        x = pow(c, self.lam, n_sq)
        l_val = (x - 1) // n
        return (l_val * self.mu) % n


def keygen(bits: int = 1024, seed_primes: Tuple[int, int] = None
           ) -> Tuple[PublicKey, PrivateKey]:
    """Generate a keypair; ``seed_primes`` lets tests inject fixed primes
    (NOT for production)."""
    if seed_primes is not None:
        p, q = seed_primes
    else:
        p = _gen_prime(bits // 2)
        q = _gen_prime(bits // 2)
        while q == p:
            q = _gen_prime(bits // 2)
    import math
    n = p * q
    lam = (p - 1) * (q - 1) // math.gcd(p - 1, q - 1)
    pub = PublicKey(n)
    x = pow(n + 1, lam, n * n)
    mu = pow((x - 1) // n, -1, n)
    return pub, PrivateKey(pub, lam, mu)


# ---------------------------------------------------------------------------
# vector packing: fixed-point floats -> packed big ints -> ciphertexts
# ---------------------------------------------------------------------------

def _slot_bias(slot_bits: int, max_added: int) -> int:
    """Per-slot bias such that ``max_added`` biased slots sum without
    carrying into the neighbour: max_added * 2 * bias <= 2^slot_bits."""
    return (1 << slot_bits) // (2 * max_added)


def pack_vector(v: np.ndarray, pub: PublicKey, frac_bits: int = 16,
                slot_bits: int = 48, max_added: int = 256) -> List[int]:
    """Quantize ``v`` (float) to signed fixed point and pack into
    ciphertexts, ``slots`` values per ciphertext. Each slot carries
    ``value + bias`` (non-negative), with the bias sized so that up to
    ``max_added`` ciphertexts can be summed without slot overflow; the
    accumulated bias is removed at unpack time."""
    q = np.rint(np.asarray(v, np.float64) * (1 << frac_bits)).astype(object)
    bias = _slot_bias(slot_bits, max_added)
    lim = bias - 1
    q = np.clip(q, -lim, lim)
    slots = max((pub.n.bit_length() - 64) // slot_bits, 1)
    out: List[int] = []
    for start in range(0, len(q), slots):
        block = q[start:start + slots]
        packed = 0
        for j, val in enumerate(block):
            packed |= (int(val) + bias) << (j * slot_bits)
        out.append(pub.encrypt_int(packed))
    return out


def add_ciphertexts(cts: Sequence[List[int]], pub: PublicKey) -> List[int]:
    """Element-wise homomorphic sum of per-client ciphertext lists."""
    agg = list(cts[0])
    for ct in cts[1:]:
        agg = [pub.add(a, c) for a, c in zip(agg, ct)]
    return agg


def unpack_vector(cts: List[int], priv: PrivateKey, length: int,
                  n_added: int, frac_bits: int = 16,
                  slot_bits: int = 48, max_added: int = 256) -> np.ndarray:
    """Decrypt + unpack the SUM of ``n_added`` packed vectors (all packed
    with the same ``max_added``)."""
    if n_added > max_added:
        raise ValueError(f"{n_added} summands > packing capacity "
                         f"{max_added}")
    bias = _slot_bias(slot_bits, max_added)
    mask = (1 << slot_bits) - 1
    slots = max((priv.public.n.bit_length() - 64) // slot_bits, 1)
    vals = np.empty(length, np.float64)
    idx = 0
    for c in cts:
        m = priv.decrypt_int(c)
        for j in range(slots):
            if idx >= length:
                break
            raw = (m >> (j * slot_bits)) & mask
            vals[idx] = float(raw - n_added * bias) / (1 << frac_bits)
            idx += 1
    return vals
