"""Renyi-DP accountant for the subsampled Gaussian mechanism.

Parity target: reference ``core/dp/budget_accountant/rdp_accountant.py`` (178
LoC) + ``rdp_analysis.py`` (220) — track cumulative RDP over FL rounds and
convert to (epsilon, delta). Implementation is the standard
Mironov/Abadi-moments math (log-space binomial expansion for integer orders,
the Wang et al. subsampling bound), written fresh in numpy.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Sequence, Tuple

import numpy as np

DEFAULT_ORDERS: Tuple[float, ...] = tuple(
    [2.0] + list(range(3, 64)) + [128.0, 256.0, 512.0])


def _log_add(a: float, b: float) -> float:
    if a == -np.inf:
        return b
    if b == -np.inf:
        return a
    hi, lo = max(a, b), min(a, b)
    return hi + math.log1p(math.exp(lo - hi))


def _log_comb(n: int, k: int) -> float:
    return (math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1))


def _rdp_gaussian(sigma: float, alpha: float) -> float:
    """RDP of the (unsubsampled) Gaussian mechanism: alpha / (2 sigma^2)."""
    return alpha / (2.0 * sigma * sigma)


def _rdp_subsampled_int(q: float, sigma: float, alpha: int) -> float:
    """RDP of the Poisson-subsampled Gaussian at integer order alpha
    (Mironov et al. 2019 binomial-sum bound, computed in log space)."""
    log_terms = []
    for k in range(alpha + 1):
        log_b = _log_comb(alpha, k)
        if q == 0:
            log_q = -np.inf if k > 0 else 0.0
        else:
            log_q = k * math.log(q) + (alpha - k) * math.log1p(-q)
        rdp_k = k * (k - 1) / (2.0 * sigma * sigma)
        log_terms.append(log_b + log_q + rdp_k)
    acc = -np.inf
    for t in log_terms:
        acc = _log_add(acc, t)
    return acc / (alpha - 1) if alpha > 1 else acc


def compute_rdp(q: float, noise_multiplier: float, steps: int,
                orders: Sequence[float] = DEFAULT_ORDERS) -> np.ndarray:
    """Cumulative RDP over ``steps`` rounds of the subsampled Gaussian with
    sampling rate ``q`` and noise multiplier sigma (noise_std / sensitivity)."""
    sigma = noise_multiplier
    rdp = []
    for a in orders:
        if q >= 1.0:
            val = _rdp_gaussian(sigma, a)
        elif float(a).is_integer() and a >= 2:
            val = _rdp_subsampled_int(q, sigma, int(a))
        elif a <= 1.0:
            raise ValueError(f"RDP orders must be > 1, got {a}")
        else:
            # Fractional orders: RDP(alpha) is non-decreasing in alpha, so the
            # value at ceil(alpha) is a sound upper bound. (Linear interpolation
            # between integer orders is NOT an upper bound for the subsampled
            # Gaussian and would under-report epsilon.)
            hi = max(int(math.ceil(a)), 2)
            val = _rdp_subsampled_int(q, sigma, hi)
        rdp.append(val * steps)
    return np.asarray(rdp)


def get_privacy_spent(orders: Sequence[float], rdp: np.ndarray,
                      target_delta: float) -> Tuple[float, float]:
    """(epsilon, optimal_order) via the improved conversion of Balle et al.:
    eps = rdp - (log(delta) + log(alpha)) / (alpha - 1) + log1p(-1/alpha)."""
    orders = np.asarray(orders, dtype=np.float64)
    rdp = np.asarray(rdp, dtype=np.float64)
    mask = orders > 1.0000001
    a = orders[mask]
    r = rdp[mask]
    eps = r - (np.log(target_delta) + np.log(a)) / (a - 1.0) + np.log1p(-1.0 / a)
    i = int(np.argmin(eps))
    return float(max(eps[i], 0.0)), float(a[i])


class RDPAccountant:
    """Accumulates per-round RDP (the reference accountant's ``add_step`` /
    ``get_epsilon`` shape)."""

    def __init__(self, orders: Sequence[float] = DEFAULT_ORDERS):
        self.orders = tuple(orders)
        self._rdp = np.zeros(len(self.orders))

    def step(self, noise_multiplier: float, sample_rate: float,
             num_steps: int = 1) -> None:
        self._rdp = self._rdp + compute_rdp(sample_rate, noise_multiplier,
                                            num_steps, self.orders)

    def get_epsilon(self, delta: float) -> float:
        eps, _ = get_privacy_spent(self.orders, self._rdp, delta)
        return eps
