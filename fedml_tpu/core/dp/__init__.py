"""Differential privacy cross-cut (reference ``core/dp/``): calibrated
mechanisms, local/central DP frames, NbAFL, and an RDP accountant.

``FedMLDifferentialPrivacy`` is the singleton engines consult (reference
``core/dp/fedml_differential_privacy.py``): LDP clips + noises each client
update *inside* the jitted round before aggregation; CDP noises the
aggregate on the server side. The accountant tracks the (epsilon, delta)
spent across rounds for the subsampled Gaussian.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from ...utils.confval import get_float
from .mechanisms import (Gaussian, Laplace, add_gaussian_noise,
                         add_laplace_noise, clip_by_global_norm,
                         create_mechanism, gaussian_sigma, laplace_scale)
from .rdp_accountant import RDPAccountant, compute_rdp, get_privacy_spent

PyTree = Any

DP_TYPE_LOCAL = "local_dp"   # aka LDP frame (reference frames/ldp.py)
DP_TYPE_CENTRAL = "central_dp"  # aka CDP frame (reference frames/cdp.py)
DP_TYPE_NBAFL = "nbafl"      # noise before+after aggregation (frames/NbAFL.py)


class FedMLDifferentialPrivacy:
    _instance: Optional["FedMLDifferentialPrivacy"] = None

    def __init__(self, args):
        self.args = args
        self.enabled = bool(getattr(args, "enable_dp", False))
        self.dp_type = str(getattr(args, "dp_type", DP_TYPE_LOCAL)
                           or DP_TYPE_LOCAL).lower()
        self.epsilon = get_float(args, "dp_epsilon", 10.0)
        self.delta = get_float(args, "dp_delta", 1e-5)
        # the clip norm IS the sensitivity — the clip is what enforces the
        # bound the noise is calibrated to; keeping them as one knob means
        # the reported (epsilon, delta) always matches the mechanism run
        self.clip_norm = float(
            getattr(args, "dp_clip_norm", None)
            or getattr(args, "dp_sensitivity", None) or 1.0)
        self.sensitivity = self.clip_norm
        self.mechanism = create_mechanism(
            getattr(args, "dp_mechanism", "gaussian"),
            self.epsilon, self.delta, self.sensitivity) if self.enabled else None
        self.accountant = RDPAccountant()
        self._laplace_rounds = 0

    @classmethod
    def get_instance(cls, args=None) -> "FedMLDifferentialPrivacy":
        if args is not None or cls._instance is None:
            cls._instance = cls(args)
        return cls._instance

    def is_dp_enabled(self) -> bool:
        return self.enabled

    def is_local_dp_enabled(self) -> bool:
        return self.enabled and self.dp_type in (DP_TYPE_LOCAL, DP_TYPE_NBAFL)

    def is_global_dp_enabled(self) -> bool:
        return self.enabled and self.dp_type in (DP_TYPE_CENTRAL, DP_TYPE_NBAFL)

    # --- jit-safe transforms ------------------------------------------------
    def add_local_noise(self, update: PyTree, rng: jax.Array) -> PyTree:
        """Clip to sensitivity then noise — applied per client before the
        aggregation collective (LDP / NbAFL uplink noise)."""
        clipped = clip_by_global_norm(update, self.clip_norm)
        return self.mechanism.add_noise(clipped, rng)

    def clip_update(self, update: PyTree) -> PyTree:
        """Per-client sensitivity bound — MUST be applied to every client
        update on the CDP path too, or the calibrated noise under-covers a
        single outlier contribution."""
        return clip_by_global_norm(update, self.clip_norm)

    def add_global_noise(self, agg: PyTree, rng: jax.Array) -> PyTree:
        """Server-side noise on the aggregate (CDP / NbAFL downlink)."""
        return self.mechanism.add_noise(agg, rng)

    # --- accounting ---------------------------------------------------------
    def record_round(self, sample_rate: float) -> None:
        if not self.enabled:
            return
        sigma = getattr(self.mechanism, "sigma", None)
        if sigma is not None:
            self.accountant.step(sigma / max(self.sensitivity, 1e-12),
                                 sample_rate)
        else:
            # Laplace: pure-DP basic composition (epsilons add per round)
            self._laplace_rounds += 1

    def get_epsilon_spent(self) -> float:
        if self._laplace_rounds:
            return self.epsilon * self._laplace_rounds
        return self.accountant.get_epsilon(self.delta)

    # --- checkpointable accounting state ------------------------------------
    def state_dict(self):
        import numpy as np
        return {"rdp": np.asarray(self.accountant._rdp),
                "laplace_rounds": np.int64(self._laplace_rounds)}

    def load_state_dict(self, st) -> None:
        import numpy as np
        self.accountant._rdp = np.asarray(st["rdp"])
        self._laplace_rounds = int(st["laplace_rounds"])


__all__ = ["FedMLDifferentialPrivacy", "Gaussian", "Laplace",
           "add_gaussian_noise", "add_laplace_noise", "clip_by_global_norm",
           "create_mechanism", "gaussian_sigma", "laplace_scale",
           "RDPAccountant", "compute_rdp", "get_privacy_spent",
           "DP_TYPE_LOCAL", "DP_TYPE_CENTRAL", "DP_TYPE_NBAFL"]
