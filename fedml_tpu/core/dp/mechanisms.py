"""DP noise mechanisms — jit-able, pytree-native.

Parity target: reference ``core/dp/mechanisms/`` (``gaussian.py``,
``laplace.py``): calibrated noise given (epsilon, delta, sensitivity). The
reference adds noise tensor-by-tensor on the host; here a mechanism is a pure
function over a pytree + PRNG key so it can run inside the jitted round
(client-side for LDP, server-side for CDP).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


def gaussian_sigma(epsilon: float, delta: float, sensitivity: float) -> float:
    """Classic analytic calibration sigma = s * sqrt(2 ln(1.25/delta)) / eps
    (Dwork & Roth; reference ``mechanisms/gaussian.py``)."""
    return sensitivity * math.sqrt(2.0 * math.log(1.25 / delta)) / epsilon


def laplace_scale(epsilon: float, sensitivity: float) -> float:
    return sensitivity / epsilon


def add_gaussian_noise(tree: PyTree, rng: jax.Array, sigma: float) -> PyTree:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [l + sigma * jax.random.normal(k, l.shape, l.dtype)
              for l, k in zip(leaves, keys)]
    return jax.tree_util.tree_unflatten(treedef, noised)


def add_laplace_noise(tree: PyTree, rng: jax.Array, scale: float) -> PyTree:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [l + scale * jax.random.laplace(k, l.shape, l.dtype)
              for l, k in zip(leaves, keys)]
    return jax.tree_util.tree_unflatten(treedef, noised)


def clip_by_global_norm(tree: PyTree, max_norm: float) -> PyTree:
    """L2-clip the whole pytree (the DP sensitivity bound)."""
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
             for l in jax.tree_util.tree_leaves(tree))
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(lambda l: l * scale.astype(l.dtype), tree)


class Gaussian:
    def __init__(self, epsilon: float, delta: float, sensitivity: float = 1.0):
        self.sigma = gaussian_sigma(epsilon, delta, sensitivity)

    def add_noise(self, tree: PyTree, rng: jax.Array) -> PyTree:
        return add_gaussian_noise(tree, rng, self.sigma)


class Laplace:
    def __init__(self, epsilon: float, delta: float = 0.0,
                 sensitivity: float = 1.0):
        self.scale = laplace_scale(epsilon, sensitivity)

    def add_noise(self, tree: PyTree, rng: jax.Array) -> PyTree:
        return add_laplace_noise(tree, rng, self.scale)


def create_mechanism(name: str, epsilon: float, delta: float,
                     sensitivity: float = 1.0):
    name = (name or "gaussian").lower()
    if name == "gaussian":
        return Gaussian(epsilon, delta, sensitivity)
    if name == "laplace":
        return Laplace(epsilon, delta, sensitivity)
    raise ValueError(f"unknown dp mechanism {name!r}")
