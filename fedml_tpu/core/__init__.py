from . import collectives, mesh
