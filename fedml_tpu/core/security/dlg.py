"""Gradient-inversion attack (DLG / "deep leakage from gradients").

Parity target: reference ``core/security/attack/dlg_attack.py`` and
``invert_gradient_attack.py`` (755 LoC) — reconstruct a client's training
batch from its shared gradient. TPU-native form: the whole inversion is one
jitted optimization (``lax.scan`` over optimizer steps, gradient-of-gradient
via ``jax.grad`` through the cosine-distance match objective).

Used in tests to demonstrate that DP noise / secure aggregation actually
protect client data.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import optax

PyTree = Any


def infer_label_idlg(target_grads: PyTree, num_classes: int):
    """iDLG label inference (Zhao et al.): for softmax cross-entropy with a
    single-sample batch, the bias gradient is p - onehot(y), whose unique
    negative entry sits at the true label. Returns the label or None if no
    bias-shaped leaf with exactly one negative entry is found."""
    for leaf in jax.tree_util.tree_leaves(target_grads):
        if leaf.ndim == 1 and leaf.shape[0] == num_classes:
            neg = jnp.sum(leaf < 0)
            if int(neg) == 1:
                return int(jnp.argmin(leaf))
    return None


def invert_gradient(
    spec,
    params: PyTree,
    target_grads: PyTree,
    x_shape: Tuple[int, ...],
    num_classes: int,
    rng: jax.Array,
    steps: int = 200,
    lr: float = 0.1,
    tv_weight: float = 0.0,
    objective: str = "l2",
) -> Dict[str, Any]:
    """Optimize dummy (x, soft-y) so their gradient matches ``target_grads``.

    Returns {"x": recovered batch, "y_logits": recovered label logits,
    "match_loss": final objective}. ``objective``: "l2" is classic DLG (Zhu
    et al.); "cosine" is Geiping et al.'s inverting-gradients variant.

    Soft-label joint optimization has an exact sign symmetry on linear
    models (x, p-y) -> (-x, y-p); when iDLG label inference succeeds
    (single-sample batch), the label is pinned one-hot, which breaks the
    symmetry and makes reconstruction exact.
    """
    x_rng, y_rng = jax.random.split(rng)
    bs = x_shape[0]
    dummy_x = jax.random.normal(x_rng, x_shape)
    known_label = infer_label_idlg(target_grads, num_classes) if bs == 1 else None
    if known_label is not None:
        fixed = jnp.full((bs, num_classes), -20.0).at[:, known_label].set(20.0)
        dummy_y = fixed
    else:
        dummy_y = jax.random.normal(y_rng, (bs, num_classes)) * 0.1

    flat_target, _ = jax.flatten_util.ravel_pytree(target_grads)
    t_norm = jnp.linalg.norm(flat_target) + 1e-12

    def grad_of(dummy):
        dx, dy = dummy
        if known_label is not None:
            dy = jax.lax.stop_gradient(dy)
        batch = {"x": dx, "y_soft": jax.nn.softmax(dy),
                 "mask": jnp.ones((bs,), jnp.float32)}

        def loss_fn(p):
            logits = spec.apply_fn(p, batch["x"], train=False)
            per_ex = -jnp.sum(
                batch["y_soft"] * jax.nn.log_softmax(logits), axis=-1)
            return jnp.mean(per_ex)

        return jax.grad(loss_fn)(params)

    def objective_fn(dummy):
        g = grad_of(dummy)
        flat_g, _ = jax.flatten_util.ravel_pytree(g)
        if objective == "cosine":
            cos = jnp.sum(flat_g * flat_target) / (
                (jnp.linalg.norm(flat_g) + 1e-12) * t_norm)
            obj = 1.0 - cos
        else:
            obj = jnp.sum((flat_g - flat_target) ** 2)
        if tv_weight > 0.0 and len(x_shape) >= 3:
            dx = dummy[0]
            tv = jnp.mean(jnp.abs(jnp.diff(dx, axis=1))) + \
                jnp.mean(jnp.abs(jnp.diff(dx, axis=2)))
            obj = obj + tv_weight * tv
        return obj

    opt = optax.adam(lr)

    def step(carry, _):
        dummy, opt_state = carry
        loss, grads = jax.value_and_grad(objective_fn)(dummy)
        updates, opt_state = opt.update(grads, opt_state, dummy)
        dummy = optax.apply_updates(dummy, updates)
        return (dummy, opt_state), loss

    dummy0 = (dummy_x, dummy_y)
    (dummy, _), losses = jax.lax.scan(
        step, (dummy0, opt.init(dummy0)), None, length=steps)
    return {"x": dummy[0], "y_logits": dummy[1], "match_loss": losses[-1],
            "loss_curve": losses}
